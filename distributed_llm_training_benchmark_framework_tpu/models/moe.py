"""Mixture-of-Experts MLP with expert parallelism — GShard-style dispatch.

The reference lists "Mistral/Mixtral architectures" and MoE only as future
work (reference ``README.md:1025``); here sparse expert layers are a
first-class model family with their own mesh axis.

Two dispatch formulations share the same routing math:

1. **Explicit all-to-all** (``_moe_mlp_a2a``) — the expert-parallel path.
   The batch is sharded over ``('data', 'expert')``
   (``strategies.batch_partition_spec``), so each of the dp x ep members
   routes its OWN tokens; inside a ``shard_map`` the dispatched
   ``(experts, capacity, d_model)`` buffer is exchanged across the
   'expert' axis with ``lax.all_to_all`` (one hop out, expert FFN on local
   experts, one hop back). This is the DeepSpeed-MoE/Tutel schedule, and
   the collective is *guaranteed* in the lowering because we emit it.

2. **GSPMD einsum** (``_moe_mlp_einsum``) — routing as two dense einsums
   against a one-hot dispatch tensor: static shapes, MXU-friendly, used on
   meshes without a >1 'expert' axis and inside the pipeline schedules'
   manual regions.

Round-5 finding (the reason the explicit path exists): the SPMD
partitioner does NOT lower the dispatch/combine einsums to all-to-all —
AOT-compiling the einsum formulation for an 8-chip v5e topology shows 0
``all-to-all`` ops; the partitioner picks all-gather/all-reduce
strategies, which move the full token buffer across the expert axis. An
earlier docstring claimed the opposite; ``tests/test_collective_lowering.py``
now pins the all-to-all in the compiled HLO of the explicit path.

Top-k routing with capacity: each token picks its top-k experts by router
probability; each expert accepts at most C = ceil(capacity_factor * k * N / E)
tokens (token order breaks ties); overflowing tokens are dropped for that
expert (their combine weight is zero) — the standard capacity discipline that
keeps every shape static under jit. In the all-to-all path N and C are
per-member quantities (capacity is provisioned per source shard), so drop
decisions are shard-local; total capacity ep * C_local matches the global
formulation's budget.

The load-balance auxiliary loss is Switch-style: E * sum_e f_e * P_e, where
f_e is the fraction of tokens dispatched to expert e (top-1 assignment) and
P_e the mean router probability — minimized at uniform routing. The
all-to-all path ``pmean``s f and P over the token-sharding axes so both
formulations optimize the same global statistic.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def capacity(n_tokens: int, n_experts: int, top_k: int, factor: float) -> int:
    c = int(factor * top_k * n_tokens / n_experts + 0.999)
    return max(c, top_k)


def _route(c, xt: jax.Array, router: jax.Array, C: int):
    """Shared routing math -> (dispatch (N,E,C), combine (N,E,C), probs,
    expert_idx). fp32 router numerics (discipline as for softmax/LN)."""
    N = xt.shape[0]
    E, K = c.n_experts, c.expert_top_k
    logits = jnp.einsum(
        "nd,de->ne", xt, router.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    probs = jax.nn.softmax(logits, axis=-1)  # (N, E)

    gate_vals, expert_idx = jax.lax.top_k(probs, K)  # (N, K)
    # Renormalize the chosen gates so they sum to 1 per token.
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
    )

    # Position of each (token, choice) in its expert's capacity buffer:
    # count prior assignments to the same expert in (token-major, choice-major)
    # order via a cumulative sum over one-hots.
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # (N, K, E)
    flat = onehot.reshape(N * K, E)
    pos_flat = jnp.cumsum(flat, axis=0) - flat  # prior count per expert
    pos = jnp.sum(pos_flat.reshape(N, K, E) * onehot, axis=-1)  # (N, K)
    keep = pos < C  # overflowing assignments are dropped

    # dispatch (N, E, C): 1 where token n occupies slot c of expert e.
    disp = (
        jax.nn.one_hot(expert_idx, E, dtype=xt.dtype)[:, :, :, None]
        * jax.nn.one_hot(jnp.where(keep, pos, C), C + 1, dtype=xt.dtype)[:, :, None, :C]
    )  # (N, K, E, C); pos>=C one-hots into the dropped C+1th slot, sliced off
    dispatch = jnp.sum(disp, axis=1)  # (N, E, C)
    combine = jnp.sum(disp * gate_vals[:, :, None, None].astype(xt.dtype), axis=1)
    drop_frac = jnp.mean(1.0 - keep.astype(jnp.float32))
    return dispatch, combine, probs, expert_idx, drop_frac


def _expert_ffn(c, xin: jax.Array, w1, b1, w2, b2) -> jax.Array:
    """(E', C', D) -> (E', C', D) batched expert MLP, bf16 MXU / fp32 accum."""
    h = jnp.einsum(
        "ecd,edf->ecf", xin, w1.astype(c.compute_dtype),
        preferred_element_type=jnp.float32,
    ).astype(c.compute_dtype) + b1.astype(c.compute_dtype)[:, None, :]
    h = jax.nn.gelu(h, approximate=False)
    return jnp.einsum(
        "ecf,efd->ecd", h, w2.astype(c.compute_dtype),
        preferred_element_type=jnp.float32,
    ).astype(c.compute_dtype) + b2.astype(c.compute_dtype)[:, None, :]


def _aux_stats(probs: jax.Array, expert_idx: jax.Array, E: int):
    """Switch load-balance statistics on the top-1 assignment -> (f, p)."""
    top1 = jax.nn.one_hot(expert_idx[:, 0], E, dtype=jnp.float32)
    f = jnp.mean(top1, axis=0)  # fraction of tokens per expert
    p = jnp.mean(probs, axis=0)  # mean router prob per expert
    return f, p


def _moe_mlp_einsum(c, layer, x, dropout_key, deterministic):
    """GSPMD formulation: dense einsums, sharding left to the partitioner."""
    from .tinygpt import _dropout

    B, S, D = x.shape
    N = B * S
    E = c.n_experts
    C = capacity(N, E, c.expert_top_k, c.capacity_factor)
    xt = x.reshape(N, D)

    dispatch, combine, probs, expert_idx, drop_frac = _route(
        c, xt, layer["router"], C
    )

    # Expert compute on (E, C, D) buffers — batched over the expert axis.
    xin = jnp.einsum("nd,nec->ecd", xt, dispatch, preferred_element_type=jnp.float32)
    out_e = _expert_ffn(
        c, xin.astype(c.compute_dtype),
        layer["moe_w1"], layer["moe_b1"], layer["moe_w2"], layer["moe_b2"],
    )
    y = jnp.einsum(
        "ecd,nec->nd", out_e, combine, preferred_element_type=jnp.float32
    ).astype(x.dtype)
    y = _dropout(y, c.dropout, dropout_key, deterministic)

    if c.moe_aux_mode == "overflow":
        return y.reshape(B, S, D), drop_frac
    f, p = _aux_stats(probs, expert_idx, E)
    aux = E * jnp.sum(f * p)
    return y.reshape(B, S, D), aux


def _moe_mlp_a2a(c, layer, x, dropout_key, deterministic, mesh, ep, dp):
    """Expert-parallel formulation: explicit all-to-all inside shard_map.

    Token layout: batch dim sharded over ('data', 'expert') — every member
    routes B*S/(dp*ep) tokens. Expert layout: weight tensors sharded over
    'expert' on their leading experts axis (strategies._EP_RULES), E/ep
    local experts per member. Two ``lax.all_to_all`` hops exchange the
    per-source-capacity buffers; the expert FFN runs on (E/ep, ep*C, D).
    """
    from .tinygpt import _dropout

    B, S, D = x.shape
    E, K = c.n_experts, c.expert_top_k
    E_loc = E // ep
    batch_ax = ("data", "expert") if dp > 1 else ("expert",)
    xspec = P(batch_ax, None, None)
    have_key = dropout_key is not None
    key = dropout_key if have_key else jax.random.key(0)

    def body(x_loc, router, w1, b1, w2, b2, key):
        Bl, S_, D_ = x_loc.shape
        N = Bl * S_
        C = capacity(N, E, K, c.capacity_factor)
        xt = x_loc.reshape(N, D_)

        dispatch, combine, probs, expert_idx, drop_frac = _route(
            c, xt, router, C
        )

        xin = jnp.einsum(
            "nd,nec->ecd", xt, dispatch, preferred_element_type=jnp.float32
        ).astype(c.compute_dtype)  # (E, C, D)

        # Hop out: split the experts axis into ep destination groups; after
        # the exchange dim 0 indexes the SOURCE member, so member m holds
        # its E_loc experts' slices from every source.
        xin = xin.reshape(ep, E_loc, C, D_)
        xin = lax.all_to_all(xin, "expert", split_axis=0, concat_axis=0)
        xe = xin.transpose(1, 0, 2, 3).reshape(E_loc, ep * C, D_)

        out = _expert_ffn(c, xe, w1, b1, w2, b2)  # (E_loc, ep*C, D)

        # Hop back: regroup by source and return each member its slots.
        out = out.reshape(E_loc, ep, C, D_).transpose(1, 0, 2, 3)
        out = lax.all_to_all(out, "expert", split_axis=0, concat_axis=0)
        out_full = out.reshape(E, C, D_)

        y = jnp.einsum(
            "ecd,nec->nd", out_full, combine, preferred_element_type=jnp.float32
        ).astype(x_loc.dtype)
        if have_key:
            # Distinct dropout stream per token shard (same discipline as
            # the pipeline schedules' per-shard fold, tinygpt.py).
            member = lax.axis_index("expert") + (
                ep * lax.axis_index("data") if dp > 1 else 0
            )
            y = _dropout(
                y, c.dropout, jax.random.fold_in(key, member), deterministic
            )

        if c.moe_aux_mode == "overflow":
            return y.reshape(Bl, S_, D_), lax.pmean(drop_frac, batch_ax)
        f, p = _aux_stats(probs, expert_idx, E)
        # Both statistics are means over the GLOBAL token set in the einsum
        # formulation; average over the token-sharding axes to match.
        f = lax.pmean(f, batch_ax)
        p = lax.pmean(p, batch_ax)
        aux = E * jnp.sum(f * p)
        return y.reshape(Bl, S_, D_), aux

    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(
            xspec,
            P(None, None),            # router replicated (tiny; all tokens need all scores)
            P("expert", None, None),  # moe_w1 (E, D, F)
            P("expert", None),        # moe_b1 (E, F)
            P("expert", None, None),  # moe_w2 (E, F, D)
            P("expert", None),        # moe_b2 (E, D)
            P(),
        ),
        out_specs=(xspec, P()),
    )
    return fn(
        x, layer["router"], layer["moe_w1"], layer["moe_b1"],
        layer["moe_w2"], layer["moe_b2"], key,
    )


def moe_mlp(
    config,
    layer: dict,  # one layer's params: router, moe_w1/b1, moe_w2/b2
    x: jax.Array,  # (B, S, D) compute dtype
    dropout_key: Optional[jax.Array],
    deterministic: bool,
) -> Tuple[jax.Array, jax.Array]:
    """-> (output (B,S,D), aux load-balance loss scalar fp32).

    Picks the dispatch formulation per ``config.moe_dispatch`` (module
    docstring): the explicit all-to-all path needs a mesh in scope with a
    >1 'expert' axis, divisible geometry, and no manual/sequence/tensor/
    pipeline axes in play; anything else falls back to the GSPMD einsums.
    """
    c = config
    B, S, D = x.shape
    mesh = None
    if c.moe_dispatch != "einsum" and c.seq_manual_axis is None:
        m = jax.sharding.get_abstract_mesh()
        if m is not None and "expert" in getattr(m, "axis_names", ()):
            mesh = m
    ep = mesh.shape.get("expert", 1) if mesh is not None else 1
    dp = mesh.shape.get("data", 1) if mesh is not None else 1
    geometry_ok = (
        ep > 1
        and c.n_experts % ep == 0
        and B % (dp * ep) == 0
        and mesh.shape.get("model", 1) == 1
        and mesh.shape.get("seq", 1) == 1
        and mesh.shape.get("pipe", 1) == 1
    )
    if c.moe_dispatch == "alltoall" and not geometry_ok:
        raise ValueError(
            "moe_dispatch='alltoall' needs an in-scope mesh with a >1 "
            "'expert' axis, n_experts % ep == 0, batch % (dp*ep) == 0, and "
            f"no model/seq/pipe axes > 1 (got mesh={mesh}, B={B})"
        )
    if geometry_ok:
        return _moe_mlp_a2a(c, layer, x, dropout_key, deterministic, mesh, ep, dp)
    return _moe_mlp_einsum(c, layer, x, dropout_key, deterministic)
