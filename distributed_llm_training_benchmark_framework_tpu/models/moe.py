"""Mixture-of-Experts MLP with expert parallelism — GShard-style dispatch.

The reference lists "Mistral/Mixtral architectures" and MoE only as future
work (reference ``README.md:1025``); here sparse expert layers are a
first-class model family with their own mesh axis.

TPU-native formulation (GShard/Switch): routing is expressed as two dense
einsums against a one-hot *dispatch* tensor instead of gather/scatter —
static shapes, MXU-friendly, and when the expert axis of the
``(experts, capacity, d_model)`` buffers is sharded over the 'expert' mesh
axis, GSPMD lowers the dispatch/combine einsums into the all-to-all exchange
expert parallelism needs.

Top-k routing with capacity: each token picks its top-k experts by router
probability; each expert accepts at most C = ceil(capacity_factor * k * N / E)
tokens (token order breaks ties); overflowing tokens are dropped for that
expert (their combine weight is zero) — the standard capacity discipline that
keeps every shape static under jit.

The load-balance auxiliary loss is Switch-style: E * sum_e f_e * P_e, where
f_e is the fraction of tokens dispatched to expert e (top-1 assignment) and
P_e the mean router probability — minimized at uniform routing.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def capacity(n_tokens: int, n_experts: int, top_k: int, factor: float) -> int:
    c = int(factor * top_k * n_tokens / n_experts + 0.999)
    return max(c, top_k)


def moe_mlp(
    config,
    layer: dict,  # one layer's params: router, moe_w1/b1, moe_w2/b2
    x: jax.Array,  # (B, S, D) compute dtype
    dropout_key: Optional[jax.Array],
    deterministic: bool,
) -> Tuple[jax.Array, jax.Array]:
    """-> (output (B,S,D), aux load-balance loss scalar fp32)."""
    from .tinygpt import _dropout  # shared dropout primitive

    c = config
    B, S, D = x.shape
    N = B * S
    E, K = c.n_experts, c.expert_top_k
    C = capacity(N, E, K, c.capacity_factor)
    xt = x.reshape(N, D)

    # Router in fp32 (numerics discipline as for softmax/LN elsewhere).
    logits = jnp.einsum(
        "nd,de->ne", xt, layer["router"].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    probs = jax.nn.softmax(logits, axis=-1)  # (N, E)

    gate_vals, expert_idx = jax.lax.top_k(probs, K)  # (N, K)
    # Renormalize the chosen gates so they sum to 1 per token.
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
    )

    # Position of each (token, choice) in its expert's capacity buffer:
    # count prior assignments to the same expert in (token-major, choice-major)
    # order via a cumulative sum over one-hots.
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # (N, K, E)
    flat = onehot.reshape(N * K, E)
    pos_flat = jnp.cumsum(flat, axis=0) - flat  # prior count per expert
    pos = jnp.sum(pos_flat.reshape(N, K, E) * onehot, axis=-1)  # (N, K)
    keep = pos < C  # overflowing assignments are dropped

    # dispatch (N, E, C): 1 where token n occupies slot c of expert e.
    disp = (
        jax.nn.one_hot(expert_idx, E, dtype=x.dtype)[:, :, :, None]
        * jax.nn.one_hot(jnp.where(keep, pos, C), C + 1, dtype=x.dtype)[:, :, None, :C]
    )  # (N, K, E, C); pos>=C one-hots into the dropped C+1th slot, sliced off
    dispatch = jnp.sum(disp, axis=1)  # (N, E, C)
    combine = jnp.sum(disp * gate_vals[:, :, None, None].astype(x.dtype), axis=1)

    # Expert compute on (E, C, D) buffers — batched over the expert axis,
    # shardable on the 'expert' mesh axis.
    xin = jnp.einsum("nd,nec->ecd", xt, dispatch, preferred_element_type=jnp.float32)
    xin = xin.astype(c.compute_dtype)
    h = jnp.einsum(
        "ecd,edf->ecf", xin, layer["moe_w1"].astype(c.compute_dtype),
        preferred_element_type=jnp.float32,
    ).astype(c.compute_dtype) + layer["moe_b1"].astype(c.compute_dtype)[:, None, :]
    h = jax.nn.gelu(h, approximate=False)
    out_e = jnp.einsum(
        "ecf,efd->ecd", h, layer["moe_w2"].astype(c.compute_dtype),
        preferred_element_type=jnp.float32,
    ).astype(c.compute_dtype) + layer["moe_b2"].astype(c.compute_dtype)[:, None, :]

    y = jnp.einsum(
        "ecd,nec->nd", out_e, combine, preferred_element_type=jnp.float32
    ).astype(x.dtype)
    y = _dropout(y, c.dropout, dropout_key, deterministic)

    # Switch load-balance loss on the top-1 assignment.
    top1 = jax.nn.one_hot(expert_idx[:, 0], E, dtype=jnp.float32)
    f = jnp.mean(top1, axis=0)           # fraction of tokens per expert
    p = jnp.mean(probs, axis=0)          # mean router prob per expert
    aux = E * jnp.sum(f * p)

    return y.reshape(B, S, D), aux
