"""Llama model family — tier table over the shared functional transformer.

The reference framework benchmarks exactly one architecture (its TinyGPT,
reference ``benchmarking/train_harness.py:36-131``); a second family is
beyond-parity surface. Rather than a parallel implementation, the family is a
CONFIGURATION of the same pytree transformer (``models.tinygpt``): RMSNorm,
rotary position embeddings, SwiGLU MLP, grouped-query attention, no biases,
untied LM head, causal masking — each an orthogonal config knob whose
numerics are pinned against HuggingFace ``LlamaForCausalLM`` by
``tests/test_llama_family.py``. Every strategy arm, pipeline schedule,
attention kernel, remat policy and the memory/FLOPs accounting work on the
family unchanged, because they only ever consumed the config and the leaf
names.

Why the tiers use head_dim 128 (vs TinyGPT's 64): the measured MXU wall
(docs/PERFORMANCE.md §15) — at D_head=64 the flash kernel's score-tile
arithmetic intensity caps the attention matmuls at ~22-26% of peak, while
the iso-FLOP D_head=128 probe reached ~35%. Llama-family shapes are how
real models buy back that headroom, so the family doubles as the
benchmark's wide-head MFU arm.

Parameter budgets (untied embeddings; SwiGLU F ≈ 8/3·D rounded to 256):
tier A ≈ 255M — comparable to TinyGPT tier A's 236M; tier B ≈ 1.62B —
comparable to tier B's 1.68B. Tier S is the CPU test tier.
"""

from __future__ import annotations

from .tinygpt import TinyGPTConfig

# (vocab, d_model, n_head, n_kv_head, n_layer, mlp_hidden). head_dim is
# d_model / n_head = 128 for A/B (the MXU-width tier design), 64 for S.
_TIERS = {
    # ~255M params. 8 query heads of 128; 4 KV heads (GQA 2:1).
    "A": dict(vocab_size=32000, n_embd=1024, n_head=8, n_kv_head=4,
              n_layer=16, mlp_hidden=2816),
    # ~1.62B params. 16 query heads of 128; 8 KV heads.
    "B": dict(vocab_size=32000, n_embd=2048, n_head=16, n_kv_head=8,
              n_layer=32, mlp_hidden=5632),
    # Tiny CPU/test tier (head_dim 64 — small enough for 8-device meshes).
    "S": dict(vocab_size=512, n_embd=128, n_head=2, n_kv_head=1,
              n_layer=2, mlp_hidden=352),
}


def get_llama_config(tier: str, seq_len: int, **overrides) -> TinyGPTConfig:
    """Llama-family tier table (same call shape as ``get_model_config``).

    ``block_size = seq_len`` follows the reference convention
    (train_harness.py:168,176) — with RoPE there is no positional table to
    size, but block_size still bounds the benchmark geometry checks.
    """
    if tier not in _TIERS:
        raise ValueError(
            f"Unknown llama tier: {tier!r} (expected one of {sorted(_TIERS)})"
        )
    kw = dict(_TIERS[tier])
    kw.update(
        block_size=seq_len,
        causal=True,            # the family is causal-LM by construction
        norm="rmsnorm",
        pos_embed="rope",
        mlp_act="swiglu",
        bias=False,
        tie_embeddings=False,
        # Llama-family pretraining runs without dropout (HF LlamaConfig
        # attention_dropout defaults to 0.0) — unlike the reference TinyGPT's
        # 0.1. Overridable like every other knob (--dropout).
        dropout=0.0,
    )
    kw.update(overrides)
    return TinyGPTConfig(**kw)
