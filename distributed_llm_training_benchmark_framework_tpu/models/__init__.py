from .tinygpt import (
    TinyGPTConfig,
    get_model_config,
    init_params,
    forward,
    loss_fn,
    count_params,
    PARAM_AXIS_RULES,
)
from .llama import get_llama_config

__all__ = [
    "TinyGPTConfig",
    "get_model_config",
    "get_llama_config",
    "init_params",
    "forward",
    "loss_fn",
    "count_params",
    "PARAM_AXIS_RULES",
]
