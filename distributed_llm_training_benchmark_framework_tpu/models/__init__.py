from .tinygpt import (
    TinyGPTConfig,
    get_model_config,
    init_params,
    forward,
    loss_fn,
    count_params,
    PARAM_AXIS_RULES,
)

__all__ = [
    "TinyGPTConfig",
    "get_model_config",
    "init_params",
    "forward",
    "loss_fn",
    "count_params",
    "PARAM_AXIS_RULES",
]
