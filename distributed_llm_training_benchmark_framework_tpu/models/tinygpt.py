"""TinyGPT — decoder-style benchmark transformer, pure functional JAX.

Capability parity with the reference model (reference
``benchmarking/train_harness.py:36-131``, classes ``TinyGPT`` /
``TransformerBlock``): token embedding + learned positional embedding +
embedding dropout + N pre-LN blocks (multi-head attention + 4x GELU MLP, both
with residuals) + final LayerNorm + weight-tied LM head + cross-entropy loss
with ``ignore_index=-1``.

TPU-first design differences (deliberate, not omissions):

- **Functional, pytree params.** No module objects. Parameters are a nested
  dict of arrays so every leaf can carry a ``jax.sharding.NamedSharding`` —
  strategies are data (PartitionSpecs), not wrapper classes.
- **Stacked layers + ``lax.scan``.** All N blocks' weights are stacked on a
  leading ``layers`` axis and the forward scans over them. One trace/compile of
  the block regardless of depth — compile time stays flat from tier S to
  tier B, and ``jax.checkpoint`` (remat) applies uniformly per-layer.
- **Mixed precision the TPU way.** Params live in fp32; matmuls run in
  bfloat16 on the MXU with fp32 accumulation (``preferred_element_type``);
  LayerNorm, softmax and the loss stay fp32. (The reference runs fp16 AMP for
  DDP/FSDP and bf16 for ZeRO — reference ``train_harness.py:334-335`` vs
  ``configs/deepspeed/zero2.json:7-9``; on TPU bf16 is the native fast path.)
- **Attention is maskless by default** for benchmark parity: the reference
  passes no causal mask (reference ``train_harness.py:127``), so it benchmarks
  bidirectional attention compute. ``causal=True`` is available as a real
  option, as is a Pallas flash-attention kernel (``ops.flash_attention``).

Tier table matches reference ``get_model_config`` (``train_harness.py:157-179``):
tier A = 1024d/16h/16L (~236M params with tied embeddings), tier B =
2048d/32h/32L (~1.68B). Tier S is ours, for CPU tests/smoke runs.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, Any]

REMAT_POLICIES = ("none", "dots", "full")


def normalize_remat(value: Any) -> str:
    """Normalize a remat policy: accepts "none"/"dots"/"full" or a legacy
    bool (True = "full"). "auto" must be resolved (utils.memory
    .resolve_auto_remat) before it reaches the model."""
    if isinstance(value, bool):
        return "full" if value else "none"
    if value in REMAT_POLICIES:
        return value
    raise ValueError(
        f"invalid remat policy {value!r} (expected one of {REMAT_POLICIES}, "
        "a bool, or 'auto' resolved upstream)"
    )


@dataclasses.dataclass(frozen=True)
class TinyGPTConfig:
    vocab_size: int = 32000
    n_embd: int = 768
    n_head: int = 12
    n_layer: int = 12
    block_size: int = 4096
    dropout: float = 0.1
    # Parity default: the reference applies no causal mask (train_harness.py:127).
    causal: bool = False
    # 'reference' = jnp softmax attention; 'flash' = Pallas TPU kernel;
    # 'ring' = ring attention over a sequence-parallel mesh axis.
    attention_impl: str = "reference"
    # Flash-kernel tile sizes (None = kernel's tuned default). Exposed as a
    # real tuning surface (--flash-block-q/k/k-bwd) because the optima are
    # device-generation dependent — and differ between forward and backward.
    flash_block_q: Optional[int] = None
    flash_block_k: Optional[int] = None
    flash_block_k_bwd: Optional[int] = None
    # Flash backward implementation: None = auto (the measured S-dependent
    # crossover in ops/flash_attention — einsum backward to S=2048, Pallas
    # kernels from S=4096); True forces the Pallas kernels, False forces the
    # XLA-fused blockwise einsum backward.
    flash_pallas_backward: Optional[bool] = None
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    # Per-layer rematerialization policy inside the scan:
    #   "none" — save every intermediate (fastest, most memory);
    #   "dots" — jax.checkpoint with the save-dots-class policy: matmul
    #            outputs are kept, only cheap elementwise/softmax work is
    #            recomputed in backward (the low-tax middle ground);
    #   "full" — all-or-nothing jax.checkpoint per layer (least memory,
    #            ~full forward recompute in backward).
    # Booleans are accepted for backward compatibility (True="full").
    remat: Any = "none"
    # lax.scan over stacked layer weights (one compiled block body, fast
    # compile, what pipeline sharding needs) vs an unrolled Python loop
    # (16x the HLO, but activations save as distinct buffers instead of
    # dynamic-update-slice stacking — a tuning surface for single-chip runs).
    scan_layers: bool = True
    # Set (to the mesh axis name, e.g. 'seq') by the pipeline schedules when
    # they run their shard_map manually over the sequence axis: activations
    # then carry LOCAL sequence chunks, attention dispatches to the
    # *_sharded ring/Ulysses bodies (which communicate over this axis), the
    # positional embedding is offset by the shard index, and per-shard
    # dropout streams are decorrelated. None = ordinary (auto/GSPMD) mode.
    seq_manual_axis: Optional[str] = None
    # Mixture-of-Experts MLP (0 = dense). When > 0 every block's MLP becomes
    # a top-k routed expert layer (models.moe) and the training loss gains
    # the Switch load-balance auxiliary term.
    n_experts: int = 0
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01
    # Zigzag causal load balancing on ring attention: None = auto (on for
    # causal rings with even local shards — ops/ring_attention.py), True =
    # force (errors when the geometry can't), False = force the contiguous
    # layout. The off switch exists for the scaling-day A/B microbench
    # (zigzag's benefit is multi-chip wall-clock, unmeasurable single-chip).
    ring_zigzag: Optional[bool] = None
    # Aux channel content: 'switch' (the load-balance loss term, default)
    # or 'overflow' (fraction of (token, choice) assignments dropped by the
    # capacity limit) — the latter powers the moe_overflow_fraction
    # diagnostic without widening the aux carry through every schedule.
    moe_aux_mode: str = "switch"
    # Expert-parallel dispatch: 'auto' uses the explicit all-to-all
    # shard_map path whenever an 'expert' mesh axis (>1) is in scope and
    # the geometry allows it, falling back to the GSPMD einsum formulation
    # (models.moe module docstring — the partitioner does NOT lower the
    # dispatch einsums to all-to-all on its own). 'alltoall' forces the
    # explicit path (raises if the geometry can't), 'einsum' forces GSPMD.
    moe_dispatch: str = "auto"
    # ------------------------------------------------------------------
    # Architecture-family knobs (models.llama sets these; the defaults
    # reproduce the reference TinyGPT architecture bit-for-bit — reference
    # train_harness.py:36-131 has none of these options).
    # ------------------------------------------------------------------
    # Normalization: 'layernorm' (mean+var, learned scale/bias) or 'rmsnorm'
    # (no mean subtraction, scale only — Llama). Statistics always fp32.
    norm: str = "layernorm"
    norm_eps: float = 1e-5
    # Position information: 'learned' (additive wpe table, the reference
    # design) or 'rope' (rotary embedding applied to q/k per head — no
    # positional parameters at all, and block_size no longer bounds the
    # table, only the benchmark geometry).
    pos_embed: str = "learned"
    rope_theta: float = 10000.0
    # MLP: 'gelu' (D -> mlp_dim -> exact-erf GELU -> D, the reference MLP)
    # or 'swiglu' (gate/up pair, silu(gate)*up -> down — Llama).
    mlp_act: str = "gelu"
    # Hidden width of the MLP. None = 4*n_embd (the reference ratio). The
    # Llama family passes an explicit width (~8/3*D rounded for SwiGLU's
    # iso-parameter budget across its three matrices).
    mlp_hidden: Optional[int] = None
    # Grouped-query attention: number of K/V heads. None = n_head (MHA).
    # Each group of n_head/n_kv_head query heads shares one K/V head; the
    # projection splits into separate wq/wkv leaves (the fused wqkv layout
    # only exists for the square MHA case).
    n_kv_head: Optional[int] = None
    # Linear/LayerNorm biases (Llama ships none anywhere).
    bias: bool = True
    # Weight-tied LM head (reference train_harness.py:61-62). False adds a
    # separate 'lm_head' (V, D) leaf (Llama unties).
    tie_embeddings: bool = True
    # ZeRO-2 per-block gradient placement (round 8): a sorted tuple of
    # (block leaf name, PartitionSpec-for-one-layer-slice) pairs, set by
    # the train step for sharded-grad/replicated-param strategies. When
    # present, apply_blocks wraps each layer's weights in an identity
    # whose COTANGENT carries the sharding constraint — so every layer's
    # grad reduce-scatter issues INSIDE the backward layer loop, right
    # after that layer's backward matmuls, instead of as one tail bundle
    # after the whole backward. That is what lets XLA's latency-hiding
    # scheduler overlap grad comms with the next layer's backward compute
    # (DeepSpeed ZeRO's bucketed overlap, GSPMD-native). A tuple (not a
    # dict) so the config stays hashable.
    block_grad_spec: Any = None
    # FSDP/ZeRO-3 per-block parameter placement (round 15) — the forward-side
    # dual of block_grad_spec: a sorted tuple of (block leaf name,
    # PartitionSpec-for-one-layer-slice) pairs, set by the train step for
    # sharded-param strategies (train/step.py::fsdp_block_param_spec). When
    # present, apply_blocks pins each layer's weight SLICE to its sharded
    # placement INSIDE the forward layer loop — so the weight all-gather the
    # matmul needs issues per block, right before that block's dots, instead
    # of being free to bundle ahead of the whole layer stack (the structure
    # XLA's latency-hiding scheduler needs to overlap weight gathers with
    # adjacent blocks' forward compute; FSDP's prefetch-one-block schedule,
    # GSPMD-native). Transposes to the same per-block constraint on the
    # cotangent — exactly the fsdp/zero3 per-block grad placement.
    block_param_spec: Any = None
    # Scan-carry activation placement (round 15): a PartitionSpec for the
    # (B, S, D) residual stream carried through the layer scan, set by the
    # train step (train/step.py::scan_carry_spec) for scanned sharded-param
    # arms on composed dp x tp meshes. Without it XLA picks its own layout
    # for the scan's stacked activation stash and reconciles per iteration
    # with collective-permute chains (the banked llama-fsdp-dp4-tp2-scan
    # replication-reshard residue); pinning the carry at the body boundary
    # pins the stash layout with it.
    scan_carry_spec: Any = None
    # Collective-matmul tp fusion (round 15, ops/collective_matmul.py): when
    # True and a >1 'model' mesh axis is in scope, the tp projections
    # (attention qkv/out, MLP up/down) run as shard_map-decomposed matmuls —
    # the activation all-gather/reduce-scatter split into per-shard chunks
    # rotated by ppermute so the comms hide INSIDE the dot, and the residual
    # stream between projections rides sequence-sharded over 'model'
    # (Megatron sequence-parallel layout). Opt-in via --tp-collective-matmul.
    tp_collective_matmul: bool = False

    @property
    def head_dim(self) -> int:
        assert self.n_embd % self.n_head == 0
        return self.n_embd // self.n_head

    @property
    def kv_heads(self) -> int:
        return self.n_kv_head if self.n_kv_head is not None else self.n_head

    @property
    def mlp_dim(self) -> int:
        return self.mlp_hidden if self.mlp_hidden is not None else 4 * self.n_embd

    def __post_init__(self):
        if self.norm not in ("layernorm", "rmsnorm"):
            raise ValueError(f"norm must be 'layernorm'|'rmsnorm', got {self.norm!r}")
        if self.pos_embed not in ("learned", "rope"):
            raise ValueError(
                f"pos_embed must be 'learned'|'rope', got {self.pos_embed!r}"
            )
        if self.mlp_act not in ("gelu", "swiglu"):
            raise ValueError(f"mlp_act must be 'gelu'|'swiglu', got {self.mlp_act!r}")
        if self.n_kv_head is not None and self.n_head % self.n_kv_head != 0:
            raise ValueError(
                f"n_kv_head={self.n_kv_head} must divide n_head={self.n_head}"
            )
        if self.n_experts > 0 and self.mlp_act != "gelu":
            raise ValueError(
                "MoE blocks are defined for the dense-GELU MLP only "
                "(n_experts > 0 with mlp_act='swiglu' is not supported)"
            )


def get_model_config(tier: str, seq_len: int, **overrides) -> TinyGPTConfig:
    """Model tier table (parity: reference train_harness.py:157-179).

    block_size = seq_len exactly as the reference sets it (:168, :176), so the
    positional table is sized to the benchmarked sequence.
    """
    tiers = {
        # ~236M params (tied embeddings) — the tier all published numbers used.
        "A": dict(vocab_size=32000, n_embd=1024, n_head=16, n_layer=16),
        # ~1.68B params — stress tier.
        "B": dict(vocab_size=32000, n_embd=2048, n_head=32, n_layer=32),
        # Ours: tiny tier for CPU tests / CI smoke. Not in the reference.
        "S": dict(vocab_size=512, n_embd=128, n_head=4, n_layer=2),
    }
    if tier not in tiers:
        raise ValueError(f"Unknown tier: {tier!r} (expected one of {sorted(tiers)})")
    kw = dict(tiers[tier])
    kw["block_size"] = seq_len
    kw.update(overrides)
    return TinyGPTConfig(**kw)


# Logical axis names for every parameter leaf, used by parallel.strategies to
# turn a strategy into per-leaf PartitionSpecs. Leaves under 'blocks' carry a
# leading 'layers' axis (the scan axis).
PARAM_AXIS_RULES: Dict[str, Tuple[Optional[str], ...]] = {
    "wte": ("vocab", "embed"),
    "wpe": ("pos", "embed"),
    "blocks/ln1_scale": ("layers", "embed"),
    "blocks/ln1_bias": ("layers", "embed"),
    # qkv is stored (layers, embed, 3, heads*head_dim) — the q/k/v axis is its
    # own dimension so sharding 'heads' on a tensor-parallel mesh axis never
    # crosses a q/k/v boundary.
    "blocks/wqkv": ("layers", "embed", "qkv3", "heads"),
    "blocks/bqkv": ("layers", "qkv3", "heads"),
    # GQA split projections (present instead of wqkv/bqkv when kv_heads <
    # n_head): q keeps its own matrix; k/v stack on a 'kv2' axis so sharding
    # 'kv_heads' never crosses the k/v boundary (same reasoning as qkv3).
    "blocks/wq": ("layers", "embed", "heads"),
    "blocks/bq": ("layers", "heads"),
    "blocks/wkv": ("layers", "embed", "kv2", "kv_heads"),
    "blocks/bkv": ("layers", "kv2", "kv_heads"),
    "blocks/wo": ("layers", "heads_merged", "embed"),
    "blocks/bo": ("layers", "embed"),
    "blocks/ln2_scale": ("layers", "embed"),
    "blocks/ln2_bias": ("layers", "embed"),
    "blocks/wfc": ("layers", "embed", "mlp"),
    "blocks/bfc": ("layers", "mlp"),
    "blocks/wproj": ("layers", "mlp", "embed"),
    "blocks/bproj": ("layers", "embed"),
    # SwiGLU variant (present instead of wfc/bfc when mlp_act='swiglu'):
    # gate and up matrices stack on a 'gate2' axis; wproj/bproj are shared
    # with the dense path (same (layers, mlp, embed) shape).
    "blocks/wgu": ("layers", "embed", "gate2", "mlp"),
    "blocks/bgu": ("layers", "gate2", "mlp"),
    # MoE variant (present instead of wfc/bfc/wproj/bproj when n_experts > 0)
    "blocks/router": ("layers", "embed", "experts"),
    "blocks/moe_w1": ("layers", "experts", "embed", "mlp"),
    "blocks/moe_b1": ("layers", "experts", "mlp"),
    "blocks/moe_w2": ("layers", "experts", "mlp", "embed"),
    "blocks/moe_b2": ("layers", "experts", "embed"),
    "lnf_scale": ("embed",),
    "lnf_bias": ("embed",),
    # Untied LM head (present when tie_embeddings=False): same logical axes
    # as wte, so TP's vocab sharding (Megatron parallel softmax) applies to
    # both ends identically.
    "lm_head": ("vocab", "embed"),
}


def init_params(config: TinyGPTConfig, key: jax.Array) -> Params:
    """Initialize the parameter pytree.

    Init scheme parity (reference ``_init_weights``, train_harness.py:69-80):
    normal(0, 0.02) for linear/embedding weights, zeros for biases, ones/zeros
    for LayerNorm scale/bias. The LM head is weight-tied to ``wte`` (reference
    ``train_harness.py:61-62``) — there is no separate head matrix at all.
    """
    c = config
    D, H, L, V, T = c.n_embd, c.n_head, c.n_layer, c.vocab_size, c.block_size
    F, Hkv, Dh = c.mlp_dim, c.kv_heads, c.head_dim
    # The legacy tree (fused qkv, tied head, learned positions) splits into
    # exactly 8 keys — pinned so every published artifact's init (and loss
    # trace) stays bit-reproducible. Family configs with extra leaves use a
    # wider split; they are new surface with no reproduction constraint.
    legacy = Hkv == H and c.tie_embeddings and c.pos_embed == "learned"
    k = iter(jax.random.split(key, 8 if legacy else 12))

    def normal(key, shape):
        return (0.02 * jax.random.normal(key, shape)).astype(c.param_dtype)

    zeros = lambda shape: jnp.zeros(shape, c.param_dtype)
    ones = lambda shape: jnp.ones(shape, c.param_dtype)

    blocks = {"ln1_scale": ones((L, D)), "ln2_scale": ones((L, D))}
    if c.norm == "layernorm":
        blocks.update(ln1_bias=zeros((L, D)), ln2_bias=zeros((L, D)))
    if Hkv == H:
        blocks["wqkv"] = normal(next(k), (L, D, 3, D))
        if c.bias:
            blocks["bqkv"] = zeros((L, 3, D))
    else:
        blocks["wq"] = normal(next(k), (L, D, H * Dh))
        blocks["wkv"] = normal(next(k), (L, D, 2, Hkv * Dh))
        if c.bias:
            blocks["bq"] = zeros((L, H * Dh))
            blocks["bkv"] = zeros((L, 2, Hkv * Dh))
    blocks["wo"] = normal(next(k), (L, D, D))
    if c.bias:
        blocks["bo"] = zeros((L, D))
    if c.n_experts > 0:
        E = c.n_experts
        blocks.update(
            router=normal(next(k), (L, D, E)),
            moe_w1=normal(next(k), (L, E, D, F)),
            moe_b1=zeros((L, E, F)),
            moe_w2=normal(next(k), (L, E, F, D)),
            moe_b2=zeros((L, E, D)),
        )
    elif c.mlp_act == "swiglu":
        blocks["wgu"] = normal(next(k), (L, D, 2, F))
        blocks["wproj"] = normal(next(k), (L, F, D))
        if c.bias:
            blocks["bgu"] = zeros((L, 2, F))
            blocks["bproj"] = zeros((L, D))
    else:
        blocks["wfc"] = normal(next(k), (L, D, F))
        blocks["wproj"] = normal(next(k), (L, F, D))
        if c.bias:
            blocks["bfc"] = zeros((L, F))
            blocks["bproj"] = zeros((L, D))
    params = {
        "wte": normal(next(k), (V, D)),
        "blocks": blocks,
        "lnf_scale": ones((D,)),
    }
    if c.pos_embed == "learned":
        params["wpe"] = normal(next(k), (T, D))
    if c.norm == "layernorm":
        params["lnf_bias"] = zeros((D,))
    if not c.tie_embeddings:
        params["lm_head"] = normal(next(k), (V, D))
    return params


def count_params(params: Params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


def _layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float = 1e-5) -> jax.Array:
    # fp32 statistics regardless of compute dtype (AMP-style numerics).
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def _rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    # Llama RMSNorm: no mean subtraction, no bias; fp32 statistics (HF
    # LlamaRMSNorm computes the rsqrt in fp32 and multiplies the scale in
    # the input dtype — we keep the whole product fp32 before the downcast,
    # which agrees to within bf16 rounding).
    xf = x.astype(jnp.float32)
    y = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (y * scale.astype(jnp.float32)).astype(x.dtype)


def _norm(
    config: TinyGPTConfig, x: jax.Array, scale: jax.Array, bias: Optional[jax.Array]
) -> jax.Array:
    if config.norm == "rmsnorm":
        return _rms_norm(x, scale, config.norm_eps)
    return _layer_norm(x, scale, bias, config.norm_eps)


def _rope(
    x: jax.Array,  # (B, S, H, Dh)
    positions: jax.Array,  # (S,) int32 global token positions
    theta: float,
) -> jax.Array:
    """Rotary position embedding, HF-Llama rotate-half convention.

    ``cos``/``sin`` are built over pairs (i, i + Dh/2) — x1 = first half,
    x2 = second half, x' = x*cos + cat(-x2, x1)*sin — matching HF
    ``apply_rotary_pos_emb`` exactly so the transformers parity test can
    load identical weights. fp32 rotation math, cast back to x.dtype.
    """
    Dh = x.shape[-1]
    half = Dh // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) * 2.0 / Dh))
    freqs = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]  # (S, Dh/2)
    cos = jnp.cos(freqs)[None, :, None, :]  # (1, S, 1, Dh/2)
    sin = jnp.sin(freqs)[None, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    out = jnp.concatenate((x1 * cos - x2 * sin, x2 * cos + x1 * sin), axis=-1)
    return out.astype(x.dtype)


def _dropout(x: jax.Array, rate: float, key: Optional[jax.Array], deterministic: bool) -> jax.Array:
    if deterministic or rate == 0.0 or key is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros((), x.dtype)).astype(x.dtype)


def _attention(
    config: TinyGPTConfig,
    q: jax.Array,  # (B, S, H, Dh)
    k: jax.Array,
    v: jax.Array,
    dropout_key: Optional[jax.Array],
    deterministic: bool,
) -> jax.Array:
    """Dispatch to the configured attention implementation. Returns (B,S,H,Dh).

    Attention-probability dropout (reference train_harness.py:116) applies in
    ALL THREE impls: materialized bernoulli in 'reference', and the shared
    global-coordinate hash mask in 'flash' (in-kernel) and 'ring' (per
    rotating K/V block) — the probabilities still never materialize in HBM
    for the latter two, and flash/ring produce bitwise-identical masks for
    equal seeds. 'reference' draws from a different RNG stream (bernoulli),
    so with dropout > 0 its parity vs flash/ring is statistical, not
    per-step exact; set dropout=0 for exact cross-impl loss comparison.
    """
    seed = None
    if not deterministic and config.dropout > 0.0 and dropout_key is not None:
        seed = jax.random.bits(dropout_key, (), jnp.uint32)
    if config.seq_manual_axis is not None:
        # Inside a shard_map that is manual over the sequence axis (the
        # pipeline schedules): q/k/v hold LOCAL sequence chunks, so dispatch
        # straight to the sharded attention bodies, which communicate over
        # that axis. The dropout seed is deliberately NOT per-shard here —
        # ring masks are keyed by global coordinates (all ring participants
        # must agree on the seed); Ulysses folds its own shard index.
        ax = config.seq_manual_axis
        if config.attention_impl == "ring":
            from ..ops.ring_attention import ring_attention_sharded

            return ring_attention_sharded(
                q, k, v, axis_name=ax, causal=config.causal,
                dropout_rate=config.dropout if seed is not None else 0.0,
                dropout_seed=seed,
                block_q=config.flash_block_q, block_k=config.flash_block_k,
                block_k_bwd=config.flash_block_k_bwd,
                zigzag=config.ring_zigzag,
            )
        if config.attention_impl == "ulysses":
            from ..ops.ulysses_attention import ulysses_attention_sharded

            return ulysses_attention_sharded(
                q, k, v, axis_name=ax, causal=config.causal,
                dropout_rate=config.dropout if seed is not None else 0.0,
                dropout_seed=seed,
                block_q=config.flash_block_q, block_k=config.flash_block_k,
                block_k_bwd=config.flash_block_k_bwd,
                pallas_backward=config.flash_pallas_backward,
            )
        raise ValueError(
            "sequence-parallel pipeline needs attention_impl 'ring' or "
            f"'ulysses' (local '{config.attention_impl}' attention over a "
            "sequence chunk would silently compute blockwise attention)"
        )
    if config.attention_impl == "flash":
        # Pallas TPU kernel; fp32 online-softmax accumulation internally.
        from ..ops.flash_attention import flash_attention

        return flash_attention(
            q, k, v, causal=config.causal,
            block_q=config.flash_block_q, block_k=config.flash_block_k,
            block_k_bwd=config.flash_block_k_bwd,
            pallas_backward=config.flash_pallas_backward,
            dropout_rate=config.dropout if seed is not None else 0.0,
            dropout_seed=seed,
        )
    if config.attention_impl == "ring":
        from ..ops.ring_attention import ring_attention

        return ring_attention(
            q, k, v, causal=config.causal,
            dropout_rate=config.dropout if seed is not None else 0.0,
            dropout_seed=seed,
            block_q=config.flash_block_q, block_k=config.flash_block_k,
            block_k_bwd=config.flash_block_k_bwd,
            zigzag=config.ring_zigzag,
        )
    if config.attention_impl == "ulysses":
        from ..ops.ulysses_attention import ulysses_attention

        return ulysses_attention(
            q, k, v, causal=config.causal,
            dropout_rate=config.dropout if seed is not None else 0.0,
            dropout_seed=seed,
        )

    # Reference jnp implementation: softmax(QK^T/sqrt(d))V with fp32 softmax.
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if config.causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    # Parity: nn.MultiheadAttention applies dropout to attention probabilities
    # (reference train_harness.py:116).
    probs = _dropout(probs, config.dropout, dropout_key, deterministic)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(q.dtype), v, preferred_element_type=jnp.float32
    )
    return out.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _with_cotangent_spec(spec, x):
    """Identity whose COTANGENT is constrained to ``spec``.

    Wrapping a layer's weights with this inside the layer loop makes that
    layer's gradient adopt its target (ZeRO-2 sharded) placement at the
    point it is produced — inside the backward scan/loop body — so the
    reduce-scatter can overlap the next layer's backward compute instead
    of queueing in a tail bundle (see TinyGPTConfig.block_grad_spec).
    """
    return x


def _wcs_fwd(spec, x):
    return x, None


def _wcs_bwd(spec, _res, g):
    return (lax.with_sharding_constraint(g, spec),)


_with_cotangent_spec.defvjp(_wcs_fwd, _wcs_bwd)


def _apply_leaf_specs(layer: Params, spec_table: Any, wrap) -> Params:
    """Apply a (leaf name, spec) table to one layer's weight slice via
    ``wrap(spec, leaf)`` — leaves without an entry pass through untouched;
    an unset table is an exact no-op. The one iteration both per-block
    placement hooks share."""
    if not spec_table:
        return layer
    specs = dict(spec_table)
    return {
        k: (wrap(specs[k], v) if k in specs else v)
        for k, v in layer.items()
    }


def _constrain_layer_grads(config: TinyGPTConfig, layer: Params) -> Params:
    """Apply ``config.block_grad_spec`` to one layer's weight slice: the
    COTANGENT constraint (zero2 per-block grad placement)."""
    return _apply_leaf_specs(layer, config.block_grad_spec, _with_cotangent_spec)


def _constrain_layer_params(config: TinyGPTConfig, layer: Params) -> Params:
    """Apply ``config.block_param_spec`` to one layer's weight slice: a
    PRIMAL sharding constraint pinning the slice to its sharded
    (fsdp/zero3) placement at the point of use, so the all-gather the
    block's matmuls need issues inside the layer loop instead of bundling
    ahead of the stack. The constraint's transpose places the cotangent
    identically — the per-block grad layout for free."""
    return _apply_leaf_specs(
        layer, config.block_param_spec,
        lambda spec, v: lax.with_sharding_constraint(v, spec),
    )


def _constrain_layer(config: TinyGPTConfig, layer: Params) -> Params:
    """Both per-block placement hooks, primal (block_param_spec) inside the
    cotangent wrap (block_grad_spec) — strategies arm at most one today."""
    return _constrain_layer_grads(
        config, _constrain_layer_params(config, layer)
    )


def _block(
    config: TinyGPTConfig,
    x: jax.Array,  # (B, S, D) compute dtype
    layer: Params,  # one layer's slice of the stacked block params
    dropout_key: Optional[jax.Array],
    deterministic: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Pre-LN transformer block -> (x, aux) where aux is the MoE load-balance
    loss contribution (0 for dense blocks).

    Parity: reference train_harness.py:108-131 for the dense path."""
    c = config
    B, S, D = x.shape
    cd = c.compute_dtype
    keys = (
        jax.random.split(dropout_key, 2) if dropout_key is not None else (None, None)
    )
    if keys[1] is not None and c.seq_manual_axis is not None:
        # Sequence shards hold different token positions: decorrelate the
        # (materialized-mask) MLP dropout stream per shard. The attention key
        # keys[0] stays shared — ring/Ulysses handle their own coordinates.
        keys = (keys[0], jax.random.fold_in(keys[1], lax.axis_index(c.seq_manual_axis)))

    # Collective-matmul tp fusion (round 15, ops/collective_matmul.py):
    # route the four projection classes through the ppermute-ring
    # decomposition — the residual stream between them rides
    # sequence-sharded over 'model', and the activation all-gather /
    # partial-sum reduce-scatter hide inside the dots. The helpers fall
    # back to the plain einsum when no >1 'model' axis is in scope, so
    # the knob is inert on pure-dp meshes. Incompatible with the
    # pipeline schedules' manual sequence region (the stream is already
    # manual over 'seq' there) — refused loudly rather than silently
    # computing a doubly-sharded projection.
    use_cmm = c.tp_collective_matmul
    if use_cmm and c.seq_manual_axis is not None:
        raise ValueError(
            "tp_collective_matmul cannot run inside a sequence-manual "
            "pipeline region (the residual stream is already sharded "
            "over the manual 'seq' axis; drop --tp-collective-matmul "
            "for pipeline arms)"
        )
    if use_cmm:
        from ..ops import collective_matmul as _cm

    # --- attention sublayer ---
    h = _norm(c, x, layer["ln1_scale"], layer.get("ln1_bias"))
    if "wqkv" in layer:  # fused MHA projection (kv_heads == n_head)
        if use_cmm:
            qkv = _cm.ag_proj(h, layer["wqkv"].astype(cd)).astype(cd)
        else:
            qkv = jnp.einsum(
                "bsd,dce->bsce", h, layer["wqkv"].astype(cd), preferred_element_type=jnp.float32
            ).astype(cd)
        if "bqkv" in layer:
            qkv = qkv + layer["bqkv"].astype(cd)
        to_heads = lambda t: t.reshape(B, S, c.n_head, c.head_dim)
        q, k, v = (to_heads(qkv[:, :, i]) for i in range(3))
    else:  # GQA: separate q and stacked k/v projections
        if use_cmm:
            q = _cm.ag_proj(h, layer["wq"].astype(cd)).astype(cd)
            # kv rides the kv-head-aligned rule (aligned_units): with a
            # misaligned 'model' degree the weight enters replicated and
            # the ring produces replicated full-kv outputs.
            kv = _cm.ag_proj(
                h, layer["wkv"].astype(cd), aligned_units=c.kv_heads
            ).astype(cd)
        else:
            q = jnp.einsum(
                "bsd,de->bse", h, layer["wq"].astype(cd), preferred_element_type=jnp.float32
            ).astype(cd)
            kv = jnp.einsum(
                "bsd,dce->bsce", h, layer["wkv"].astype(cd), preferred_element_type=jnp.float32
            ).astype(cd)
        if "bq" in layer:
            q = q + layer["bq"].astype(cd)
            kv = kv + layer["bkv"].astype(cd)
        q = q.reshape(B, S, c.n_head, c.head_dim)
        k = kv[:, :, 0].reshape(B, S, c.kv_heads, c.head_dim)
        v = kv[:, :, 1].reshape(B, S, c.kv_heads, c.head_dim)
    if c.pos_embed == "rope":
        # Global token positions; under a sequence-manual pipeline this
        # shard holds positions [shard*S, shard*S + S) (same offset rule as
        # the learned table's dynamic slice in embed()). The zigzag ring
        # redistribution happens INSIDE ring_attention, after rotation, so
        # the rotated rows travel with their tokens.
        pos = jnp.arange(S, dtype=jnp.int32)
        if c.seq_manual_axis is not None:
            pos = pos + S * lax.axis_index(c.seq_manual_axis)
        q = _rope(q, pos, c.rope_theta)
        k = _rope(k, pos, c.rope_theta)
    if c.kv_heads != c.n_head:
        # Broadcast each K/V head to its query group. Consecutive-block
        # repetition matches the TP layout: query-head shard j needs exactly
        # kv-head shard j when the 'model' degree divides kv_heads; when it
        # does not, the kv-head-aligned spec rule keeps wkv replicated over
        # 'model' (strategies.param_partition_specs) so this reshape never
        # needs the partitioner's full-replicate resharding fallback.
        rep = c.n_head // c.kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    attn = _attention(c, q, k, v, keys[0], deterministic)
    attn = attn.reshape(B, S, D)
    if use_cmm:
        attn = _cm.rs_proj(attn, layer["wo"].astype(cd)).astype(cd)
    else:
        attn = jnp.einsum(
            "bsd,de->bse", attn, layer["wo"].astype(cd), preferred_element_type=jnp.float32
        ).astype(cd)
    if "bo" in layer:
        attn = attn + layer["bo"].astype(cd)
    x = x + attn

    # --- MLP sublayer: dense D -> mlp_dim -> GELU(exact) -> D -> dropout,
    #     SwiGLU (silu(gate)*up -> down), or the routed expert layer ---
    h = _norm(c, x, layer["ln2_scale"], layer.get("ln2_bias"))
    if c.n_experts > 0:
        from .moe import moe_mlp

        h, aux = moe_mlp(c, layer, h, keys[1], deterministic)
        return x + h, aux
    if c.mlp_act == "swiglu":
        if use_cmm:
            gu = _cm.ag_proj(h, layer["wgu"].astype(cd)).astype(cd)
        else:
            gu = jnp.einsum(
                "bsd,dcf->bscf", h, layer["wgu"].astype(cd), preferred_element_type=jnp.float32
            ).astype(cd)
        if "bgu" in layer:
            gu = gu + layer["bgu"].astype(cd)
        h = jax.nn.silu(gu[:, :, 0]) * gu[:, :, 1]
    else:
        if use_cmm:
            h = _cm.ag_proj(h, layer["wfc"].astype(cd)).astype(cd)
        else:
            h = jnp.einsum(
                "bsd,df->bsf", h, layer["wfc"].astype(cd), preferred_element_type=jnp.float32
            ).astype(cd)
        if "bfc" in layer:
            h = h + layer["bfc"].astype(cd)
        h = jax.nn.gelu(h, approximate=False)  # torch nn.GELU default is exact erf
    if use_cmm:
        h = _cm.rs_proj(h, layer["wproj"].astype(cd)).astype(cd)
    else:
        h = jnp.einsum(
            "bsf,fd->bsd", h, layer["wproj"].astype(cd), preferred_element_type=jnp.float32
        ).astype(cd)
    if "bproj" in layer:
        h = h + layer["bproj"].astype(cd)
    h = _dropout(h, c.dropout, keys[1], deterministic)
    return x + h, jnp.zeros((), jnp.float32)


def embed(
    config: TinyGPTConfig,
    params: Params,
    idx: jax.Array,  # (B, S) int32
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> jax.Array:
    """Token + positional embedding -> dropout -> (B, S, D) compute dtype.

    Under a sequence-manual pipeline (``config.seq_manual_axis``), ``idx`` is
    this shard's chunk of the sequence: the positional table is sliced at the
    shard's global offset and the embedding-dropout stream is decorrelated
    per shard.
    """
    c = config
    S = idx.shape[1]
    tok = jnp.take(params["wte"], idx, axis=0)
    if c.seq_manual_axis is not None:
        shard = lax.axis_index(c.seq_manual_axis)
        if dropout_key is not None:
            dropout_key = jax.random.fold_in(dropout_key, shard)
    if c.pos_embed == "rope":
        # Rotary positions are applied to q/k inside each block (_rope in
        # _block); the residual stream carries no additive position signal.
        x = tok.astype(c.compute_dtype)
    else:
        if c.seq_manual_axis is not None:
            pos = lax.dynamic_slice_in_dim(params["wpe"], shard * S, S, axis=0)
        else:
            pos = params["wpe"][:S]
        x = (tok + pos[None, :, :]).astype(c.compute_dtype)
    if dropout_key is not None and not deterministic:
        x = _dropout(x, c.dropout, dropout_key, deterministic)
    return x


def apply_blocks(
    config: TinyGPTConfig,
    blocks: Params,  # stacked block params, leading 'layers' axis (may be a slice)
    x: jax.Array,  # (B, S, D) compute dtype
    base_key: Optional[jax.Array] = None,
    deterministic: bool = True,
    layer_offset: int = 0,
) -> jax.Array:
    """Scan the given stacked blocks over x.

    ``layer_offset`` keeps per-layer dropout keys globally consistent when the
    stack is a pipeline stage's slice: layer i's key is fold_in(base_key,
    layer_offset + i) regardless of which stage runs it.

    Returns (x, aux_sum): aux_sum accumulates MoE load-balance contributions
    over the scanned layers (0 for dense models).
    """
    c = config
    block = functools.partial(_block, c, deterministic=deterministic)
    pol = normalize_remat(c.remat)
    if pol == "full":
        block = jax.checkpoint(block)
    elif pol == "dots":
        # Save matmul (dot_general without dot-batch dims, i.e. x @ W)
        # outputs; recompute only LN/GELU/softmax/dropout in backward —
        # removes most of full remat's recompute tax while still dropping
        # the elementwise intermediates from liveness.
        block = jax.checkpoint(
            block,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )

    # Inside a partially-manual shard_map (the pipeline), x is varying over
    # the manual axes; the scalar aux carry must match that type or the scan
    # rejects the carry (invariant in, varying out after the first MoE add).
    def _aux0():
        from ..utils.vma import pcast_like

        return pcast_like(jnp.zeros((), jnp.float32), x)

    if not c.scan_layers:
        n_local = jax.tree_util.tree_leaves(blocks)[0].shape[0]
        aux = _aux0()
        live = base_key is not None and not deterministic
        for i in range(n_local):
            layer = jax.tree_util.tree_map(lambda t: t[i], blocks)
            ki = (
                jax.random.fold_in(base_key, layer_offset + i) if live else None
            )
            x, a = block(x, _constrain_layer(c, layer), ki)
            aux = aux + a
        return x, aux

    def _pin_carry(x):
        # Scan-carry placement (round 15): pinning the residual stream at
        # the body boundary pins the backward's stacked activation-stash
        # layout with it — without this XLA picks a stash layout of its own
        # and reconciles per iteration with collective-permute chains (the
        # banked llama-fsdp-dp4-tp2-scan reshard residue).
        if c.scan_carry_spec is None:
            return x
        return lax.with_sharding_constraint(x, c.scan_carry_spec)

    if base_key is None or deterministic:
        def scan_body(carry, layer):
            x, aux = carry
            x, a = block(_pin_carry(x), _constrain_layer(c, layer), None)
            return (x, aux + a), None

        (x, aux), _ = lax.scan(scan_body, (x, _aux0()), blocks)
    else:
        n_local = jax.tree_util.tree_leaves(blocks)[0].shape[0]
        idxs = jnp.arange(n_local) + layer_offset

        def scan_body(carry, li):
            x, aux = carry
            x, a = block(
                _pin_carry(x), _constrain_layer(c, li[0]),
                jax.random.fold_in(base_key, li[1]),
            )
            return (x, aux + a), None

        (x, aux), _ = lax.scan(
            scan_body, (x, _aux0()), (blocks, idxs)
        )
    return x, aux


def embed_param_names(config: TinyGPTConfig) -> Tuple[str, ...]:
    """Top-level leaves embed() reads — the pipeline schedules replicate
    exactly these across stages (wpe only exists for learned positions)."""
    return ("wte", "wpe") if config.pos_embed == "learned" else ("wte",)


def head_param_names(config: TinyGPTConfig) -> Tuple[str, ...]:
    """Top-level leaves head() reads (lnf_bias only for layernorm; the head
    matrix is wte when tied, lm_head when untied)."""
    names = ["lnf_scale"]
    if config.norm == "layernorm":
        names.append("lnf_bias")
    names.append("wte" if config.tie_embeddings else "lm_head")
    return tuple(names)


def head(config: TinyGPTConfig, params: Params, x: jax.Array) -> jax.Array:
    """Final norm + LM head -> fp32 logits (B, S, V).

    The head matrix is ``wte`` when weight-tied (reference
    train_harness.py:61-62) or the separate ``lm_head`` leaf when untied
    (the Llama family) — same (V, D) layout and vocab-sharding either way.
    """
    x = _norm(config, x, params["lnf_scale"], params.get("lnf_bias"))
    w = params["wte"] if config.tie_embeddings else params["lm_head"]
    return jnp.einsum(
        "bsd,vd->bsv",
        x,
        w.astype(config.compute_dtype),
        preferred_element_type=jnp.float32,
    )


def forward(
    config: TinyGPTConfig,
    params: Params,
    idx: jax.Array,  # (B, S) int32 token ids
    targets: Optional[jax.Array] = None,  # (B, S) int32, -1 = ignore
    *,
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Forward pass -> (logits fp32 (B,S,V), loss fp32 scalar or None).

    Structure parity: reference ``TinyGPT.forward`` (train_harness.py:80-105):
    tok_emb + pos_emb -> dropout -> blocks -> ln_f -> tied lm_head ->
    cross-entropy(ignore_index=-1). The layer loop is a ``lax.scan`` over
    stacked weights (single compiled block body; optional per-layer remat);
    the embed/apply_blocks/head pieces are reused by the pipeline-parallel
    schedule (parallel.pipeline), which runs them stage-by-stage.
    """
    c = config
    B, S = idx.shape
    if S > c.block_size:
        raise ValueError(f"Sequence {S} exceeds block size {c.block_size}")

    if dropout_key is not None and not deterministic:
        emb_key, scan_key = jax.random.split(dropout_key)
    else:
        emb_key = scan_key = None

    x = embed(c, params, idx, emb_key, deterministic)
    x, aux = apply_blocks(c, params["blocks"], x, scan_key, deterministic)
    logits = head(c, params, x)

    loss = None
    if targets is not None:
        loss = _cross_entropy(logits, targets)
        if c.n_experts > 0:
            # Mean aux per layer, Switch-style coefficient.
            loss = loss + c.router_aux_coef * aux / c.n_layer
    return logits, loss


def moe_overflow_fraction(
    config: TinyGPTConfig, params: Params, idx: jax.Array
) -> jax.Array:
    """Diagnostic: mean fraction of (token, choice) expert assignments
    dropped by the capacity limit, averaged over layers, on one batch.

    Powers the published MoE row's ``expert_overflow_pct`` (the analogue
    of DeepSpeed's dropped-token logging; the reference has no MoE at
    all). Runs a dropout-free forward with the aux channel switched to
    overflow accounting (``moe_aux_mode='overflow'``) — zero impact on the
    training step itself.
    """
    import dataclasses

    c = dataclasses.replace(config, moe_aux_mode="overflow", dropout=0.0)
    x = embed(c, params, idx, None, True)
    _, aux = apply_blocks(c, params["blocks"], x, None, True)
    return aux / c.n_layer


def _cross_entropy_parts(
    logits: jax.Array, targets: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """(nll_sum, valid_count) over positions where target != -1 — the
    unreduced halves of the mean CE, so sequence-parallel callers can psum
    both across shards before dividing."""
    V = logits.shape[-1]
    logits = logits.reshape(-1, V).astype(jnp.float32)
    targets = targets.reshape(-1)
    valid = targets != -1
    safe = jnp.where(valid, targets, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
    nll = jnp.where(valid, logz - gold, 0.0)
    return nll.sum(), valid.sum()


def _cross_entropy(
    logits: jax.Array, targets: jax.Array, seq_axis: Optional[str] = None
) -> jax.Array:
    """Mean CE over positions where target != -1 (parity: ignore_index=-1,
    reference train_harness.py:98-103). ``seq_axis`` names a manual mesh axis
    the positions are sharded over (the sequence-parallel pipeline): sums and
    counts combine across shards before the divide."""
    nll_sum, count = _cross_entropy_parts(logits, targets)
    if seq_axis is not None:
        nll_sum = lax.psum(nll_sum, seq_axis)
        count = lax.psum(count, seq_axis)
    return nll_sum / jnp.maximum(count, 1)


def loss_fn(
    config: TinyGPTConfig,
    params: Params,
    batch: jax.Array,
    targets: jax.Array,
    dropout_key: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> jax.Array:
    """Scalar training loss (the differentiated function in the train step)."""
    _, loss = forward(
        config, params, batch, targets, dropout_key=dropout_key, deterministic=deterministic
    )
    return loss
