#!/usr/bin/env python
"""Step anatomy: trace-derived compute / comms / idle attribution.

``profile_summary`` answers "which ops burned the time"; this engine answers
the question the plateau-attack directions (ROADMAP direction 2) actually
need: **how much of each device step is compute, how much is collective time
— split into the fraction exposed on the critical path vs. overlapped under
compute — and how much is idle/host gap**, plus where the arm sits on the
roofline (achieved vs. peak FLOP/s and HBM GB/s, peaks from
``utils/platform.py``). Exposed-communication fraction and overlap are the
decisive levers at scale ("Exploring the limits of Concurrency in ML
Training on Google TPUs"; "Scalable Training of Language Models using JAX
pjit and TPUv4" — PAPERS.md), and until they are measured, first-class
metrics, every overlap/reshard PR is flying blind.

Inputs, all already captured by the harness:

- the Chrome-trace export under ``--profile-dir`` (the ``jax.profiler``
  bracket around the timed window in ``train/loop.py``);
- ``cost_analysis.json`` beside the trace — FLOPs / bytes accessed of the
  jitted step, written by the loop from ``compiled.cost_analysis()``
  (available even on the CPU dryrun) — powers the roofline row;
- the run's flight-recorder JSONL (``--telemetry``, auto-discovered when a
  ``telemetry_*.jsonl`` sits inside the profile dir): its ``timed``
  phase-wall intervals clip the analysis to the timed region, and its
  ``run_meta`` names the pipeline schedule for the bubble-fraction row.

Decomposition per traced device step (interval arithmetic over the XLA Ops
lane, clipped to the step's bounds):

- ``compute``   = union length of non-collective op intervals;
- ``exposed``   = collective-op union length NOT covered by compute;
- ``overlapped``= collective ∩ compute length (hidden under compute);
- ``idle``      = step length − union(all ops) (device gaps: host dispatch,
  pipeline bubbles, stragglers).

``compute + exposed + idle == step`` exactly (overlapped is accounted
inside compute), so the fractions are additive. ``overlap_frac`` =
overlapped / total collective time. Per-rank sibling traces
(``*.rank<r>.trace.json.gz``, or several device pids inside one trace)
join into a straggler-skew column. For pipeline arms the device-idle
fraction inside the step IS the schedule's bubble, published per schedule.

CPU-dryrun caveats: the CPU backend's trace has no meaningful device-op
lanes (and no known peaks), so the engine is exercised hermetically by the
frozen fixtures under ``tests/fixtures/trace_frozen*/``; on hardware every
number is measured. ``cost_analysis()`` FLOPs count the GLOBAL module under
GSPMD — per-chip values divide by ``world_size`` (recorded in the cost
JSON).

    python -m distributed_llm_training_benchmark_framework_tpu.analysis.step_anatomy \
        --profile-dir /tmp/prof [--run NAME] [--telemetry telemetry_<arm>.jsonl] \
        [--cost-json cost_analysis.json] [--pipeline-schedule gpipe] [--json]
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import profile_summary as ps

COST_JSON_FILENAME = "cost_analysis.json"

#: Absolute slack (fraction of step time) the measured bubble may exceed
#: the schedule's structural bound before the anatomy/structure mismatch
#: finding fires: trace idle includes host dispatch gaps the schedule
#: grid does not model, so a tight-to-the-bound run is healthy.
BUBBLE_BOUND_SLACK = 0.10

#: XLA collective-op name patterns. Substring match on the op/base name for
#: the unambiguous collective families; ``send``/``recv`` (pipeline
#: transfers) only as a leading token so e.g. a custom-call mentioning
#: "sender" cannot misclassify.
_COLLECTIVE_RE = re.compile(
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute"
    r"|collective-broadcast|ppermute)",
    re.IGNORECASE,
)
_SENDRECV_RE = re.compile(r"^(send|recv)([-.\d]|$)", re.IGNORECASE)

#: Rank-sibling trace naming, mirroring the telemetry rank-file contract
#: (telemetry_<arm>.rank<r>.jsonl): <stem>.rank<r>.trace.json.gz.
_RANK_TRACE_RE = re.compile(r"\.rank(\d+)\.trace\.json\.gz$")


def is_collective_op(name: str) -> bool:
    return bool(_COLLECTIVE_RE.search(name) or _SENDRECV_RE.match(name))


# ---------------------------------------------------------------------------
# Interval arithmetic (all times in trace microseconds)
# ---------------------------------------------------------------------------


def merge_intervals(ivs: Sequence[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Union of (start, end) intervals as a sorted disjoint list."""
    out: List[Tuple[float, float]] = []
    for a, b in sorted(iv for iv in ivs if iv[1] > iv[0]):
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def intervals_length(ivs: Sequence[Tuple[float, float]]) -> float:
    return sum(b - a for a, b in ivs)


def intersect_intervals(
    a: Sequence[Tuple[float, float]], b: Sequence[Tuple[float, float]]
) -> List[Tuple[float, float]]:
    """Intersection of two DISJOINT-SORTED interval lists (two-pointer)."""
    out: List[Tuple[float, float]] = []
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            out.append((lo, hi))
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


def clip_intervals(
    ivs: Sequence[Tuple[float, float]], lo: float, hi: float
) -> List[Tuple[float, float]]:
    return [(max(a, lo), min(b, hi)) for a, b in ivs if min(b, hi) > max(a, lo)]


# ---------------------------------------------------------------------------
# Trace extraction
# ---------------------------------------------------------------------------


def device_timelines(events: List[dict]) -> Dict[int, Dict[str, Any]]:
    """{device pid: {"device", "ops": [(name, t0, t1)], "steps": [...]}}.

    Only ``/device:*`` processes count; the host lanes (python, plugin
    threads) never enter the attribution.
    """
    pids, tids = ps._lane_names(events)
    out: Dict[int, Dict[str, Any]] = {}
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        pid = e.get("pid")
        pname = pids.get(pid, "")
        if not pname.startswith("/device:"):
            continue
        lane = tids.get((pid, e.get("tid")), "")
        dev = out.setdefault(pid, {"device": pname, "ops": [], "steps": []})
        t0 = float(e["ts"])
        t1 = t0 + float(e["dur"])
        if lane == "XLA Ops":
            dev["ops"].append((e["name"], t0, t1))
        elif lane == "Steps":
            dev["steps"].append((e["name"], t0, t1))
    return out


def per_step_op_classes(events: List[dict]) -> List[Dict[str, Any]]:
    """Per traced step: op-class self-time breakdown (first device lane).

    The anomaly↔trace join (``telemetry_report``) compares a spiked step's
    class times against the median step's to name the class that grew.
    """
    devs = device_timelines(events)
    if not devs:
        return []
    dev = devs[sorted(devs)[0]]
    out: List[Dict[str, Any]] = []
    for name, t0, t1 in sorted(dev["steps"], key=lambda s: s[1]):
        classes: collections.Counter = collections.Counter()
        for op, a, b in dev["ops"]:
            lo, hi = max(a, t0), min(b, t1)
            if hi > lo:
                classes[ps.op_class(op)] += hi - lo
        out.append({"step": name, "t0": t0, "t1": t1, "classes": classes})
    return out


# ---------------------------------------------------------------------------
# Per-step decomposition
# ---------------------------------------------------------------------------


def analyze_steps(
    ops: Sequence[Tuple[str, float, float]],
    steps: Sequence[Tuple[str, float, float]],
    clip_wall_us: Optional[Sequence[Tuple[float, float]]] = None,
) -> List[Dict[str, Any]]:
    """Decompose each traced step into compute/exposed/overlapped/idle (us).

    ``clip_wall_us`` (the telemetry timed-phase wall intervals, in trace
    microseconds) drops steps whose midpoint falls outside the timed region
    — compile/warmup steps must not dilute the attribution.
    """
    out: List[Dict[str, Any]] = []
    for name, t0, t1 in sorted(steps, key=lambda s: s[1]):
        if clip_wall_us:
            mid = (t0 + t1) / 2.0
            if not any(lo <= mid <= hi for lo, hi in clip_wall_us):
                continue
        comp_iv: List[Tuple[float, float]] = []
        coll_iv: List[Tuple[float, float]] = []
        coll_by_class: collections.Counter = collections.Counter()
        class_iv: Dict[str, List[Tuple[float, float]]] = {}
        for op, a, b in ops:
            lo, hi = max(a, t0), min(b, t1)
            if hi <= lo:
                continue
            if is_collective_op(op):
                coll_iv.append((lo, hi))
                cls = ps.op_class(op)
                coll_by_class[cls] += hi - lo
                class_iv.setdefault(cls, []).append((lo, hi))
            else:
                comp_iv.append((lo, hi))
        comp_u = merge_intervals(comp_iv)
        coll_u = merge_intervals(coll_iv)
        busy = merge_intervals(list(comp_u) + list(coll_u))
        compute = intervals_length(comp_u)
        coll_total = intervals_length(coll_u)
        overlapped = intervals_length(intersect_intervals(coll_u, comp_u))
        exposed = coll_total - overlapped
        # Per-class EXPOSED time: the class's own interval union minus the
        # part hidden under compute — this is what names WHICH collective
        # to overlap first (round-8 satellite). Classes are exposed
        # independently, so two different-class collectives overlapping
        # each other (and not compute) each count their shared time: the
        # per-class sum may slightly exceed `exposed`, which is the
        # union-accurate total.
        exposed_by_class: collections.Counter = collections.Counter()
        for cls, ivs in class_iv.items():
            u = merge_intervals(ivs)
            exp_c = (intervals_length(u)
                     - intervals_length(intersect_intervals(u, comp_u)))
            if exp_c > 0:
                exposed_by_class[cls] = exp_c
        dur = t1 - t0
        idle = max(dur - intervals_length(busy), 0.0)
        out.append({
            "step": name,
            "dur_us": dur,
            "compute_us": compute,
            "exposed_us": exposed,
            "overlapped_us": overlapped,
            "idle_us": idle,
            "coll_by_class": coll_by_class,
            "exposed_by_class": exposed_by_class,
        })
    return out


def _median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2] if s else 0.0


# ---------------------------------------------------------------------------
# Telemetry join (timed-region clip + run meta)
# ---------------------------------------------------------------------------


def timed_wall_intervals_us(
    events: Sequence[Dict[str, Any]],
) -> List[Tuple[float, float]]:
    """Wall-clock (ts) intervals of the ``timed`` phase, in microseconds.

    The recorder's phase events carry unix ``ts``; the jax Chrome-trace
    export stamps ``ts`` in microseconds on the same epoch, so the two
    clocks join directly. A phase left open by a crash closes at the last
    event's ts.
    """
    out: List[Tuple[float, float]] = []
    open_t: Optional[float] = None
    last_ts = 0.0
    for e in events:
        ts = float(e.get("ts", 0.0) or 0.0)
        last_ts = max(last_ts, ts)
        if e.get("event") == "phase_begin" and e.get("phase") == "timed":
            open_t = ts
        elif e.get("event") == "phase_end" and e.get("phase") == "timed":
            if open_t is not None:
                out.append((open_t * 1e6, ts * 1e6))
                open_t = None
    if open_t is not None:
        out.append((open_t * 1e6, last_ts * 1e6))
    return out


def telemetry_run_meta(events: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    return next((e for e in events if e.get("event") == "run_meta"), {})


# ---------------------------------------------------------------------------
# Discovery (rank-sibling aware) + cost JSON
# ---------------------------------------------------------------------------


def discover_traces(
    profile_dir: str, run: Optional[str] = None
) -> Dict[int, str]:
    """{rank: trace path} under a profile dir.

    Standard ``plugins/profile/<run>/`` layouts and bare traces both count;
    ``*.rank<r>.trace.json.gz`` siblings (one per non-zero rank, mirroring
    the telemetry rank-file convention) key by their rank, everything else
    is rank 0 (newest wins). ``run`` filters every candidate — rank
    siblings included, so a multi-run dir cannot mix another run's rank
    traces into the skew — by run-dir or file name, and a filter that
    matches NOTHING raises (like ``profile_summary --run``) instead of
    silently analyzing the wrong run.
    """
    cands = sorted(glob.glob(os.path.join(
        profile_dir, "plugins", "profile", "*", "*.trace.json.gz"
    ))) + sorted(glob.glob(os.path.join(profile_dir, "*.trace.json.gz")))
    if run is not None and cands:
        sel = [
            f for f in cands
            if run in os.path.basename(os.path.dirname(f))
            or run in os.path.basename(f)
        ]
        if not sel:
            raise ValueError(
                f"--run {run!r} matches none of the "
                f"{len(cands)} trace(s) under {profile_dir}: "
                + ", ".join(os.path.basename(f) for f in cands[:8])
            )
        cands = sel
    ranks: Dict[int, str] = {}
    plain: List[str] = []
    for f in cands:
        m = _RANK_TRACE_RE.search(f)
        if m:
            ranks.setdefault(int(m.group(1)), f)
        else:
            plain.append(f)
    out: Dict[int, str] = {}
    if plain:
        out[0] = max(plain, key=os.path.getmtime)
    out.update(ranks)
    return out


def cost_from_compiled(
    compiled, *, device_kind: str = "", world_size: int = 1
) -> Optional[Dict[str, Any]]:
    """FLOPs / bytes-accessed of a ``jax.stages.Compiled`` step.

    ``cost_analysis()`` returns a dict on current jax (a one-element list
    of dicts on older versions); under GSPMD the counts cover the global
    module, so consumers divide by ``world_size`` for per-chip numbers —
    both facts recorded in the payload. Returns None when the runtime
    exposes no cost analysis.
    """
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = float(ca.get("flops", 0.0) or 0.0)
    byts = float(
        ca.get("bytes accessed", ca.get("bytes_accessed", 0.0)) or 0.0
    )
    if flops <= 0 and byts <= 0:
        return None
    return {
        "flops": flops,
        "bytes_accessed": byts,
        "device_kind": device_kind,
        "world_size": int(world_size),
        "scope": "global_module",
    }


def write_cost_json(profile_dir: str, cost: Dict[str, Any]) -> Optional[str]:
    """Drop ``cost_analysis.json`` beside the trace (best-effort)."""
    try:
        path = os.path.join(profile_dir, COST_JSON_FILENAME)
        with open(path, "w") as f:
            json.dump(cost, f, indent=2, sort_keys=True)
            f.write("\n")
        return path
    except OSError:
        return None


def load_cost_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        cost = json.load(open(path))
    except (OSError, json.JSONDecodeError):
        return None
    return cost if isinstance(cost, dict) else None


# ---------------------------------------------------------------------------
# The full analysis
# ---------------------------------------------------------------------------


def analyze_profile_dir(
    profile_dir: str,
    *,
    run: Optional[str] = None,
    telemetry_path: Optional[str] = None,
    cost: Optional[Dict[str, Any]] = None,
    pipeline_schedule: Optional[str] = None,
) -> Dict[str, Any]:
    """Trace(s) + optional telemetry/cost -> the step-anatomy report dict.

    Raises ValueError when no trace exists. Auto-discovers
    ``cost_analysis.json`` and a single ``telemetry_*.jsonl`` inside the
    profile dir when not given explicitly.
    """
    traces = discover_traces(profile_dir, run=run)
    if not traces:
        raise ValueError(
            f"no *.trace.json.gz under {profile_dir} (did the run include "
            "--profile-dir and >= warmup steps?)"
        )
    if cost is None:
        cost = load_cost_json(os.path.join(profile_dir, COST_JSON_FILENAME))
    if telemetry_path is None:
        tcands = sorted(glob.glob(
            os.path.join(profile_dir, "telemetry_*.jsonl")
        ))
        if len(tcands) == 1:
            telemetry_path = tcands[0]

    clip: Optional[List[Tuple[float, float]]] = None
    meta: Dict[str, Any] = {}
    if telemetry_path and os.path.exists(telemetry_path):
        from ..telemetry import read_events

        try:
            tev = read_events(telemetry_path)
        except (OSError, ValueError):
            tev = []
        meta = telemetry_run_meta(tev)
        clip = timed_wall_intervals_us(tev) or None

    if pipeline_schedule is None:
        if int(meta.get("pipeline_parallel", 1) or 1) > 1:
            pipeline_schedule = meta.get("pipeline_schedule") or "gpipe"

    per_rank: Dict[int, Dict[str, Any]] = {}
    clipped = False
    clip_fallback_lanes = 0
    for rank, trace in sorted(traces.items()):
        events = ps.load_events(trace)
        devs = device_timelines(events)
        rank_steps: List[Dict[str, Any]] = []
        dev_medians: List[float] = []
        for pid in sorted(devs):
            dev = devs[pid]
            steps = analyze_steps(dev["ops"], dev["steps"], clip)
            if clip and not steps:
                # Clock bases disagree (some exports stamp ts relative to
                # trace start): clipping would silently drop everything —
                # fall back to the full trace and say so.
                steps = analyze_steps(dev["ops"], dev["steps"], None)
                if steps:
                    clip_fallback_lanes += 1
            elif clip and steps:
                clipped = True
            if steps:
                dev_medians.append(_median([s["dur_us"] for s in steps]))
            rank_steps.extend(steps)
        per_rank[rank] = {
            "trace": trace,
            "n_devices": len(devs),
            "steps": rank_steps,
            "device_median_step_us": dev_medians,
        }

    all_steps = [s for r in per_rank.values() for s in r["steps"]]
    if not all_steps:
        raise ValueError(
            f"trace(s) under {profile_dir} carry no device step lane "
            "(no 'Steps' thread on a /device: process)"
        )
    totals = {
        k: sum(s[k] for s in all_steps)
        for k in ("dur_us", "compute_us", "exposed_us", "overlapped_us",
                  "idle_us")
    }
    coll_total = totals["exposed_us"] + totals["overlapped_us"]
    dur = totals["dur_us"] or 1.0
    coll_classes: collections.Counter = collections.Counter()
    exposed_classes: collections.Counter = collections.Counter()
    for s in all_steps:
        coll_classes.update(s["coll_by_class"])
        exposed_classes.update(s.get("exposed_by_class", {}))
    n_steps = len(all_steps)
    median_step_us = _median([s["dur_us"] for s in all_steps])

    # Straggler skew across rank/device step medians: how far the slowest
    # lane's median step sits above the fastest's.
    medians = [m for r in per_rank.values()
               for m in r["device_median_step_us"]]
    skew_pct = (
        100.0 * (max(medians) - min(medians)) / min(medians)
        if len(medians) > 1 and min(medians) > 0 else None
    )
    if clipped and clip_fallback_lanes:
        # Mixing clipped lanes with full-trace fallbacks (warmup/compile
        # steps included) would mint a phantom straggler.
        skew_pct = None

    agg: Dict[str, Any] = {
        "n_steps": n_steps,
        "n_ranks": len(per_rank),
        "n_devices": sum(r["n_devices"] for r in per_rank.values()),
        "clipped_to_timed": clipped,
        # Lanes whose clock base disagreed with the telemetry epoch and
        # fell back to the full (unclipped) trace. Non-zero alongside
        # clipped_to_timed means the sample mixes clipped and unclipped
        # lanes — straggler skew is then unreliable.
        "clip_fallback_lanes": clip_fallback_lanes,
        "median_step_us": median_step_us,
        "mean_step_us": dur / n_steps,
        "compute_frac": totals["compute_us"] / dur,
        "comms_exposed_frac": totals["exposed_us"] / dur,
        "comms_overlapped_frac_of_step": totals["overlapped_us"] / dur,
        "idle_frac": totals["idle_us"] / dur,
        # Overlap fraction OF COLLECTIVE TIME: the direction-2b lever.
        "comms_overlap_frac": (
            totals["overlapped_us"] / coll_total if coll_total > 0 else None
        ),
        "straggler_skew_pct": skew_pct,
        "top_collectives": coll_classes.most_common(6),
        # Exposed time split by collective class (all-gather /
        # reduce-scatter / all-reduce / collective-permute / ...), most
        # exposed first — the table that names which collective the next
        # overlap PR should chase. Per-class values are independent
        # unions minus compute cover, so their sum can slightly exceed
        # exposed_us when different-class collectives co-expose.
        "comms_exposed_by_class": exposed_classes.most_common(6),
        "pipeline_schedule": pipeline_schedule,
        # Device idle inside the step IS the pipeline bubble when the arm
        # runs a schedule; None for non-pipeline arms.
        "bubble_frac": (
            totals["idle_us"] / dur if pipeline_schedule else None
        ),
    }

    # Schedule-auditor cross-check: the measured bubble must not exceed
    # the schedule's STRUCTURAL bound (the graftcheck closed forms /
    # scheduler tables — analysis.static.hlo_audit.pipeline_bubble_bound)
    # plus measurement slack. Exceeding it is not noise: the executed
    # overlap does not match the schedule's structure, which is exactly
    # the regression an unaudited schedule would hide. Only computed when
    # the run's telemetry carries the (S, M, V) inputs; old traces
    # without them keep bubble_frac un-verdicted.
    agg["bubble_frac_bound"] = None
    agg["bubble_structure_mismatch"] = False
    if agg["bubble_frac"] is not None:
        s_stages = int(meta.get("pipeline_parallel", 0) or 0)
        m_micro = int(meta.get("grad_accum", 0) or 0)
        v_chunks = int(meta.get("virtual_stages", 1) or 1)
        if (
            agg["pipeline_schedule"] == "interleaved"
            and "virtual_stages" not in meta
        ):
            # Interleaved bounds NEED the real V (interleaving shrinks
            # the bubble, so a defaulted V=1 bound would be silently
            # loose); pre-schedule-auditor traces never recorded it —
            # leave those un-verdicted rather than mis-bounded.
            s_stages = 0
        if s_stages > 1 and m_micro > 0:
            from .static.hlo_audit import pipeline_bubble_bound

            try:
                bound = pipeline_bubble_bound(
                    agg["pipeline_schedule"], s_stages, m_micro, v_chunks
                )
            except ValueError:
                bound = None
            if bound is not None:
                agg["bubble_frac_bound"] = round(bound, 6)
                agg["bubble_structure_mismatch"] = bool(
                    agg["bubble_frac"] > bound + BUBBLE_BOUND_SLACK
                )

    roofline: Optional[Dict[str, Any]] = None
    if cost and agg["median_step_us"] > 0:
        from ..utils import platform as platform_mod

        ws = max(int(cost.get("world_size", 1) or 1), 1)
        step_sec = agg["median_step_us"] * 1e-6
        flops_chip = float(cost.get("flops", 0.0) or 0.0) / ws
        bytes_chip = float(cost.get("bytes_accessed", 0.0) or 0.0) / ws
        kind = cost.get("device_kind", "") or ""
        peak_flops = platform_mod.device_peak_flops(kind)
        peak_bw = platform_mod.device_peak_hbm_gbps(kind)
        roofline = {
            "device_kind": kind,
            "achieved_tflops_per_sec": (
                flops_chip / step_sec / 1e12 if flops_chip > 0 else None
            ),
            "achieved_hbm_gbps": (
                bytes_chip / step_sec / 1e9 if bytes_chip > 0 else None
            ),
            "peak_tflops_per_sec": (
                peak_flops / 1e12 if peak_flops else None
            ),
            "peak_hbm_gbps": peak_bw,
            "flops_pct_of_peak": (
                100.0 * flops_chip / step_sec / peak_flops
                if peak_flops and flops_chip > 0 else None
            ),
            "hbm_pct_of_peak": (
                100.0 * (bytes_chip / step_sec / 1e9) / peak_bw
                if peak_bw and bytes_chip > 0 else None
            ),
        }

    return {
        "profile_dir": profile_dir,
        "trace": per_rank[sorted(per_rank)[0]]["trace"],
        "per_rank": per_rank,
        "agg": agg,
        "roofline": roofline,
        "arm": meta.get("arm"),
    }


def exposed_by_class_fracs(report: Dict[str, Any]) -> Dict[str, float]:
    """{collective class: exposed fraction OF THE STEP}, rounded.

    The per-class payload the telemetry ``step_anatomy`` event carries
    (train/loop.py) beside the scalar result fields — NOT a
    BenchmarkResult field (``compute_result`` pins that schema), but the
    flight-recorder record of which collective class the exposed time
    belongs to.
    """
    agg = report["agg"]
    dur = agg["mean_step_us"] * agg["n_steps"]
    if dur <= 0:
        return {}
    return {
        cls: round(us / dur, 4)
        for cls, us in agg.get("comms_exposed_by_class", [])
    }


def result_fields(report: Dict[str, Any]) -> Dict[str, Any]:
    """The additive BenchmarkResult fields this report feeds.

    Keys match ``utils.metrics.BenchmarkResult``; values rounded so result
    rows and registry records stay byte-stable across identical inputs.
    """

    def r4(v):
        return round(v, 4) if v is not None else None

    agg = report["agg"]
    roof = report.get("roofline") or {}
    return {
        "anatomy_compute_frac": r4(agg["compute_frac"]),
        "comms_exposed_frac": r4(agg["comms_exposed_frac"]),
        "comms_overlap_frac": r4(agg["comms_overlap_frac"]),
        "anatomy_idle_frac": r4(agg["idle_frac"]),
        "bubble_frac": r4(agg["bubble_frac"]),
        "roofline_flops_pct_of_peak": r4(roof.get("flops_pct_of_peak")),
        "roofline_hbm_pct_of_peak": r4(roof.get("hbm_pct_of_peak")),
        "straggler_skew_pct": r4(agg["straggler_skew_pct"]),
    }


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------


def format_report(report: Dict[str, Any]) -> str:
    agg = report["agg"]
    out: List[str] = []
    arm = f" [{report['arm']}]" if report.get("arm") else ""
    out.append(f"== Step anatomy: {report['profile_dir']}{arm} ==")
    out.append(f"  trace: {report['trace']}"
               + (f" (+{agg['n_ranks'] - 1} rank sibling(s))"
                  if agg["n_ranks"] > 1 else ""))
    clip_note = ""
    if agg["clipped_to_timed"]:
        clip_note = " [clipped to telemetry timed region]"
        if agg.get("clip_fallback_lanes"):
            clip_note = (
                f" [PARTIALLY clipped: {agg['clip_fallback_lanes']} "
                "lane(s) fell back to the full trace on a clock-base "
                "mismatch — skew unreliable]"
            )
    out.append(
        f"  traced steps: {agg['n_steps']} over {agg['n_devices']} "
        f"device lane(s){clip_note}"
    )
    out.append(f"  median step: {agg['median_step_us'] / 1e3:.3f} ms")
    out.append("")
    mean_us = agg["mean_step_us"]

    def row(label, frac):
        return (f"  {label:<18} {frac * mean_us / 1e3:9.3f} ms  "
                f"{100.0 * frac:5.1f}%")

    out.append(f"  {'component':<18} {'time/step':>12}   frac")
    out.append(row("compute", agg["compute_frac"]))
    out.append(row("comms (exposed)", agg["comms_exposed_frac"]))
    ov = agg["comms_overlap_frac"]
    out.append(
        f"  {'comms (overlapped)':<18} "
        f"{agg['comms_overlapped_frac_of_step'] * mean_us / 1e3:9.3f} ms  "
        + (f"[overlap_frac {100.0 * ov:.1f}% of collective time]"
           if ov is not None else "[no collectives traced]")
    )
    out.append(row("idle / host gap", agg["idle_frac"]))
    if agg["top_collectives"]:
        per_step = agg["n_steps"] or 1
        tops = ", ".join(
            f"{name} {dur / per_step / 1e3:.3f} ms"
            for name, dur in agg["top_collectives"]
        )
        out.append("")
        out.append(f"  top collectives (per step): {tops}")
    if agg.get("comms_exposed_by_class"):
        # The overlap worklist: which collective class owns the exposed
        # time (most exposed first — chase that one).
        per_step = agg["n_steps"] or 1
        exp_total = sum(us for _cls, us in agg["comms_exposed_by_class"])
        byc = ", ".join(
            f"{cls} {us / per_step / 1e3:.3f} ms"
            + (f" ({100.0 * us / exp_total:.0f}%)" if exp_total > 0 else "")
            for cls, us in agg["comms_exposed_by_class"]
        )
        out.append(f"  exposed by class (per step): {byc}")
    if agg["bubble_frac"] is not None:
        line = (
            f"  bubble fraction ({agg['pipeline_schedule']}): "
            f"{100.0 * agg['bubble_frac']:.1f}%"
        )
        if agg.get("bubble_frac_bound") is not None:
            line += (
                f" (structural bound "
                f"{100.0 * agg['bubble_frac_bound']:.1f}%)"
            )
        out.append(line)
        if agg.get("bubble_structure_mismatch"):
            out.append(
                "  ANATOMY/STRUCTURE MISMATCH: measured bubble "
                f"{100.0 * agg['bubble_frac']:.1f}% exceeds the "
                f"{agg['pipeline_schedule']} schedule's structural bound "
                f"{100.0 * agg['bubble_frac_bound']:.1f}% + "
                f"{100.0 * BUBBLE_BOUND_SLACK:.0f}pp slack — the executed "
                "overlap does not match the schedule (not noise; see "
                "docs/STATIC_ANALYSIS.md schedule auditor)"
            )
    if agg["straggler_skew_pct"] is not None:
        out.append(
            f"  straggler skew: {agg['straggler_skew_pct']:.1f}% across "
            f"{agg['n_ranks']} rank(s) / {agg['n_devices']} device lane(s)"
        )
    roof = report.get("roofline")
    if roof:
        bits = []
        if roof["achieved_tflops_per_sec"] is not None:
            s = f"{roof['achieved_tflops_per_sec']:.2f} TFLOP/s"
            if roof["flops_pct_of_peak"] is not None:
                s += (f" = {roof['flops_pct_of_peak']:.1f}% of "
                      f"{roof['peak_tflops_per_sec']:.0f} peak")
            bits.append(s)
        if roof["achieved_hbm_gbps"] is not None:
            s = f"{roof['achieved_hbm_gbps']:.1f} GB/s HBM"
            if roof["hbm_pct_of_peak"] is not None:
                s += (f" = {roof['hbm_pct_of_peak']:.1f}% of "
                      f"{roof['peak_hbm_gbps']:.0f} GB/s peak")
            bits.append(s)
        if bits:
            out.append(f"  roofline ({roof['device_kind'] or 'unknown'}): "
                       + "; ".join(bits))
    return "\n".join(out)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--profile-dir", required=True,
                   help="the directory passed to the harness's --profile-dir")
    p.add_argument("--run", default=None,
                   help="profile run name filter when the dir holds several")
    p.add_argument("--telemetry", default=None,
                   help="the run's telemetry_<arm>.jsonl: clips the "
                        "analysis to the timed phase and names the "
                        "pipeline schedule (auto-discovered when a single "
                        "telemetry_*.jsonl sits inside the profile dir)")
    p.add_argument("--cost-json", default=None,
                   help=f"cost-analysis JSON (default: "
                        f"{COST_JSON_FILENAME} inside the profile dir, "
                        "written by the harness)")
    p.add_argument("--pipeline-schedule", default=None,
                   help="publish the idle fraction as this schedule's "
                        "bubble (auto from telemetry run_meta when "
                        "pipeline_parallel > 1)")
    p.add_argument("--json", action="store_true",
                   help="emit the result_fields dict as one JSON line "
                        "instead of the table")
    args = p.parse_args(argv)
    cost = None
    if args.cost_json:
        cost = load_cost_json(args.cost_json)
        if cost is None:
            # An explicit --cost-json that fails to load must not fall
            # through to the auto-discovered file from some other run.
            print(f"ERROR: --cost-json {args.cost_json} missing or "
                  "unreadable", file=sys.stderr)
            return 1
    try:
        report = analyze_profile_dir(
            args.profile_dir, run=args.run, telemetry_path=args.telemetry,
            cost=cost, pipeline_schedule=args.pipeline_schedule,
        )
    except ValueError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(result_fields(report), sort_keys=True))
    else:
        print(format_report(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
