#!/usr/bin/env python
"""Memory anatomy: compile-time accounting + measured-peak reconciliation.

The memory-domain sibling of ``step_anatomy``: where the time domain got
its compute / exposed-comms / idle attribution (PR 7), peak HBM — one of
the four headline metrics, and the wall that ends the scaling curves in
both PAPERS.md TPU studies — was still a single opaque scalar beside an
analytic pre-flight estimate whose ±20% disclaimer was never tested.
This engine reconciles the THREE independent sources every run already
has into one attributed answer:

- the **analytic model** (``utils.memory.estimate_hbm``): per-class
  params / grads / optimizer / activations+remat / logits / dataset
  bytes, predicted before anything allocates;
- the **compiler's own accounting** (``compiled.memory_analysis()`` on
  the jitted train step): XLA's buffer-assignment argument / output /
  temp / donation-alias sizes — a *measured* property of the compiled
  program, available even on the CPU dryrun, with a graceful ``None``
  when a backend exposes nothing;
- the **runtime allocator** (``device.memory_stats()`` peak/current
  bytes-in-use): the true high-water mark, sampled per sync window into
  the flight-recorder stream and read at finalize — explicitly
  null-with-reason on backends (CPU) that expose no stats.

The reconciliation attributes the reference peak (measured when
available, else the compile-time peak, else the analytic total) across
the classes ``params / grads / opt_state / activations / dataset /
xla_temp / unattributed``:

- the five analytic classes come straight from the estimate (logits fold
  into activations — they are activations);
- ``xla_temp`` is the compile-time temp bytes the analytic model did
  NOT predict (XLA temps minus predicted grads+activations, floored at
  0) — fusion scratch, collective staging buffers, padding;
- ``unattributed`` is the signed residual that closes the books exactly
  (sum of all classes == the reference peak).

``hbm_model_drift_frac`` — |reference − analytic| / analytic — is the
scalar that turns the estimator's disclaimer into a gated invariant: it
rides the result row into the benchreg registry and verdicts as a
secondary metric (``regress.stats.SECONDARY_METRICS``), so a drifting
memory model fails CI by name instead of silently degrading the
pre-flight refusals and the auto-remat resolver that depend on it.

    python -m distributed_llm_training_benchmark_framework_tpu.analysis.memory_anatomy \
        --result results/..._results/result_<arm>.json

recomputes the attribution offline from a stored row (the persisted
``hbm_estimate`` / ``hbm_measured`` fields), so drift is auditable from
artifacts alone — no rerun needed.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Optional, Tuple

GIB = 1024**3

#: Attribution classes, in report order. The first five are the analytic
#: model's prediction; ``xla_temp`` is the compiler's unpredicted temp
#: bytes; ``unattributed`` is the signed book-closing residual.
ATTRIBUTION_CLASSES = (
    "params", "grads", "opt_state", "activations", "dataset",
    "xla_temp", "unattributed",
)

#: The compile-time accounting fields extracted from memory_analysis(),
#: shared with the graftcheck GC110 memory-budget audit so the static
#: and runtime layers can never disagree about what "temp bytes" means.
COMPILE_FIELDS = (
    "argument_bytes", "output_bytes", "temp_bytes", "alias_bytes",
    "peak_bytes",
)


# ---------------------------------------------------------------------------
# Source extraction
# ---------------------------------------------------------------------------


def compile_memory_fields(compiled) -> Optional[Dict[str, int]]:
    """XLA's compile-time memory accounting for one executable, or None.

    Works on both current jaxlib (``peak_memory_in_bytes`` exposed
    directly) and the older ``CompiledMemoryStats`` form (component
    sizes only — the peak is then arguments + outputs + temps minus the
    donation-aliased bytes, the same buffer-assignment quantity
    ``utils.metrics.buffer_assignment_peak_bytes`` computes). Returns
    None when the backend exposes no analysis at all, or only zeros —
    the caller's fallback path, exercised by the frozen-payload tests.
    """
    if compiled is None:
        return None
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out: Dict[str, int] = {}
    for key, attr in (
        ("argument_bytes", "argument_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
        ("temp_bytes", "temp_size_in_bytes"),
        ("alias_bytes", "alias_size_in_bytes"),
    ):
        try:
            out[key] = int(getattr(ma, attr, 0) or 0)
        except (TypeError, ValueError):
            out[key] = 0
    try:
        peak = int(getattr(ma, "peak_memory_in_bytes", 0) or 0)
    except (TypeError, ValueError):
        peak = 0
    if peak <= 0:
        peak = max(
            out["argument_bytes"] + out["output_bytes"]
            + out["temp_bytes"] - out["alias_bytes"],
            0,
        )
    out["peak_bytes"] = peak
    if all(v == 0 for v in out.values()):
        return None  # a stats object with no content is no accounting
    return out


def measured_peak_bytes(
    prior_peak_bytes: Optional[int] = None,
) -> Tuple[Optional[int], str]:
    """(allocator peak bytes | None, reason) for THIS run's measurement.

    Mirrors ``utils.metrics.measure_peak_hbm`` rung 1, including the
    shared-process guard: the allocator high-water mark is
    process-lifetime with no reset, so when an earlier arm in the same
    process already raised it higher, this arm's peak is unknowable from
    the allocator and the honest answer is null-with-reason — never an
    inherited number.
    """
    from ..utils import metrics as metrics_mod

    peak = metrics_mod.peak_hbm_bytes()
    if peak is None:
        return None, "backend exposes no memory_stats()"
    if prior_peak_bytes is not None and peak <= prior_peak_bytes:
        return None, (
            "allocator high-water predates this arm (shared-process "
            "mark not raised)"
        )
    return int(peak), "allocator"


def analytic_class_bytes(est) -> Dict[str, int]:
    """The estimate's per-class bytes on the attribution class space.

    ``est`` is a ``utils.memory.HBMEstimate``; logits fold into
    ``activations`` (the fp32 logits + cotangent ARE head activations).
    """
    return {
        "params": int(est.params),
        "grads": int(est.grads),
        "opt_state": int(est.opt_state),
        "activations": int(est.activations) + int(est.logits),
        "dataset": int(est.dataset),
    }


# ---------------------------------------------------------------------------
# Reconciliation
# ---------------------------------------------------------------------------


def reconcile(
    est,
    compile_mem: Optional[Dict[str, int]] = None,
    measured_bytes: Optional[int] = None,
    measured_reason: str = "",
) -> Dict[str, Any]:
    """Three sources -> one attributed peak + the model-drift scalar.

    The reference peak is the best measurement available — allocator >
    compile-time buffer assignment > the analytic total itself (in which
    degenerate case no drift is claimed: a model cannot drift from
    itself). ``unattributed`` is SIGNED so the books close exactly:
    a negative residual means the classes over-predict the reference
    (XLA aliased/scheduled buffers below the model), which is exactly as
    informative as a positive one.
    """
    analytic = analytic_class_bytes(est)
    analytic_total = int(est.total)
    if measured_bytes is not None and measured_bytes > 0:
        reference, source = int(measured_bytes), "allocator"
    elif compile_mem is not None and compile_mem.get("peak_bytes", 0) > 0:
        reference, source = int(compile_mem["peak_bytes"]), "xla_buffer_assignment"
    else:
        reference, source = analytic_total, "analytic"
    predicted_temp = analytic["grads"] + analytic["activations"]
    xla_temp = 0
    if compile_mem is not None:
        xla_temp = max(int(compile_mem.get("temp_bytes", 0)) - predicted_temp, 0)
    attribution = dict(analytic)
    attribution["xla_temp"] = xla_temp
    attribution["unattributed"] = reference - sum(attribution.values())
    drift = (
        abs(reference - analytic_total) / analytic_total
        if source != "analytic" and analytic_total > 0 else None
    )
    return {
        "analytic_bytes": analytic,
        "analytic_total_bytes": analytic_total,
        "compile": compile_mem,
        "measured_bytes": measured_bytes,
        "measured_reason": measured_reason or (
            "allocator" if measured_bytes is not None else "unknown"
        ),
        "reference_bytes": reference,
        "reference_source": source,
        "attribution_bytes": attribution,
        "drift_frac": drift,
    }


def result_fields(report: Dict[str, Any], est_breakdown=None) -> Dict[str, Any]:
    """The additive BenchmarkResult fields this report feeds.

    Keys match ``utils.metrics.BenchmarkResult`` (``compute_result``
    refuses unknown keys, so engine and schema cannot drift). All sizes
    are GiB, rounded so result rows and registry records stay
    byte-stable across identical inputs. ``est_breakdown`` (the
    ``HBMEstimate.breakdown()`` dict) persists the full pre-flight
    breakdown — previously print-only — so the drift metric is
    computable offline from stored runs.
    """

    def gib(b):
        return round(b / GIB, 4)

    measured = report["measured_bytes"]
    return {
        "hbm_estimate": (
            {k: round(v, 4) for k, v in est_breakdown.items()}
            if est_breakdown else None
        ),
        "hbm_measured": gib(measured) if measured is not None else None,
        "hbm_measured_reason": report["measured_reason"],
        "hbm_attribution": {
            cls: gib(report["attribution_bytes"][cls])
            for cls in ATTRIBUTION_CLASSES
        },
        "hbm_attribution_source": report["reference_source"],
        "hbm_reference_gib": gib(report["reference_bytes"]),
        "hbm_model_drift_frac": (
            round(report["drift_frac"], 4)
            if report["drift_frac"] is not None else None
        ),
    }


def reconcile_from_result_row(row: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Rebuild the attributed report from a STORED result row (offline).

    Uses the persisted ``hbm_estimate`` breakdown + ``hbm_measured`` so
    the drift and attribution are auditable from artifacts alone. Rows
    whose reference was the COMPILE-TIME peak (the CPU dryrun shape:
    ``hbm_attribution_source == "xla_buffer_assignment"``) reconstruct
    that reference from the persisted ``hbm_reference_gib`` +
    ``hbm_attribution['xla_temp']`` — the offline recompute must agree
    with the stored, gate-fed drift, not silently fall back to the
    analytic reference. Returns None when the row predates the
    memory-anatomy fields.
    """
    est_bd = row.get("hbm_estimate")
    if not isinstance(est_bd, dict):
        return None

    class _Est:
        params = int(est_bd.get("params_gib", 0.0) * GIB)
        grads = int(est_bd.get("grads_gib", 0.0) * GIB)
        opt_state = int(est_bd.get("opt_state_gib", 0.0) * GIB)
        activations = int(est_bd.get("activations_gib", 0.0) * GIB)
        logits = int(est_bd.get("logits_gib", 0.0) * GIB)
        dataset = int(est_bd.get("dataset_gib", 0.0) * GIB)
        total = params + grads + opt_state + activations + logits + dataset

    compile_mem = None
    ref = row.get("hbm_reference_gib")
    if (
        row.get("hbm_attribution_source") == "xla_buffer_assignment"
        and isinstance(ref, (int, float)) and ref > 0
    ):
        attr = row.get("hbm_attribution") or {}
        xla_temp = attr.get("xla_temp", 0.0)
        compile_mem = {
            "argument_bytes": 0,
            "output_bytes": 0,
            # reconcile derives the xla_temp class as compile temps
            # minus predicted grads+activations — invert that so the
            # rebuilt class matches the stored one.
            "temp_bytes": (
                int(xla_temp * GIB) + _Est.grads + _Est.activations
                + _Est.logits
                if isinstance(xla_temp, (int, float)) else 0
            ),
            "alias_bytes": 0,
            "peak_bytes": int(ref * GIB),
        }
    measured = row.get("hbm_measured")
    return reconcile(
        _Est,
        compile_mem=compile_mem,
        measured_bytes=(
            int(measured * GIB) if isinstance(measured, (int, float))
            else None
        ),
        measured_reason=row.get("hbm_measured_reason", ""),
    )


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------


def format_report(
    report: Dict[str, Any], est_breakdown: Optional[Dict[str, float]] = None,
) -> str:
    """The console memory waterfall (the loop prints it at finalize)."""
    out = ["== Memory anatomy (per chip) =="]
    ana = report["analytic_bytes"]
    out.append(
        f"  analytic estimate: {report['analytic_total_bytes'] / GIB:.3f} GiB"
        f"  (params {ana['params'] / GIB:.3f} / grads {ana['grads'] / GIB:.3f}"
        f" / opt {ana['opt_state'] / GIB:.3f} / act "
        f"{ana['activations'] / GIB:.3f} / data {ana['dataset'] / GIB:.3f})"
    )
    cm = report["compile"]
    if cm is not None:
        out.append(
            f"  compile-time (XLA): args {cm['argument_bytes'] / GIB:.3f} /"
            f" out {cm['output_bytes'] / GIB:.3f} /"
            f" temps {cm['temp_bytes'] / GIB:.3f} /"
            f" aliased {cm['alias_bytes'] / GIB:.3f} ->"
            f" peak {cm['peak_bytes'] / GIB:.3f} GiB"
        )
    else:
        out.append("  compile-time (XLA): unavailable (backend exposes no "
                   "memory_analysis)")
    m = report["measured_bytes"]
    if m is not None:
        out.append(f"  measured peak: {m / GIB:.3f} GiB (allocator)")
    else:
        out.append(f"  measured peak: unavailable ({report['measured_reason']})")
    ref = report["reference_bytes"] or 1
    out.append(
        f"  attribution of the {report['reference_source']} peak "
        f"({ref / GIB:.3f} GiB):"
    )
    for cls in ATTRIBUTION_CLASSES:
        b = report["attribution_bytes"][cls]
        label = "unattributed residual" if cls == "unattributed" else cls
        out.append(f"    {label:<22} {b / GIB:+9.3f} GiB  "
                   f"{100.0 * b / ref:+6.1f}%")
    if report["drift_frac"] is not None:
        out.append(
            f"  model drift: {100.0 * report['drift_frac']:.1f}% "
            f"(|{report['reference_source']} - analytic| / analytic — "
            "gated as hbm_model_drift_frac)"
        )
    else:
        out.append("  model drift: not measurable (no independent peak "
                   "source on this backend)")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# CLI (offline, from a stored result row)
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--result", required=True,
                   help="a stored result_<arm>.json carrying the persisted "
                        "hbm_estimate / hbm_measured fields")
    p.add_argument("--json", action="store_true",
                   help="emit the recomputed result_fields as one JSON line")
    args = p.parse_args(argv)
    try:
        row = json.load(open(args.result))
    except (OSError, json.JSONDecodeError) as e:
        print(f"ERROR: cannot read {args.result}: {e}", file=sys.stderr)
        return 2
    report = reconcile_from_result_row(row)
    if report is None:
        print(f"ERROR: {args.result} carries no hbm_estimate breakdown "
              "(pre-memory-anatomy artifact)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(
            result_fields(report, est_breakdown=row.get("hbm_estimate")),
            sort_keys=True,
        ))
    else:
        print(format_report(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
