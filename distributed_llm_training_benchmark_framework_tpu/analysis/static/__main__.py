"""graftcheck CLI.

    python -m distributed_llm_training_benchmark_framework_tpu.analysis.static --all

Exit codes: 0 clean, 1 findings (budget deltas / lint violations),
2 operational error (an arm failed to compile, bad usage).

The audit engine is only meaningful under the conditions the budgets were
frozen on — the CPU backend with 8 forced host devices — so this entry
point pins both BEFORE jax initializes a backend, regardless of the
caller's env (bench.py runs it as a TPU-process subprocess; the k8s image
via scripts/graftcheck.sh). The budgets file records the freeze conditions
and the audit refuses to compare across a jax-version mismatch.
"""

import argparse
import os
import re
import sys


def _force_cpu_audit_env() -> None:
    """CPU backend + exactly 8 virtual host devices, before jax spins up."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    want = "--xla_force_host_platform_device_count=8"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", want, flags
        )
    else:
        flags = (flags + " " + want).strip()
    os.environ["XLA_FLAGS"] = flags

    from ...utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            from jax.extend.backend import clear_backends

            clear_backends()
    except Exception:
        pass


def _git_changed_files():
    """Repo-relative paths changed vs the merge-base with the default
    branch, plus staged/unstaged/untracked work. Tuple (possibly empty);
    None only when git itself is unavailable — the caller then falls
    back to a full lint rather than silently passing.
    """
    import subprocess

    from .hlo_audit import REPO_ROOT

    def git(*a):
        try:
            out = subprocess.run(
                ["git", *a], cwd=REPO_ROOT, capture_output=True,
                text=True, timeout=30,
            )
        except Exception:
            return None
        return out.stdout if out.returncode == 0 else None

    if git("rev-parse", "HEAD") is None:
        # No git (or not a repo): the caller must fall back to a FULL
        # lint — an empty changed set here would pass the pre-commit
        # hook without linting anything.
        return None
    # git emits toplevel-relative paths; Violation.path is
    # REPO_ROOT-relative. When this checkout is a SUBDIRECTORY of a
    # larger repo the two bases differ, and comparing them unrebased
    # would scope every finding to nothing — the same silent-pass mode
    # as the no-git case. Rebase (and drop files outside this project).
    toplevel = (git("rev-parse", "--show-toplevel") or "").strip()
    prefix = ""
    if toplevel:
        rel = os.path.relpath(os.path.abspath(REPO_ROOT), toplevel)
        if rel not in (".", ""):
            if rel.startswith(".."):
                return None  # REPO_ROOT outside the repo git sees: full lint
            prefix = rel.replace(os.sep, "/") + "/"

    def rebase(path):
        path = path.replace(os.sep, "/")
        if not prefix:
            return path
        if path.startswith(prefix):
            return path[len(prefix):]
        return None
    base = None
    for ref in ("origin/main", "origin/master", "main", "master"):
        out = git("merge-base", "HEAD", ref)
        if out and out.strip():
            base = out.strip()
            break
    files = set()
    # Committed + working-tree changes vs the merge-base (diff against a
    # commit includes staged AND unstaged edits), plus untracked files.
    # `git diff` paths are toplevel-relative regardless of cwd;
    # `ls-files` paths are cwd-relative, so run everything from
    # REPO_ROOT (the subprocess cwd above) and rebase the diff output.
    if base:
        out = git("diff", "--name-only", base)
    else:
        out = git("diff", "--name-only", "HEAD")
    if out:
        files.update(
            r for l in out.splitlines() if l.strip()
            for r in (rebase(l.strip()),) if r is not None
        )
    out = git("ls-files", "--others", "--exclude-standard")
    if out:
        # cwd-relative (== REPO_ROOT-relative) already.
        files.update(
            l.strip().replace(os.sep, "/")
            for l in out.splitlines() if l.strip()
        )
    return tuple(sorted(files))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributed_llm_training_benchmark_framework_tpu"
             ".analysis.static",
        description="graftcheck: static collective-budget audit + JAX "
                    "hot-path lint (docs/STATIC_ANALYSIS.md)",
    )
    p.add_argument("--all", action="store_true",
                   help="run both engines over the full arm roster")
    p.add_argument("--audit", action="store_true",
                   help="run the HLO collective-budget auditor")
    p.add_argument("--lint", action="store_true",
                   help="run the AST lint rules")
    p.add_argument("--changed", action="store_true",
                   help="fast pre-commit mode: lint ONLY files changed vs "
                        "the merge-base with the default branch (plus "
                        "staged/unstaged/untracked work) — no audits. "
                        "Rules still read unchanged files for context; "
                        "findings are scoped to the changed set")
    p.add_argument("--memory", action="store_true",
                   help="GC110 memory-budget audit: lower every roster arm "
                        "on the CPU host and verdict its compile-time "
                        "memory accounting (argument/output/temp/alias/"
                        "peak bytes from XLA's memory_analysis) against "
                        "the frozen memory_budgets section, plus the "
                        "cross-tier growth laws (per-chip temps flat "
                        "along the data axis; fsdp/zero argument bytes "
                        "shrinking) over the frozen topology-tier memory "
                        "budgets. With --topology TIERS, the named tiers "
                        "are memory-audited fresh; with --update-budgets, "
                        "freezes the memory_budgets section (only)")
    p.add_argument("--arms", default=None,
                   help="comma-separated arm subset for --audit/--memory/"
                        "--topology (default: the whole roster)")
    p.add_argument("--topology", default=None,
                   help="comma-separated topology tier(s) "
                        "(v5e-16|v5e-64|v5e-256): AOT-compile the scalable "
                        "roster subset against the REAL TPU topology on "
                        "this CPU host and verdict per-tier budgets + "
                        "growth laws (docs/STATIC_ANALYSIS.md). --all "
                        "includes the default tiers "
                        "(v5e-16,v5e-64) when the host's libtpu can build "
                        "compile-only clients")
    p.add_argument("--list-arms", action="store_true",
                   help="print the audit roster and exit")
    p.add_argument("--list-rules", action="store_true",
                   help="print the lint rule catalog and exit")
    p.add_argument("--budgets", default=None,
                   help="budgets file (default: configs/collective_budgets.json)")
    p.add_argument("--update-budgets", action="store_true",
                   help="regenerate the budgets file from fresh audits "
                        "instead of diffing against it")
    p.add_argument("--json", action="store_true",
                   help="emit the audit reports as JSON on stdout")
    p.add_argument("--inject", default=None,
                   choices=["bad-kv-spec", "bad-fsdp-axis",
                            "bad-pipeline-spec", "bad-forward-gather",
                            "bad-cmm-ring"],
                   help="self-test: deliberately reintroduce a known-bad "
                        "configuration (bad-kv-spec = the PR 1 GQA kv "
                        "full-replicate fallback; bad-fsdp-axis = the "
                        "pre-round-8 composed dp x tp fsdp placement; "
                        "bad-pipeline-spec = the seed-old typed-key "
                        "shard_map boundary that broke the interleaved "
                        "arm's compile; bad-forward-gather = the round-15 "
                        "fsdp/zero3 per-block forward param placement "
                        "reverted; bad-cmm-ring = the collective-matmul "
                        "ppermute decomposition reverted to bulk "
                        "collectives) — the audit MUST then fail")
    args = p.parse_args(argv)

    if args.changed and (args.all or args.audit or args.topology
                         or args.memory or args.update_budgets):
        p.error("--changed is the fast lint-only pre-commit path; run the "
                "audits separately (--all / --audit / --topology)")

    if args.inject and args.update_budgets:
        # Freezing deliberately-injected-bad counts as the new budget would
        # make the known-bad schedule the audited baseline.
        p.error("--inject is a self-test knob and cannot be combined with "
                "--update-budgets")

    if args.arms and args.topology and args.update_budgets:
        # write_topology_budgets replaces a tier's arms block wholesale;
        # freezing a subset would silently drop the other arms' pins.
        p.error("--arms with --topology --update-budgets would freeze a "
                "partial tier; freeze whole tiers")

    # Static tool: never let it spin up a TPU backend (lint's GC201 imports
    # the harness module, and the audit must match the budgets' freeze
    # conditions), so pin the CPU env before anything queries devices.
    _force_cpu_audit_env()

    from . import hlo_audit, lint

    if args.list_rules:
        for rule in lint.RULES.values():
            print(f"{rule.id}  {rule.name}")
            print(f"       {rule.description}")
            print(f"       fix: {rule.fix_hint}")
        return 0
    if args.list_arms:
        for spec in hlo_audit.ROSTER.values():
            geom = "x".join(map(str, spec.mesh_shape))
            print(f"{spec.name}: {spec.strategy} x {spec.model_family} x "
                  f"mesh {geom} {spec.axes}")
        for spec in hlo_audit.PIPELINE_ROSTER.values():
            geom = "x".join(map(str, spec.mesh_shape))
            print(f"[pipeline] {spec.name}: {spec.pipeline_schedule} "
                  f"(V={spec.virtual_stages}) x {spec.model_family} x "
                  f"mesh {geom} M={spec.grad_accum}")
        for tier in hlo_audit.TOPOLOGY_TIERS.values():
            print(f"[topology] {tier.name}: {tier.topology_name} "
                  f"({tier.device_count} devices; arms "
                  f"{', '.join(hlo_audit.TOPOLOGY_ARMS)})")
        return 0

    topo_tiers = (
        [t.strip() for t in args.topology.split(",") if t.strip()]
        if args.topology else []
    )
    unknown_tiers = [t for t in topo_tiers if t not in hlo_audit.TOPOLOGY_TIERS]
    if unknown_tiers:
        print(f"graftcheck: unknown topology tier(s) {unknown_tiers}; "
              f"tiers: {list(hlo_audit.TOPOLOGY_TIERS)}", file=sys.stderr)
        return 2

    # --topology alone runs only the topology audit; --update-budgets
    # beside it freezes those tiers and NEVER the CPU arm roster — the
    # roster only regenerates when --update-budgets is given with no
    # --topology (or the roster audit is explicitly requested via
    # --all/--audit), so adding a read-only flag like --lint to a
    # topology freeze cannot silently churn the arm budgets.
    # write_budgets carries the other section through untouched.
    # --memory claims --topology for ITSELF (the named tiers are
    # memory-audited); the collective topology audit still runs under
    # --all, or via --topology without --memory. A --memory freeze never
    # regenerates the collective arm budgets (and vice versa).
    do_memory = args.memory
    do_audit = (args.all or args.audit
                or (args.update_budgets and not topo_tiers
                    and not args.memory))
    do_lint = args.all or args.lint or args.changed
    do_topology = (bool(topo_tiers) and not args.memory) or args.all
    if not (do_audit or do_lint or do_topology or do_memory):
        p.error("nothing to do: pass --all, --audit, --lint, --changed, "
                "--memory, --topology or --update-budgets")

    failures = 0

    if do_lint:
        changed_files = None
        if args.changed:
            changed_files = _git_changed_files()
            if changed_files is None:
                # git unavailable: degrade to the FULL lint, visibly —
                # never pass a pre-commit hook by linting nothing.
                print("graftcheck lint: --changed cannot reach git; "
                      "falling back to a FULL lint", file=sys.stderr)
            elif not changed_files:
                print("graftcheck lint: no changed files vs merge-base — "
                      "clean", file=sys.stderr)
                return 0
            else:
                print(f"graftcheck lint: --changed scoping to "
                      f"{len(changed_files)} file(s)", file=sys.stderr)
        violations = lint.run_lint(files=changed_files)
        for v in violations:
            print(str(v), file=sys.stderr)
        n = len(violations)
        print(
            f"graftcheck lint: {n} violation(s) across "
            f"{len(lint.RULES)} rules" if n else
            f"graftcheck lint: clean ({len(lint.RULES)} rules)",
            file=sys.stderr,
        )
        failures += n

    if do_audit:
        budgets_path = args.budgets or hlo_audit.DEFAULT_BUDGETS_PATH
        if args.arms:
            requested = [a.strip() for a in args.arms.split(",") if a.strip()]
            names = [n for n in requested if n in hlo_audit.ROSTER]
            pipe_names = [
                n for n in requested if n in hlo_audit.PIPELINE_ROSTER
            ]
            unknown = [
                n for n in requested
                if n not in hlo_audit.ROSTER
                and n not in hlo_audit.PIPELINE_ROSTER
            ]
            if unknown:
                print(f"graftcheck: unknown arm(s) {unknown}; roster: "
                      f"{list(hlo_audit.ROSTER)} + pipeline roster: "
                      f"{list(hlo_audit.PIPELINE_ROSTER)}", file=sys.stderr)
                return 2
        else:
            names = list(hlo_audit.ROSTER)
            pipe_names = list(hlo_audit.PIPELINE_ROSTER)

        import dataclasses as _dc

        reports = []
        for name in names:
            spec = hlo_audit.ROSTER[name]
            if args.inject:
                spec = _dc.replace(spec, inject=args.inject)
            print(f"graftcheck audit: lowering {name} ...", file=sys.stderr)
            try:
                reports.append(hlo_audit.audit_arm(spec))
            except Exception as e:
                print(f"graftcheck audit: arm {name} failed to compile: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
                return 2

        pipe_results = []
        for name in pipe_names:
            spec = hlo_audit.PIPELINE_ROSTER[name]
            if args.inject:
                spec = _dc.replace(spec, inject=args.inject)
            m2 = spec.grad_accum * hlo_audit.PIPELINE_GROWTH_M_FACTOR
            print(f"graftcheck audit: lowering {name} (schedule laws, "
                  f"M={spec.grad_accum} and M={m2}) ...", file=sys.stderr)
            # Compile failures become schedule-compiles law findings
            # (exit 1), not operational errors: these arms carry a known
            # compile-failure history and the injection proof reverts
            # exactly that fix.
            pipe_results.append(hlo_audit.audit_pipeline_arm(spec))

        if args.json:
            import json as _json

            doc = {r.arm: r.to_budget_entry() for r in reports}
            doc.update({
                p.arm: (
                    p.to_budget_entry() if p.compile_error is None
                    else {"compile_error": p.compile_error}
                )
                for p in pipe_results
            })
            print(_json.dumps(doc, indent=2, sort_keys=True))

        if args.update_budgets:
            existing = None
            if os.path.exists(budgets_path):
                existing = hlo_audit.load_budgets(budgets_path)
            if reports:
                existing = hlo_audit.write_budgets(
                    reports, budgets_path, existing=existing
                )
                print(f"graftcheck audit: froze {len(reports)} arm "
                      f"budget(s) into {budgets_path}", file=sys.stderr)
            if pipe_results:
                hlo_audit.write_pipeline_budgets(
                    pipe_results, budgets_path, existing=existing
                )
                print(f"graftcheck audit: froze {len(pipe_results)} "
                      f"pipeline_schedules budget(s) into {budgets_path}",
                      file=sys.stderr)
        else:
            if not os.path.exists(budgets_path):
                print(f"graftcheck audit: no budgets file at {budgets_path} "
                      "(run --update-budgets first)", file=sys.stderr)
                return 2
            budgets = hlo_audit.load_budgets(budgets_path)
            import jax

            frozen_on = budgets.get("jax_version")
            if reports and frozen_on is not None and (
                frozen_on != jax.__version__
            ):
                print(
                    f"graftcheck audit: budgets frozen on jax {frozen_on} "
                    f"but running jax {jax.__version__} — counts are not "
                    "comparable; regenerate with --update-budgets",
                    file=sys.stderr,
                )
                return 2
            deltas = []
            for rep in reports:
                deltas.extend(hlo_audit.diff_against_budget(rep, budgets))
            if pipe_results:
                pipe_frozen = budgets.get("pipeline_schedules", {}).get(
                    "jax_version"
                )
                if pipe_frozen is not None and (
                    pipe_frozen != jax.__version__
                ):
                    print(
                        "graftcheck audit: pipeline_schedules budgets "
                        f"frozen on jax {pipe_frozen} but running jax "
                        f"{jax.__version__} — regenerate with "
                        "--update-budgets", file=sys.stderr,
                    )
                    return 2
                for p in pipe_results:
                    deltas.extend(
                        hlo_audit.diff_pipeline_against_budget(p, budgets)
                    )
            for d in deltas:
                print(f"graftcheck audit: {d}", file=sys.stderr)
            print(
                f"graftcheck audit: {len(reports)} arm(s) + "
                f"{len(pipe_results)} pipeline arm(s), "
                f"{len(deltas)} finding(s)", file=sys.stderr,
            )
            failures += len(deltas)

    if do_topology:
        budgets_path = args.budgets or hlo_audit.DEFAULT_BUDGETS_PATH
        tiers = topo_tiers or list(hlo_audit.TOPOLOGY_DEFAULT_TIERS)
        # Subset only an EXPLICIT --topology request: under --all the
        # roster subset in --arms addresses the CPU audit, not the tiers.
        topo_arm_names = None
        if args.arms and topo_tiers:
            requested = [a.strip() for a in args.arms.split(",") if a.strip()]
            unknown = [
                n for n in requested if n not in hlo_audit.TOPOLOGY_ARMS
            ]
            if unknown:
                print(f"graftcheck topology: unknown arm(s) {unknown}; "
                      f"topology roster: {list(hlo_audit.TOPOLOGY_ARMS)}",
                      file=sys.stderr)
                return 2
            topo_arm_names = tuple(requested)
        fresh = {}
        try:
            for tier_name in tiers:
                tier = hlo_audit.TOPOLOGY_TIERS[tier_name]
                n_arms = len(topo_arm_names or hlo_audit.TOPOLOGY_ARMS)
                print(f"graftcheck topology: compiling "
                      f"{n_arms} arm(s) against "
                      f"{tier_name} ({tier.topology_name}, "
                      f"{tier.device_count} devices) ...", file=sys.stderr)
                fresh[tier_name] = hlo_audit.audit_topology_tier(
                    tier, arm_names=topo_arm_names, inject=args.inject
                )
        except hlo_audit.TopologyUnavailable as e:
            if topo_tiers:
                # Explicitly requested: the answer must be loud.
                print(f"graftcheck topology: {e}", file=sys.stderr)
                return 2
            # --all degrades to a visible skip — but findings already
            # computed for earlier tiers must not be discarded with it.
            unaudited = [t for t in tiers if t not in fresh]
            print(f"graftcheck topology: tier(s) {unaudited} SKIPPED "
                  f"under --all ({e})", file=sys.stderr)
        except Exception as e:
            print(f"graftcheck topology: arm failed to compile: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2

        if fresh:
            if args.json:
                import json as _json

                print(_json.dumps(
                    {t: {r.arm: r.to_budget_entry() for r in reps}
                     for t, reps in fresh.items()},
                    indent=2, sort_keys=True,
                ))
            if args.update_budgets and topo_tiers:
                doc = hlo_audit.write_topology_budgets(fresh, budgets_path)
                print(f"graftcheck topology: froze {len(fresh)} tier "
                      f"budget(s) into {budgets_path}", file=sys.stderr)
                growth_doc, _stale = hlo_audit.commensurable_topology_tiers(
                    doc, fresh_tiers=tuple(fresh)
                )
                growth = hlo_audit.growth_law_findings(
                    hlo_audit.assemble_per_tier(growth_doc)
                )
                for g in growth:
                    print(f"graftcheck topology: WARNING (frozen anyway): "
                          f"{g}", file=sys.stderr)
            else:
                budgets = (
                    hlo_audit.load_budgets(budgets_path)
                    if os.path.exists(budgets_path) else {}
                )
                import jax

                deltas = []
                for tier_name, reports in fresh.items():
                    frozen_on = budgets.get("topology_tiers", {}).get(
                        tier_name, {}
                    ).get("jax_version")
                    if frozen_on is not None and frozen_on != jax.__version__:
                        print(
                            f"graftcheck topology: {tier_name} budgets "
                            f"frozen on jax {frozen_on} but running jax "
                            f"{jax.__version__} — regenerate with "
                            f"--topology {tier_name} --update-budgets",
                            file=sys.stderr,
                        )
                        return 2
                    deltas.extend(hlo_audit.diff_topology_against_budget(
                        tier_name, reports, budgets
                    ))
                # Growth laws judge the fresh reports overlaid on every
                # OTHER tier's frozen structure, so a one-tier audit still
                # sees the cross-tier shape — but only tiers frozen on
                # THIS jax are commensurable with the fresh counts.
                growth_budgets, stale_tiers = (
                    hlo_audit.commensurable_topology_tiers(
                        budgets, fresh_tiers=tuple(fresh),
                        jax_version=jax.__version__,
                    )
                )
                if stale_tiers:
                    print(
                        "graftcheck topology: growth laws exclude "
                        f"tier(s) {stale_tiers} frozen on a different "
                        "jax — regenerate them with --topology "
                        f"{','.join(stale_tiers)} --update-budgets",
                        file=sys.stderr,
                    )
                deltas.extend(hlo_audit.growth_law_findings(
                    hlo_audit.assemble_per_tier(growth_budgets, fresh)
                ))
                for d in deltas:
                    print(f"graftcheck topology: {d}", file=sys.stderr)
                print(
                    f"graftcheck topology: {len(fresh)} tier(s), "
                    f"{len(deltas)} finding(s)", file=sys.stderr,
                )
                failures += len(deltas)

    if do_memory:
        budgets_path = args.budgets or hlo_audit.DEFAULT_BUDGETS_PATH
        if args.arms:
            mem_names = [a.strip() for a in args.arms.split(",") if a.strip()]
            unknown = [n for n in mem_names if n not in hlo_audit.ROSTER]
            if unknown:
                print(f"graftcheck memory: unknown arm(s) {unknown}; "
                      f"roster: {list(hlo_audit.ROSTER)}", file=sys.stderr)
                return 2
        else:
            mem_names = list(hlo_audit.ROSTER)

        import dataclasses as _dc

        mem_reports = []
        for name in mem_names:
            spec = hlo_audit.ROSTER[name]
            if args.inject:
                spec = _dc.replace(spec, inject=args.inject)
            print(f"graftcheck memory: lowering {name} ...", file=sys.stderr)
            try:
                mem_reports.append(hlo_audit.audit_arm_memory(spec))
            except Exception as e:
                print(f"graftcheck memory: arm {name} failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
                return 2

        fresh_mem_tiers = {}
        if topo_tiers:
            try:
                for tier_name in topo_tiers:
                    tier = hlo_audit.TOPOLOGY_TIERS[tier_name]
                    print(f"graftcheck memory: compiling "
                          f"{len(hlo_audit.TOPOLOGY_ARMS)} arm(s) against "
                          f"{tier_name} ({tier.topology_name}) ...",
                          file=sys.stderr)
                    fresh_mem_tiers[tier_name] = (
                        hlo_audit.audit_topology_tier_memory(
                            tier, inject=args.inject
                        )
                    )
            except hlo_audit.TopologyUnavailable as e:
                # Tiers were explicitly requested with --memory: loud.
                print(f"graftcheck memory: {e}", file=sys.stderr)
                return 2
            except Exception as e:
                print(f"graftcheck memory: tier arm failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
                return 2

        if args.json:
            import json as _json

            doc = {r.arm: r.to_budget_entry() for r in mem_reports}
            doc.update({
                t: {r.arm: r.to_budget_entry() for r in reps}
                for t, reps in fresh_mem_tiers.items()
            })
            print(_json.dumps(doc, indent=2, sort_keys=True))

        if args.update_budgets:
            hlo_audit.write_memory_budgets(
                mem_reports, budgets_path, tier_reports=fresh_mem_tiers,
            )
            print(f"graftcheck memory: froze {len(mem_reports)} arm + "
                  f"{len(fresh_mem_tiers)} tier memory budget(s) into "
                  f"{budgets_path}", file=sys.stderr)
            per_tier, _stale = hlo_audit.commensurable_memory_tiers(
                hlo_audit.load_budgets(budgets_path),
                fresh_tiers=tuple(fresh_mem_tiers),
            )
            for g in hlo_audit.memory_growth_law_findings(per_tier):
                print(f"graftcheck memory: WARNING (frozen anyway): {g}",
                      file=sys.stderr)
        else:
            if not os.path.exists(budgets_path):
                print(f"graftcheck memory: no budgets file at "
                      f"{budgets_path} (run --memory --update-budgets "
                      "first)", file=sys.stderr)
                return 2
            budgets = hlo_audit.load_budgets(budgets_path)
            import jax

            section = budgets.get("memory_budgets", {})
            frozen_on = section.get("jax_version")
            if frozen_on is not None and frozen_on != jax.__version__:
                print(
                    f"graftcheck memory: memory_budgets frozen on jax "
                    f"{frozen_on} but running jax {jax.__version__} — "
                    "byte counts are not comparable; regenerate with "
                    "--memory --update-budgets", file=sys.stderr,
                )
                return 2
            deltas = []
            for rep in mem_reports:
                deltas.extend(
                    hlo_audit.diff_memory_against_budget(rep, budgets)
                )
            per_tier, stale_tiers = hlo_audit.commensurable_memory_tiers(
                budgets, fresh_tiers=tuple(fresh_mem_tiers),
                jax_version=jax.__version__,
            )
            if stale_tiers:
                print(
                    "graftcheck memory: growth laws exclude tier(s) "
                    f"{stale_tiers} frozen on a different jax — "
                    "regenerate with --memory --topology "
                    f"{','.join(stale_tiers)} --update-budgets",
                    file=sys.stderr,
                )
            for tier_name, reps in fresh_mem_tiers.items():
                # Same loud refusal as the collective topology path: a
                # tier frozen on a different jax must not be byte-diffed
                # against fresh counts (commensurable_memory_tiers keeps
                # fresh tiers in the LAW overlay, so the version check
                # has to happen here, before the exact pins).
                tier_frozen = section.get("topology_tiers", {}).get(
                    tier_name, {}
                ).get("jax_version")
                if tier_frozen is not None and tier_frozen != jax.__version__:
                    print(
                        f"graftcheck memory: {tier_name} memory budgets "
                        f"frozen on jax {tier_frozen} but running jax "
                        f"{jax.__version__} — regenerate with --memory "
                        f"--topology {tier_name} --update-budgets",
                        file=sys.stderr,
                    )
                    return 2
                frozen_arms = per_tier.get(tier_name, {})
                for rep in reps:
                    deltas.extend(hlo_audit.diff_memory_against_budget(
                        rep, budgets, arms_override=frozen_arms,
                    ))
                per_tier.setdefault(tier_name, {}).update(
                    {r.arm: r.to_budget_entry() for r in reps}
                )
            deltas.extend(hlo_audit.memory_growth_law_findings(per_tier))
            for d in deltas:
                print(f"graftcheck memory: {d}", file=sys.stderr)
            print(
                f"graftcheck memory: {len(mem_reports)} arm(s) + "
                f"{len(fresh_mem_tiers) or len(per_tier)} tier(s), "
                f"{len(deltas)} finding(s)", file=sys.stderr,
            )
            failures += len(deltas)

    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
