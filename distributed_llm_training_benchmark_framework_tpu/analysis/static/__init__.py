"""graftcheck — static analysis over the framework's compiled and source artifacts.

Two engines, one CLI (``python -m
distributed_llm_training_benchmark_framework_tpu.analysis.static``):

- ``hlo_audit``: lowers every (strategy x model-family x mesh-geometry) arm
  of the audit roster on CPU — abstract avals, no allocation — and diffs the
  compiled module's collective schedule (all-gather / reduce-scatter /
  all-reduce / collective-permute / all-to-all counts, donation coverage,
  bf16->f32 promotions, full-replication reshard suspects) against the
  frozen per-arm budgets in ``configs/collective_budgets.json``.
- ``lint``: repo-specific AST rules over the package source (jit donation
  discipline, host syncs in the timed loop, unknown mesh axes in sharding
  constraints, wall-clock calls under jit, entrypoint<->harness flag drift),
  each with an id, a fix hint, and ``# graftcheck: disable=RULE``
  suppression.

Both run as a preflight gate in ``bench.py`` and
``scripts/run_all_benchmarks.sh`` (see ``scripts/graftcheck.sh``) and as the
tier-1 module ``tests/test_graftcheck.py``. Docs: ``docs/STATIC_ANALYSIS.md``.
"""

from .hlo_audit import (  # noqa: F401
    ArmSpec,
    ArmReport,
    ROSTER,
    audit_arm,
    diff_against_budget,
    load_budgets,
    write_budgets,
    DEFAULT_BUDGETS_PATH,
)
from .lint import RULES, Violation, run_lint  # noqa: F401

__all__ = [
    "ArmSpec",
    "ArmReport",
    "ROSTER",
    "audit_arm",
    "diff_against_budget",
    "load_budgets",
    "write_budgets",
    "DEFAULT_BUDGETS_PATH",
    "RULES",
    "Violation",
    "run_lint",
]
