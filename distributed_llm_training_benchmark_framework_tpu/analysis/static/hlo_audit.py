"""Engine 1: the HLO collective-budget auditor.

The compiled HLO for every strategy arm is a deterministic, CPU-lowerable
artifact: ``train.step.abstract_compile_step`` compiles the REAL train-step
executable from ``ShapeDtypeStruct``s over a virtual CPU mesh (the same
machinery the auto-remat probe and ``tests/test_collective_lowering.py``
use), so regressions in collective counts, donation, and dtype promotion
are catchable in CI before any TPU time is spent. PR 1's motivating case:
a single unchased GSPMD full-replication fallback on the llama x tp GQA kv
projections cost 6 collective-permutes + 8 all-gathers per step and was
only caught by a one-off HLO test — this module makes that class of check
systematic, per arm, against frozen budgets.

Determinism contract: counts are a property of (jax/XLA version, backend,
device count, arm config). Budgets are frozen on the CPU backend with 8
forced host devices (``scripts/graftcheck.sh`` / the CLI force both); a
jax upgrade legitimately moves counts — regenerate with
``--update-budgets`` and review the diff like any other lockfile change.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, List, Mapping, Optional, Tuple

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "..")
)
DEFAULT_BUDGETS_PATH = os.path.join(REPO_ROOT, "configs", "collective_budgets.json")

#: The collective opcodes the auditor counts, in report order.
COLLECTIVE_OPS = (
    "all-gather",
    "reduce-scatter",
    "all-reduce",
    "collective-permute",
    "all-to-all",
)

_INJECTIONS = (
    "bad-kv-spec", "bad-fsdp-axis", "bad-pipeline-spec",
    "bad-forward-gather", "bad-cmm-ring",
)


@dataclasses.dataclass(frozen=True)
class ArmSpec:
    """One auditable arm: strategy x model family x mesh geometry.

    ``config_overrides`` is a tuple of (key, value) pairs passed to the
    model-config factory (tuple, not dict, so the spec stays hashable);
    ``inject`` deliberately reintroduces a known-bad configuration for
    self-tests — 'bad-kv-spec' disables the kv-head-aligned PartitionSpec
    rule, bringing back the GQA full-replicate resharding fallback PR 1
    fixed (the auditor must flag it); 'bad-pipeline-spec' reverts the
    typed-key/shard_map boundary fix, bringing back the seed-old u32
    tile-assignment compile failure on the pipeline arms.

    ``pipeline_schedule``/``virtual_stages`` only matter when the mesh
    carries a >1 'pipe' axis (the schedule-auditor roster below); they
    flow into ``train.step.abstract_compile_step`` unchanged.
    """

    name: str
    strategy: str
    mesh_shape: Tuple[int, ...]
    axes: Tuple[str, ...]
    global_batch: int
    model_family: str = "tinygpt"
    tier: str = "S"
    seq_len: int = 64
    grad_accum: int = 1
    config_overrides: Tuple[Tuple[str, Any], ...] = ()
    inject: Optional[str] = None
    pipeline_schedule: str = "gpipe"
    virtual_stages: int = 1


@dataclasses.dataclass(frozen=True)
class ArmReport:
    """Structured audit result for one arm — everything the budget pins."""

    arm: str
    collectives: Mapping[str, int]
    # collective-permutes in an arm whose mesh has no >1 'seq'/'pipe' axis:
    # rings and pipelines legitimately permute; a pure dp/tp/ep arm only
    # emits them when the SPMD partitioner fell back to
    # full-replicate-then-repartition resharding (the PR 1 GQA fallback
    # lowered exactly so on this jaxlib).
    replication_reshard_suspects: int
    # Donation: aliased entry-parameter buffers vs donatable leaves
    # (params + optimizer state, donate_argnums=(0, 1) in the train step).
    donated_inputs: int
    donatable_inputs: int
    # bf16 -> f32 convert instructions in the module. bf16-compute arms
    # expect a stable population (fp32 loss/accum upcasts); growth means a
    # new unintended promotion of bf16 tensors to f32.
    bf16_to_f32_converts: int

    def to_budget_entry(self) -> Dict[str, Any]:
        return {
            "collectives": dict(self.collectives),
            "replication_reshard_suspects": self.replication_reshard_suspects,
            "donated_inputs": self.donated_inputs,
            "donatable_inputs": self.donatable_inputs,
            "bf16_to_f32_converts": self.bf16_to_f32_converts,
        }


#: The audit roster: one arm per (strategy x model-family x mesh-geometry)
#: shape the suite roster exercises (scripts/run_all_benchmarks.sh), scaled
#: to tier S / seq 64 so each compiles in seconds on the CPU backend. All
#: arms assume 8 devices (the virtual-mesh test geometry).
ROSTER: Dict[str, ArmSpec] = {
    spec.name: spec
    for spec in (
        # The pure-strategy matrix at dp=8.
        ArmSpec("ddp-dp8", "ddp", (8,), ("data",), global_batch=16),
        ArmSpec("fsdp-dp8", "fsdp", (8,), ("data",), global_batch=16),
        ArmSpec("zero2-dp8", "zero2", (8,), ("data",), global_batch=16),
        ArmSpec("zero3-dp8", "zero3", (8,), ("data",), global_batch=16),
        # llama x tensor parallel — the GQA kv-alignment arm (PR 1): a
        # 'model' degree that does not divide the family's kv heads must
        # NOT trip the full-replicate resharding fallback.
        ArmSpec(
            "llama-tp2-gqa", "ddp", (1, 1, 2), ("data", "seq", "model"),
            global_batch=2, model_family="llama",
        ),
        # llama x fsdp x tp — the suite's llama-tp2 composition arm shape,
        # compiled with the UNROLLED layer loop because that is what the
        # suite actually runs (scripts/run_all_benchmarks.sh LAYER_LOOP
        # defaults to 'unrolled'; through PR 7 this arm audited the scan
        # lowering the suite never measures). Round 8 fixed the composed
        # dp x tp fsdp-axis placement (strategies._shard_largest_free_axis
        # tile-order hygiene): the 13 banked replication-reshard suspects
        # (collective-permutes against transposed device orders) are now 0.
        # `--inject bad-fsdp-axis` proves the auditor still catches the old
        # placement.
        ArmSpec(
            "llama-fsdp-dp4-tp2", "fsdp", (4, 1, 2), ("data", "seq", "model"),
            global_batch=8, model_family="llama",
            config_overrides=(("scan_layers", False),),
        ),
        # The same composition under the scan layer loop (the harness
        # default; pipeline-sharded runs and compile-time-sensitive runs
        # still use it). The round-8 spec rules cut its fallback 13 -> 4;
        # the residue is the scan-carry layout XLA picks for the stacked
        # activation stash — banked here so it cannot grow, and so a future
        # scan-carry fix shows up as a bankable improvement.
        ArmSpec(
            "llama-fsdp-dp4-tp2-scan", "fsdp", (4, 1, 2),
            ("data", "seq", "model"),
            global_batch=8, model_family="llama",
        ),
        # llama x tp with the collective-matmul fusion (round 15,
        # ops/collective_matmul.py): the gqa arm's shape with
        # --tp-collective-matmul on. Its frozen budget IS the fusion's
        # signature — the plain arm's 21 projection all-gathers collapse
        # to the 5 embed/head-boundary gathers outside the layer stack,
        # replaced by the ppermute ring (2 hops per projection class per
        # layer, fwd+bwd), reshard suspects 0 (ring permutes are the
        # budgeted schedule — audit_arm knows cmm arms permute
        # legitimately). `--inject bad-cmm-ring` reverts the ring to the
        # unfused all-gather/reduce-scatter lowering and the audit must
        # flag the arm by name.
        ArmSpec(
            "llama-tp2-gqa-cmm", "ddp", (1, 1, 2), ("data", "seq", "model"),
            global_batch=2, model_family="llama",
            config_overrides=(("tp_collective_matmul", True),),
        ),
        # Sequence parallel: the ring's collective-permute hops are the
        # budgeted schedule, not a regression.
        ArmSpec(
            "zero2-sp4-ring", "zero2", (1, 4, 1), ("data", "seq", "model"),
            global_batch=2,
            config_overrides=(("attention_impl", "ring"),),
        ),
        # Expert parallel: the MoE dispatch/combine all-to-alls.
        ArmSpec(
            "zero2-ep2-moe", "zero2", (4, 1, 1, 1, 2),
            ("data", "seq", "model", "pipe", "expert"),
            global_batch=16,
            config_overrides=(("n_experts", 4),),
        ),
    )
}


def _model_config(spec: ArmSpec):
    from ...models import get_model_config
    from ...models.llama import get_llama_config

    overrides = dict(spec.config_overrides)
    # Dropout adds RNG ops whose count is batch-geometry noise; the audit
    # pins the communication schedule, so arms lower dropout-free (the same
    # choice the original HLO pin tests made).
    overrides.setdefault("dropout", 0.0)
    if spec.model_family == "llama":
        return get_llama_config(spec.tier, spec.seq_len, **overrides)
    if spec.model_family == "tinygpt":
        return get_model_config(spec.tier, spec.seq_len, **overrides)
    raise ValueError(
        f"arm {spec.name!r}: unknown model_family {spec.model_family!r}"
    )


def lower_arm(spec: ArmSpec, devices=None):
    """Compile the arm's train step abstractly; return the jax.stages.Compiled.

    Pure compiler work — no params are initialized and no device memory is
    allocated. Needs ``prod(mesh_shape)`` visible devices (the CLI forces
    8 virtual CPU devices; in-process callers run under the test mesh).
    The lowering path goes through ``train.step`` and therefore through the
    ``utils.jax_compat`` polyfills (``jax.set_mesh`` et al.), so it stays
    green on the image's jax 0.4.37; ``jax.sharding.AbstractMesh`` lowering
    is not used because the collective schedule only exists in the
    POST-partitioning executable, which requires a concrete backend to
    build.
    """
    # Idempotent: a strict no-op when the package import already installed
    # the shims or the runtime has the real APIs.
    from ...utils import jax_compat

    jax_compat.install()

    import jax

    from ...parallel import get_strategy, make_mesh
    from ...train.step import abstract_compile_step

    if spec.inject is not None and spec.inject not in _INJECTIONS:
        raise ValueError(
            f"arm {spec.name!r}: unknown injection {spec.inject!r} "
            f"(expected one of {_INJECTIONS})"
        )
    if devices is None:
        devices = jax.devices()
    n_needed = 1
    for d in spec.mesh_shape:
        n_needed *= d
    if len(devices) < n_needed:
        raise RuntimeError(
            f"arm {spec.name!r} needs {n_needed} devices, have "
            f"{len(devices)} (run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8)"
        )
    cfg = _model_config(spec)
    mesh = make_mesh(spec.mesh_shape, spec.axes, devices=devices[:n_needed])
    strategy = get_strategy(spec.strategy)

    def compile_():
        return abstract_compile_step(
            cfg, strategy, mesh,
            grad_accum=spec.grad_accum, seed=0, from_table=False,
            global_micro=spec.global_batch, seq_len=spec.seq_len,
            pipeline_schedule=spec.pipeline_schedule,
            virtual_stages=spec.virtual_stages,
        )

    if spec.inject == "bad-kv-spec":
        return _with_bad_kv_spec(compile_)
    if spec.inject == "bad-fsdp-axis":
        return _with_bad_fsdp_axis(compile_)
    if spec.inject == "bad-pipeline-spec":
        return _with_bad_pipeline_spec(compile_)
    if spec.inject == "bad-forward-gather":
        return _with_bad_forward_gather(compile_)
    if spec.inject == "bad-cmm-ring":
        return _with_bad_cmm_ring(compile_)
    return compile_()


def _with_bad_kv_spec(fn):
    """Run ``fn`` with the kv-head-aligned PartitionSpec rule disabled.

    Forcing ``kv_heads=None`` makes ``param_partition_specs`` column-shard
    wkv/bkv over 'model' even when the degree does not divide the kv-head
    count — the misaligned split whose consecutive-block kv repeat has no
    in-place reshard, so GSPMD falls back to full replication (measured on
    this jaxlib as collective-permute + all-gather chains). This is the
    regression the llama-tp2-gqa budget exists to catch; the injection
    exists so CI can prove the auditor catches it.
    """
    from ...parallel import strategies as strat

    real = strat.param_partition_specs

    def misaligned(params, mesh, shard, kv_heads=None, scan_stacked=False):
        return real(params, mesh, shard=shard, kv_heads=None,
                    scan_stacked=scan_stacked)

    strat.param_partition_specs = misaligned
    try:
        return fn()
    finally:
        strat.param_partition_specs = real


def _with_bad_fsdp_axis(fn):
    """Run ``fn`` with the composed dp x tp fsdp-axis hygiene disabled.

    Reverts ``strategies._shard_largest_free_axis`` to the pre-round-8
    unrestricted largest-free-axis placement: fsdp 'data' lands AFTER the
    leaf's 'model' axis on row-parallel/vocab leaves (wo/wproj/wte/
    lm_head), producing the transposed device-order tilings whose reshard
    chains lowered as 13 collective-permutes per step on the
    llama-fsdp-dp4-tp2 arm. The audit must flag the regression; the
    injection exists so CI can prove it does.
    """
    from ...parallel import strategies as strat

    strat._COMPOSED_FSDP_HYGIENE = False
    try:
        return fn()
    finally:
        strat._COMPOSED_FSDP_HYGIENE = True


def _with_bad_forward_gather(fn):
    """Run ``fn`` with the round-15 forward-side per-block param placement
    reverted.

    ``train.step._FORWARD_GATHER_OVERLAP = False`` makes
    ``fsdp_block_param_spec`` return None, so the sharded-param arms'
    weight slices lose their in-loop placement pins — the scanned
    fsdp/zero3 lowerings regrow the full-stack activation gather (+1
    all-gather, +1 all-to-all per arm on this jaxlib) the constraint
    removed, and the audit must name the arms and the deltas.
    """
    from ...train import step as step_mod

    step_mod._FORWARD_GATHER_OVERLAP = False
    try:
        return fn()
    finally:
        step_mod._FORWARD_GATHER_OVERLAP = True


def _with_bad_cmm_ring(fn):
    """Run ``fn`` with the collective-matmul ppermute decomposition broken.

    ``ops.collective_matmul._CMM_RING = False`` reverts the ring bodies to
    their unfused all_gather / psum_scatter forms — mathematically equal,
    structurally the bulk collectives the fusion exists to remove. The
    llama-tp2-gqa-cmm frozen budget (projection all-gathers gone, ring
    permutes in their place) must flag the arm by name with the
    all-gather/reduce-scatter growth and the vanished permutes.
    """
    from ...ops import collective_matmul as cm

    cm._CMM_RING = False
    try:
        return fn()
    finally:
        cm._CMM_RING = True


def _with_bad_pipeline_spec(fn):
    """Run ``fn`` with the pipeline typed-key boundary fix reverted.

    ``parallel.pipeline._key_data_or_none`` exists because a typed PRNG
    key must cross the pipeline shard_map boundary as raw u32 key data —
    passing the key itself resurrects the seed-old interleaved compile
    failure (the partial-auto boundary builds a rank-0 sharding for the
    key aval and XLA rejects it against the rank-1 physical u32 data:
    "Number of tile assignment dimensions ... is different than the input
    rank ... u32[...]"). The pipeline roster arms audit with live dropout
    keys precisely so this injection makes them fail to compile, and the
    schedule auditor must then exit 1 naming the arm and the
    schedule-compiles law.
    """
    from ...parallel import pipeline as pl

    pl._TYPED_KEY_BOUNDARY_FIX = False
    try:
        return fn()
    finally:
        pl._TYPED_KEY_BOUNDARY_FIX = True


# One instruction definition per line: "%name = <shape> <opcode>(...". The
# instruction NAME usually embeds the opcode too (%all-gather.3), so a raw
# substring count double-counts — anchor on the "= ... opcode(" form.
# Tuple-shaped (variadic / async -start) definitions are counted once;
# async -done halves are not re-counted.
_COLLECTIVE_DEF = re.compile(
    r"= .*?\b(" + "|".join(re.escape(op) for op in COLLECTIVE_OPS)
    + r")(?:-start)?\("
)
_BF16_TO_F32_CONVERT = re.compile(r"= f32\[[^\]]*\]\S* convert\(bf16\[")


def count_collectives(hlo_text: str) -> Dict[str, int]:
    counts = {op: 0 for op in COLLECTIVE_OPS}
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_DEF.search(line)
        if m:
            counts[m.group(1)] += 1
    return counts


def _donatable_leaves(spec: ArmSpec) -> int:
    """Leaf count of (params, opt_state) — the donate_argnums=(0, 1) trees."""
    import jax

    from ...models import tinygpt
    from ...parallel import get_strategy
    from ...parallel import strategies as strat
    from ...train.step import _resolve_model_config

    strategy = get_strategy(spec.strategy)
    cfg = _resolve_model_config(_model_config(spec), strategy)
    params_shape = jax.eval_shape(
        lambda k: tinygpt.init_params(cfg, k), jax.random.key(0)
    )
    optimizer = strat.make_optimizer(strategy)
    opt_shape = jax.eval_shape(optimizer.init, params_shape)
    return len(jax.tree.leaves(params_shape)) + len(jax.tree.leaves(opt_shape))


def audit_arm(spec: ArmSpec, devices=None) -> ArmReport:
    """Lower one arm and extract its structured collective report."""
    compiled = lower_arm(spec, devices=devices)
    txt = compiled.as_text()
    collectives = count_collectives(txt)
    seq = dict(zip(spec.axes, spec.mesh_shape)).get("seq", 1)
    pipe = dict(zip(spec.axes, spec.mesh_shape)).get("pipe", 1)
    # Collective-matmul arms permute legitimately too: the ppermute ring
    # IS the fusion's comms (the exact pin still catches drift — a real
    # reshard fallback grows the frozen permute count by name).
    cmm = bool(dict(spec.config_overrides).get("tp_collective_matmul"))
    permutes_legit = seq > 1 or pipe > 1 or cmm
    return ArmReport(
        arm=spec.name,
        collectives=collectives,
        replication_reshard_suspects=(
            0 if permutes_legit else collectives["collective-permute"]
        ),
        donated_inputs=txt.count("may-alias") + txt.count("must-alias"),
        donatable_inputs=_donatable_leaves(spec),
        bf16_to_f32_converts=len(_BF16_TO_F32_CONVERT.findall(txt)),
    )


# ---------------------------------------------------------------------------
# Pipeline schedule auditor: closed-form send/recv + bubble laws
# ---------------------------------------------------------------------------

#: Pipeline arms in the audited roster — the suite's pp compositions
#: (scripts/run_all_benchmarks.sh pp2-{gpipe,1f1b,interleaved}) at the
#: interleaved-CLI mesh shape (dp=2 x pipe=2, 4 of the 8 virtual
#: devices), plus a llama-family composition so the GQA blocks audit
#: under pipeline layer sharding too. Unlike the CPU arm roster these
#: lower WITH live dropout keys (``dropout`` pinned to the family
#: default instead of the roster's dropout-free choice): the typed-key
#: shard_map boundary was the seed-old interleaved compile failure, and
#: an audit that DCEs the keys away could never catch its return —
#: ``--inject bad-pipeline-spec`` reverts exactly that fix. Dropout adds
#: RNG ops but no collectives, so the pinned schedule stays
#: deterministic. The interleaved arm runs V=2 real virtual chunks
#: (n_layer=4) so the audit covers actual interleaving, not the V=1
#: degenerate shape.
PIPELINE_ROSTER: Dict[str, ArmSpec] = {
    spec.name: spec
    for spec in (
        ArmSpec(
            "pp2-gpipe", "ddp", (2, 1, 1, 2),
            ("data", "seq", "model", "pipe"),
            global_batch=4, grad_accum=4, pipeline_schedule="gpipe",
            config_overrides=(("dropout", 0.1),),
        ),
        ArmSpec(
            "pp2-1f1b", "ddp", (2, 1, 1, 2),
            ("data", "seq", "model", "pipe"),
            global_batch=4, grad_accum=4, pipeline_schedule="1f1b",
            config_overrides=(("dropout", 0.1),),
        ),
        ArmSpec(
            "pp2-interleaved-v2", "ddp", (2, 1, 1, 2),
            ("data", "seq", "model", "pipe"),
            global_batch=4, grad_accum=4, pipeline_schedule="interleaved",
            virtual_stages=2,
            config_overrides=(("dropout", 0.1), ("n_layer", 4)),
        ),
        ArmSpec(
            "llama-pp2-1f1b", "ddp", (2, 1, 1, 2),
            ("data", "seq", "model", "pipe"),
            global_batch=4, grad_accum=4, model_family="llama",
            pipeline_schedule="1f1b",
            config_overrides=(("dropout", 0.1),),
        ),
    )
}

#: Second microbatch count each pipeline arm is audited at: the growth
#: law needs two M points to verdict the affine-in-M shape.
PIPELINE_GROWTH_M_FACTOR = 2


def expected_pipeline_permutes(
    schedule: str, stages: int, microbatches: int, virtual: int = 1
) -> int:
    """Closed-form collective-permute count of the compiled step.

    Counts are HLO *instructions* in the lowered module, which is what
    :func:`count_collectives` measures — each instruction moves every
    stage's current payload one ring hop, so the per-direction data
    movement (e.g. GPipe forward: M*(S-1) stage-to-stage sends) rides
    fewer instructions than sends:

    - **gpipe**: the Python tick loop unrolls — forward emits ticks-1 =
      M+S-2 ppermutes and ``jax.value_and_grad`` transposes each for the
      backward: 2*(M+S-2). Affine in M, slope 2.
    - **1f1b**: hand-scheduled — M+S-2 forward-ring + M+S-2
      backward-ring instructions: 2*(M+S-2). Affine in M, slope 2.
    - **interleaved**: the executor replays the schedule tables with ONE
      ``lax.scan`` tick body holding exactly one fwd-ring and one
      bwd-ring ppermute — 2 instructions regardless of M (the tick count
      lives in the scan trip count, not the instruction count). Slope 0.
    """
    S, M = stages, microbatches
    if schedule == "gpipe":
        return 2 * (M + S - 2)
    if schedule == "1f1b":
        return 2 * (M + S - 2)
    if schedule == "interleaved":
        return 2
    raise ValueError(f"unknown pipeline schedule {schedule!r}")


def pipeline_permute_slope(schedule: str) -> int:
    """d(collective-permute instructions)/dM for the affine growth law."""
    return 0 if schedule == "interleaved" else 2


def pipeline_bubble_bound(
    schedule: str, stages: int, microbatches: int, virtual: int = 1
) -> float:
    """Structural bubble-fraction upper bound for one schedule.

    The fraction of schedule capacity the fill/drain ramps waste —
    trace-measured ``bubble_frac`` (step-anatomy device idle) must not
    exceed this plus measurement slack; exceeding it means the executed
    overlap does NOT match the schedule's structure (an
    anatomy/structure mismatch, not noise):

    - **gpipe**: (S-1)/(M+S-1) for each of the forward and transposed
      backward phases — the classic fill/drain ratio.
    - **1f1b (lockstep)**: fill+drain are 2(S-1) of the M+2(S-1) ticks,
      each tick holding up to one fwd and one bwd unit:
      2(S-1)/(M+2(S-1)).
    - **interleaved**: the exact idle fraction of the (ticks x P) unit
      grid from the real scheduler tables
      (``parallel.interleaved.build_schedule().bubble_fraction``) — the
      v*S-aware variant, tighter than any closed form because the greedy
      scheduler's concrete tick count is known.
    """
    S, M = stages, microbatches
    if schedule == "gpipe":
        return (S - 1) / (M + S - 1)
    if schedule == "1f1b":
        return 2 * (S - 1) / (M + 2 * (S - 1))
    if schedule == "interleaved":
        from ...parallel.interleaved import build_schedule

        return float(build_schedule(S, virtual, M).bubble_fraction)
    raise ValueError(f"unknown pipeline schedule {schedule!r}")


@dataclasses.dataclass(frozen=True)
class PipelineAuditResult:
    """One pipeline arm's audit: counts at two M values + the law inputs.

    ``compile_error`` set (and both reports None) when the arm failed to
    lower — for pipeline arms that is a FINDING (the schedule-compiles
    law), not an operational error: these arms have a known compile-
    failure history (the seed-old interleaved bug) and the injection
    proof reverts exactly that fix.
    """

    arm: str
    schedule: str
    stages: int
    microbatches: int
    virtual: int
    grown_microbatches: int
    base: Optional[ArmReport] = None
    grown: Optional[ArmReport] = None
    compile_error: Optional[str] = None

    def to_budget_entry(self) -> Dict[str, Any]:
        assert self.base is not None and self.grown is not None
        return {
            "schedule": {
                "schedule": self.schedule,
                "stages": self.stages,
                "microbatches": self.microbatches,
                "virtual": self.virtual,
                "grown_microbatches": self.grown_microbatches,
                "expected_collective_permutes": expected_pipeline_permutes(
                    self.schedule, self.stages, self.microbatches,
                    self.virtual,
                ),
                "bubble_frac_bound": round(pipeline_bubble_bound(
                    self.schedule, self.stages, self.microbatches,
                    self.virtual,
                ), 6),
            },
            "base": self.base.to_budget_entry(),
            "grown": self.grown.to_budget_entry(),
        }


def audit_pipeline_arm(
    spec: ArmSpec, devices=None
) -> PipelineAuditResult:
    """Audit one pipeline arm at its roster M and at M*growth-factor.

    The (S, M, V) law inputs mirror ``train.step.pipeline_schedule_meta``
    (M == grad_accum — the step feeds its whole accumulation axis to the
    schedule); a test pins the two against each other so the laws cannot
    drift from what the step compiles.
    """
    pipe = dict(zip(spec.axes, spec.mesh_shape)).get("pipe", 1)
    if pipe <= 1:
        raise ValueError(
            f"arm {spec.name!r} has no >1 'pipe' axis — not a pipeline arm"
        )
    m2 = spec.grad_accum * PIPELINE_GROWTH_M_FACTOR
    meta = {
        "schedule": spec.pipeline_schedule,
        "stages": pipe,
        "microbatches": spec.grad_accum,
        "virtual": (
            spec.virtual_stages
            if spec.pipeline_schedule == "interleaved" else 1
        ),
    }
    try:
        base = audit_arm(spec, devices=devices)
        grown = audit_arm(
            dataclasses.replace(spec, grad_accum=m2), devices=devices
        )
    except Exception as e:
        msg = f"{type(e).__name__}: {e}"
        return PipelineAuditResult(
            arm=spec.name, grown_microbatches=m2,
            compile_error=msg[:500], **meta,
        )
    return PipelineAuditResult(
        arm=spec.name, grown_microbatches=m2, base=base, grown=grown,
        **meta,
    )


def pipeline_law_findings(result: PipelineAuditResult) -> List[str]:
    """The schedule laws, each named per arm + law when broken.

    - **schedule-compiles**: the arm must lower at all (the seed-old
      interleaved bug class; what ``--inject bad-pipeline-spec``
      resurrects).
    - **permute-law**: collective-permute instructions must equal the
      closed form at BOTH audited M values — the excess is the pipeline
      analogue of a replication-reshard suspect (GSPMD resharding the
      manual region's operands lowers as extra permute chains).
    - **affine-growth**: the count must grow affinely in M with the
      schedule's slope (2 for the unrolled tick loops, 0 for the
      scanned interleaved executor) — a superlinear term means
      per-microbatch resharding.
    """
    arm, sched = result.arm, result.schedule
    if result.compile_error is not None:
        return [
            f"schedule-law: {arm} VIOLATES schedule-compiles "
            f"[{sched} S={result.stages} M={result.microbatches} "
            f"V={result.virtual}]: {result.compile_error}"
        ]
    findings: List[str] = []
    for label, rep, m in (
        ("base", result.base, result.microbatches),
        ("grown", result.grown, result.grown_microbatches),
    ):
        want = expected_pipeline_permutes(
            sched, result.stages, m, result.virtual
        )
        got = rep.collectives.get("collective-permute", 0)
        if got != want:
            findings.append(
                f"schedule-law: {arm} VIOLATES permute-law at {label} "
                f"M={m}: {got} collective-permutes != closed-form {want} "
                f"for {sched}(S={result.stages}, V={result.virtual}) — "
                f"{max(got - want, 0)} excess permute(s) are pipeline "
                "reshard suspects"
            )
    d_got = (
        result.grown.collectives.get("collective-permute", 0)
        - result.base.collectives.get("collective-permute", 0)
    )
    d_m = result.grown_microbatches - result.microbatches
    slope = pipeline_permute_slope(sched)
    if d_got != slope * d_m:
        findings.append(
            f"schedule-law: {arm} VIOLATES affine-growth: permutes grew "
            f"{d_got:+d} over {d_m:+d} microbatches (expected slope "
            f"{slope}/microbatch for {sched})"
        )
    return findings


def write_pipeline_budgets(
    results: List[PipelineAuditResult],
    path: str = DEFAULT_BUDGETS_PATH,
    existing: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Freeze pipeline-arm budgets into the ``pipeline_schedules`` section.

    Merges over the existing document — the CPU arm roster and the
    topology tiers pass through byte-unchanged, mirroring
    :func:`write_budgets` / :func:`write_topology_budgets`.
    """
    import jax

    failed = [r.arm for r in results if r.compile_error is not None]
    if failed:
        raise ValueError(
            "refusing to freeze pipeline budgets with arms that failed "
            f"to compile: {failed}"
        )
    doc = (
        dict(existing) if existing is not None
        else (load_budgets(path) if os.path.exists(path) else {"arms": {}})
    )
    section = dict(doc.get("pipeline_schedules", {}))
    arms = dict(section.get("arms", {}))
    frozen = section.get("jax_version")
    if frozen is not None and frozen != jax.__version__:
        # Same refusal as write_budgets: merging fresh counts over arms
        # frozen on a different jax and restamping the section's version
        # would claim incomparable counts are commensurable.
        regenerated = {r.arm for r in results}
        stale = set(arms) - regenerated
        if stale:
            raise ValueError(
                f"pipeline_schedules budgets were frozen on jax {frozen} "
                f"but this is jax {jax.__version__}: a partial --arms "
                "regeneration would mix incomparable counts — regenerate "
                f"the full pipeline roster (missing: {sorted(stale)})"
            )
        arms = {}
    for r in results:
        arms[r.arm] = r.to_budget_entry()
    doc["pipeline_schedules"] = {
        "jax_version": jax.__version__,
        "arms": arms,
    }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return doc


def diff_pipeline_against_budget(
    result: PipelineAuditResult, budgets: Dict[str, Any]
) -> List[str]:
    """Law findings + exact-pin diffs for one pipeline arm.

    The laws run unconditionally (they need no frozen state); the pins
    then hold the full collective/donation/convert profile at both M
    values against the frozen ``pipeline_schedules`` budgets, so even a
    law-respecting drift (e.g. +2 all-reduces) fails loudly.
    """
    findings = pipeline_law_findings(result)
    if result.compile_error is not None:
        return findings
    section = budgets.get("pipeline_schedules", {})
    arm_budget = section.get("arms", {}).get(result.arm)
    if arm_budget is None:
        return findings + [
            f"{result.arm}: no frozen pipeline_schedules budget for this "
            "arm (run --update-budgets to freeze one)"
        ]
    frozen_meta = dict(arm_budget.get("schedule", {}))
    live_meta = result.to_budget_entry()["schedule"]
    if frozen_meta != live_meta:
        findings.append(
            f"{result.arm}: schedule metadata drifted from the frozen "
            f"budget ({frozen_meta} != {live_meta}) — regenerate with "
            "--update-budgets and review"
        )
    for label, rep in (("base", result.base), ("grown", result.grown)):
        scoped = {"arms": {result.arm: arm_budget.get(label, {})}}
        findings.extend(
            f"{label}: {d}" for d in diff_against_budget(rep, scoped)
        )
    return findings


# ---------------------------------------------------------------------------
# GC110: the memory-budget audit (compile-time memory anatomy, frozen)
# ---------------------------------------------------------------------------

#: Slack the per-chip XLA temp bytes may grow along the data axis before
#: the GC110 temp-flat growth law fires. Weak scaling keeps per-chip work
#: constant, so temps should be flat; a few percent covers partitioner
#: padding differences between tier shapes.
MEMORY_TEMP_FLAT_TOL = 0.10


@dataclasses.dataclass(frozen=True)
class MemoryReport:
    """One arm's compile-time memory accounting — what GC110 pins.

    Bytes come from the compiled step's ``memory_analysis()`` via
    ``analysis.memory_anatomy.compile_memory_fields`` (ONE extractor for
    the static audit and the runtime reconciliation, so the two layers
    cannot disagree about what "temp bytes" means). Per-device under
    GSPMD — the module is the per-chip program.
    """

    arm: str
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    alias_bytes: int
    peak_bytes: int

    def to_budget_entry(self) -> Dict[str, Any]:
        return {
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "alias_bytes": self.alias_bytes,
            "peak_bytes": self.peak_bytes,
        }


def arm_shards_state_over_data(arm_name: str) -> bool:
    """True when the arm's strategy shards params or optimizer state over
    the 'data' axis (fsdp/zero) — the class whose per-chip argument bytes
    must SHRINK as the data axis grows (a flat curve there means the
    state is silently replicating, the exact regression GC110 exists to
    catch AOT)."""
    from ...parallel import get_strategy

    spec = ROSTER.get(arm_name) or PIPELINE_ROSTER.get(arm_name)
    if spec is None:
        raise KeyError(f"unknown arm {arm_name!r}")
    strategy = get_strategy(spec.strategy)
    return bool(
        getattr(strategy, "shard_params", False)
        or getattr(strategy, "shard_opt_state", False)
    )


def audit_arm_memory(spec: ArmSpec, devices=None) -> MemoryReport:
    """Lower one arm and extract its compile-time memory accounting."""
    from ...analysis.memory_anatomy import compile_memory_fields

    compiled = lower_arm(spec, devices=devices)
    fields = compile_memory_fields(compiled)
    if fields is None:
        raise RuntimeError(
            f"arm {spec.name!r}: backend exposes no memory_analysis() — "
            "the memory audit needs a compiler that reports buffer sizes"
        )
    return MemoryReport(
        arm=spec.name,
        argument_bytes=fields["argument_bytes"],
        output_bytes=fields["output_bytes"],
        temp_bytes=fields["temp_bytes"],
        alias_bytes=fields["alias_bytes"],
        peak_bytes=fields["peak_bytes"],
    )


def audit_topology_tier_memory(
    tier: "TopologyTier",
    arm_names: Optional[Tuple[str, ...]] = None,
    inject: Optional[str] = None,
) -> List[MemoryReport]:
    """Memory accounting of the scalable roster subset at one real tier."""
    devices = topology_devices(tier)
    reports: List[MemoryReport] = []
    for name in arm_names or TOPOLOGY_ARMS:
        spec = ROSTER.get(name) or PIPELINE_ROSTER[name]
        scaled = scale_spec_to_devices(spec, tier.device_count)
        if inject:
            scaled = dataclasses.replace(scaled, inject=inject)
        reports.append(audit_arm_memory(scaled, devices=devices))
    return reports


def write_memory_budgets(
    reports: List[MemoryReport],
    path: str = DEFAULT_BUDGETS_PATH,
    tier_reports: Optional[Dict[str, List[MemoryReport]]] = None,
) -> Dict[str, Any]:
    """Freeze GC110 budgets into the ``memory_budgets`` section.

    Merges over the existing document (the collective/pipeline/topology
    sections pass through byte-unchanged); a partial regeneration across
    jax versions refuses like :func:`write_budgets` — byte counts from
    two compilers are not commensurable.
    """
    import jax

    doc = load_budgets(path) if os.path.exists(path) else {"arms": {}}
    section = dict(doc.get("memory_budgets", {}))
    arms = dict(section.get("arms", {}))
    frozen = section.get("jax_version")
    if frozen is not None and frozen != jax.__version__ and reports:
        regenerated = {r.arm for r in reports}
        stale = set(arms) - regenerated
        if stale:
            raise ValueError(
                f"memory_budgets were frozen on jax {frozen} but this is "
                f"jax {jax.__version__}: a partial regeneration would mix "
                "incomparable byte counts — regenerate the full roster "
                f"(missing: {sorted(stale)})"
            )
        arms = {}
    for r in reports:
        arms[r.arm] = r.to_budget_entry()
    tiers = dict(section.get("topology_tiers", {}))
    for tier_name, reps in (tier_reports or {}).items():
        tier = TOPOLOGY_TIERS[tier_name]
        tiers[tier_name] = {
            "device_count": tier.device_count,
            "topology_name": tier.topology_name,
            "jax_version": jax.__version__,
            "arms": {r.arm: r.to_budget_entry() for r in reps},
        }
    doc["memory_budgets"] = {
        "jax_version": jax.__version__ if reports else section.get(
            "jax_version", jax.__version__
        ),
        "arms": arms,
        "topology_tiers": tiers,
    }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return doc


def diff_memory_against_budget(
    report: MemoryReport, budgets: Dict[str, Any],
    arms_override: Optional[Dict[str, Any]] = None,
) -> List[str]:
    """GC110 exact-pin deltas for one arm vs the frozen memory budgets.

    Same posture as the collective pins: growth of argument/output/temp/
    peak bytes REGRESSES (an accidental replication of optimizer state
    shows up as argument growth; a remat regression as temp growth),
    shrinkage is an improvement to bank; LOST donation aliasing (alias
    bytes shrinking) regresses in the other direction.
    """
    arms = (
        arms_override if arms_override is not None
        else budgets.get("memory_budgets", {}).get("arms", {})
    )
    entry = arms.get(report.arm)
    if entry is None:
        return [
            f"GC110: {report.arm}: no frozen memory budget for this arm "
            "(run --memory --update-budgets to freeze one)"
        ]
    deltas: List[str] = []

    def check(label: str, got: int, want: int, more_is_worse: bool = True):
        if got == want:
            return
        delta = got - want
        pct = 100.0 * delta / want if want else float("inf")
        if (delta > 0) == more_is_worse:
            deltas.append(
                f"GC110: {report.arm}: {label} REGRESSED {want} -> {got} "
                f"({delta:+d} bytes, {pct:+.1f}%)"
            )
        else:
            deltas.append(
                f"GC110: {report.arm}: {label} improved {want} -> {got} "
                f"({delta:+d} bytes) — bank it with --memory "
                "--update-budgets"
            )

    check("argument bytes", report.argument_bytes, entry["argument_bytes"])
    check("output bytes", report.output_bytes, entry["output_bytes"])
    check("temp bytes", report.temp_bytes, entry["temp_bytes"])
    check("donation-alias bytes", report.alias_bytes, entry["alias_bytes"],
          more_is_worse=False)
    check("buffer-assignment peak bytes", report.peak_bytes,
          entry["peak_bytes"])
    return deltas


def memory_growth_law_findings(
    per_tier: Dict[str, Dict[str, Dict[str, Any]]],
) -> List[str]:
    """GC110 cross-tier memory laws over the topology tiers.

    ``per_tier`` maps tier name -> arm -> memory budget entry (frozen
    and/or fresh — the caller overlays). Two laws, one per sharded axis
    class, each named per arm + tier pair when broken:

    - **temp-flat (dp law)**: per-chip XLA temp bytes must stay flat
      (within :data:`MEMORY_TEMP_FLAT_TOL`) as the data axis grows —
      weak scaling keeps per-chip batch constant, so growing temps mean
      per-chip activation/staging state is scaling with the MESH (a
      remat or collective-staging regression that only hurts at pod
      scale).
    - **sharded-state-shrinks (fsdp/zero law)**: arms whose strategy
      shards params/optimizer state over 'data'
      (:func:`arm_shards_state_over_data`) must show per-chip argument
      bytes strictly DECREASING as the data axis grows — a flat curve
      means the sharded state silently replicated (the exact failure
      class the ZeRO papers' memory math exists to prevent).
    """
    findings: List[str] = []
    tiers = sorted(
        (t for t in per_tier if t in TOPOLOGY_TIERS),
        key=lambda t: TOPOLOGY_TIERS[t].device_count,
    )
    arms = sorted({a for t in tiers for a in per_tier[t]})
    for arm in arms:
        present = [t for t in tiers if arm in per_tier[t]]
        try:
            shrinks = arm_shards_state_over_data(arm)
        except KeyError:
            shrinks = False
        for lo, hi in zip(present, present[1:]):
            e_lo, e_hi = per_tier[lo][arm], per_tier[hi][arm]
            t_lo = int(e_lo.get("temp_bytes", 0))
            t_hi = int(e_hi.get("temp_bytes", 0))
            if t_lo > 0 and t_hi > t_lo * (1.0 + MEMORY_TEMP_FLAT_TOL):
                findings.append(
                    f"GC110 growth-law: {arm} per-chip temp bytes grew "
                    f"{100.0 * (t_hi - t_lo) / t_lo:+.1f}% along the data "
                    f"axis ({lo}: {t_lo} -> {hi}: {t_hi}; weak scaling "
                    "must keep per-chip temps flat within "
                    f"{100 * MEMORY_TEMP_FLAT_TOL:.0f}%)"
                )
            if shrinks:
                a_lo = int(e_lo.get("argument_bytes", 0))
                a_hi = int(e_hi.get("argument_bytes", 0))
                if a_lo > 0 and a_hi >= a_lo:
                    findings.append(
                        f"GC110 growth-law: {arm} per-chip argument bytes "
                        f"did not shrink along the fsdp/zero shard axis "
                        f"({lo}: {a_lo} -> {hi}: {a_hi}) — sharded "
                        "param/optimizer state is replicating instead of "
                        "sharding"
                    )
    return findings


def commensurable_memory_tiers(
    budgets: Dict[str, Any],
    fresh_tiers: Tuple[str, ...] = (),
    jax_version: Optional[str] = None,
) -> Tuple[Dict[str, Dict[str, Dict[str, Any]]], List[str]]:
    """(per-tier memory entries with cross-version tiers dropped, dropped).

    The memory analogue of :func:`commensurable_topology_tiers`: byte
    counts from a different compiler must not enter the cross-tier laws.
    Returns the assembled ``{tier: {arm: entry}}`` view directly.
    """
    if jax_version is None:
        import jax

        jax_version = jax.__version__
    blocks = budgets.get("memory_budgets", {}).get("topology_tiers", {})
    stale = sorted(
        t for t, b in blocks.items()
        if t not in fresh_tiers
        and b.get("jax_version") not in (None, jax_version)
    )
    per_tier = {
        t: dict(b.get("arms", {}))
        for t, b in blocks.items() if t not in stale
    }
    return per_tier, stale


# ---------------------------------------------------------------------------
# Topology tiers: AOT audits of pod-scale meshes on the CPU host
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TopologyTier:
    """One auditable TPU topology the host compiles AGAINST, not ON.

    ``jax.experimental.topologies.get_topology_desc`` builds a
    compile-only PJRT client from libtpu's topology tables — no chips,
    no runtime — so a 1-core CPU host can lower the REAL train step for
    a v5e-256 mesh and read its collective schedule off the compiled
    module. The wall clock of such a run is unknowable here; its
    *structure* (collective counts, reshard suspects, donation) is
    exact, and that is what the per-tier budgets and growth laws pin.
    """

    name: str
    topology_name: str  # libtpu topology string, e.g. "v5e:8x8"
    device_count: int
    accelerator_type: str  # silences libtpu's metadata-probe warnings


TOPOLOGY_TIERS: Dict[str, TopologyTier] = {
    t.name: t
    for t in (
        TopologyTier("v5e-16", "v5e:4x4", 16, "v5litepod-16"),
        TopologyTier("v5e-64", "v5e:8x8", 64, "v5litepod-64"),
        TopologyTier("v5e-256", "v5e:16x16", 256, "v5litepod-256"),
    )
}

#: Roster arms audited per tier — the scalable subset: each scales its
#: 'data' axis (and global batch with it) to fill the tier's device
#: count, so the growth laws below have one well-defined growing axis.
#: ``pp2-gpipe`` (from PIPELINE_ROSTER) brings a pipeline composition
#: under the per-tier budgets: its pipe degree is identity, the data
#: axis absorbs the tier, and its ring-permute count must stay CONSTANT
#: as data grows (the growth laws' at-most-linear bound covers it).
#: ``llama-tp2-gqa-cmm`` (round 15) rides the same contract for the
#: collective-matmul ring: the ppermute count is a function of the tp
#: degree alone (2 hops per projection class per layer at tp=2), so it
#: must stay FLAT along the data axis — each tier's exact pin freezes
#: it, and the at-most-linear law bounds any drift between tiers.
TOPOLOGY_ARMS = (
    "zero2-dp8", "fsdp-dp8", "llama-tp2-gqa", "pp2-gpipe",
    "llama-tp2-gqa-cmm",
)

#: Tiers ``graftcheck --all`` audits by default. v5e-256 compiles in
#: ~40s+ per arm on a small host — audit it explicitly with
#: ``--topology v5e-256`` (its budgets are frozen like the others).
TOPOLOGY_DEFAULT_TIERS = ("v5e-16", "v5e-64")


class TopologyUnavailable(RuntimeError):
    """libtpu topology tables are not loadable on this host."""


def _topology_env() -> None:
    """Compile-only client env, BEFORE libtpu first loads.

    Without ``TPU_SKIP_MDS_QUERY`` libtpu retries the GCE metadata
    server for minutes on any non-GCP host; the worker vars silence the
    single-host init warnings. All setdefault — a real TPU VM's env wins.
    """
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "true")
    os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
    os.environ.setdefault("TPU_WORKER_ID", "0")
    # Compile-only clients hold no chips, but libtpu still takes the
    # host-wide lockfile on load; without this a test process auditing a
    # topology would block the CLI subprocess it spawns (and vice versa).
    os.environ.setdefault("ALLOW_MULTIPLE_LIBTPU_LOAD", "1")


#: Set once we claim TPU_ACCELERATOR_TYPE: a real TPU VM's own value is
#: never overwritten, but OUR per-tier value must not stick across tiers
#: (setdefault alone would pin the first tier's type on every later one).
_ACCEL_ENV_OWNED = "_GRAFTCHECK_OWNS_TPU_ACCELERATOR_TYPE"


def topology_devices(tier: TopologyTier):
    """The tier's compile-only device list (raises TopologyUnavailable)."""
    _topology_env()
    if (
        os.environ.get(_ACCEL_ENV_OWNED)
        or "TPU_ACCELERATOR_TYPE" not in os.environ
    ):
        os.environ["TPU_ACCELERATOR_TYPE"] = tier.accelerator_type
        os.environ[_ACCEL_ENV_OWNED] = "1"
    try:
        from jax.experimental import topologies

        topo = topologies.get_topology_desc(
            platform="tpu", topology_name=tier.topology_name
        )
        devices = list(topo.devices)
    except Exception as e:
        raise TopologyUnavailable(
            f"cannot build a compile-only client for {tier.name} "
            f"({tier.topology_name}): {type(e).__name__}: {e} — topology "
            "AOT audits need a libtpu with topology tables (the benchmark "
            "image has one; plain CPU wheels may not)"
        )
    if len(devices) != tier.device_count:
        raise TopologyUnavailable(
            f"topology {tier.topology_name} yielded {len(devices)} devices, "
            f"expected {tier.device_count}"
        )
    return devices


def topology_available() -> bool:
    """Cheap availability probe (the description is table lookup only)."""
    try:
        topology_devices(TOPOLOGY_TIERS["v5e-16"])
        return True
    except TopologyUnavailable:
        return False


def scale_spec_to_devices(spec: ArmSpec, n_devices: int) -> ArmSpec:
    """The roster arm at a tier's device count: only 'data' grows.

    The non-data axes (tp/sp/pp/ep degree) are the arm's identity; the
    data axis absorbs the tier, and the global batch scales with it so
    per-replica work is constant (weak-scaling shape — the same shape
    the scaling suite sweeps). Refuses non-divisible tiers loudly.
    """
    if "data" not in spec.axes:
        raise ValueError(f"arm {spec.name!r} has no 'data' axis to scale")
    di = spec.axes.index("data")
    other = 1
    for i, d in enumerate(spec.mesh_shape):
        if i != di:
            other *= d
    if n_devices % other:
        raise ValueError(
            f"arm {spec.name!r}: non-data axes fill {other} devices, which "
            f"does not divide the tier's {n_devices}"
        )
    new_data = n_devices // other
    old_data = spec.mesh_shape[di]
    if new_data % old_data and old_data % new_data:
        raise ValueError(
            f"arm {spec.name!r}: data axis {old_data} does not scale "
            f"evenly to {new_data}"
        )
    shape = list(spec.mesh_shape)
    shape[di] = new_data
    return dataclasses.replace(
        spec,
        mesh_shape=tuple(shape),
        global_batch=max(spec.global_batch * new_data // old_data, 1),
    )


def audit_topology_tier(
    tier: TopologyTier,
    arm_names: Optional[Tuple[str, ...]] = None,
    inject: Optional[str] = None,
) -> List[ArmReport]:
    """Audit the scalable roster subset against one tier's real topology."""
    devices = topology_devices(tier)
    reports: List[ArmReport] = []
    for name in arm_names or TOPOLOGY_ARMS:
        # Pipeline compositions live in their own roster; per-tier they
        # audit as plain count pins (the dual-M schedule laws run on the
        # CPU roster — the tier audit pins the at-scale lowering).
        spec = ROSTER.get(name) or PIPELINE_ROSTER[name]
        scaled = scale_spec_to_devices(spec, tier.device_count)
        if inject:
            scaled = dataclasses.replace(scaled, inject=inject)
        reports.append(audit_arm(scaled, devices=devices))
    return reports


def write_topology_budgets(
    tier_reports: Dict[str, List[ArmReport]],
    path: str = DEFAULT_BUDGETS_PATH,
) -> Dict[str, Any]:
    """Freeze per-tier budgets into the ``topology_tiers`` section.

    Merges over the existing file: regenerating one tier never drops
    another tier's (or the CPU roster's) budgets, and the serialization
    stays deterministic so diffs always mean a schedule change.
    """
    import jax

    doc = load_budgets(path) if os.path.exists(path) else {"arms": {}}
    topo = dict(doc.get("topology_tiers", {}))
    for tier_name, reports in tier_reports.items():
        tier = TOPOLOGY_TIERS[tier_name]
        topo[tier_name] = {
            "device_count": tier.device_count,
            "topology_name": tier.topology_name,
            "jax_version": jax.__version__,
            "arms": {rep.arm: rep.to_budget_entry() for rep in reports},
        }
    doc["topology_tiers"] = topo
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return doc


def diff_topology_against_budget(
    tier_name: str, reports: List[ArmReport], budgets: Dict[str, Any],
) -> List[str]:
    """Per-tier exact-pin diffs, mirroring :func:`diff_against_budget`."""
    tier_budget = budgets.get("topology_tiers", {}).get(tier_name)
    if tier_budget is None:
        return [
            f"{tier_name}: no frozen topology budgets for this tier "
            "(run --topology " + tier_name + " --update-budgets)"
        ]
    scoped = {"arms": tier_budget.get("arms", {})}
    out: List[str] = []
    for rep in reports:
        out.extend(
            f"{tier_name}/{d}" for d in diff_against_budget(rep, scoped)
        )
    return out


def growth_law_findings(
    per_tier: Dict[str, Dict[str, Dict[str, Any]]],
) -> List[str]:
    """Cross-tier structural laws a scalable program must obey.

    ``per_tier`` maps tier name -> arm -> budget entry (fresh reports
    and/or frozen budgets — the caller overlays). Two laws, both named
    per arm + tier + collective when broken:

    - **Reshard suspects stay zero.** A full-replication reshard
      fallback that appears at ANY tier is a scaling bug by definition —
      its cost grows with the mesh (the PR 1 GQA fallback and the PR 8
      composed-mesh fallback were exactly this class).
    - **Per-collective counts grow at most linearly in the data axis.**
      SPMD per-step collective COUNTS should be near-constant as the
      data axis grows (each instruction just spans more devices); a
      count that grows faster than the device ratio between two tiers —
      or appears from zero — means the partitioner is emitting
      per-shard chains, the structure that killed the pod-scale curves
      in the MLPerf TPU papers. Counts may always drop.
    """
    findings: List[str] = []
    tiers = sorted(
        (t for t in per_tier if t in TOPOLOGY_TIERS),
        key=lambda t: TOPOLOGY_TIERS[t].device_count,
    )
    arms = sorted({a for t in tiers for a in per_tier[t]})
    for arm in arms:
        present = [t for t in tiers if arm in per_tier[t]]
        for t in present:
            entry = per_tier[t][arm]
            suspects = int(entry.get("replication_reshard_suspects", 0))
            if suspects > 0:
                findings.append(
                    f"growth-law: {arm}@{t} has {suspects} full-replication "
                    "reshard suspect(s) — reshard suspects must stay 0 "
                    "across topology tiers (a reshard's cost grows with "
                    "the mesh)"
                )
        for lo, hi in zip(present, present[1:]):
            ratio = (
                TOPOLOGY_TIERS[hi].device_count
                / TOPOLOGY_TIERS[lo].device_count
            )
            lo_c = per_tier[lo][arm].get("collectives", {})
            hi_c = per_tier[hi][arm].get("collectives", {})
            for op in COLLECTIVE_OPS:
                n_lo, n_hi = int(lo_c.get(op, 0)), int(hi_c.get(op, 0))
                if n_lo == 0 and n_hi > 0:
                    findings.append(
                        f"growth-law: {arm} {op} appears from zero "
                        f"({lo}: 0 -> {hi}: {n_hi}) — a collective the "
                        "small mesh never needed is growing with the mesh"
                    )
                elif n_lo > 0 and n_hi > n_lo * ratio:
                    findings.append(
                        f"growth-law: {arm} {op} grows superlinearly in "
                        f"the data axis ({lo}: {n_lo} -> {hi}: {n_hi}; "
                        f"linear ceiling {int(n_lo * ratio)} at "
                        f"{ratio:g}x devices)"
                    )
    return findings


def commensurable_topology_tiers(
    budgets: Dict[str, Any],
    fresh_tiers: Tuple[str, ...] = (),
    jax_version: Optional[str] = None,
) -> Tuple[Dict[str, Any], List[str]]:
    """(budgets view with cross-version tiers dropped, dropped tier names).

    The growth laws compare counts ACROSS tiers, so overlaying a fresh
    audit on a tier frozen under a different jax would mix incomparable
    compiler outputs — minting spurious appears-from-zero/superlinear
    findings (or masking real ones), the exact cross-version mixing
    write_budgets refuses for the CPU roster. Frozen tiers whose
    ``jax_version`` differs from the running one are excluded from the
    overlay (fresh-audited tiers always stay: their counts ARE current).
    """
    if jax_version is None:
        import jax

        jax_version = jax.__version__
    blocks = budgets.get("topology_tiers", {})
    stale = sorted(
        t for t, b in blocks.items()
        if t not in fresh_tiers
        and b.get("jax_version") not in (None, jax_version)
    )
    if not stale:
        return budgets, []
    kept = {t: b for t, b in blocks.items() if t not in stale}
    return dict(budgets, topology_tiers=kept), stale


def assemble_per_tier(
    budgets: Dict[str, Any],
    fresh: Optional[Dict[str, List[ArmReport]]] = None,
) -> Dict[str, Dict[str, Dict[str, Any]]]:
    """Frozen topology budgets overlaid with fresh reports, for the
    growth laws: an audit of ONE tier still judges growth against the
    other tiers' frozen structure."""
    per_tier: Dict[str, Dict[str, Dict[str, Any]]] = {
        t: dict(block.get("arms", {}))
        for t, block in budgets.get("topology_tiers", {}).items()
    }
    for tier_name, reports in (fresh or {}).items():
        per_tier.setdefault(tier_name, {})
        per_tier[tier_name].update(
            {rep.arm: rep.to_budget_entry() for rep in reports}
        )
    return per_tier


# ---------------------------------------------------------------------------
# Budget file I/O + diffing
# ---------------------------------------------------------------------------


def load_budgets(path: str = DEFAULT_BUDGETS_PATH) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def write_budgets(
    reports: List[ArmReport], path: str = DEFAULT_BUDGETS_PATH,
    existing: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Freeze ``reports`` as the budget file (merging over ``existing`` so a
    partial ``--arms`` regeneration never drops the other arms' budgets).
    Deterministic serialization (sorted keys, fixed indent) — regenerating
    without a real change is a byte-level no-op, so budget diffs in review
    always mean something."""
    import jax

    doc: Dict[str, Any] = {
        "_comment": (
            "Frozen per-arm collective budgets — regenerate with "
            "`python -m distributed_llm_training_benchmark_framework_tpu"
            ".analysis.static --update-budgets` and review the diff. "
            "Counts are pinned on the CPU backend with 8 forced host "
            "devices; see docs/STATIC_ANALYSIS.md."
        ),
        "backend": "cpu",
        "device_count": 8,
        "jax_version": jax.__version__,
        "arms": dict((existing or {}).get("arms", {})),
    }
    if existing is not None and existing.get("topology_tiers"):
        # The topology-tier budgets are frozen by their own writer
        # (write_topology_budgets); an arm-roster regeneration must carry
        # them through untouched, not silently drop a whole section.
        doc["topology_tiers"] = existing["topology_tiers"]
    if existing is not None and existing.get("pipeline_schedules"):
        # Same carry-through contract for the pipeline-schedule budgets
        # (frozen by write_pipeline_budgets).
        doc["pipeline_schedules"] = existing["pipeline_schedules"]
    if existing is not None and existing.get("memory_budgets"):
        # ...and for the GC110 memory budgets (write_memory_budgets).
        doc["memory_budgets"] = existing["memory_budgets"]
    if existing is not None:
        # A partial regeneration on a different jax than the file was
        # frozen on would mix incomparable counts — and silently dropping
        # the stale arms would break the merge promise above, so a partial
        # regen across versions refuses with the remedy instead.
        frozen = existing.get("jax_version")
        if frozen is not None and frozen != jax.__version__:
            kept = set(existing.get("arms", {}))
            regenerated = {rep.arm for rep in reports}
            if kept - regenerated:
                raise ValueError(
                    f"budgets were frozen on jax {frozen} but this is jax "
                    f"{jax.__version__}: a partial --arms regeneration "
                    "would mix incomparable counts — regenerate the full "
                    f"roster (missing: {sorted(kept - regenerated)})"
                )
            doc["arms"] = {}
    for rep in reports:
        doc["arms"][rep.arm] = rep.to_budget_entry()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return doc


def diff_against_budget(
    report: ArmReport, budgets: Dict[str, Any]
) -> List[str]:
    """Human-readable deltas between a fresh report and the frozen budget.

    Empty list = within budget. Budgets are EXACT pins, not ceilings:
    an improvement (fewer collectives) also fails, with wording telling
    you to bank it via --update-budgets — otherwise the next regression
    hides inside the slack the improvement left behind.
    """
    arm_budget = budgets.get("arms", {}).get(report.arm)
    if arm_budget is None:
        return [
            f"{report.arm}: no frozen budget for this arm "
            "(run --update-budgets to freeze one)"
        ]
    deltas: List[str] = []

    def check(label: str, got: int, want: int, more_is_worse: bool = True):
        if got == want:
            return
        delta = got - want
        if (delta > 0) == more_is_worse:
            deltas.append(
                f"{report.arm}: {label} REGRESSED {want} -> {got} "
                f"({delta:+d} per step)"
            )
        else:
            deltas.append(
                f"{report.arm}: {label} improved {want} -> {got} "
                f"({delta:+d}) — bank it with --update-budgets"
            )

    for op in COLLECTIVE_OPS:
        check(op, report.collectives.get(op, 0), arm_budget["collectives"].get(op, 0))
    check(
        "full-replication reshard suspects",
        report.replication_reshard_suspects,
        arm_budget["replication_reshard_suspects"],
    )
    check(
        "donated inputs", report.donated_inputs, arm_budget["donated_inputs"],
        more_is_worse=False,
    )
    check(
        "donatable inputs", report.donatable_inputs,
        arm_budget["donatable_inputs"], more_is_worse=False,
    )
    check(
        "bf16->f32 converts", report.bf16_to_f32_converts,
        arm_budget["bf16_to_f32_converts"],
    )
    return deltas
