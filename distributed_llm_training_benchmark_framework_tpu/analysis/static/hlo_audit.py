"""Engine 1: the HLO collective-budget auditor.

The compiled HLO for every strategy arm is a deterministic, CPU-lowerable
artifact: ``train.step.abstract_compile_step`` compiles the REAL train-step
executable from ``ShapeDtypeStruct``s over a virtual CPU mesh (the same
machinery the auto-remat probe and ``tests/test_collective_lowering.py``
use), so regressions in collective counts, donation, and dtype promotion
are catchable in CI before any TPU time is spent. PR 1's motivating case:
a single unchased GSPMD full-replication fallback on the llama x tp GQA kv
projections cost 6 collective-permutes + 8 all-gathers per step and was
only caught by a one-off HLO test — this module makes that class of check
systematic, per arm, against frozen budgets.

Determinism contract: counts are a property of (jax/XLA version, backend,
device count, arm config). Budgets are frozen on the CPU backend with 8
forced host devices (``scripts/graftcheck.sh`` / the CLI force both); a
jax upgrade legitimately moves counts — regenerate with
``--update-budgets`` and review the diff like any other lockfile change.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, List, Mapping, Optional, Tuple

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "..")
)
DEFAULT_BUDGETS_PATH = os.path.join(REPO_ROOT, "configs", "collective_budgets.json")

#: The collective opcodes the auditor counts, in report order.
COLLECTIVE_OPS = (
    "all-gather",
    "reduce-scatter",
    "all-reduce",
    "collective-permute",
    "all-to-all",
)

_INJECTIONS = ("bad-kv-spec", "bad-fsdp-axis")


@dataclasses.dataclass(frozen=True)
class ArmSpec:
    """One auditable arm: strategy x model family x mesh geometry.

    ``config_overrides`` is a tuple of (key, value) pairs passed to the
    model-config factory (tuple, not dict, so the spec stays hashable);
    ``inject`` deliberately reintroduces a known-bad configuration for
    self-tests — 'bad-kv-spec' disables the kv-head-aligned PartitionSpec
    rule, bringing back the GQA full-replicate resharding fallback PR 1
    fixed (the auditor must flag it).
    """

    name: str
    strategy: str
    mesh_shape: Tuple[int, ...]
    axes: Tuple[str, ...]
    global_batch: int
    model_family: str = "tinygpt"
    tier: str = "S"
    seq_len: int = 64
    grad_accum: int = 1
    config_overrides: Tuple[Tuple[str, Any], ...] = ()
    inject: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class ArmReport:
    """Structured audit result for one arm — everything the budget pins."""

    arm: str
    collectives: Mapping[str, int]
    # collective-permutes in an arm whose mesh has no >1 'seq'/'pipe' axis:
    # rings and pipelines legitimately permute; a pure dp/tp/ep arm only
    # emits them when the SPMD partitioner fell back to
    # full-replicate-then-repartition resharding (the PR 1 GQA fallback
    # lowered exactly so on this jaxlib).
    replication_reshard_suspects: int
    # Donation: aliased entry-parameter buffers vs donatable leaves
    # (params + optimizer state, donate_argnums=(0, 1) in the train step).
    donated_inputs: int
    donatable_inputs: int
    # bf16 -> f32 convert instructions in the module. bf16-compute arms
    # expect a stable population (fp32 loss/accum upcasts); growth means a
    # new unintended promotion of bf16 tensors to f32.
    bf16_to_f32_converts: int

    def to_budget_entry(self) -> Dict[str, Any]:
        return {
            "collectives": dict(self.collectives),
            "replication_reshard_suspects": self.replication_reshard_suspects,
            "donated_inputs": self.donated_inputs,
            "donatable_inputs": self.donatable_inputs,
            "bf16_to_f32_converts": self.bf16_to_f32_converts,
        }


#: The audit roster: one arm per (strategy x model-family x mesh-geometry)
#: shape the suite roster exercises (scripts/run_all_benchmarks.sh), scaled
#: to tier S / seq 64 so each compiles in seconds on the CPU backend. All
#: arms assume 8 devices (the virtual-mesh test geometry).
ROSTER: Dict[str, ArmSpec] = {
    spec.name: spec
    for spec in (
        # The pure-strategy matrix at dp=8.
        ArmSpec("ddp-dp8", "ddp", (8,), ("data",), global_batch=16),
        ArmSpec("fsdp-dp8", "fsdp", (8,), ("data",), global_batch=16),
        ArmSpec("zero2-dp8", "zero2", (8,), ("data",), global_batch=16),
        ArmSpec("zero3-dp8", "zero3", (8,), ("data",), global_batch=16),
        # llama x tensor parallel — the GQA kv-alignment arm (PR 1): a
        # 'model' degree that does not divide the family's kv heads must
        # NOT trip the full-replicate resharding fallback.
        ArmSpec(
            "llama-tp2-gqa", "ddp", (1, 1, 2), ("data", "seq", "model"),
            global_batch=2, model_family="llama",
        ),
        # llama x fsdp x tp — the suite's llama-tp2 composition arm shape,
        # compiled with the UNROLLED layer loop because that is what the
        # suite actually runs (scripts/run_all_benchmarks.sh LAYER_LOOP
        # defaults to 'unrolled'; through PR 7 this arm audited the scan
        # lowering the suite never measures). Round 8 fixed the composed
        # dp x tp fsdp-axis placement (strategies._shard_largest_free_axis
        # tile-order hygiene): the 13 banked replication-reshard suspects
        # (collective-permutes against transposed device orders) are now 0.
        # `--inject bad-fsdp-axis` proves the auditor still catches the old
        # placement.
        ArmSpec(
            "llama-fsdp-dp4-tp2", "fsdp", (4, 1, 2), ("data", "seq", "model"),
            global_batch=8, model_family="llama",
            config_overrides=(("scan_layers", False),),
        ),
        # The same composition under the scan layer loop (the harness
        # default; pipeline-sharded runs and compile-time-sensitive runs
        # still use it). The round-8 spec rules cut its fallback 13 -> 4;
        # the residue is the scan-carry layout XLA picks for the stacked
        # activation stash — banked here so it cannot grow, and so a future
        # scan-carry fix shows up as a bankable improvement.
        ArmSpec(
            "llama-fsdp-dp4-tp2-scan", "fsdp", (4, 1, 2),
            ("data", "seq", "model"),
            global_batch=8, model_family="llama",
        ),
        # Sequence parallel: the ring's collective-permute hops are the
        # budgeted schedule, not a regression.
        ArmSpec(
            "zero2-sp4-ring", "zero2", (1, 4, 1), ("data", "seq", "model"),
            global_batch=2,
            config_overrides=(("attention_impl", "ring"),),
        ),
        # Expert parallel: the MoE dispatch/combine all-to-alls.
        ArmSpec(
            "zero2-ep2-moe", "zero2", (4, 1, 1, 1, 2),
            ("data", "seq", "model", "pipe", "expert"),
            global_batch=16,
            config_overrides=(("n_experts", 4),),
        ),
    )
}


def _model_config(spec: ArmSpec):
    from ...models import get_model_config
    from ...models.llama import get_llama_config

    overrides = dict(spec.config_overrides)
    # Dropout adds RNG ops whose count is batch-geometry noise; the audit
    # pins the communication schedule, so arms lower dropout-free (the same
    # choice the original HLO pin tests made).
    overrides.setdefault("dropout", 0.0)
    if spec.model_family == "llama":
        return get_llama_config(spec.tier, spec.seq_len, **overrides)
    if spec.model_family == "tinygpt":
        return get_model_config(spec.tier, spec.seq_len, **overrides)
    raise ValueError(
        f"arm {spec.name!r}: unknown model_family {spec.model_family!r}"
    )


def lower_arm(spec: ArmSpec, devices=None):
    """Compile the arm's train step abstractly; return the jax.stages.Compiled.

    Pure compiler work — no params are initialized and no device memory is
    allocated. Needs ``prod(mesh_shape)`` visible devices (the CLI forces
    8 virtual CPU devices; in-process callers run under the test mesh).
    The lowering path goes through ``train.step`` and therefore through the
    ``utils.jax_compat`` polyfills (``jax.set_mesh`` et al.), so it stays
    green on the image's jax 0.4.37; ``jax.sharding.AbstractMesh`` lowering
    is not used because the collective schedule only exists in the
    POST-partitioning executable, which requires a concrete backend to
    build.
    """
    # Idempotent: a strict no-op when the package import already installed
    # the shims or the runtime has the real APIs.
    from ...utils import jax_compat

    jax_compat.install()

    import jax

    from ...parallel import get_strategy, make_mesh
    from ...train.step import abstract_compile_step

    if spec.inject is not None and spec.inject not in _INJECTIONS:
        raise ValueError(
            f"arm {spec.name!r}: unknown injection {spec.inject!r} "
            f"(expected one of {_INJECTIONS})"
        )
    if devices is None:
        devices = jax.devices()
    n_needed = 1
    for d in spec.mesh_shape:
        n_needed *= d
    if len(devices) < n_needed:
        raise RuntimeError(
            f"arm {spec.name!r} needs {n_needed} devices, have "
            f"{len(devices)} (run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8)"
        )
    cfg = _model_config(spec)
    mesh = make_mesh(spec.mesh_shape, spec.axes, devices=devices[:n_needed])
    strategy = get_strategy(spec.strategy)

    def compile_():
        return abstract_compile_step(
            cfg, strategy, mesh,
            grad_accum=spec.grad_accum, seed=0, from_table=False,
            global_micro=spec.global_batch, seq_len=spec.seq_len,
        )

    if spec.inject == "bad-kv-spec":
        return _with_bad_kv_spec(compile_)
    if spec.inject == "bad-fsdp-axis":
        return _with_bad_fsdp_axis(compile_)
    return compile_()


def _with_bad_kv_spec(fn):
    """Run ``fn`` with the kv-head-aligned PartitionSpec rule disabled.

    Forcing ``kv_heads=None`` makes ``param_partition_specs`` column-shard
    wkv/bkv over 'model' even when the degree does not divide the kv-head
    count — the misaligned split whose consecutive-block kv repeat has no
    in-place reshard, so GSPMD falls back to full replication (measured on
    this jaxlib as collective-permute + all-gather chains). This is the
    regression the llama-tp2-gqa budget exists to catch; the injection
    exists so CI can prove the auditor catches it.
    """
    from ...parallel import strategies as strat

    real = strat.param_partition_specs

    def misaligned(params, mesh, shard, kv_heads=None):
        return real(params, mesh, shard=shard, kv_heads=None)

    strat.param_partition_specs = misaligned
    try:
        return fn()
    finally:
        strat.param_partition_specs = real


def _with_bad_fsdp_axis(fn):
    """Run ``fn`` with the composed dp x tp fsdp-axis hygiene disabled.

    Reverts ``strategies._shard_largest_free_axis`` to the pre-round-8
    unrestricted largest-free-axis placement: fsdp 'data' lands AFTER the
    leaf's 'model' axis on row-parallel/vocab leaves (wo/wproj/wte/
    lm_head), producing the transposed device-order tilings whose reshard
    chains lowered as 13 collective-permutes per step on the
    llama-fsdp-dp4-tp2 arm. The audit must flag the regression; the
    injection exists so CI can prove it does.
    """
    from ...parallel import strategies as strat

    strat._COMPOSED_FSDP_HYGIENE = False
    try:
        return fn()
    finally:
        strat._COMPOSED_FSDP_HYGIENE = True


# One instruction definition per line: "%name = <shape> <opcode>(...". The
# instruction NAME usually embeds the opcode too (%all-gather.3), so a raw
# substring count double-counts — anchor on the "= ... opcode(" form.
# Tuple-shaped (variadic / async -start) definitions are counted once;
# async -done halves are not re-counted.
_COLLECTIVE_DEF = re.compile(
    r"= .*?\b(" + "|".join(re.escape(op) for op in COLLECTIVE_OPS)
    + r")(?:-start)?\("
)
_BF16_TO_F32_CONVERT = re.compile(r"= f32\[[^\]]*\]\S* convert\(bf16\[")


def count_collectives(hlo_text: str) -> Dict[str, int]:
    counts = {op: 0 for op in COLLECTIVE_OPS}
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_DEF.search(line)
        if m:
            counts[m.group(1)] += 1
    return counts


def _donatable_leaves(spec: ArmSpec) -> int:
    """Leaf count of (params, opt_state) — the donate_argnums=(0, 1) trees."""
    import jax

    from ...models import tinygpt
    from ...parallel import get_strategy
    from ...parallel import strategies as strat
    from ...train.step import _resolve_model_config

    strategy = get_strategy(spec.strategy)
    cfg = _resolve_model_config(_model_config(spec), strategy)
    params_shape = jax.eval_shape(
        lambda k: tinygpt.init_params(cfg, k), jax.random.key(0)
    )
    optimizer = strat.make_optimizer(strategy)
    opt_shape = jax.eval_shape(optimizer.init, params_shape)
    return len(jax.tree.leaves(params_shape)) + len(jax.tree.leaves(opt_shape))


def audit_arm(spec: ArmSpec, devices=None) -> ArmReport:
    """Lower one arm and extract its structured collective report."""
    compiled = lower_arm(spec, devices=devices)
    txt = compiled.as_text()
    collectives = count_collectives(txt)
    seq = dict(zip(spec.axes, spec.mesh_shape)).get("seq", 1)
    pipe = dict(zip(spec.axes, spec.mesh_shape)).get("pipe", 1)
    permutes_legit = seq > 1 or pipe > 1
    return ArmReport(
        arm=spec.name,
        collectives=collectives,
        replication_reshard_suspects=(
            0 if permutes_legit else collectives["collective-permute"]
        ),
        donated_inputs=txt.count("may-alias") + txt.count("must-alias"),
        donatable_inputs=_donatable_leaves(spec),
        bf16_to_f32_converts=len(_BF16_TO_F32_CONVERT.findall(txt)),
    )


# ---------------------------------------------------------------------------
# Budget file I/O + diffing
# ---------------------------------------------------------------------------


def load_budgets(path: str = DEFAULT_BUDGETS_PATH) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def write_budgets(
    reports: List[ArmReport], path: str = DEFAULT_BUDGETS_PATH,
    existing: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Freeze ``reports`` as the budget file (merging over ``existing`` so a
    partial ``--arms`` regeneration never drops the other arms' budgets).
    Deterministic serialization (sorted keys, fixed indent) — regenerating
    without a real change is a byte-level no-op, so budget diffs in review
    always mean something."""
    import jax

    doc: Dict[str, Any] = {
        "_comment": (
            "Frozen per-arm collective budgets — regenerate with "
            "`python -m distributed_llm_training_benchmark_framework_tpu"
            ".analysis.static --update-budgets` and review the diff. "
            "Counts are pinned on the CPU backend with 8 forced host "
            "devices; see docs/STATIC_ANALYSIS.md."
        ),
        "backend": "cpu",
        "device_count": 8,
        "jax_version": jax.__version__,
        "arms": dict((existing or {}).get("arms", {})),
    }
    if existing is not None:
        # A partial regeneration on a different jax than the file was
        # frozen on would mix incomparable counts — and silently dropping
        # the stale arms would break the merge promise above, so a partial
        # regen across versions refuses with the remedy instead.
        frozen = existing.get("jax_version")
        if frozen is not None and frozen != jax.__version__:
            kept = set(existing.get("arms", {}))
            regenerated = {rep.arm for rep in reports}
            if kept - regenerated:
                raise ValueError(
                    f"budgets were frozen on jax {frozen} but this is jax "
                    f"{jax.__version__}: a partial --arms regeneration "
                    "would mix incomparable counts — regenerate the full "
                    f"roster (missing: {sorted(kept - regenerated)})"
                )
            doc["arms"] = {}
    for rep in reports:
        doc["arms"][rep.arm] = rep.to_budget_entry()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return doc


def diff_against_budget(
    report: ArmReport, budgets: Dict[str, Any]
) -> List[str]:
    """Human-readable deltas between a fresh report and the frozen budget.

    Empty list = within budget. Budgets are EXACT pins, not ceilings:
    an improvement (fewer collectives) also fails, with wording telling
    you to bank it via --update-budgets — otherwise the next regression
    hides inside the slack the improvement left behind.
    """
    arm_budget = budgets.get("arms", {}).get(report.arm)
    if arm_budget is None:
        return [
            f"{report.arm}: no frozen budget for this arm "
            "(run --update-budgets to freeze one)"
        ]
    deltas: List[str] = []

    def check(label: str, got: int, want: int, more_is_worse: bool = True):
        if got == want:
            return
        delta = got - want
        if (delta > 0) == more_is_worse:
            deltas.append(
                f"{report.arm}: {label} REGRESSED {want} -> {got} "
                f"({delta:+d} per step)"
            )
        else:
            deltas.append(
                f"{report.arm}: {label} improved {want} -> {got} "
                f"({delta:+d}) — bank it with --update-budgets"
            )

    for op in COLLECTIVE_OPS:
        check(op, report.collectives.get(op, 0), arm_budget["collectives"].get(op, 0))
    check(
        "full-replication reshard suspects",
        report.replication_reshard_suspects,
        arm_budget["replication_reshard_suspects"],
    )
    check(
        "donated inputs", report.donated_inputs, arm_budget["donated_inputs"],
        more_is_worse=False,
    )
    check(
        "donatable inputs", report.donatable_inputs,
        arm_budget["donatable_inputs"], more_is_worse=False,
    )
    check(
        "bf16->f32 converts", report.bf16_to_f32_converts,
        arm_budget["bf16_to_f32_converts"],
    )
    return deltas
