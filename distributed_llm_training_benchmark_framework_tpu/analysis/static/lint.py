"""Engine 2: repo-specific AST lint rules over the package source.

Not a general Python linter — every rule encodes a JAX hot-path or
deployment invariant this codebase has already paid for once:

- GC101  ``jax.jit`` in ``train/``/``models/`` without ``donate_argnums``
         or ``out_shardings``: an undonated jit of params-sized state
         doubles its HBM footprint, and missing out_shardings lets GSPMD
         choose layouts the budgets never audited.
- GC102  host-sync calls (``.item()``, ``float()``, ``np.asarray``,
         ``jax.device_get``) inside the timed ``for step`` loop in
         ``train/loop.py``: each one fences the device per step and
         corrupts the published step timing (the loop's whole design is
         sync-window batching — see its timing-discipline note).
- GC103  ``with_sharding_constraint`` specs naming mesh axes that no mesh
         in the package defines: GSPMD treats an unknown axis name as
         simply unconstrained, so the typo'd constraint silently no-ops.
- GC104  ``time.time()`` in jit-adjacent modules (``train/``, ``models/``,
         ``ops/``, ``parallel/``): under trace it constant-folds to the
         trace-time clock; host-side timing uses ``time.perf_counter``.
- GC105  telemetry/file-IO/print calls inside the timed ``for step`` loop
         of ``train/loop.py`` that are not fenced at a ``sync_window``
         boundary: the flight recorder (telemetry/) writes JSONL and
         heartbeats, and the ONLY sanctioned cadence is the sync-window
         boundary — unfenced host IO mid-window lands inside the very
         step times the loop publishes.
- GC106  signal-handler installation or blocking file IO (fsync-class)
         inside the timed ``for step`` loop of ``train/loop.py``: the
         SIGTERM preemption handler must be installed OUTSIDE the loop
         (a handler interrupting arbitrary bytecode mid-commit is how
         torn state happens), and fsync/fdatasync block the host thread
         for device-unrelated milliseconds inside published step times.
- GC107  dtype-less ``jnp.asarray``/``jnp.array``/constant constructors
         (``jnp.ones``/``jnp.zeros``/``jnp.empty``/``jnp.full``) inside
         jitted model code (``models/``, ``train/step.py``): the default
         dtype is float32, and one f32 constant silently promotes the
         surrounding bf16 arithmetic — exactly the bf16->f32 convert
         chains the HLO auditor budgets (``bf16_to_f32_converts``).
- GC108  collective/axis-query calls (``psum``/``ppermute``/
         ``all_gather``/...) inside a ``shard_map`` body naming a literal
         axis outside the site's fully-literal ``axis_names`` set: the
         bad axis only raises at trace time, deep inside a jit. Sites
         whose axis set is not fully static are skipped, never guessed.
- GC111  blocking file IO (``open``/``.read()``/``.seek()``-class),
         host-iterator ``next()`` pulls, or ``time.sleep`` inside a
         timed ``for step`` loop in ``data/`` or ``train/`` with no
         sync_window fence earlier in the block and outside the
         prefetch fence: the streaming data path's ONE sanctioned
         blocking pull is the prefetcher's ``get()`` (receiver named
         ``*prefetch*``) — any other host read inside the loop
         serializes input IO into the very step times the loop
         publishes (the regression ``data_stall_frac`` exists to
         measure, not to hide).
- GC109  ``with_sharding_constraint``/``device_put``/host-sync calls
         inside a per-microbatch Python loop (``for _ in range(...)``)
         in ``parallel/``: the pipeline tick loops unroll at trace time,
         so one such call becomes M per-microbatch reshards (or M device
         fences) in the compiled step — the per-microbatch reshard
         hazard the schedule auditor's growth laws exist to catch.
- GC201  entrypoint<->harness flag-surface drift (PR 1's detector, now a
         registry rule): every ``train/harness.py`` flag must be reachable
         from the container env in ``docker/entrypoint.sh`` and vice versa.

Suppression: append ``# graftcheck: disable=GC101`` (comma-separated ids,
or ``all``) on the offending line or the line above it.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from .hlo_audit import REPO_ROOT

PACKAGE = "distributed_llm_training_benchmark_framework_tpu"

#: Harness flags deliberately NOT reachable from the container env, with the
#: reason each is exempt from GC201 (moved here from the PR 1 ad-hoc test so
#: there is exactly one registry):
#:   --local-rank        accepted for reference-CLI parity only; device
#:                       selection is mesh-driven on TPU (harness help text)
#:   --deepspeed-config  alias of --strategy-config, which the entrypoint
#:   --fsdp-config       already sets for the ZeRO arms
ENTRYPOINT_EXEMPT_FLAGS = frozenset(
    {"--local-rank", "--deepspeed-config", "--fsdp-config"}
)

#: Flags the entrypoint passes to scripts/with_retries.sh (the retry
#: wrapper it execs in retry mode) — wrapper surface, not harness surface,
#: so they are neither "stale" nor expected in build_parser().
ENTRYPOINT_WRAPPER_FLAGS = frozenset(
    {"--drop-on-retry", "--resume-flag"}
)


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    description: str
    fix_hint: str


@dataclasses.dataclass(frozen=True)
class Violation:
    rule_id: str
    path: str  # repo-relative
    line: int
    message: str
    fix_hint: str

    def __str__(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.rule_id} {self.message}\n"
            f"    fix: {self.fix_hint}"
        )


RULES: Dict[str, Rule] = {}
_CHECKS: List[Tuple[Rule, Callable]] = []


def _rule(id: str, name: str, description: str, fix_hint: str):
    def register(fn):
        rule = Rule(id=id, name=name, description=description, fix_hint=fix_hint)
        RULES[id] = rule
        _CHECKS.append((rule, fn))
        return fn

    return register


# ---------------------------------------------------------------------------
# Shared source helpers
# ---------------------------------------------------------------------------


class _Tree:
    def __init__(self, path: str, rel: str):
        with open(path) as f:
            self.source = f.read()
        self.rel = rel
        self.lines = self.source.splitlines()
        self.ast = ast.parse(self.source, filename=rel)


def _package_files(root: str, subdirs: Tuple[str, ...]) -> Iterator[_Tree]:
    for sub in subdirs:
        base = os.path.join(root, PACKAGE, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                yield _Tree(path, os.path.relpath(path, root))


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_SUPPRESS = re.compile(r"#\s*graftcheck:\s*disable=([A-Za-z0-9_,\s]+)")


def _timed_loops(tree_ast: ast.AST) -> Iterator[ast.For]:
    """Every `for step in ...` loop — the timed-loop shape GC102/105/106
    police in train/loop.py."""
    for n in ast.walk(tree_ast):
        if (
            isinstance(n, ast.For)
            and isinstance(n.target, ast.Name)
            and n.target.id == "step"
        ):
            yield n


def _contains_sync(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and _dotted(n.func) in (
            "sync_window", "self.sync_window"
        ):
            return True
    return False


def _stmt_calls(stmt: ast.AST) -> Iterator[ast.Call]:
    """Calls directly in ``stmt``, excluding nested function defs
    (sync_window-style boundary helpers are the sanctioned fenced
    context themselves)."""
    stack = [stmt]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _iter_timed_loop_calls(tree: "_Tree") -> Iterator[Tuple[ast.Call, bool]]:
    """(call, fenced) for every call inside the file's timed loops.

    The ONE fence walk GC105 and GC106 share (a fix to its semantics must
    never be applied twice): statement-ordered traversal where a
    statement whose subtree calls ``sync_window`` fences everything AFTER
    it in the same block (and in blocks nested under those later
    statements); compound statements pass the current flag down to their
    bodies, and their test/iter/with-item expressions are scanned
    directly (``with open(...)`` is IO too). Conservative in the right
    direction: a fence from a previous loop iteration never carries over.
    Rules decide what the flag means — GC105 ignores fenced calls
    entirely, GC106 flags signal installs through fences.
    """

    def walk_block(stmts, fenced: bool):
        for stmt in stmts:
            if isinstance(stmt, (ast.If, ast.With, ast.Try, ast.For,
                                 ast.While)):
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        yield from walk_block(sub, fenced)
                for handler in getattr(stmt, "handlers", []):
                    yield from walk_block(handler.body, fenced)
                scan_nodes = [getattr(stmt, "test", None),
                              getattr(stmt, "iter", None)]
                scan_nodes += [
                    item.context_expr for item in getattr(stmt, "items", [])
                ]
                calls = [
                    c for n in scan_nodes if n is not None
                    for c in _stmt_calls(n)
                ]
            else:
                calls = list(_stmt_calls(stmt))
            for call in calls:
                yield call, fenced
            if _contains_sync(stmt):
                fenced = True

    for loop in _timed_loops(tree.ast):
        yield from walk_block(loop.body, False)


def _suppressed(tree: _Tree, line: int, rule_id: str) -> bool:
    for ln in (line, line - 1):
        if 1 <= ln <= len(tree.lines):
            m = _SUPPRESS.search(tree.lines[ln - 1])
            if m:
                ids = {t.strip() for t in m.group(1).split(",")}
                if rule_id in ids or "all" in ids:
                    return True
    return False


# ---------------------------------------------------------------------------
# GC101: jit donation / out_shardings discipline
# ---------------------------------------------------------------------------


@_rule(
    "GC101",
    "jit-missing-donation-or-out-shardings",
    "jax.jit in train/ or models/ without donate_argnums/donate_argnames "
    "or out_shardings",
    "pass donate_argnums= (state the jit updates in place) or out_shardings= "
    "(pin the layout the budgets audit); suppress deliberate diagnostics "
    "with '# graftcheck: disable=GC101'",
)
def _check_jit_discipline(root: str) -> Iterator[Violation]:
    ok_kwargs = {"donate_argnums", "donate_argnames", "out_shardings"}
    for tree in _package_files(root, ("train", "models")):
        for node in ast.walk(tree.ast):
            if not (
                isinstance(node, ast.Call)
                and _dotted(node.func) in ("jax.jit", "jit")
            ):
                continue
            if any(kw.arg in ok_kwargs for kw in node.keywords):
                continue
            if _suppressed(tree, node.lineno, "GC101"):
                continue
            yield Violation(
                "GC101", tree.rel, node.lineno,
                "jax.jit call carries neither donate_argnums/donate_argnames "
                "nor out_shardings",
                RULES["GC101"].fix_hint,
            )


# ---------------------------------------------------------------------------
# GC102: host syncs inside the timed loop
# ---------------------------------------------------------------------------

@_rule(
    "GC102",
    "host-sync-in-timed-loop",
    "host-synchronizing call inside the timed `for step` loop of "
    "train/loop.py",
    "move the sync to a sync_window boundary (the loop already batches "
    "syncs every --sync-every steps); never fetch per-step values mid-window",
)
def _check_timed_loop_syncs(root: str) -> Iterator[Violation]:
    path = os.path.join(root, PACKAGE, "train", "loop.py")
    if not os.path.exists(path):
        return
    tree = _Tree(path, os.path.relpath(path, root))

    def body_calls(for_node):
        # Lexical scope only (no fence concept: a host sync is hostile at
        # ANY cadence inside the loop body — fenced syncs live INSIDE the
        # sync_window helper, which _stmt_calls excludes as a nested def).
        for stmt in for_node.body:
            yield from _stmt_calls(stmt)

    for loop in _timed_loops(tree.ast):
        for call in body_calls(loop):
            name = _dotted(call.func)
            kind = None
            if name in ("float", "int") and call.args:
                kind = ".item()-class host sync"
            elif name in ("np.asarray", "numpy.asarray", "np.array",
                          "jax.device_get"):
                kind = "device->host transfer"
            elif (
                isinstance(call.func, ast.Attribute)
                and call.func.attr == "item"
            ):
                kind = ".item() host sync"
            if kind and not _suppressed(tree, call.lineno, "GC102"):
                yield Violation(
                    "GC102", tree.rel, call.lineno,
                    f"{name or call.func.attr}(...) is a {kind} inside the "
                    "timed step loop",
                    RULES["GC102"].fix_hint,
                )


# ---------------------------------------------------------------------------
# GC105: unfenced telemetry / file IO / prints in the timed loop
# ---------------------------------------------------------------------------


def _is_telemetry_io_call(call: ast.Call) -> Optional[str]:
    """Classify a call as loop-hostile IO, or None.

    Targets: ``print``/``open``/``os.write``/``json.dump``, any
    ``*.write()``/``.writelines()``/``.flush()`` method, and any call on a
    receiver whose name mentions ``recorder``/``telemetry`` (the flight
    recorder's surface). Device work and pure bookkeeping stay out of
    scope — the rule polices host IO cadence, not computation.
    """
    name = _dotted(call.func)
    if name in ("print", "open", "os.write", "json.dump", "json.dumps"):
        # json.dumps is not IO itself, but in the timed loop it only ever
        # exists to feed a write — flag the serialization too.
        return f"{name}() host IO"
    if isinstance(call.func, ast.Attribute):
        if call.func.attr in ("write", "writelines", "flush"):
            return f".{call.func.attr}() file IO"
        recv = _dotted(call.func.value) or ""
        if "recorder" in recv.lower() or "telemetry" in recv.lower():
            return f"telemetry call {recv}.{call.func.attr}()"
    return None


@_rule(
    "GC105",
    "unfenced-telemetry-io-in-timed-loop",
    "telemetry/file-IO/print call inside the timed `for step` loop of "
    "train/loop.py with no sync_window fence earlier in its block — host "
    "IO mid-window skews the very step times the loop publishes",
    "emit telemetry from inside sync_window (the sanctioned boundary), or "
    "place the call after a sync_window(...) fence in the same block; "
    "suppress deliberate exceptions with '# graftcheck: disable=GC105'",
)
def _check_timed_loop_telemetry_io(root: str) -> Iterator[Violation]:
    path = os.path.join(root, PACKAGE, "train", "loop.py")
    if not os.path.exists(path):
        return
    tree = _Tree(path, os.path.relpath(path, root))
    for call, fenced in _iter_timed_loop_calls(tree):
        if fenced:
            continue
        kind = _is_telemetry_io_call(call)
        if kind and not _suppressed(tree, call.lineno, "GC105"):
            yield Violation(
                "GC105", tree.rel, call.lineno,
                f"{kind} inside the timed step loop with no "
                "sync_window fence earlier in its block",
                RULES["GC105"].fix_hint,
            )


# ---------------------------------------------------------------------------
# GC106: signal handlers / blocking file IO in the timed loop
# ---------------------------------------------------------------------------

#: Handler-installation calls: flagged ANYWHERE inside the timed loop,
#: fenced or not — a handler swap has no business at any step cadence
#: (install once, outside; faults/preemption.py is the sanctioned home).
_SIGNAL_CALLS = frozenset({
    "signal.signal", "signal.setitimer", "signal.siginterrupt",
    "signal.pthread_sigmask", "signal.sigwait", "signal.sigtimedwait",
})
#: Blocking file IO: flagged unless fenced by a sync_window earlier in
#: the block (same fence rule as GC105's telemetry IO).
_BLOCKING_IO_CALLS = frozenset({
    "os.fsync", "os.fdatasync", "os.sync",
    "shutil.copy", "shutil.copy2", "shutil.copytree", "shutil.move",
})


@_rule(
    "GC106",
    "signal-handler-or-blocking-io-in-timed-loop",
    "signal-handler installation (anywhere) or unfenced blocking file IO "
    "(fsync-class) inside the timed `for step` loop of train/loop.py — "
    "the SIGTERM handler must live outside the loop (faults.PreemptionGuard "
    "installs it before the first dispatch), and fsync blocks the host "
    "thread inside published step times",
    "install signal handlers once, before the loop (faults/preemption.py); "
    "move fsync-class IO behind a sync_window fence (runtime/checkpoint.py "
    "owns durable writes at checkpoint boundaries); suppress deliberate "
    "exceptions with '# graftcheck: disable=GC106'",
)
def _check_timed_loop_signal_and_blocking_io(root: str) -> Iterator[Violation]:
    path = os.path.join(root, PACKAGE, "train", "loop.py")
    if not os.path.exists(path):
        return
    tree = _Tree(path, os.path.relpath(path, root))
    # Same fence walk as GC105 (shared _iter_timed_loop_calls); the rules
    # differ only in classification — signal installs ignore the fence.
    for call, fenced in _iter_timed_loop_calls(tree):
        name = _dotted(call.func)
        if name in _SIGNAL_CALLS:
            if not _suppressed(tree, call.lineno, "GC106"):
                yield Violation(
                    "GC106", tree.rel, call.lineno,
                    f"{name}(...) installs/changes a signal handler "
                    "inside the timed step loop",
                    RULES["GC106"].fix_hint,
                )
        elif (
            name in _BLOCKING_IO_CALLS and not fenced
            and not _suppressed(tree, call.lineno, "GC106")
        ):
            yield Violation(
                "GC106", tree.rel, call.lineno,
                f"{name}(...) is blocking file IO inside the timed "
                "step loop with no sync_window fence earlier in its "
                "block",
                RULES["GC106"].fix_hint,
            )


# ---------------------------------------------------------------------------
# GC111: blocking input IO / host-iterator pulls in the timed loop
# ---------------------------------------------------------------------------

#: Dotted-name calls GC111 classifies as blocking input IO. ``next`` is
#: the host-iterator pull (a DataLoader-style ``next(it)`` inside the
#: loop is exactly the serialization the prefetcher exists to remove);
#: ``time.sleep`` is an explicit stall.
_GC111_IO_NAMES = frozenset({
    "open", "io.open", "os.read", "os.pread", "time.sleep",
})
#: Attribute calls (``f.read()``/``f.seek()``-class) GC111 flags unless
#: the receiver is the sanctioned prefetch surface.
_GC111_ATTR_IO = frozenset({
    "read", "readline", "readlines", "readinto", "seek",
})


def _is_blocking_data_io(call: ast.Call) -> Optional[str]:
    """Classify a call as loop-hostile input IO, or None.

    The prefetch fence: any call whose receiver name mentions
    ``prefetch`` is the sanctioned blocking pull (data/prefetch.py
    ``HostPrefetcher.get`` — it measures its own wait into
    ``data_stall_frac``) and is never flagged.
    """
    name = _dotted(call.func)
    if name in _GC111_IO_NAMES:
        return f"{name}() blocking host IO"
    if name == "next" and call.args:
        return "next() host-iterator pull"
    if isinstance(call.func, ast.Attribute):
        recv = _dotted(call.func.value) or ""
        if "prefetch" in recv.lower():
            return None  # the sanctioned fence itself
        if call.func.attr in _GC111_ATTR_IO:
            return f".{call.func.attr}() blocking file IO"
    return None


@_rule(
    "GC111",
    "blocking-input-io-in-timed-loop",
    "blocking file IO / host-iterator next() / time.sleep inside a timed "
    "`for step` loop in data/ or train/ with no sync_window fence earlier "
    "in its block and outside the prefetch fence — input IO serialized "
    "into the timed loop lands inside the very step times the loop "
    "publishes (the starvation data_stall_frac exists to MEASURE)",
    "pull batches through the host prefetcher (data/prefetch.py "
    "HostPrefetcher.get — the sanctioned, wait-measured fence), or move "
    "the IO behind a sync_window fence; suppress deliberate exceptions "
    "with '# graftcheck: disable=GC111'",
)
def _check_timed_loop_blocking_input_io(root: str) -> Iterator[Violation]:
    for tree in _package_files(root, ("data", "train")):
        # Same fence walk as GC105/GC106 (shared _iter_timed_loop_calls):
        # a sync_window earlier in the block fences what follows; files
        # without a sync_window helper simply never fence.
        for call, fenced in _iter_timed_loop_calls(tree):
            if fenced:
                continue
            kind = _is_blocking_data_io(call)
            if kind and not _suppressed(tree, call.lineno, "GC111"):
                yield Violation(
                    "GC111", tree.rel, call.lineno,
                    f"{kind} inside the timed step loop with no "
                    "sync_window fence earlier in its block (and outside "
                    "the prefetch fence)",
                    RULES["GC111"].fix_hint,
                )


# ---------------------------------------------------------------------------
# GC103: unknown mesh axes in sharding-constraint specs
# ---------------------------------------------------------------------------


def known_mesh_axes(root: str) -> frozenset:
    """Axis names any mesh in the package can define: the ``MeshAxes``
    canon in parallel/mesh.py plus every literal axis-name tuple passed to
    ``make_mesh``/``Mesh`` anywhere in the package (which is how 'expert'
    enters — the loop builds a 5-axis mesh)."""
    axes = set()
    mesh_py = os.path.join(root, PACKAGE, "parallel", "mesh.py")
    if os.path.exists(mesh_py):
        tree = _Tree(mesh_py, "parallel/mesh.py")
        for node in ast.walk(tree.ast):
            if isinstance(node, ast.ClassDef) and node.name == "MeshAxes":
                for stmt in node.body:
                    if (
                        isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, str)
                    ):
                        axes.add(stmt.value.value)
    for tree in _package_files(root, ("",)):
        for node in ast.walk(tree.ast):
            if not (
                isinstance(node, ast.Call)
                and _dotted(node.func) in ("make_mesh", "Mesh", "jax.sharding.Mesh")
            ):
                continue
            candidates = list(node.args[1:2]) + [
                kw.value for kw in node.keywords
                if kw.arg in ("axis_names", "axis_name")
            ]
            for cand in candidates:
                if isinstance(cand, (ast.Tuple, ast.List)):
                    for el in cand.elts:
                        if isinstance(el, ast.Constant) and isinstance(el.value, str):
                            axes.add(el.value)
                elif isinstance(cand, ast.Constant) and isinstance(cand.value, str):
                    axes.add(cand.value)
    return frozenset(axes)


@_rule(
    "GC103",
    "unknown-mesh-axis-in-sharding-constraint",
    "with_sharding_constraint PartitionSpec naming an axis no package mesh "
    "defines (GSPMD silently ignores unknown axes — the constraint no-ops)",
    "use an axis from parallel/mesh.py (MeshAxes / the loop's 5-axis mesh), "
    "or add the new axis to the mesh construction first",
)
def _check_sharding_constraint_axes(root: str) -> Iterator[Violation]:
    known = known_mesh_axes(root)
    if not known:
        return
    for tree in _package_files(root, ("",)):
        for node in ast.walk(tree.ast):
            if not (
                isinstance(node, ast.Call)
                and _dotted(node.func) in (
                    "with_sharding_constraint",
                    "lax.with_sharding_constraint",
                    "jax.lax.with_sharding_constraint",
                )
            ):
                continue
            # Only literal axis names inside P(...)/PartitionSpec(...) are
            # statically checkable; computed spec trees audit elsewhere.
            for sub in ast.walk(node):
                if not (
                    isinstance(sub, ast.Call)
                    and _dotted(sub.func) in ("P", "PartitionSpec",
                                              "jax.sharding.PartitionSpec")
                ):
                    continue
                for arg in sub.args:
                    elts = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) else [arg]
                    for el in elts:
                        if (
                            isinstance(el, ast.Constant)
                            and isinstance(el.value, str)
                            and el.value not in known
                            and not _suppressed(tree, el.lineno, "GC103")
                        ):
                            yield Violation(
                                "GC103", tree.rel, el.lineno,
                                f"PartitionSpec names axis {el.value!r}; "
                                f"known mesh axes are {sorted(known)}",
                                RULES["GC103"].fix_hint,
                            )


# ---------------------------------------------------------------------------
# GC104: wall-clock reads in jit-adjacent modules
# ---------------------------------------------------------------------------


@_rule(
    "GC104",
    "time-time-in-jit-scope",
    "time.time() in a jit-adjacent module (train/, models/, ops/, "
    "parallel/) — under trace it constant-folds to the trace-time clock",
    "host-side timing uses time.perf_counter() outside jit; device timing "
    "belongs to the profiler (--profile-dir)",
)
def _check_time_time(root: str) -> Iterator[Violation]:
    for tree in _package_files(root, ("train", "models", "ops", "parallel")):
        for node in ast.walk(tree.ast):
            if (
                isinstance(node, ast.Call)
                and _dotted(node.func) == "time.time"
                and not _suppressed(tree, node.lineno, "GC104")
            ):
                yield Violation(
                    "GC104", tree.rel, node.lineno,
                    "time.time() call in jit-adjacent code",
                    RULES["GC104"].fix_hint,
                )


# ---------------------------------------------------------------------------
# GC107: implicit f32 constant promotion in jitted model code
# ---------------------------------------------------------------------------

#: Constructor -> index of the positional argument that IS the dtype (a
#: call with that many positionals has pinned it positionally, like
#: ``jnp.zeros(shape, c.param_dtype)``). ``asarray``/``array`` take dtype
#: second; ``full`` takes (shape, fill_value, dtype).
_GC107_CONSTRUCTORS = {
    "jnp.asarray": 1, "jnp.array": 1,
    "jnp.ones": 1, "jnp.zeros": 1, "jnp.empty": 1,
    "jnp.full": 2,
}


@_rule(
    "GC107",
    "implicit-f32-constant-in-model-code",
    "dtype-less jnp.asarray/jnp.array/ones/zeros/empty/full inside jitted "
    "model code (models/, train/step.py) — the float32 default silently "
    "promotes bf16 arithmetic around it, minting the bf16->f32 convert "
    "chains the collective budgets pin",
    "pass dtype= (the config's compute/param dtype, or the operand's "
    "x.dtype) so the constant joins the surrounding precision; python "
    "scalars in arithmetic stay weakly typed and need no wrapper — often "
    "the fix is deleting the jnp.asarray() entirely; suppress deliberate "
    "f32 islands (loss accumulators) with '# graftcheck: disable=GC107'",
)
def _check_implicit_f32_constants(root: str) -> Iterator[Violation]:
    targets = list(_package_files(root, ("models",)))
    step_py = os.path.join(root, PACKAGE, "train", "step.py")
    if os.path.exists(step_py):
        targets.append(_Tree(step_py, os.path.relpath(step_py, root)))
    for tree in targets:
        for node in ast.walk(tree.ast):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            dtype_pos = _GC107_CONSTRUCTORS.get(name or "")
            if dtype_pos is None:
                continue
            if len(node.args) > dtype_pos:  # dtype pinned positionally
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            if _suppressed(tree, node.lineno, "GC107"):
                continue
            yield Violation(
                "GC107", tree.rel, node.lineno,
                f"{name}(...) without a dtype defaults to float32 inside "
                "jitted model code",
                RULES["GC107"].fix_hint,
            )


# ---------------------------------------------------------------------------
# GC108: collective axis names vs the enclosing shard_map's axis set
# ---------------------------------------------------------------------------

#: Collective / axis-query callables whose axis argument GC108 checks,
#: mapped to the positional index of that argument (kwarg ``axis_name=``
#: is always honored too).
_GC108_COLLECTIVES = {
    "lax.psum": 1, "psum": 1,
    "lax.pmean": 1, "pmean": 1,
    "lax.pmax": 1, "pmax": 1,
    "lax.pmin": 1, "pmin": 1,
    "lax.ppermute": 1, "ppermute": 1,
    "lax.all_gather": 1, "all_gather": 1,
    "lax.all_to_all": 1, "all_to_all": 1,
    "lax.psum_scatter": 1, "psum_scatter": 1,
    "lax.axis_index": 0, "axis_index": 0,
    "lax.axis_size": 0, "axis_size": 0,
    "jax.lax.psum": 1, "jax.lax.pmean": 1, "jax.lax.ppermute": 1,
    "jax.lax.all_gather": 1, "jax.lax.all_to_all": 1,
}

_SHARD_MAP_NAMES = (
    "shard_map", "jax.shard_map", "jax.experimental.shard_map.shard_map",
)


def _literal_axis_names(node: ast.AST) -> List[Tuple[str, int]]:
    """(axis, lineno) for every string literal in an axis-bearing arg —
    a bare 'data', ('pipe', 'seq') tuples, lists."""
    out: List[Tuple[str, int]] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.append((node.value, node.lineno))
    elif isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append((el.value, el.lineno))
    return out


_P_NAMES = ("P", "PartitionSpec", "jax.sharding.PartitionSpec")


def _shard_map_axis_set(call: ast.Call) -> Optional[frozenset]:
    """The axis names one shard_map call site pins statically, or None.

    The set only CLOSES when the site passes a fully-literal
    ``axis_names=`` — that kwarg is shard_map's own declaration of the
    manual axes, so it is the one thing that bounds what a collective
    may legally name. Spec ``P(...)`` literals join the set as extras
    (defensive; they must be a subset of axis_names anyway), but
    without an explicit literal axis_names the set is OPEN and the site
    is skipped: axis_names defaults to ALL mesh axes, and the mesh is a
    runtime value, so spec literals alone under-approximate the legal
    set (a psum over an unnamed mesh axis would be a false positive).
    Any non-literal component — a partially-literal tuple
    (("data", extra_axis)), a spec variable, a helper call — also opens
    the set (models/moe.py's dp-conditional batch spec is the live
    example; such sites audit through the HLO engine instead).
    """
    axes: set = set()
    closed = False
    for kw in call.keywords:
        if kw.arg == "axis_names":
            found = _literal_axis_names(kw.value)
            axes.update(a for a, _ in found)
            # Closed ONLY when every element is literal: one runtime
            # element (("data", extra_axis)) means unknown axes exist.
            n_elts = (
                len(kw.value.elts)
                if isinstance(kw.value, (ast.Tuple, ast.List))
                else 1
            )
            closed = bool(found) and len(found) == n_elts
        elif kw.arg in ("in_specs", "out_specs") and kw.value is not None:
            stack = [kw.value]
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.Tuple, ast.List)):
                    stack.extend(n.elts)
                elif isinstance(n, ast.Call) and _dotted(n.func) in _P_NAMES:
                    for arg in n.args:
                        elts = (
                            arg.elts
                            if isinstance(arg, (ast.Tuple, ast.List))
                            else [arg]
                        )
                        for el in elts:
                            if (
                                isinstance(el, ast.Constant)
                                and isinstance(el.value, str)
                            ):
                                axes.add(el.value)
    if not closed or not axes:
        return None
    return frozenset(axes)


def _mapped_function_body(call: ast.Call, tree_ast: ast.AST) -> Optional[ast.AST]:
    """The AST region shard_map maps over: a Lambda argument directly, or
    the nearest same-module ``def`` a Name argument refers to."""
    if not call.args:
        return None
    target = call.args[0]
    if isinstance(target, ast.Lambda):
        return target
    if isinstance(target, ast.Name):
        best: Optional[ast.FunctionDef] = None
        for node in ast.walk(tree_ast):
            if (
                isinstance(node, ast.FunctionDef)
                and node.name == target.id
                and node.lineno <= call.lineno
            ):
                if best is None or node.lineno > best.lineno:
                    best = node
        return best
    return None


@_rule(
    "GC108",
    "collective-axis-outside-shard-map-axes",
    "psum/ppermute/all_gather/... inside a shard_map body naming a literal "
    "axis the enclosing shard_map does not define — the collective raises "
    "(or silently binds a different mesh's axis) only at trace time, deep "
    "inside a jit",
    "use an axis from the shard_map's axis_names/in_specs set, or thread "
    "the axis name in as a parameter like ops/ring_attention.py does; "
    "suppress deliberate cross-mesh collectives with "
    "'# graftcheck: disable=GC108'",
)
def _check_shard_map_collective_axes(root: str) -> Iterator[Violation]:
    for tree in _package_files(root, ("",)):
        for call in ast.walk(tree.ast):
            if not (
                isinstance(call, ast.Call)
                and _dotted(call.func) in _SHARD_MAP_NAMES
            ):
                continue
            axes = _shard_map_axis_set(call)
            if not axes:
                continue  # nothing statically known to check against
            body = _mapped_function_body(call, tree.ast)
            if body is None:
                continue
            # Walk the mapped region but never descend into a NESTED
            # shard_map call — the inner map owns its own axis scope and
            # is checked at its own call site against its own set.
            stack = list(ast.iter_child_nodes(body))
            region: List[ast.AST] = []
            while stack:
                n = stack.pop()
                if (
                    isinstance(n, ast.Call)
                    and _dotted(n.func) in _SHARD_MAP_NAMES
                ):
                    continue
                region.append(n)
                stack.extend(ast.iter_child_nodes(n))
            for sub in region:
                if not isinstance(sub, ast.Call):
                    continue
                pos = _GC108_COLLECTIVES.get(_dotted(sub.func) or "")
                if pos is None:
                    continue
                axis_nodes = [
                    kw.value for kw in sub.keywords if kw.arg == "axis_name"
                ]
                if not axis_nodes and len(sub.args) > pos:
                    axis_nodes = [sub.args[pos]]
                for node in axis_nodes:
                    for axis, line in _literal_axis_names(node):
                        if axis in axes:
                            continue
                        if _suppressed(tree, line, "GC108"):
                            continue
                        yield Violation(
                            "GC108", tree.rel, line,
                            f"{_dotted(sub.func)}(..., {axis!r}) names an "
                            f"axis outside the enclosing shard_map's set "
                            f"{sorted(axes)}",
                            RULES["GC108"].fix_hint,
                        )


# ---------------------------------------------------------------------------
# GC109: per-microbatch reshard hazard in parallel/ schedule loops
# ---------------------------------------------------------------------------

#: Calls that re-place or re-lay-out device values: one of these inside a
#: trace-time-unrolled schedule loop becomes M copies in the compiled step.
_GC109_RESHARD_CALLS = frozenset({
    "with_sharding_constraint", "lax.with_sharding_constraint",
    "jax.lax.with_sharding_constraint",
    "device_put", "jax.device_put",
})
#: Host-synchronizing calls (the GC102 classes, scoped to parallel/):
#: inside a schedule loop each unrolled copy fences the device.
_GC109_HOST_SYNC_CALLS = frozenset({
    "np.asarray", "numpy.asarray", "np.array", "jax.device_get",
})


def _gc109_classify(call: ast.Call, traced_loop: bool) -> Optional[str]:
    name = _dotted(call.func)
    if name in _GC109_RESHARD_CALLS:
        return f"{name}(...) re-places/re-lays-out a value"
    if not traced_loop:
        # Host-sync classes only matter in loops that touch jax at all:
        # the schedule BUILDERS (build_schedule's numpy/heapq passes) are
        # pure host code where int()/np.asarray are innocent — flagging
        # them would force disable= pragmas onto correct code.
        return None
    if name in _GC109_HOST_SYNC_CALLS:
        return f"{name}(...) is a device->host transfer"
    if name in ("float", "int") and call.args:
        return f"{name}(...) is a .item()-class host sync"
    if isinstance(call.func, ast.Attribute) and call.func.attr in (
        "item", "block_until_ready"
    ):
        return f".{call.func.attr}() is a host sync"
    return None


def _loop_touches_jax(loop: ast.For) -> bool:
    """True when the loop subtree references jax/jnp/lax names — the
    trace-time-unrolled shape GC109's host-sync classes police."""
    for n in ast.walk(loop):
        name = _dotted(n) if isinstance(n, (ast.Attribute, ast.Name)) else None
        if name and name.split(".", 1)[0] in ("jax", "jnp", "lax"):
            return True
    return False


@_rule(
    "GC109",
    "per-microbatch-reshard-hazard-in-schedule-loop",
    "with_sharding_constraint/device_put/host-sync call inside a "
    "`for _ in range(...)` loop body in parallel/ — schedule loops unroll "
    "at trace time, so the call becomes one reshard/fence PER MICROBATCH "
    "in the compiled step (the growth the schedule auditor's affine law "
    "flags as pipeline reshard suspects)",
    "hoist the placement to the shard_map boundary (in_specs/out_specs or "
    "a single constraint outside the loop); derive per-tick values from "
    "sharded operands instead of host syncs; suppress deliberate "
    "exceptions with '# graftcheck: disable=GC109'",
)
def _check_schedule_loop_reshards(root: str) -> Iterator[Violation]:
    for tree in _package_files(root, ("parallel",)):
        seen = set()  # nested range loops would double-report inner calls
        for node in ast.walk(tree.ast):
            if not (
                isinstance(node, ast.For)
                and isinstance(node.iter, ast.Call)
                and _dotted(node.iter.func) == "range"
            ):
                continue
            traced = _loop_touches_jax(node)
            # Full subtree walk, INCLUDING nested function defs (unlike
            # _stmt_calls): the real tick loops put per-tick work in
            # closures invoked via lax.cond/switch each unrolled tick, so
            # a hazard inside one is still one copy per microbatch.
            for stmt in node.body + node.orelse:
                for call in ast.walk(stmt):
                    if not isinstance(call, ast.Call):
                        continue
                    kind = _gc109_classify(call, traced)
                    if (
                        kind
                        and (call.lineno, call.col_offset) not in seen
                        and not _suppressed(tree, call.lineno, "GC109")
                    ):
                        seen.add((call.lineno, call.col_offset))
                        yield Violation(
                            "GC109", tree.rel, call.lineno,
                            f"{kind} inside a range() schedule loop "
                            "(unrolls per microbatch at trace time)",
                            RULES["GC109"].fix_hint,
                        )


# ---------------------------------------------------------------------------
# GC112: hard-coded exit-code literals outside the central EXIT_* registry
# ---------------------------------------------------------------------------

#: Receiver names that mark a comparison as exit-code-shaped: `rc == 75`,
#: `proc.returncode in (75, 76)`, `exit_code != 77`. Deliberately narrow —
#: a bare 75 elsewhere (a percentile, a size) is not this rule's business.
_GC112_RECEIVER = re.compile(
    r"(^|_)(rc|returncode|exit_?code|exit_?status)(_|\d*$)", re.IGNORECASE
)
_GC112_EXIT_NAME = re.compile(r"^EXIT_[A-Z0-9_]+$")
#: Call targets whose integer argument IS a process exit code.
_GC112_EXIT_CALLS = frozenset({"sys.exit", "os._exit", "exit", "SystemExit"})


def _gc112_registry(root: str):
    """Harvest the central registry: every module-level ``EXIT_NAME = int``
    assignment in the package -> {value: name}, plus the defining
    (file, line) pairs (exempt by construction — the registry itself is
    the one place the literals belong)."""
    values: Dict[int, str] = {}
    defining = set()
    for tree in _package_files(root, ("",)):
        for node in tree.ast.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not (isinstance(target, ast.Name)
                    and _GC112_EXIT_NAME.match(target.id)):
                continue
            if isinstance(node.value, ast.Constant) and isinstance(
                node.value.value, int
            ):
                values[node.value.value] = target.id
                defining.add((tree.rel, node.lineno))
    return values, defining


def _gc112_compare_is_exitish(node: ast.Compare) -> bool:
    for side in [node.left, *node.comparators]:
        ident = None
        if isinstance(side, ast.Attribute):
            ident = side.attr
        elif isinstance(side, ast.Name):
            ident = side.id
        if ident and _GC112_RECEIVER.search(ident):
            return True
    return False


def _gc112_literals(node: ast.AST) -> Iterator[ast.Constant]:
    """Int literals inside one expression (tuples/lists/sets unpacked —
    the ``rc in (75, 76)`` shape)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and type(sub.value) is int:
            yield sub


@_rule(
    "GC112",
    "hard-coded-exit-code-literal",
    "a registry exit-code value (EXIT_PREEMPTED 75 / EXIT_HUNG 76 / "
    "EXIT_NOTHING_TO_RESUME 77 / EXIT_DATA_STALL 78 — harvested, not "
    "hard-coded here either) as a bare integer literal in an exit call "
    "or an exit-code comparison, outside the defining EXIT_* assignment",
    "import the named constant from the faults package (e.g. "
    "`from ..faults import EXIT_PREEMPTED`) instead of its integer value — "
    "the renumbering that moved EXIT_NOTHING_TO_RESUME 76 -> 77 is exactly "
    "the drift this rule exists to catch",
)
def _check_exit_code_literals(root: str) -> Iterator[Violation]:
    values, defining = _gc112_registry(root)
    if not values:
        return
    for tree in _package_files(root, ("",)):
        for node in ast.walk(tree.ast):
            hits: List[ast.Constant] = []
            if isinstance(node, ast.Call):
                name = _dotted(node.func)
                if name in _GC112_EXIT_CALLS:
                    hits = [
                        c for arg in node.args for c in _gc112_literals(arg)
                    ]
            elif isinstance(node, ast.Compare):
                if _gc112_compare_is_exitish(node):
                    hits = [
                        c for side in [node.left, *node.comparators]
                        for c in _gc112_literals(side)
                    ]
            for lit in hits:
                if lit.value not in values:
                    continue
                if (tree.rel, lit.lineno) in defining:
                    continue
                if _suppressed(tree, lit.lineno, "GC112"):
                    continue
                yield Violation(
                    "GC112", tree.rel, lit.lineno,
                    f"hard-coded exit code {lit.value} "
                    f"({values[lit.value]}) outside the central EXIT_* "
                    "registry",
                    RULES["GC112"].fix_hint,
                )


# ---------------------------------------------------------------------------
# GC201: entrypoint <-> harness flag-surface drift
# ---------------------------------------------------------------------------

_FLAG_TOKEN = re.compile(r"--[a-z][a-z0-9-]+")


@_rule(
    "GC201",
    "entrypoint-flag-drift",
    "docker/entrypoint.sh env contract out of sync with "
    "train/harness.py::build_parser() — in either direction",
    "plumb the new flag through an env var in docker/entrypoint.sh (or add "
    "it to lint.ENTRYPOINT_EXEMPT_FLAGS with a reason); delete stale flags "
    "the harness no longer defines",
)
def _check_entrypoint_drift(root: str) -> Iterator[Violation]:
    entrypoint = os.path.join(root, "docker", "entrypoint.sh")
    if not os.path.exists(entrypoint):
        return
    from ...train.harness import build_parser

    parser_flags = set()
    for action in build_parser()._actions:
        parser_flags.update(
            o for o in action.option_strings if o.startswith("--")
        )
    parser_flags.discard("--help")

    text = open(entrypoint).read()
    entry_flags = set(_FLAG_TOKEN.findall(text))

    stale = entry_flags - parser_flags - ENTRYPOINT_WRAPPER_FLAGS
    if stale:
        yield Violation(
            "GC201", "docker/entrypoint.sh", 1,
            f"passes flags the harness does not define: {sorted(stale)}",
            RULES["GC201"].fix_hint,
        )
    missing = parser_flags - entry_flags - ENTRYPOINT_EXEMPT_FLAGS
    if missing:
        yield Violation(
            "GC201", "docker/entrypoint.sh", 1,
            f"harness flags with no container-env plumbing: {sorted(missing)}",
            RULES["GC201"].fix_hint,
        )


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


def run_lint(
    root: str = REPO_ROOT,
    rules: Optional[Tuple[str, ...]] = None,
    files: Optional[Tuple[str, ...]] = None,
) -> List[Violation]:
    """Run every registered rule (or the named subset) over ``root``.

    ``files`` (repo-relative paths) scopes the REPORT to those files —
    the `--changed` pre-commit path. Rules still scan the whole package
    for their knowledge bases (GC103's mesh-axis harvest, GC201's flag
    surfaces), so a changed file is judged against unchanged context; a
    violation is only emitted when it sits in a changed file.
    """
    out: List[Violation] = []
    for rule, check in _CHECKS:
        if rules is not None and rule.id not in rules:
            continue
        out.extend(v for v in check(root) if v is not None)
    if files is not None:
        wanted = {f.replace(os.sep, "/") for f in files}
        out = [v for v in out if v.path.replace(os.sep, "/") in wanted]
    return sorted(out, key=lambda v: (v.path, v.line, v.rule_id))
