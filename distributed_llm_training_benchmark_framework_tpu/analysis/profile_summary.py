"""Summarize a ``--profile-dir`` trace: where device time actually goes.

The reference's profiling story is aspirational (its docs *recommend* pynvml
sampling and ``torch.profiler`` as future additions; SURVEY §5.1) — the
harness here already captures real traces (``--profile-dir`` wraps the timed
window in ``jax.profiler``), and this tool closes the loop by reading them
back: per-lane totals (device vs host), an XLA-op *class* breakdown, and the
top individual ops with their HLO provenance. This is exactly the analysis
that produced docs/PERFORMANCE.md §§8-9 (it started as an ad-hoc script;
promoting it makes the workflow reproducible):

    python -u benchmarking/train_harness.py ... --profile-dir /tmp/prof
    python -m distributed_llm_training_benchmark_framework_tpu.analysis.profile_summary \
        --profile-dir /tmp/prof --top 20

Reads the Chrome-trace export (``*.trace.json.gz``) the profiler writes under
``plugins/profile/<run>/``; no TensorBoard or tensorflow dependency.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple


def list_profile_runs(profile_dir: str) -> List[Tuple[str, str]]:
    """All (run_name, newest trace file) pairs under a profiler directory.

    jax.profiler writes one ``plugins/profile/<run>/`` directory per
    ``start_trace`` call, so a profile dir reused across benchmark arms
    holds several runs. Sorted oldest-first by trace mtime; bare traces at
    the top level (non-standard layouts) appear under run name ``'.'``.
    """
    per_run: Dict[str, str] = {}
    for f in glob.glob(
        os.path.join(profile_dir, "plugins", "profile", "*", "*.trace.json.gz")
    ):
        run = os.path.basename(os.path.dirname(f))
        if run not in per_run or os.path.getmtime(f) > os.path.getmtime(per_run[run]):
            per_run[run] = f
    for f in glob.glob(os.path.join(profile_dir, "*.trace.json.gz")):
        if "." not in per_run or os.path.getmtime(f) > os.path.getmtime(per_run["."]):
            per_run["."] = f
    return sorted(per_run.items(), key=lambda kv: os.path.getmtime(kv[1]))


def find_trace_file(profile_dir: str, run: Optional[str] = None) -> Optional[str]:
    """Chrome-trace file under a jax.profiler output directory.

    With one run present (the common case) its trace is returned. A
    profile dir reused across several runs used to silently yield the
    globally newest trace — an operator summarizing arm A after re-running
    arm B got B's trace under A's name. Now: ``run`` selects by run-dir
    name (exact, then unique substring; ValueError naming the candidates
    otherwise), and with no selector the newest run is still returned but
    the ambiguity is WARNED on stderr with the candidate list.
    """
    runs = list_profile_runs(profile_dir)
    if not runs:
        return None
    if run is not None:
        exact = [f for name, f in runs if name == run]
        if exact:
            return exact[0]
        sub = [(name, f) for name, f in runs if run in name]
        if len(sub) == 1:
            return sub[0][1]
        raise ValueError(
            f"--run {run!r} matches {len(sub)} of the profile runs in "
            f"{profile_dir}; candidates: {[name for name, _ in runs]}"
        )
    if len(runs) > 1:
        print(
            f"WARNING: {profile_dir} holds {len(runs)} profile runs; "
            "summarizing the newest. Pass --run <name> to pick one of: "
            + ", ".join(name for name, _ in runs),
            file=sys.stderr,
        )
    return runs[-1][1]


def load_events(trace_file: str) -> List[dict]:
    with gzip.open(trace_file, "rt") as f:
        return json.load(f).get("traceEvents", [])


def _lane_names(events) -> Tuple[Dict[int, str], Dict[Tuple[int, int], str]]:
    pids: Dict[int, str] = {}
    tids: Dict[Tuple[int, int], str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pids[e["pid"]] = e["args"].get("name", "")
        elif e.get("name") == "thread_name":
            tids[(e["pid"], e["tid"])] = e["args"].get("name", "")
    return pids, tids


def op_class(name: str) -> str:
    """Collapse XLA op names to a class: 'fusion.1234' -> 'fusion',
    'while.35' -> 'while', 'jvp_jit_flash_attention__.3' -> 'flash_kernel'."""
    if "flash_attention" in name:
        return "flash_kernel"
    base = re.sub(r"[.\d]+$", "", name)
    return base or name


def summarize(
    events: List[dict], top: int = 15
) -> Dict[str, object]:
    """-> {lanes, op_classes, top_ops, steps} aggregates (durations in us)."""
    pids, tids = _lane_names(events)
    lanes: collections.Counter = collections.Counter()
    classes: collections.Counter = collections.Counter()
    ops: Dict[str, List] = {}
    step_durs: List[float] = []
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        pname = pids.get(e.get("pid"), "")
        lname = tids.get((e.get("pid"), e.get("tid")), "")
        lanes[(pname, lname)] += e["dur"]
        if not pname.startswith("/device:"):
            continue
        if lname == "XLA Ops":
            classes[op_class(e["name"])] += e["dur"]
            rec = ops.setdefault(e["name"], [0, e.get("args", {})])
            rec[0] += e["dur"]
        elif lname == "Steps":
            step_durs.append(e["dur"])
    top_ops = sorted(ops.items(), key=lambda kv: -kv[1][0])[:top]
    return {
        "lanes": lanes,
        "op_classes": classes,
        "top_ops": [
            (name, dur, (args.get("long_name") or args.get("tf_op") or ""))
            for name, (dur, args) in top_ops
        ],
        "step_durs_us": step_durs,
    }


def format_summary(s: Dict[str, object], top: int = 15) -> str:
    out: List[str] = []
    lanes = s["lanes"]
    out.append("== Lanes (total self time) ==")
    for (p, t), dur in lanes.most_common(8):
        out.append(f"  {dur/1e6:9.3f}s  {p} / {t}")
    cls_total = sum(s["op_classes"].values()) or 1
    steps = s["step_durs_us"]
    if steps:
        steps_s = sorted(steps)
        out.append(
            f"\n== Device steps: {len(steps)} traced, "
            f"median {steps_s[len(steps_s)//2]/1e3:.2f} ms, "
            f"max {steps_s[-1]/1e3:.2f} ms =="
        )
    out.append("\n== XLA op classes (device) ==")
    for name, dur in s["op_classes"].most_common(20):
        out.append(f"  {100*dur/cls_total:5.1f}%  {dur/1e6:8.3f}s  {name}")
    out.append(f"\n== Top {top} ops (device) ==")
    for name, dur, prov in s["top_ops"]:
        line = f"  {100*dur/cls_total:5.1f}%  {dur/1e6:8.3f}s  {name[:48]}"
        if prov:
            line += f"\n             {prov[:110]}"
        out.append(line)
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--profile-dir", required=True,
                   help="the directory passed to the harness's --profile-dir")
    p.add_argument("--top", type=int, default=15,
                   help="individual ops to list with provenance")
    p.add_argument("--run", default=None,
                   help="profile run directory name (or unique substring) "
                        "when --profile-dir holds several runs; default: "
                        "newest, with a warning listing the candidates")
    args = p.parse_args(argv)
    # ERROR lines go to STDERR: a scripted `summary=$(... profile_summary)`
    # capture must see the failure on the terminal (and in the exit code),
    # not swallow it into the captured variable.
    try:
        trace = find_trace_file(args.profile_dir, run=args.run)
    except ValueError as e:
        print(f"ERROR: {e}", file=sys.stderr)
        return 1
    if trace is None:
        print(f"ERROR: no *.trace.json.gz under {args.profile_dir} "
              "(did the run include --profile-dir and >= warmup steps?)",
              file=sys.stderr)
        return 1
    print(f"Trace: {trace}")
    print(format_summary(summarize(load_events(trace), args.top), args.top))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
