"""The scaling observatory: weak/strong scaling curves from the registry.

The paper's core deliverable is a cross-strategy scaling comparison, and
through PR 9 the framework could *measure* single geometries but never
*relate* them: nothing assembled tokens/sec-vs-device-count curves, and
nothing said WHERE efficiency dies as the mesh grows. This module closes
both gaps from evidence the stack already records:

- **Curves** are assembled per *lineage* — one configuration scaled over
  its data axis. A lineage is ``regress.store.config_key`` with the
  geometry axes (world size, per-device batch, grad accum) factored out;
  the parallel-composition degrees (tp/sp/pp/ep) stay in the lineage
  identity, so "zero2 over dp" and "zero2 x pp2 over dp" are separate
  curves rather than colliding points. Within a lineage the points are
  the newest baseline-eligible record per geometry (the same
  ``Registry._eligible`` chain the gate trusts: ok-status, unbanked,
  non-resumed, non-healed). Stitched points — a resumed /
  geometry-changed run from the scaling suite's reshard-on-restore legs
  — and sentinel-healed points are *flagged* in the curve instead of
  silently mixed in; partial records are excluded with a visible count.

- **Weak vs strong** is classified from the points themselves: constant
  per-device batch while the data axis grows is weak scaling (global
  batch grows with the mesh); constant *global* batch is strong scaling
  (per-device work shrinks). Mixed sweeps are labeled mixed rather than
  guessed at.

- **Efficiency** is per-chip throughput retention vs the smallest-mesh
  clean point: ``eff = (tps/ws) / (tps_base/ws_base) * 100``. With a
  single-chip base this is exactly the reference formula
  ``parse_metrics.add_scaling_efficiency`` reproduces; unlike the
  reference's 2-GPU-minimum data it normalizes honestly when the
  smallest measured mesh is larger than one chip.

- **The efficiency-loss waterfall** attributes each point's loss
  (100 - eff, in percentage points) from the step-anatomy fields already
  riding every profiled record (PR 7): the *growth vs the base point* of
  exposed-collective time (``comms_exposed_frac``), pipeline bubble
  (``bubble_frac``) and straggler skew (``straggler_skew_pct``), plus a
  residual for what the anatomy cannot see (dispatch overhead,
  composition effects, input). First-order accounting — an extra X pp of
  step time on exposed comms costs ~X pp of throughput — the same
  decomposition "Scale MLPerf-0.6 models on TPU-v3 Pods" (1909.09756)
  and "Exploring the limits of Concurrency in ML Training on Google
  TPUs" (2011.03641) apply to their pod-scale curves, automated per
  geometry. Points without anatomy render unattributed rather than
  pretending.

The gate integration rides a separate, run-time path:
:func:`stamp_results_dir` post-processes a suite results tree and writes
each clean row's ``scaling_efficiency`` (a 0-1 fraction of ideal) into
its ``result_*.json`` BEFORE registry ingest, computed against the
smallest-geometry row of its own suite — so the value is part of the
measurement record, and ``stats.SECONDARY_METRICS`` verdicts it per
geometry exactly like ``comms_exposed_frac`` (absolute
percentage-point scale; the arm slug names the geometry in the gate
line). See docs/SCALING.md for the full methodology.
"""

from __future__ import annotations

import argparse
import dataclasses
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from ..regress import store as rstore

#: Result-row axes that define one scaling lineage (a curve). Everything
#: ``regress.store.config_key`` pins EXCEPT the geometry axes below —
#: the composition degrees stay here so a tp2 sweep never collides with
#: a pure-dp sweep. Kept as an explicit list (not derived from
#: config_key's tuple positions) so either side can evolve loudly.
LINEAGE_KEYS = (
    "model_family", "strategy", "tier", "seq_len", "attention_impl",
    "sync_every", "tensor_parallel", "sequence_parallel",
    "pipeline_parallel", "pipeline_schedule", "expert_parallel",
    "n_experts", "param_dtype", "causal", "ring_zigzag",
    "steps", "warmup_steps", "remat_policy", "xla_scheduler_flags",
    "tp_collective_matmul",
)

#: Axes that vary along a curve: the mesh size and the per-device work.
GEOMETRY_KEYS = ("world_size", "per_device_batch", "grad_accum")


@dataclasses.dataclass
class ScalingPoint:
    """One measured geometry on a curve."""

    world_size: int
    per_device_batch: int
    grad_accum: int
    dp: int
    global_batch: int
    tokens_per_sec: float
    tokens_per_sec_per_chip: float
    mfu_pct: Optional[float]
    record_id: str
    flags: Tuple[str, ...] = ()
    # Anatomy inputs (fractions / pct as recorded; None when unprofiled).
    comms_exposed_frac: Optional[float] = None
    bubble_frac: Optional[float] = None
    straggler_skew_pct: Optional[float] = None
    # Derived vs the curve's base point (filled by build_curves).
    efficiency_pct: Optional[float] = None
    loss_pp: Optional[float] = None
    d_comms_pp: Optional[float] = None
    d_bubble_pp: Optional[float] = None
    d_skew_pp: Optional[float] = None
    residual_pp: Optional[float] = None


@dataclasses.dataclass
class ScalingCurve:
    lineage: Dict[str, Any]
    mode: str  # 'weak' | 'strong' | 'mixed' | 'single-point'
    points: List[ScalingPoint]
    base_world_size: Optional[int] = None

    def label(self) -> str:
        l = self.lineage
        comp = []
        for key, tag in (("tensor_parallel", "tp"),
                         ("sequence_parallel", "sp"),
                         ("pipeline_parallel", "pp"),
                         ("expert_parallel", "ep")):
            d = l.get(key) or 1
            if d and int(d) > 1:
                part = f"{tag}{int(d)}"
                if tag == "pp" and l.get("pipeline_schedule"):
                    part += f"-{l['pipeline_schedule']}"
                comp.append(part)
        comp_s = (" x " + "+".join(comp)) if comp else ""
        return (
            f"{l.get('strategy')}{comp_s} x {l.get('model_family')} "
            f"tier{l.get('tier')} seq{l.get('seq_len')}"
        )


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) and v == v else None


def _dp(row: Dict[str, Any]) -> int:
    denom = 1
    for k in ("tensor_parallel", "sequence_parallel", "pipeline_parallel",
              "expert_parallel"):
        denom *= int(row.get(k) or 1)
    return max(int(row.get("world_size") or 1) // max(denom, 1), 1)


def lineage_of(row: Dict[str, Any]) -> Tuple:
    # The trailing element mirrors regress.store.config_key's
    # profiled-ness axis: anatomy fields are non-null exactly when the
    # run profiled, and the trace bracket's overhead makes a PROFILE=1
    # sweep a different measurement lineage than an unprofiled one — a
    # profiled re-sweep must form its own curve (and its own stamp
    # group), never silently replace unprofiled points.
    return tuple(row.get(k) for k in LINEAGE_KEYS) + (
        row.get("comms_exposed_frac") is not None,
    )


def _point_from_record(rec: Dict[str, Any], flags: Tuple[str, ...]) -> ScalingPoint:
    row = rec.get("result") or {}
    ws = int(row.get("world_size") or 1)
    tps = _num(row.get("tokens_per_sec")) or 0.0
    dp = _dp(row)
    pdb = int(row.get("per_device_batch") or 1)
    ga = int(row.get("grad_accum") or 1)
    mfu = _num(row.get("mfu_pct"))
    return ScalingPoint(
        world_size=ws,
        per_device_batch=pdb,
        grad_accum=ga,
        dp=dp,
        global_batch=pdb * ga * dp,
        tokens_per_sec=tps,
        tokens_per_sec_per_chip=tps / ws if ws else 0.0,
        mfu_pct=mfu if (mfu or 0) > 0 else None,
        record_id=rec.get("record_id", "?"),
        flags=flags,
        comms_exposed_frac=_num(row.get("comms_exposed_frac")),
        bubble_frac=_num(row.get("bubble_frac")),
        straggler_skew_pct=_num(row.get("straggler_skew_pct")),
    )


#: Lineage axes that describe run LENGTH rather than configuration. A
#: stitch leg (reshard-on-restore continuation) necessarily runs a few
#: extra steps past the source run's final checkpoint, so flagged points
#: match their clean curve modulo these axes (clean points never do —
#: mixing a 12-step smoke curve with a 100-step curve is exactly the
#: cross-lineage comparison the registry config key exists to prevent).
RUN_LENGTH_KEYS = ("steps", "warmup_steps")


def _sans_length(lineage_key: Tuple) -> Tuple:
    named = tuple(
        None if k in RUN_LENGTH_KEYS else v
        for k, v in zip(LINEAGE_KEYS, lineage_key)
    )
    # Derived trailing elements (the profiled-ness axis) are identity,
    # not run length — carry them through the relaxation.
    return named + tuple(lineage_key[len(LINEAGE_KEYS):])


def collect_points(
    reg: rstore.Registry,
) -> Tuple[
    Dict[Tuple, Dict[Tuple, ScalingPoint]],
    Dict[Tuple, Dict[Tuple, ScalingPoint]],
    int,
]:
    """(clean, flagged) lineage -> geometry -> newest point, + n partial.

    Ingest order is the registry's clock: for each (lineage, geometry)
    the newest record wins, with the gate's eligibility rules deciding
    whether it lands clean or flagged — a stitched (resumed /
    geometry-changed) or healed (sentinel-rollback) record is shown
    FLAGGED, never silently curve-worthy, and a banked regression is
    skipped entirely (it is a known-bad measurement, not a point).
    """
    clean: Dict[Tuple, Dict[Tuple, ScalingPoint]] = {}
    flagged: Dict[Tuple, Dict[Tuple, ScalingPoint]] = {}
    n_partial = 0
    banked = reg.banked_ids()
    for arm in reg.arms():
        for rec in reg.records(arm):
            row = rec.get("result") or {}
            if row.get("world_size") is None or row.get("strategy") is None:
                continue  # multichip dryruns / non-run records
            if rec.get("status") != "ok":
                n_partial += 1
                continue
            if rec.get("record_id") in banked:
                continue
            flags: Tuple[str, ...] = ()
            if row.get("resumed") or row.get("resume_geometry_changed"):
                flags = ("stitched",)
            elif row.get("n_rollbacks"):
                flags = ("healed",)
            geom = tuple(row.get(k) for k in GEOMETRY_KEYS)
            dest = flagged if flags else clean
            dest.setdefault(lineage_of(row), {})[geom] = _point_from_record(
                rec, flags
            )
    return clean, flagged, n_partial


def _classify_mode(points: List[ScalingPoint]) -> str:
    if len({p.world_size for p in points}) < 2:
        return "single-point"
    weak = (
        len({(p.per_device_batch, p.grad_accum) for p in points}) == 1
    )
    strong = len({p.global_batch for p in points}) == 1
    if weak and not strong:
        return "weak"
    if strong:
        return "strong"
    return "mixed"


def build_curves(reg: rstore.Registry) -> Tuple[List[ScalingCurve], int]:
    """Assemble every >=2-point curve, derived fields filled in.

    A curve needs at least one clean point (the base) and two points
    total. Flagged (stitched/healed) points attach to the clean curve
    whose lineage matches exactly, else — unique match only — modulo the
    run-length axes (see RUN_LENGTH_KEYS); an ambiguous or matchless
    flagged point is dropped rather than guessed onto a curve.
    """
    raw, flagged_raw, n_partial = collect_points(reg)
    # Attach flagged points to their clean lineage.
    sans = {}
    for lk in raw:
        sans.setdefault(_sans_length(lk), []).append(lk)
    for flk, by_geom in flagged_raw.items():
        if flk in raw:
            target = flk
        else:
            candidates = sans.get(_sans_length(flk), [])
            if len(candidates) != 1:
                continue
            target = candidates[0]
        for geom, point in by_geom.items():
            # Keyed beside (never over) the clean point at the same
            # geometry: both rows are honest and both must render.
            raw[target][geom + ("flagged",)] = point
    curves: List[ScalingCurve] = []
    for lineage_key, by_geom in raw.items():
        points = sorted(
            by_geom.values(),
            key=lambda p: (p.world_size, p.per_device_batch, p.grad_accum,
                           len(p.flags)),
        )
        if len(points) < 2:
            continue
        lineage = dict(zip(LINEAGE_KEYS, lineage_key))
        clean = [p for p in points if not p.flags]
        base = clean[0] if clean else None
        for p in points:
            if base is None:
                continue
            ideal_per_chip = base.tokens_per_sec_per_chip
            if ideal_per_chip <= 0:
                continue
            p.efficiency_pct = round(
                100.0 * p.tokens_per_sec_per_chip / ideal_per_chip, 2
            )
            p.loss_pp = round(100.0 - p.efficiency_pct, 2)
            if p is base:
                continue
            # The waterfall: anatomy GROWTH vs the base point, in pp.
            # First-order: +X pp of step time on exposed comms / bubble
            # costs ~X pp of throughput; skew is already a percent.
            attributed = 0.0
            any_attr = False
            if (p.comms_exposed_frac is not None
                    and base.comms_exposed_frac is not None):
                p.d_comms_pp = round(
                    100.0 * (p.comms_exposed_frac - base.comms_exposed_frac),
                    2,
                )
                attributed += p.d_comms_pp
                any_attr = True
            if p.bubble_frac is not None and base.bubble_frac is not None:
                p.d_bubble_pp = round(
                    100.0 * (p.bubble_frac - base.bubble_frac), 2
                )
                attributed += p.d_bubble_pp
                any_attr = True
            if (p.straggler_skew_pct is not None
                    and base.straggler_skew_pct is not None):
                p.d_skew_pp = round(
                    p.straggler_skew_pct - base.straggler_skew_pct, 2
                )
                attributed += p.d_skew_pp
                any_attr = True
            if any_attr:
                p.residual_pp = round(p.loss_pp - attributed, 2)
        curves.append(ScalingCurve(
            lineage=lineage,
            mode=_classify_mode(points),
            points=points,
            base_world_size=base.world_size if base else None,
        ))
    curves.sort(key=lambda c: c.label())
    return curves, n_partial


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------


def _cell(v, fmt="{:,.1f}", missing="--") -> str:
    return fmt.format(v) if v is not None else missing


def format_curve(curve: ScalingCurve) -> str:
    head = (
        f"-- {curve.label()} [{curve.mode} scaling, "
        f"{len(curve.points)} points"
        + (f", base ws={curve.base_world_size}" if curve.base_world_size
           else ", NO CLEAN BASE")
        + "] --"
    )
    lines = [
        head,
        "  ws  b/dev  acc    tokens/s  tok/s/chip   MFU%    eff%  "
        "dcomms  dbubble  dskew   resid  flags",
    ]
    for p in curve.points:
        flags = ",".join(f.upper() for f in p.flags)
        if p.world_size == curve.base_world_size and not p.flags:
            flags = "base"
        unattr = (
            p.efficiency_pct is not None
            and p.world_size != curve.base_world_size
            and p.residual_pp is None
        )
        lines.append(
            f"{p.world_size:>4}  {p.per_device_batch:>5}  {p.grad_accum:>3}"
            f"  {p.tokens_per_sec:>10,.0f}"
            f"  {p.tokens_per_sec_per_chip:>10,.0f}"
            f"  {_cell(p.mfu_pct, '{:.1f}', '-'):>5}"
            f"  {_cell(p.efficiency_pct):>6}"
            f"  {_cell(p.d_comms_pp, '{:+.1f}'):>6}"
            f"  {_cell(p.d_bubble_pp, '{:+.1f}'):>7}"
            f"  {_cell(p.d_skew_pp, '{:+.1f}'):>5}"
            f"  {_cell(p.residual_pp, '{:+.1f}'):>6}"
            + (f"  {flags}" if flags else "")
            + ("  [unattributed: no anatomy]" if unattr else "")
        )
    return "\n".join(lines)


def format_report(
    curves: List[ScalingCurve], n_partial: int, registry_root: str,
) -> str:
    out = [f"== Scaling curves (registry: {registry_root}) =="]
    if not curves:
        out.append(
            "  no lineage spans >= 2 geometries yet — run "
            "scripts/scaling_suite.sh (or ingest a multi-world-size suite) "
            "to grow curves"
        )
    for c in curves:
        out.append("")
        out.append(format_curve(c))
    out.append("")
    out.append(
        f"{len(curves)} curve(s); dcomms/dbubble/dskew = efficiency-loss "
        "attribution in pp vs the base point (step-anatomy growth; "
        "docs/SCALING.md); resid = loss the anatomy cannot see."
    )
    if n_partial:
        out.append(
            f"NOTE: {n_partial} partial (heartbeat-salvaged) record(s) "
            "excluded — a truncated run's rate is not a scaling point."
        )
    return "\n".join(out)


def curves_to_json(curves: List[ScalingCurve], n_partial: int) -> Dict[str, Any]:
    return {
        "curves": [
            {
                "lineage": c.lineage,
                "label": c.label(),
                "mode": c.mode,
                "base_world_size": c.base_world_size,
                "points": [dataclasses.asdict(p) for p in c.points],
            }
            for c in curves
        ],
        "excluded_partial_records": n_partial,
    }


def write_curves_png(curves: List[ScalingCurve], path: str) -> Optional[str]:
    """Throughput + efficiency panels, one line per curve. None when
    nothing is plottable (no curve with a base)."""
    plottable = [c for c in curves if c.base_world_size is not None]
    if not plottable:
        return None
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, (ax_tps, ax_eff) = plt.subplots(1, 2, figsize=(10, 3.6), dpi=150)
    for c in plottable:
        xs = [p.world_size for p in c.points]
        ys = [p.tokens_per_sec for p in c.points]
        (line,) = ax_tps.plot(xs, ys, marker="o", linewidth=1.2,
                              label=f"{c.label()} ({c.mode})")
        base = next(
            p for p in c.points
            if p.world_size == c.base_world_size and not p.flags
        )
        ideal = [base.tokens_per_sec_per_chip * x for x in xs]
        ax_tps.plot(xs, ideal, linestyle="--", linewidth=0.8,
                    color=line.get_color(), alpha=0.5)
        effs = [(p.world_size, p.efficiency_pct) for p in c.points
                if p.efficiency_pct is not None]
        ax_eff.plot([e[0] for e in effs], [e[1] for e in effs],
                    marker="o", linewidth=1.2, color=line.get_color())
        for p in c.points:
            if p.flags and p.efficiency_pct is not None:
                ax_eff.scatter([p.world_size], [p.efficiency_pct],
                               marker="x", color="#c0392b", zorder=5)
    ax_tps.set_xscale("log", base=2)
    ax_tps.set_yscale("log", base=2)
    ax_tps.set_xlabel("devices")
    ax_tps.set_ylabel("tokens/sec (dashed = ideal)")
    ax_tps.legend(fontsize=6)
    ax_eff.set_xscale("log", base=2)
    ax_eff.set_xlabel("devices")
    ax_eff.set_ylabel("scaling efficiency % (x = stitched/healed)")
    ax_eff.axhline(100.0, color="#d9d8d4", linewidth=0.8)
    for ax in (ax_tps, ax_eff):
        ax.grid(color="#d9d8d4", linewidth=0.5)
        for spine in ("top", "right"):
            ax.spines[spine].set_visible(False)
    fig.tight_layout()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fig.savefig(path)
    plt.close(fig)
    return path


def scaling_section(registry_root: str) -> List[str]:
    """The make_report section: one markdown table per curve.

    Mirrors the CLI table from the same engine, so the report and the
    console can never disagree about a curve. SchemaDrift degrades to an
    "unavailable" note, the posture every registry-fed section takes.
    """
    try:
        reg = rstore.Registry(registry_root)
        if not reg.exists():
            return []
        curves, n_partial = build_curves(reg)
    except rstore.SchemaDrift as e:
        return ["## Scaling curves", "", f"_unavailable: {e}_", ""]
    if not curves:
        return []
    out = ["## Scaling curves", "",
           "Per-lineage weak/strong scaling with the efficiency-loss "
           "waterfall attributed from step anatomy (pp vs the base "
           "geometry; `python -m ...analysis.scaling` for the full "
           "tables, docs/SCALING.md for semantics). Stitched "
           "(reshard-on-restore) and healed points are flagged and never "
           "anchor the curve.", ""]
    for c in curves:
        out.append(f"### {c.label()} — {c.mode} scaling")
        out.append("")
        out.append("| ws | tokens/s | tok/s/chip | MFU % | eff % "
                   "| Δcomms pp | Δbubble pp | Δskew pp | residual pp "
                   "| flags |")
        out.append("|---|---|---|---|---|---|---|---|---|---|")
        for p in c.points:
            flags = ",".join(p.flags) or (
                "base" if p.world_size == c.base_world_size else "-"
            )
            out.append(
                f"| {p.world_size} | {p.tokens_per_sec:,.0f} "
                f"| {p.tokens_per_sec_per_chip:,.0f} "
                f"| {_cell(p.mfu_pct, '{:.1f}', '-')} "
                f"| {_cell(p.efficiency_pct)} "
                f"| {_cell(p.d_comms_pp, '{:+.1f}')} "
                f"| {_cell(p.d_bubble_pp, '{:+.1f}')} "
                f"| {_cell(p.d_skew_pp, '{:+.1f}')} "
                f"| {_cell(p.residual_pp, '{:+.1f}')} "
                f"| {flags} |"
            )
        out.append("")
    if n_partial:
        out.append(f"_{n_partial} partial record(s) excluded from the "
                   "curves._")
        out.append("")
    return out


# ---------------------------------------------------------------------------
# Result-row stamping (the gate path)
# ---------------------------------------------------------------------------


def compute_efficiency_stamps(
    rows: List[Dict[str, Any]],
) -> Dict[int, float]:
    """index -> scaling_efficiency fraction for the stampable rows.

    Grouping matches the curve lineage (LINEAGE_KEYS); the base is the
    smallest-world-size CLEAN row of each group (never resumed / healed
    / partial — the `_eligible` posture applied at stamp time). Only
    clean rows are stamped: a stitched run's throughput folds the
    restore, so minting it an efficiency would gate the recovery
    machinery, not the scaling.
    """
    def clean(row):
        return not (
            row.get("partial")
            or row.get("resumed")
            or row.get("resume_geometry_changed")
            or row.get("n_rollbacks")
        )

    groups: Dict[Tuple, List[int]] = {}
    for i, row in enumerate(rows):
        if row.get("tokens_per_sec") is None or row.get("world_size") is None:
            continue
        groups.setdefault(lineage_of(row), []).append(i)
    stamps: Dict[int, float] = {}
    for idxs in groups.values():
        clean_idxs = [i for i in idxs if clean(rows[i])]
        if not clean_idxs:
            continue
        base = min(
            clean_idxs,
            key=lambda i: (int(rows[i].get("world_size") or 1),
                           int(rows[i].get("per_device_batch") or 1),
                           int(rows[i].get("grad_accum") or 1)),
        )
        base_row = rows[base]
        base_per_chip = (
            float(base_row["tokens_per_sec"])
            / max(int(base_row.get("world_size") or 1), 1)
        )
        if base_per_chip <= 0:
            continue
        for i in clean_idxs:
            row = rows[i]
            per_chip = (
                float(row["tokens_per_sec"])
                / max(int(row.get("world_size") or 1), 1)
            )
            stamps[i] = round(per_chip / base_per_chip, 6)
    return stamps


def stamp_results_dir(results_dir: str) -> List[Tuple[str, float]]:
    """Write ``scaling_efficiency`` into each clean ``result_*.json``.

    Runs BEFORE registry ingest (scripts/scaling_suite.sh order), so the
    fraction rides the ingested record's result row and the secondary-
    metric gate can verdict it per geometry. Returns the
    (path, fraction) stamps applied. Idempotent: re-stamping recomputes
    from the same rows and writes the same values.
    """
    paths = sorted(
        p for p in glob.glob(
            os.path.join(results_dir, "**", "result*.json"), recursive=True
        )
        if os.path.basename(p).startswith(("result_", "result."))
        or os.path.basename(p) == "result.json"
    )
    rows: List[Dict[str, Any]] = []
    keep: List[str] = []
    for path in paths:
        try:
            row = json.load(open(path))
        except (json.JSONDecodeError, OSError):
            continue
        if not isinstance(row, dict) or "tokens_per_sec" not in row:
            continue
        rows.append(row)
        keep.append(path)
    stamps = compute_efficiency_stamps(rows)
    out: List[Tuple[str, float]] = []
    for i, frac in sorted(stamps.items()):
        rows[i]["scaling_efficiency"] = frac
        with open(keep[i], "w") as f:
            json.dump(rows[i], f, indent=2)
            f.write("\n")
        out.append((keep[i], frac))
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributed_llm_training_benchmark_framework_tpu"
             ".analysis.scaling",
        description="scaling observatory: weak/strong curves + "
                    "efficiency-loss waterfall from the run registry "
                    "(docs/SCALING.md)",
    )
    p.add_argument("--registry", default=None,
                   help="registry root (default: $REGRESS_REGISTRY or "
                        "results/registry)")
    p.add_argument("--out", default=None,
                   help="directory for scaling_curves.{png,json}")
    p.add_argument("--png", action="store_true",
                   help="write scaling_curves.png under --out (or cwd)")
    p.add_argument("--json", action="store_true",
                   help="write scaling_curves.json under --out (or cwd)")
    p.add_argument("--stamp-results-dir", default=None, metavar="DIR",
                   help="stamp mode: write scaling_efficiency into each "
                        "clean result_*.json under DIR (run before "
                        "registry ingest), then exit")
    args = p.parse_args(argv)

    if args.stamp_results_dir:
        if not os.path.isdir(args.stamp_results_dir):
            print(f"scaling: no such results dir "
                  f"{args.stamp_results_dir!r}", file=sys.stderr)
            return 2
        stamped = stamp_results_dir(args.stamp_results_dir)
        print(f"scaling stamp: {len(stamped)} row(s) stamped with "
              "scaling_efficiency")
        for path, frac in stamped:
            print(f"  {os.path.relpath(path, args.stamp_results_dir)}: "
                  f"{100.0 * frac:.1f}%")
        return 0

    try:
        reg = rstore.Registry(args.registry)
    except rstore.SchemaDrift as e:
        print(f"scaling: {e}", file=sys.stderr)
        return 2
    if not reg.exists():
        print(f"scaling: no registry at {reg.root} (run a suite, or "
              "`regress ingest` first)", file=sys.stderr)
        return 2
    curves, n_partial = build_curves(reg)
    print(format_report(curves, n_partial, reg.root))
    out_dir = args.out or "."
    if args.json:
        path = os.path.join(out_dir, "scaling_curves.json")
        os.makedirs(out_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(curves_to_json(curves, n_partial), f, indent=2,
                      sort_keys=True)
            f.write("\n")
        print(f"Wrote {path}")
    if args.png:
        path = write_curves_png(
            curves, os.path.join(out_dir, "scaling_curves.png")
        )
        if path:
            print(f"Wrote {path}")
        else:
            print("scaling: nothing plottable yet (no curve with a clean "
                  "base)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
