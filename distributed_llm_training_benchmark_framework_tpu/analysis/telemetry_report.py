#!/usr/bin/env python
"""Reconstruct a run timeline from its flight-recorder telemetry JSONL.

The write side is ``telemetry.TelemetryRecorder`` (threaded through
``train/loop.py``); this is the read side — everything an operator needs
to answer "where did the run's time go, and how was it doing when it
died" from the JSONL alone:

- **timeline**: the phase intervals (init/compile/warmup/timed/
  checkpoint/trace/finalize) in run order, with an ASCII gantt bar;
- **phase attribution**: per-phase totals as a fraction of wall time —
  the compile-vs-timed split that a single tokens/sec number hides;
- **trajectories**: loss / window step time / allocator HBM over the
  run's sync windows (``--plots-out`` renders PNGs; the text report
  always carries the endpoints and extrema);
- **anomalies**: NaN-loss and step-time-spike events, with whether they
  resolved;
- **profiler join** (``--profile-dir``): lines the JSONL's host-clock
  step windows up against the Chrome-trace device step lane from
  ``profile_summary``, so host-side overhead (dispatch, sync RPCs) is
  separable from device time. ``--run`` picks a run when the profile dir
  holds several;
- **cross-run comparison** (``--compare A.jsonl B.jsonl``): per-phase
  wall-time deltas plus timed-window step-time/throughput distributions
  with significance verdicts, delegated to the ``regress.stats`` engine
  (the registry gate's statistics — one implementation, two views).

Works on aborted/truncated files: a run killed mid-write still renders a
partial timeline (that is the point of a flight recorder).

    python -m distributed_llm_training_benchmark_framework_tpu.analysis.telemetry_report \
        --telemetry results/run_results/telemetry_zero2_ws4_seq2048_tierA.jsonl \
        [--profile-dir /tmp/prof [--run <name>]] [--plots-out plots/]
"""

from __future__ import annotations

import argparse
import glob
import os
from typing import Any, Dict, List, Optional

from ..telemetry import (
    PHASES,
    is_rank_sibling,
    rank_telemetry_files,
    read_events,
)


# ---------------------------------------------------------------------------
# Timeline reconstruction
# ---------------------------------------------------------------------------


def build_timeline(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """-> {meta, intervals, phase_times, windows, anomalies, end, wall}.

    ``intervals`` is the ordered list of ``{phase, start_rel, end_rel}``
    (an interval left open by a crash is closed at the last event's
    ``rel``); ``phase_times`` sums them per phase; ``end`` is the
    ``run_end``/``run_aborted`` event when one exists.
    """
    meta: Dict[str, Any] = {}
    intervals: List[Dict[str, Any]] = []
    windows: List[Dict[str, Any]] = []
    anomalies: List[Dict[str, Any]] = []
    data_events: List[Dict[str, Any]] = []
    end: Optional[Dict[str, Any]] = None
    open_iv: Optional[Dict[str, Any]] = None
    last_rel = 0.0
    for e in events:
        last_rel = max(last_rel, float(e.get("rel", 0.0)))
        kind = e.get("event")
        if kind == "run_meta":
            meta = e
        elif kind == "phase_begin":
            if open_iv is not None:
                open_iv["end_rel"] = e["rel"]
            open_iv = {"phase": e["phase"], "start_rel": e["rel"],
                       "end_rel": None}
            intervals.append(open_iv)
        elif kind == "phase_end":
            if open_iv is not None and open_iv["phase"] == e["phase"]:
                open_iv["end_rel"] = e["rel"]
                open_iv = None
        elif kind == "step_window":
            windows.append(e)
        elif kind in ("anomaly", "anomaly_resolved"):
            anomalies.append(e)
        elif kind in ("data_stall", "data_corrupt_record"):
            data_events.append(e)
        elif kind in ("run_end", "run_aborted"):
            end = e
    for iv in intervals:
        if iv["end_rel"] is None:
            iv["end_rel"] = last_rel  # crash left the phase open
    phase_times: Dict[str, float] = {}
    for iv in intervals:
        phase_times[iv["phase"]] = (
            phase_times.get(iv["phase"], 0.0)
            + max(iv["end_rel"] - iv["start_rel"], 0.0)
        )
    wall = float(end.get("wall_time_total_sec", last_rel)) if end else last_rel
    return {
        "meta": meta, "intervals": intervals, "phase_times": phase_times,
        "windows": windows, "anomalies": anomalies,
        "data_events": data_events, "end": end,
        "wall": wall,
    }


def hbm_timeline_lines(
    windows: List[Dict[str, Any]], width: int = 44,
) -> List[str]:
    """The HBM high-water timeline across a run's sync windows.

    Memory-anatomy round: the recorder samples the allocator's peak (and
    live bytes-in-use) per window, so a run's memory trajectory — when
    the high-water mark was set, how close to it the steady state runs —
    is reconstructible from the heartbeat/JSONL channel alone, mid-run
    or post-mortem. Renders an ASCII sparkline scaled to the run's own
    maximum plus first/high-water/last figures; empty list when no
    window carried a sample (CPU backends).
    """
    pts = [(w.get("step"), w.get("peak_hbm_bytes"), w.get("hbm_bytes_in_use"))
           for w in windows if w.get("peak_hbm_bytes") is not None]
    if not pts:
        return []
    peaks = [p for _s, p, _c in pts]
    hi = max(peaks) or 1
    levels = " .:-=+*#%@"
    spark = "".join(
        levels[min(int(p / hi * (len(levels) - 1)), len(levels) - 1)]
        for _s, p, _c in pts[-width:]
    )
    hw_step = next(s for s, p, _c in pts if p == max(peaks))
    out = [
        f"  HBM high-water timeline ({len(pts)} sampled windows): "
        f"first {peaks[0] / 2**30:.2f} GiB -> high-water "
        f"{max(peaks) / 2**30:.2f} GiB @ step {hw_step} -> last "
        f"{peaks[-1] / 2**30:.2f} GiB",
        f"    |{spark}|",
    ]
    in_use = [c for _s, _p, c in pts if c is not None]
    if in_use:
        out.append(
            f"    live bytes-in-use: last {in_use[-1] / 2**30:.2f} GiB "
            f"({100.0 * in_use[-1] / hi:.0f}% of the high-water mark)"
        )
    return out


def data_stall_timeline_lines(
    events: List[Dict[str, Any]],
    windows: List[Dict[str, Any]],
    width: int = 44,
) -> List[str]:
    """The input-starvation timeline across a run's sync windows.

    Streaming round: stream runs stamp each ``step_window`` with
    ``data_wait_sec`` (the loop's measured wait for that window's
    batches), so the stall trajectory sits beside the HBM high-water line
    in the same JSONL-only report. Renders a sparkline of the per-window
    wait fraction plus the totals, the quarantine count, and any
    ``data_stall`` events (non-fatal window stalls and the fatal
    classification). Empty list for synthetic runs (no window carries the
    field).
    """
    pts = []
    for w in windows:
        wait = w.get("data_wait_sec")
        if wait is None:
            continue
        wall = (
            (w.get("window_mean_step_time_sec") or 0.0)
            * (w.get("steps_in_window") or 1)
        )
        pts.append((w.get("step"), float(wait), wall))
    if not pts:
        return []
    levels = " .:-=+*#%@"
    fracs = [min(wait / wall, 1.0) if wall > 0 else 0.0
             for _s, wait, wall in pts]
    spark = "".join(
        levels[min(int(fr * (len(levels) - 1)), len(levels) - 1)]
        for fr in fracs[-width:]
    )
    total_wait = sum(wait for _s, wait, _w in pts)
    total_wall = sum(wall for _s, _w2, wall in pts)
    frac = total_wait / total_wall if total_wall > 0 else 0.0
    out = [
        f"  Data-stall timeline ({len(pts)} sampled windows): "
        f"{total_wait:.2f}s waiting on input over {total_wall:.2f}s of "
        f"windows ({100.0 * frac:.1f}%)",
        f"    |{spark}|",
    ]
    stalls = [e for e in events if e.get("event") == "data_stall"]
    if stalls:
        fatal = [e for e in stalls if e.get("fatal")]
        out.append(
            f"    data_stall events: {len(stalls)}"
            + (f" (FATAL at step {fatal[-1].get('step')} — run classified "
               "reason=data_stall)" if fatal else " (all transient)")
        )
    skipped = [w.get("records_skipped") for w in windows
               if w.get("records_skipped") is not None]
    if skipped and skipped[-1]:
        out.append(
            f"    records skipped/quarantined: {skipped[-1]} "
            "(data_corrupt_record events carry the ledger)"
        )
    return out


def _gantt_bar(iv: Dict[str, Any], wall: float, width: int = 44) -> str:
    if wall <= 0:
        return ""
    a = int(round(iv["start_rel"] / wall * width))
    b = max(int(round(iv["end_rel"] / wall * width)), a + 1)
    return " " * a + "#" * min(b - a, width - a)


def format_report(tl: Dict[str, Any]) -> str:
    out: List[str] = []
    meta, end, wall = tl["meta"], tl["end"], tl["wall"]
    arm = meta.get("arm", "?")
    out.append(f"== Telemetry: {arm} ==")
    if meta:
        out.append(
            "  run: "
            + " ".join(
                f"{k}={meta[k]}" for k in (
                    "strategy", "world_size", "seq_len", "tier",
                    "model_family", "total_steps",
                ) if k in meta
            )
        )
    if end is None:
        out.append("  STATUS: no run_end/run_aborted event — process was "
                   "killed outright; timeline below ends at the last sync")
    elif end["event"] == "run_aborted":
        out.append(f"  STATUS: ABORTED in phase {end.get('phase')!r} at "
                   f"step {end.get('last_step')} — {end.get('reason')}")
    else:
        out.append(f"  STATUS: completed ({end.get('status')}), "
                   f"last step {end.get('last_step')}")

    out.append("")
    out.append(f"== Timeline (wall {wall:.2f}s) ==")
    for iv in tl["intervals"]:
        dur = iv["end_rel"] - iv["start_rel"]
        out.append(
            f"  {iv['phase']:>10}  {iv['start_rel']:8.2f}s ->"
            f" {iv['end_rel']:8.2f}s ({dur:7.2f}s)  |{_gantt_bar(iv, wall)}"
        )

    out.append("")
    out.append("== Phase attribution ==")
    total = sum(tl["phase_times"].values()) or 1.0
    for phase in PHASES:
        if phase not in tl["phase_times"]:
            continue
        sec = tl["phase_times"][phase]
        out.append(f"  {100.0 * sec / wall if wall else 0:5.1f}%  "
                   f"{sec:9.3f}s  {phase}")
    covered = 100.0 * total / wall if wall else 0.0
    out.append(f"  (phases cover {covered:.1f}% of wall time)")

    ws = tl["windows"]
    if ws:
        losses = [w["loss"] for w in ws if w.get("loss") is not None]
        dts = sorted(w["window_mean_step_time_sec"] for w in ws)
        hbm = [w["peak_hbm_bytes"] for w in ws
               if w.get("peak_hbm_bytes") is not None]
        out.append("")
        out.append(f"== Trajectories ({len(ws)} sync windows, last step "
                   f"{ws[-1]['step']}) ==")
        if losses:
            out.append(f"  loss: first {losses[0]:.4f} -> last "
                       f"{losses[-1]:.4f} (min {min(losses):.4f})")
        out.append(
            f"  window mean step time: median {dts[len(dts) // 2]:.4f}s, "
            f"max {dts[-1]:.4f}s"
        )
        out.append(f"  cumulative tokens/sec: {ws[-1]['tokens_per_sec']:,.0f}"
                   f" ({ws[-1]['cum_tokens']:,} tokens)")
        if hbm:
            out.append(f"  peak HBM (allocator): {max(hbm) / 1e9:.2f} GB")
        out.extend(hbm_timeline_lines(ws))
        out.extend(data_stall_timeline_lines(tl.get("data_events", []), ws))

    if tl["anomalies"]:
        out.append("")
        out.append(f"== Anomalies ({len(tl['anomalies'])} events) ==")
        # A spike's opening event must not read as OPEN when a later
        # anomaly_resolved event closed it.
        resolved_opens = {
            a.get("opened_at_step") for a in tl["anomalies"]
            if a["event"] == "anomaly_resolved"
        }
        for a in tl["anomalies"]:
            if a["event"] == "anomaly_resolved":
                tag = "resolved"
            elif a.get("kind") == "step_time_spike":
                tag = ("resolved later" if a.get("step") in resolved_opens
                       else "OPEN")
            else:
                tag = "UNRESOLVED"
            out.append(f"  step {a.get('step')}: {a.get('kind')} [{tag}] "
                       f"{a.get('detail', '')}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Per-rank merge (multi-host runs)
# ---------------------------------------------------------------------------


def merge_rank_timelines(
    path: str, rank0_tl: Optional[Dict[str, Any]] = None
) -> Dict[int, Dict[str, Any]]:
    """{rank: timeline} for a rank-0 telemetry file and its rank siblings.

    Multi-host runs stream one ``telemetry_<arm>.rank<r>.jsonl`` per
    non-zero rank beside the canonical file (telemetry.telemetry_filename)
    — merging them is what makes a straggling or preempted NON-ZERO rank
    visible directly instead of only through rank 0's window times.
    Unreadable rank files are skipped (a SIGKILL'd rank's torn tail is
    already tolerated by read_events). ``rank0_tl`` lets a caller that
    already built the canonical file's timeline skip re-reading it.
    """
    out: Dict[int, Dict[str, Any]] = {}
    for rank, rpath in sorted(rank_telemetry_files(path).items()):
        if rank == 0 and rank0_tl is not None:
            out[0] = rank0_tl
            continue
        try:
            events = read_events(rpath)
        except (OSError, ValueError):
            continue
        if events:
            out[rank] = build_timeline(events)
    return out


def format_rank_merge(ranks: Dict[int, Dict[str, Any]]) -> str:
    """Straggler/preemption table across a run's per-rank streams."""
    out: List[str] = [f"== Per-rank telemetry ({len(ranks)} ranks) =="]
    max_step = max(
        (tl["windows"][-1]["step"] for tl in ranks.values() if tl["windows"]),
        default=None,
    )
    for rank, tl in sorted(ranks.items()):
        end = tl["end"]
        last_step = tl["windows"][-1]["step"] if tl["windows"] else None
        if end is None:
            status = "KILLED (no terminal event)"
        elif end["event"] == "run_aborted":
            status = f"aborted: {end.get('reason')}"
        else:
            status = f"completed ({end.get('status')})"
        straggle = ""
        if (
            max_step is not None and last_step is not None
            and last_step < max_step
        ):
            straggle = f"  <-- straggler ({max_step - last_step} steps behind)"
        out.append(
            f"  rank {rank}: last step "
            f"{'-' if last_step is None else last_step}, wall "
            f"{tl['wall']:.2f}s, {status}{straggle}"
        )
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Profiler join
# ---------------------------------------------------------------------------


def join_profile(
    tl: Dict[str, Any], profile_dir: str, run: Optional[str] = None
) -> str:
    """Line the JSONL host-clock windows up against the device step lane."""
    from . import profile_summary as ps

    trace = ps.find_trace_file(profile_dir, run=run)
    if trace is None:
        return f"== Profiler join ==\n  no trace under {profile_dir}"
    s = ps.summarize(ps.load_events(trace))
    dev = sorted(s["step_durs_us"])
    out = ["== Profiler join ==", f"  trace: {trace}"]
    if not dev:
        out.append("  trace has no device step lane (no 'Steps' thread)")
        return "\n".join(out)
    dev_med = dev[len(dev) // 2] / 1e6
    # Only the timed windows are comparable: the trace starts after warmup
    # (train/loop.py starts it at the warmup boundary), so compile/warmup
    # windows would skew the host-side median.
    host = sorted(
        w["window_mean_step_time_sec"] for w in tl["windows"]
        if w.get("phase") == "timed"
    ) or sorted(w["window_mean_step_time_sec"] for w in tl["windows"])
    host_med = host[len(host) // 2]
    overhead = host_med - dev_med
    out.append(f"  device steps traced: {len(dev)}, median {dev_med:.4f}s")
    out.append(f"  telemetry windows:   {len(host)}, median host step "
               f"{host_med:.4f}s")
    out.append(
        f"  host-side overhead:  {overhead:+.4f}s/step "
        f"({100.0 * overhead / host_med if host_med else 0:.1f}% of the "
        "host step — dispatch, sync RPCs, python)"
    )
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Anomaly <-> trace join (telemetry follow-up (b))
# ---------------------------------------------------------------------------


def _match_traced_step(
    anomaly: Dict[str, Any],
    window: Optional[Dict[str, Any]],
    traced: List[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """The traced step covering one spike anomaly, or None.

    Two rungs: the trace's step names ARE step numbers on jax exports, so
    an exact name match wins; otherwise the spike window's wall-clock span
    (the ``step_window`` event's unix ``ts``, trace ``ts`` in epoch
    microseconds) catches traces whose step counter restarted.
    """
    step = anomaly.get("step")
    for t in traced:
        try:
            if int(t["step"]) == step:
                return t
        except (TypeError, ValueError):
            pass
    if window is not None and window.get("ts"):
        n = window.get("steps_in_window", 1) or 1
        dt = window.get("window_mean_step_time_sec", 0.0) or 0.0
        hi = float(window["ts"]) * 1e6
        lo = hi - n * dt * 1e6
        for t in traced:
            mid = (t["t0"] + t["t1"]) / 2.0
            if lo <= mid <= hi:
                return t
    return None


def join_anomaly_trace(
    tl: Dict[str, Any], profile_dir: str, run: Optional[str] = None
) -> Optional[str]:
    """Name the op class that grew in each spiked step vs the median step.

    Auto-joins the recorder's ``step_time_spike`` anomalies against the
    profiler trace whenever ``--profile-dir`` covered the spike window:
    the spiked step's per-op-class self time is compared against the
    per-class median over the other traced steps, and the class with the
    largest growth is named — the triage answer ("the all-reduce grew,
    not the matmuls") that used to require a by-hand trace read. Returns
    None when the run recorded no spikes.
    """
    spikes = [a for a in tl["anomalies"]
              if a.get("event") == "anomaly"
              and a.get("kind") == "step_time_spike"]
    if not spikes:
        return None
    from . import step_anatomy as sa

    out = ["== Anomaly <-> trace join =="]
    traces = sa.discover_traces(profile_dir, run=run)
    if 0 not in traces:
        out.append(f"  no trace under {profile_dir} — spikes not joinable")
        return "\n".join(out)
    from . import profile_summary as ps

    traced = sa.per_step_op_classes(ps.load_events(traces[0]))
    if len(traced) < 2:
        out.append("  trace holds < 2 device steps — no median to compare "
                   "a spike against")
        return "\n".join(out)
    windows_by_step = {w.get("step"): w for w in tl["windows"]}
    for a in spikes:
        target = _match_traced_step(a, windows_by_step.get(a.get("step")),
                                    traced)
        if target is None:
            out.append(
                f"  spike at step {a.get('step')}: outside the traced "
                "window (the profiler did not cover the spike)"
            )
            continue
        others = [t for t in traced if t is not target]
        growth: List[tuple] = []
        for cls, dur in target["classes"].items():
            meds = sorted(t["classes"].get(cls, 0.0) for t in others)
            med = meds[len(meds) // 2] if meds else 0.0
            growth.append((dur - med, med, dur, cls))
        if not growth:
            out.append(f"  spike at step {a.get('step')}: traced step has "
                       "no op self-time to attribute")
            continue
        delta, med, dur, cls = max(growth)
        ratio = f"{dur / med:.1f}x" if med > 0 else "new"
        out.append(
            f"  spike at step {a.get('step')}: '{cls}' grew {ratio} vs "
            f"the median step ({med / 1e3:.2f} ms -> {dur / 1e3:.2f} ms, "
            f"+{delta / 1e3:.2f} ms)"
        )
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Cross-run comparison (--compare A.jsonl B.jsonl)
# ---------------------------------------------------------------------------


def format_compare(rep: Dict[str, Any]) -> str:
    """Render the regress.stats.compare_telemetry report (regression
    triage across two runs — the ROADMAP telemetry follow-up (d)).

    The statistics are the regress engine's — the same seeded bootstrap
    / rank test / verdict rule the registry gate applies — so this view
    and `regress compare` can never disagree about the same two runs.
    """
    out: List[str] = ["== Telemetry compare =="]
    for tag in ("a", "b"):
        side = rep[tag]
        masked = (f" masked_windows={side['masked_windows']}"
                  if side.get("masked_windows") else "")
        out.append(
            f"  {tag.upper()}: arm={side['arm']} wall={side['wall']:.2f}s "
            f"timed_windows={side['n_timed_windows']}{masked}"
        )
    out.append("")
    out.append("== Phase delta (seconds) ==")
    out.append(f"  {'phase':>10}  {'A':>9}  {'B':>9}  {'delta':>9}  {'%':>8}")
    for row in rep["phases"]:
        a = f"{row['a_sec']:.3f}" if row["a_sec"] is not None else "-"
        b = f"{row['b_sec']:.3f}" if row["b_sec"] is not None else "-"
        d = (f"{row['delta_sec']:+.3f}" if row["delta_sec"] is not None
             else "-")
        pct = (f"{row['delta_pct']:+.1f}%" if row["delta_pct"] is not None
               else "-")
        out.append(f"  {row['phase']:>10}  {a:>9}  {b:>9}  {d:>9}  {pct:>8}")
    out.append("")
    out.append("== Timed-window distributions (regress.stats) ==")
    for c in rep["comparisons"]:
        out.append(
            f"  {c.metric}: A mean {c.base_mean:,.4f} -> B mean "
            f"{c.cand_mean:,.4f} (n={c.n_base}/{c.n_cand})"
        )
        out.append(f"    {c.summary()}")
    verdicts = [c.verdict for c in rep["comparisons"]]
    overall = verdicts[0] if verdicts else "insufficient-data"
    out.append(f"  VERDICT: {overall}")
    return "\n".join(out)


def run_compare(path_a: str, path_b: str) -> int:
    """Exit codes match `regress compare` (the same stats engine, so the
    two views must also agree as gates): 0 clean/neutral, 1 the primary
    comparison verdicts a regression, 2 unreadable input."""
    from ..regress import stats as regress_stats

    events = []
    for path in (path_a, path_b):
        try:
            evs = read_events(path)
        except (OSError, ValueError) as e:
            print(f"ERROR: cannot read {path}: {e}")
            return 2
        if not evs:
            print(f"ERROR: {path} holds no events")
            return 2
        events.append(evs)
    rep = regress_stats.compare_telemetry(events[0], events[1])
    print(f"A: {path_a}")
    print(f"B: {path_b}")
    print(format_compare(rep))
    comps = rep["comparisons"]
    primary = comps[0].verdict if comps else None
    return 1 if primary == regress_stats.VERDICT_REGRESSION else 0


# ---------------------------------------------------------------------------
# Plots (optional)
# ---------------------------------------------------------------------------


def write_plots(tl: Dict[str, Any], out_dir: str) -> List[str]:
    """Loss / step-time / HBM trajectory PNGs; returns written paths."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    ws = tl["windows"]
    if not ws:
        return []
    os.makedirs(out_dir, exist_ok=True)
    steps = [w["step"] for w in ws]
    written: List[str] = []
    series = [
        ("loss", [w.get("loss") for w in ws], "loss",
         "telemetry_loss.png"),
        ("window mean step time (s)",
         [w["window_mean_step_time_sec"] for w in ws], "step time",
         "telemetry_step_time.png"),
        ("peak HBM (GB)",
         [None if w.get("peak_hbm_bytes") is None
          else w["peak_hbm_bytes"] / 1e9 for w in ws], "HBM",
         "telemetry_hbm.png"),
        ("HBM in use (GB)",
         [None if w.get("hbm_bytes_in_use") is None
          else w["hbm_bytes_in_use"] / 1e9 for w in ws], "HBM in use",
         "telemetry_hbm_in_use.png"),
        ("data wait (s/window)",
         [w.get("data_wait_sec") for w in ws], "input wait",
         "telemetry_data_wait.png"),
    ]
    for ylabel, ys, title, fname in series:
        pts = [(s, y) for s, y in zip(steps, ys) if y is not None]
        if not pts:
            continue
        fig, ax = plt.subplots(figsize=(6, 3.2), dpi=150)
        ax.plot([p[0] for p in pts], [p[1] for p in pts],
                color="#2a78d6", linewidth=1.2)
        ax.set_xlabel("step")
        ax.set_ylabel(ylabel)
        ax.set_title(f"{tl['meta'].get('arm', '')} {title}", fontsize=9)
        ax.grid(color="#d9d8d4", linewidth=0.5)
        for spine in ("top", "right"):
            ax.spines[spine].set_visible(False)
        fig.tight_layout()
        path = os.path.join(out_dir, fname)
        fig.savefig(path)
        plt.close(fig)
        written.append(path)
    return written


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _discover(results_dir: str) -> List[str]:
    return sorted(
        p for p in glob.glob(
            os.path.join(results_dir, "**", "telemetry_*.jsonl"),
            recursive=True,
        )
        # Rank siblings report under their rank-0 file's per-rank section,
        # not as standalone runs.
        if not is_rank_sibling(p)
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--telemetry", help="one telemetry_<arm>.jsonl file")
    src.add_argument("--results-dir",
                     help="directory searched recursively for "
                          "telemetry_*.jsonl (reports each)")
    src.add_argument("--compare", nargs=2, metavar=("A", "B"),
                     help="two telemetry JSONL files: per-phase + "
                          "per-window delta tables with significance "
                          "verdicts (regress.stats engine)")
    p.add_argument("--profile-dir", default=None,
                   help="the harness's --profile-dir: join the JSONL step "
                        "windows against the Chrome-trace device step lane")
    p.add_argument("--run", default=None,
                   help="profile run to join when --profile-dir holds "
                        "several (see profile_summary --run)")
    p.add_argument("--plots-out", default=None,
                   help="directory for loss/step-time/HBM trajectory PNGs")
    args = p.parse_args(argv)

    if args.compare:
        return run_compare(args.compare[0], args.compare[1])

    paths = [args.telemetry] if args.telemetry else _discover(args.results_dir)
    if not paths:
        print(f"ERROR: no telemetry_*.jsonl under {args.results_dir}")
        return 1
    rc = 0
    for i, path in enumerate(paths):
        if i:
            print("\n" + "-" * 72 + "\n")
        try:
            events = read_events(path)
        except (OSError, ValueError) as e:
            print(f"ERROR: cannot read {path}: {e}")
            rc = 1
            continue
        if not events:
            print(f"ERROR: {path} holds no events")
            rc = 1
            continue
        tl = build_timeline(events)
        print(f"File: {path}")
        print(format_report(tl))
        ranks = merge_rank_timelines(path, rank0_tl=tl)
        if len(ranks) > 1:
            print()
            print(format_rank_merge(ranks))
        if args.profile_dir:
            print()
            try:
                print(join_profile(tl, args.profile_dir, run=args.run))
                # Telemetry follow-up (b): spikes auto-join against the
                # trace whenever the profile dir covered them.
                anomaly_join = join_anomaly_trace(
                    tl, args.profile_dir, run=args.run
                )
                if anomaly_join:
                    print()
                    print(anomaly_join)
            except ValueError as e:
                # Bad/ambiguous --run: report and keep going — the JSONL
                # reports for the remaining files are still wanted.
                print(f"ERROR: {e}")
                rc = 1
        if args.plots_out:
            for out in write_plots(tl, args.plots_out):
                print(f"Wrote {out}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
