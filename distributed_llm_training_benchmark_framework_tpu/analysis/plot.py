#!/usr/bin/env python
"""Plot metrics.csv into the reference's five benchmark figures.

Figure-for-figure parity with the reference plotter (``scripts/plot.py``):
tokens/sec vs chips, step-time vs chips, peak memory vs seq-len (only when
multiple seq-lens exist), scaling efficiency vs chips with the ideal line, and
the H2D-proxy vs chips — one line per strategy, 150-dpi PNGs, Agg backend.

Styling follows a validated colorblind-safe categorical palette (fixed slot
order per strategy, never cycled; worst adjacent CVD deltaE 9.1), thin marks,
recessive grid, direct axis labels.
"""

from __future__ import annotations

import argparse
import os
from typing import List

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import pandas as pd  # noqa: E402

# Fixed categorical slot order (validated palette; strategy -> slot, stable
# across filtered subsets so a missing arm never repaints the survivors).
STRATEGY_COLORS = {
    "ddp": "#2a78d6",    # blue
    "fsdp": "#eb6834",   # orange
    "zero2": "#1baf7a",  # aqua
    "zero3": "#eda100",  # yellow
}
FALLBACK_COLORS = ["#e87ba4", "#008300", "#4a3aa7", "#e34948"]

SURFACE = "#fcfcfb"
TEXT = "#0b0b0b"
TEXT_2 = "#52514e"
GRID = "#d9d8d4"


def _style_axes(ax, xlabel: str, ylabel: str, title: str) -> None:
    ax.set_facecolor(SURFACE)
    ax.set_xlabel(xlabel, color=TEXT)
    ax.set_ylabel(ylabel, color=TEXT)
    ax.set_title(title, color=TEXT, fontsize=12)
    ax.grid(True, color=GRID, linewidth=0.6, alpha=0.8)
    ax.tick_params(colors=TEXT_2)
    for s in ax.spines.values():
        s.set_color(GRID)


def _color_for(strategy: str, i: int) -> str:
    return STRATEGY_COLORS.get(strategy, FALLBACK_COLORS[i % len(FALLBACK_COLORS)])


def _seq_key_cols(df: pd.DataFrame) -> List[str]:
    """Line-grouping key for the vs-sequence-length figures: a mixed results
    dir holds several rows per (strategy, seq_len) — one per attention impl /
    world size / model family / composition arm — and merging them into one
    line would draw vertical zigzags. Every identity axis that actually
    varies in the frame joins the key (and the line label)."""
    return ["strategy"] + [
        c for c in (
            "attention_impl", "world_size", "tier", "model_family",
            "causal", "ring_zigzag", "tp_collective_matmul",
            "n_experts", "param_dtype",
            "offload_opt_state", "offload_delayed_update",
            "offload_dpu_start_step", "tensor_parallel", "sequence_parallel",
            "pipeline_parallel", "pipeline_schedule", "virtual_stages",
            "expert_parallel",
        )
        if c in df.columns and df[c].nunique(dropna=False) > 1
    ]


def _line_per_strategy(df: pd.DataFrame, x: str, y: str, ax) -> None:
    for i, (strategy, g) in enumerate(sorted(df.groupby("strategy"))):
        g = g.sort_values(x)
        ax.plot(
            g[x], g[y],
            label=strategy, color=_color_for(strategy, i),
            linewidth=2, marker="o", markersize=6,
        )
    ax.legend(frameon=False, labelcolor=TEXT)


def _save(fig, out_dir: str, name: str, names: List[str]) -> None:
    path = os.path.join(out_dir, name)
    fig.patch.set_facecolor(SURFACE)
    fig.tight_layout()
    fig.savefig(path, dpi=150)
    plt.close(fig)
    names.append(name)
    print(f"Wrote {path}")


def make_plots(df: pd.DataFrame, out_dir: str) -> List[str]:
    os.makedirs(out_dir, exist_ok=True)
    written: List[str] = []

    fig, ax = plt.subplots(figsize=(7, 4.5))
    _line_per_strategy(df, "world_size", "tokens_per_sec", ax)
    _style_axes(ax, "Chips", "Tokens/sec", "Throughput vs chip count")
    _save(fig, out_dir, "tokens_per_sec_vs_gpu.png", written)

    fig, ax = plt.subplots(figsize=(7, 4.5))
    _line_per_strategy(df, "world_size", "mean_step_time_sec", ax)
    _style_axes(ax, "Chips", "Mean step time (s)", "Step time vs chip count")
    _save(fig, out_dir, "step_time_vs_gpu.png", written)

    if df["seq_len"].nunique() > 1:
        # Measured peak when the platform reports allocator stats; the
        # pre-flight analytic estimate otherwise (all-zero measured column).
        mem_col, mem_label = "peak_vram_gb", "Peak HBM (GB)"
        if df["peak_vram_gb"].max() == 0 and "est_hbm_gb" in df.columns:
            mem_col, mem_label = "est_hbm_gb", "Estimated HBM (GB)"
        fig, ax = plt.subplots(figsize=(7, 4.5))
        for i, (key, g) in enumerate(sorted(df.groupby(_seq_key_cols(df)))):
            key = key if isinstance(key, tuple) else (key,)
            g = g.sort_values("seq_len")
            ax.plot(
                g["seq_len"], g[mem_col],
                label=" ".join(str(k) for k in key),
                color=_color_for(key[0], i),
                linestyle="--" if "reference" in key else "-",
                linewidth=2, marker="o", markersize=6,
            )
        ax.legend(frameon=False, labelcolor=TEXT, fontsize=8)
        _style_axes(ax, "Sequence length", mem_label, "Memory vs sequence length")
        _save(fig, out_dir, "vram_vs_seqlen.png", written)

    fig, ax = plt.subplots(figsize=(7, 4.5))
    _line_per_strategy(df, "world_size", "scaling_efficiency_pct", ax)
    xs = sorted(df["world_size"].unique())
    ax.plot(xs, [100.0] * len(xs), linestyle="--", color=TEXT_2, linewidth=1.5,
            label="ideal (100%)")
    ax.legend(frameon=False, labelcolor=TEXT)
    _style_axes(ax, "Chips", "Scaling efficiency (%)", "Scaling efficiency vs chip count")
    _save(fig, out_dir, "scaling_efficiency.png", written)

    fig, ax = plt.subplots(figsize=(7, 4.5))
    _line_per_strategy(df, "world_size", "h2d_gbps_per_gpu", ax)
    _style_axes(ax, "Chips", "H2D GB/s per chip (proxy)", "Host-to-device transfer proxy")
    _save(fig, out_dir, "gbps_vs_gpu.png", written)

    # --- Beyond-reference figures (rendered when the data supports them) ---

    # Per-strategy throughput bars, grouped by attention impl: the natural
    # view for a single-chip (world_size-degenerate) suite.
    impls = (
        sorted(df["attention_impl"].dropna().unique())
        if "attention_impl" in df.columns else []
    )
    base_seq = df["seq_len"].min()
    base = df[df["seq_len"] == base_seq]
    if impls:
        strategies = sorted(base["strategy"].unique())
        fig, ax = plt.subplots(figsize=(7, 4.5))
        width = 0.8 / max(len(impls), 1)
        hatches = {impl: h for impl, h in zip(impls, ["", "//", "..", "xx"])}
        for i, strategy in enumerate(strategies):
            for j, impl in enumerate(impls):
                rows = base[(base["strategy"] == strategy)
                            & (base["attention_impl"] == impl)]
                if rows.empty:
                    continue
                val = rows["tokens_per_sec"].max()
                ax.bar(
                    i + (j - (len(impls) - 1) / 2) * width, val, width * 0.92,
                    color=_color_for(strategy, i), hatch=hatches.get(impl, ""),
                    edgecolor=SURFACE, linewidth=0.5,
                )
                ax.text(
                    i + (j - (len(impls) - 1) / 2) * width, val, impl,
                    ha="center", va="bottom", fontsize=8, color=TEXT_2,
                    rotation=0,
                )
        ax.set_xticks(range(len(strategies)))
        ax.set_xticklabels(strategies)
        _style_axes(
            ax, "Strategy", "Tokens/sec",
            f"Throughput by strategy and attention impl (seq {base_seq})",
        )
        ax.grid(axis="x", visible=False)
        _save(fig, out_dir, "tokens_per_sec_by_strategy.png", written)

    # MFU bars — the metric the reference never measured.
    if "mfu_pct" in df.columns and (base["mfu_pct"] > 0).any():
        fig, ax = plt.subplots(figsize=(7, 4.5))
        rows = (
            base[base["mfu_pct"] > 0]
            .sort_values("mfu_pct", ascending=False)
            .drop_duplicates(subset=[c for c in ("strategy", "attention_impl")
                                     if c in base.columns])
        )
        labels = [
            f"{r.strategy}\n({getattr(r, 'attention_impl', '')})"
            for r in rows.itertuples()
        ]
        ax.bar(
            range(len(rows)), rows["mfu_pct"],
            color=[_color_for(s, i) for i, s in enumerate(rows["strategy"])],
            edgecolor=SURFACE, linewidth=0.5,
        )
        ax.set_xticks(range(len(rows)))
        ax.set_xticklabels(labels, fontsize=8)
        _style_axes(
            ax, "Strategy (attention)", "Model FLOPs utilization (%)",
            f"MFU by strategy (seq {base_seq})",
        )
        ax.grid(axis="x", visible=False)
        _save(fig, out_dir, "mfu_by_strategy.png", written)

    # Memory waterfall (memory-anatomy round): per-arm stacked attribution
    # of the reference peak — params/grads/opt/activations/dataset/
    # XLA-temp — with the signed unattributed residual as a floating tail
    # and the analytic estimate as a tick. Rendered whenever parse_metrics
    # flattened hbm_attr_* columns into the frame (rows without the
    # reconciliation are skipped). The memory-domain sibling of the time
    # waterfall in the anatomy/scaling sections.
    from .memory_anatomy import ATTRIBUTION_CLASSES

    class_colors = {
        "params": "#2a78d6", "grads": "#eb6834", "opt_state": "#eda100",
        "activations": "#1baf7a", "dataset": "#e87ba4",
        "xla_temp": "#4a3aa7",
    }
    attr_classes = [
        (c, class_colors.get(c, "#008300"))
        for c in ATTRIBUTION_CLASSES if c != "unattributed"
    ]
    attr_cols = [f"hbm_attr_{c}" for c, _ in attr_classes]
    if all(c in df.columns for c in attr_cols):
        rows = df[df[attr_cols[0]].notna()]
        if len(rows):
            fig, ax = plt.subplots(
                figsize=(7, max(2.5, 0.5 * len(rows) + 1.5))
            )
            labels = []
            for y, (_, r) in enumerate(rows.iterrows()):
                left = 0.0
                for (cls, color), col in zip(attr_classes, attr_cols):
                    w = float(r[col]) if r[col] == r[col] else 0.0
                    ax.barh(y, w, left=left, color=color,
                            edgecolor=SURFACE, linewidth=0.4,
                            label=cls if y == 0 else None)
                    left += max(w, 0.0)
                resid = r.get("hbm_attr_unattributed")
                if resid is not None and resid == resid:
                    ax.barh(y, float(resid), left=left, color="#52514e",
                            alpha=0.5, edgecolor=SURFACE, linewidth=0.4,
                            label="unattributed" if y == 0 else None)
                est = r.get("hbm_est_total_gib")
                if est is not None and est == est:
                    ax.plot([float(est)] * 2, [y - 0.4, y + 0.4],
                            color=TEXT, linewidth=1.2, linestyle="--",
                            label="analytic est" if y == 0 else None)
                labels.append(
                    f"{r['strategy']} ws{int(r['world_size'])} "
                    f"seq{int(r['seq_len'])}"
                )
            ax.set_yticks(range(len(rows)))
            ax.set_yticklabels(labels, fontsize=8)
            ax.legend(frameon=False, labelcolor=TEXT, fontsize=7, ncol=4)
            _style_axes(ax, "GiB per chip", "",
                        "HBM peak attribution (memory anatomy)")
            ax.grid(axis="y", visible=False)
            _save(fig, out_dir, "hbm_anatomy.png", written)

    # Long-context throughput: tokens/sec vs sequence length. One line per
    # (strategy, attention impl, world size) — a mixed results dir holds
    # several rows per (strategy, seq_len) and merging them into one line
    # would draw meaningless vertical zigzags.
    if df["seq_len"].nunique() > 1:
        fig, ax = plt.subplots(figsize=(7, 4.5))
        for i, (key, g) in enumerate(sorted(df.groupby(_seq_key_cols(df)))):
            key = key if isinstance(key, tuple) else (key,)
            g = g.sort_values("seq_len")
            ax.plot(
                g["seq_len"], g["tokens_per_sec"],
                label=" ".join(str(k) for k in key),
                color=_color_for(key[0], i),
                linestyle="--" if "reference" in key else "-",
                linewidth=2, marker="o", markersize=6,
            )
        ax.set_xscale("log", base=2)
        ax.legend(frameon=False, labelcolor=TEXT, fontsize=8)
        _style_axes(
            ax, "Sequence length", "Tokens/sec",
            "Throughput vs sequence length",
        )
        _save(fig, out_dir, "tokens_vs_seqlen.png", written)

    return written


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--results", required=True, help="path to metrics.csv")
    p.add_argument("--out", required=True, help="output directory for PNGs")
    args = p.parse_args(argv)
    df = pd.read_csv(args.results)
    make_plots(df, args.out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
