#!/usr/bin/env python
"""Aggregate result.json files into metrics.csv with scaling efficiency.

Contract parity with the reference aggregator (``scripts/parse_metrics.py``):

- discovers results by recursive glob for ``result*.json`` under
  ``--results-dir`` (reference ``parse_metrics.py:21``);
- emits ``metrics.csv`` whose leading columns are exactly the reference's
  (sample: ``results/example_output/README.md:85-92``), with
  ``scaling_efficiency_pct`` last; TPU-additive columns sit in between and
  name-based consumers are unaffected;
- scaling efficiency uses the *same formula* (reference
  ``parse_metrics.py:50-63``): for each (strategy, seq_len) group the baseline
  is the row with minimum world_size, and

      efficiency_pct = tokens_per_sec / (baseline_tps * world_size) * 100

  which pins baseline-world-size rows at ``100/baseline_ws`` % — with the
  reference's 2-GPU-minimum data that produced the "50% at 2 GPU" quirk; our
  suites include world_size=1 rows so the baseline is a true single-chip run
  and the numbers become honest automatically.
"""

from __future__ import annotations

import argparse
import json
import os
from pathlib import Path
from typing import List

import pandas as pd

REFERENCE_COLUMNS = [
    "strategy", "world_size", "rank", "seq_len", "tier", "steps",
    "per_device_batch", "grad_accum", "tokens_per_sec", "mean_step_time_sec",
    "mean_loss", "peak_vram_gb", "h2d_gbps_per_gpu",
]


def _partial_row(p: dict) -> dict:
    """Map a salvaged heartbeat payload (collect_results.sh
    ``partial_<arm>.json``) onto the result-row column space.

    A dead arm's last heartbeat carries its run identity plus the
    progress metrics at its final sync window; mapping them here is what
    makes failed arms appear in metrics.csv/the report as visibly-partial
    rows instead of vanishing. Metrics the heartbeat cannot know (peak
    memory, MFU, ...) stay absent -> NaN in the frame.
    """
    row = {
        k: p[k] for k in (
            "strategy", "world_size", "rank", "seq_len", "tier",
            "model_family", "per_device_batch", "grad_accum",
            "tokens_per_sec",
            # Composition axes (in the heartbeat meta since round 8): keep
            # partial rows from colliding arms — e.g. the zigzag A/B pair —
            # distinct under the dedup key below.
            "attention_impl", "tensor_parallel", "sequence_parallel",
            "pipeline_parallel", "pipeline_schedule", "expert_parallel",
            "n_experts", "causal", "ring_zigzag",
            # Streaming-data progress (stream runs stamp these on every
            # heartbeat): a salvaged input-starved arm keeps its honest
            # stall/skip accounting AND its stream lineage identity in
            # the partial row (store.config_key reads data_mode — a dead
            # stream arm must not be misfiled into the synthetic lineage).
            "data_mode", "data_stall_frac", "records_skipped",
            # Collective-matmul identity (round 15): keeps a dead cmm
            # arm's partial row distinct from its plain-tp A/B partner
            # and in the cmm regress lineage.
            "tp_collective_matmul",
        ) if k in p
    }
    if "total_steps" in p:
        row["steps"] = p["total_steps"]
    if "window_mean_step_time_sec" in p:
        row["mean_step_time_sec"] = p["window_mean_step_time_sec"]
    if "loss" in p and p["loss"] is not None:
        # The LAST observed loss, not a run mean — close enough for a
        # partial row, and the partial flag warns every consumer.
        row["mean_loss"] = p["loss"]
    row["last_step"] = p.get("step")
    row["partial"] = True
    # Death classification + stitched-run accounting (chaos round): the
    # collect script stamps reason=preempted|crash, and a resumed arm's
    # heartbeats carry resumed/n_restarts — the report separates a
    # preempted pod (checkpointed, resumable) from a genuine crash.
    for k in ("reason", "resumed", "n_restarts", "resume_geometry_changed"):
        if k in p:
            row[k] = p[k]
    return row


def _flatten_memory_anatomy(row: dict) -> dict:
    """Expand the memory-anatomy dict fields into scalar CSV columns.

    ``hbm_attribution`` becomes one ``hbm_attr_<class>`` column per
    attribution class and ``hbm_estimate`` collapses to its total
    (``hbm_est_total_gib``) — metrics.csv is the plot/report substrate
    and dict-valued cells would stringify uselessly there; the full
    dicts stay in the result JSON (the registry records keep them too).
    """
    attr = row.pop("hbm_attribution", None)
    if isinstance(attr, dict):
        for cls, val in attr.items():
            row[f"hbm_attr_{cls}"] = val
    est = row.pop("hbm_estimate", None)
    if isinstance(est, dict):
        row["hbm_est_total_gib"] = est.get("total_gib")
    return row


def _flatten_supervision(row: dict) -> dict:
    """Expand the fleet supervisor's recovery-history stamp into scalar
    CSV columns.

    ``supervision`` is the summary the supervisor copies from its
    ``supervision.json`` ledger onto the final result row of a RECOVERED
    run (runtime/supervisor.py): attempt count, the actions taken, and
    any geometry shrink/regrow legs. Flattened beside the existing
    resumed/healed/partial accounting so the report (and a human
    grepping the CSV) sees the whole recovery history; unsupervised
    rows omit the columns entirely.
    """
    sup = row.pop("supervision", None)
    if isinstance(sup, dict):
        row["supervised_attempts"] = sup.get("n_attempts")
        row["supervised_actions"] = ",".join(sup.get("actions") or [])
        row["supervised_shrink_legs"] = ",".join(sup.get("shrink_legs") or [])
    return row


def _note_give_up_ledgers(results_dir: str) -> None:
    """Name every supervision ledger that ended in give-up: those arms
    published no result row (at most a salvaged partial), so the ledger
    on disk is their only first-class trace — surface it here rather
    than letting the aggregation silently read as 'arm never ran'."""
    for path in sorted(Path(results_dir).rglob("supervision*.json")):
        try:
            with open(path) as f:
                ledger = json.load(f)
        except (json.JSONDecodeError, OSError):
            continue
        if ledger.get("gave_up"):
            print(
                f"NOTE: supervisor gave up after "
                f"{ledger.get('n_attempts')} attempt(s) "
                f"(final class: {ledger.get('final_class')}) — see {path}"
            )


def load_results(results_dir: str) -> pd.DataFrame:
    rows = []
    for path in sorted(Path(results_dir).rglob("result*.json")):
        try:
            with open(path) as f:
                rows.append(
                    _flatten_supervision(_flatten_memory_anatomy(json.load(f)))
                )
        except (json.JSONDecodeError, OSError) as e:
            print(f"WARNING: skipping unreadable {path}: {e}")
    n_full = len(rows)
    _note_give_up_ledgers(results_dir)
    for path in sorted(Path(results_dir).rglob("partial_*.json")):
        try:
            with open(path) as f:
                rows.append(_partial_row(json.load(f)))
        except (json.JSONDecodeError, OSError) as e:
            print(f"WARNING: skipping unreadable {path}: {e}")
    if not rows:
        raise SystemExit(f"No result*.json files found under {results_dir}")
    if len(rows) > n_full:
        print(f"NOTE: {len(rows) - n_full} partial row(s) from heartbeat "
              "salvage (runs that died before their final result marker)")
        for r in rows[:n_full]:
            r.setdefault("partial", False)
    df = pd.DataFrame(rows)
    # The same run can surface twice: the harness writes result_<arm>.json and
    # the log scraper extracts result.json for the identical run. Dedupe on
    # the run identity key.
    key = [
        c for c in (
            "strategy", "world_size", "seq_len", "tier", "model_family",
            "rank", "per_device_batch", "grad_accum", "steps",
            "attention_impl",
            # Composition axes: a pipeline/TP/SP/MoE/bf16 arm is a DIFFERENT
            # run from the baseline with the same batch geometry — without
            # these in the key, a composition suite sharing RESULTS_DIR with
            # a baseline suite would dedupe one of them away.
            "tensor_parallel", "sequence_parallel", "pipeline_parallel",
            "pipeline_schedule", "virtual_stages", "expert_parallel",
            "n_experts", "remat_policy", "param_dtype", "offload_opt_state",
            "offload_delayed_update", "offload_dpu_start_step", "causal",
            "ring_zigzag", "tp_collective_matmul",
            # Stitched-run identity (scaling suite): a reshard-on-restore
            # continuation shares every config axis with the fresh point
            # at the same geometry — without these, one of the two honest
            # rows silently vanishes from metrics.csv.
            "resumed", "resume_geometry_changed",
        ) if c in df.columns
    ]
    df = df.drop_duplicates(subset=key, keep="first")
    return df.sort_values(["strategy", "seq_len", "world_size"]).reset_index(drop=True)


def add_scaling_efficiency(df: pd.DataFrame) -> pd.DataFrame:
    """Reference formula (parse_metrics.py:50-63), reproduced exactly.

    Grouping extends the reference's (strategy, seq_len) with every other
    config axis we preserve through dedup (attention_impl, batch shape, ...),
    so a row's baseline always ran the identical configuration at the smallest
    world size — never a different kernel's throughput.
    """
    group_cols = ["strategy", "seq_len"] + [
        c for c in (
            "tier", "model_family", "per_device_batch", "grad_accum",
            "attention_impl",
            "tensor_parallel", "sequence_parallel", "pipeline_parallel",
            "pipeline_schedule", "virtual_stages", "expert_parallel",
            "n_experts", "param_dtype", "offload_opt_state",
            "offload_delayed_update", "offload_dpu_start_step", "causal",
            "ring_zigzag", "tp_collective_matmul",
        )
        if c in df.columns
    ]
    df = df.copy()
    df["scaling_efficiency_pct"] = 0.0
    # Partial rows (heartbeat salvage): a truncated run's throughput must
    # neither serve as a group baseline nor mint an efficiency number of
    # its own — its last-window rate is not a run mean. NaN marks the cell
    # as not-measured (0.0 would read as a catastrophic measurement).
    if "partial" in df.columns:
        is_partial = df["partial"].fillna(False).astype(bool)
        df.loc[is_partial, "scaling_efficiency_pct"] = float("nan")
        eligible = df[~is_partial]
    else:
        eligible = df
    # Stitched (resumed) and sentinel-healed rows get their efficiency
    # computed — they are honest rows and the report flags them — but
    # never serve as a group BASELINE: a restore-folding first window is
    # not the per-chip ideal everything else should be normalized by
    # (the same posture the regress registry's _eligible chain takes).
    ineligible_base = pd.Series(False, index=eligible.index)
    for col in ("resumed", "resume_geometry_changed"):
        if col in eligible.columns:
            ineligible_base |= eligible[col].fillna(False).astype(bool)
    if "n_rollbacks" in eligible.columns:
        ineligible_base |= eligible["n_rollbacks"].fillna(0).astype(float) > 0
    if "supervised_attempts" in eligible.columns:
        # Supervisor-recovered rows (attempt > 1: the measurement spans a
        # restart, possibly a geometry shrink leg) never anchor the ideal.
        ineligible_base |= (
            eligible["supervised_attempts"].fillna(1).astype(float) > 1
        )
    # dropna=False: rows from before a schema addition carry NaN in the
    # newer axis columns and must still get their efficiency computed
    # (pandas silently drops NaN-keyed groups by default).
    for _, group in eligible.groupby(group_cols, dropna=False):
        base_pool = group[~ineligible_base.loc[group.index]]
        if not len(base_pool):
            # Only stitched/healed rows at this config: no honest ideal
            # to normalize by — leave their efficiency unmeasured.
            df.loc[group.index, "scaling_efficiency_pct"] = float("nan")
            continue
        base = base_pool.loc[base_pool["world_size"].idxmin()]
        for i in group.index:
            row = df.loc[i]
            denom = base["tokens_per_sec"] * row["world_size"]
            df.loc[i, "scaling_efficiency_pct"] = (
                row["tokens_per_sec"] / denom * 100.0 if denom > 0 else 0.0
            )
    return df


def to_csv(df: pd.DataFrame, out_path: str) -> None:
    extras = [
        c for c in df.columns
        if c not in REFERENCE_COLUMNS + ["scaling_efficiency_pct"]
    ]
    cols = [c for c in REFERENCE_COLUMNS if c in df.columns] + extras + [
        "scaling_efficiency_pct"
    ]
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    df[cols].to_csv(out_path, index=False)


def main(argv: List[str] | None = None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--results-dir", required=True)
    p.add_argument("--out", required=True, help="output directory for metrics.csv")
    args = p.parse_args(argv)

    df = add_scaling_efficiency(load_results(args.results_dir))
    out_csv = os.path.join(args.out, "metrics.csv")
    to_csv(df, out_csv)

    print(f"Parsed {len(df)} results -> {out_csv}")
    summary_cols = [
        "strategy", "world_size", "seq_len", "tokens_per_sec",
        "mean_step_time_sec", "peak_vram_gb", "scaling_efficiency_pct",
    ]
    print(df[[c for c in summary_cols if c in df.columns]].to_string(index=False))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
