#!/usr/bin/env python
"""Executable result-sanity checks (the validation envelopes, enforced).

The reference *documents* expected-result bands for operators to eyeball
(reference ``results/example_output/README.md:120-146``: loss range, <10%
step-time variance, plausible VRAM); this repo's
``results/example_output/README.md`` documents the TPU equivalents. This
module turns those prose envelopes into a suite step that fails loudly:

- **schema**: every ``result*.json`` carries the reference-contract keys with
  sane values (tokens_per_sec > 0, step time > 0);
- **markers**: every captured run log contains exactly one
  ``BENCHMARK_RESULT_JSON_START``/``_END`` pair whose payload parses — the
  contract the kubectl-logs collector scrapes (reference
  ``scripts/collect_results.sh:50-59``);
- **loss band**: mean_loss below the ~ln(V) random-init ceiling and above a
  degenerate floor — training happened and did not diverge/NaN;
- **step-time variance**: coefficient of variation < 10% over the timed
  steps (reference envelope "<10% variance"), checked only where
  ``sync_every == 1`` makes per-step times individually meaningful;
- **memory**: measured peak (when the platform reports one) and the
  analytic estimate agree within a stated tolerance, and neither exceeds
  the device's HBM capacity;
- **MFU floors** (round 5): published single-chip tier-A rows must not
  silently regress — per-seq-len floors a few points under the measured
  table (docs/PERFORMANCE.md §9/§12), applied only to the published-arm
  geometry (tier A, ws=1, v5e, dense, no offload) so experimental configs
  aren't blocked;
- **offload CV allowance**: ZeRO-Offload rows run the optimizer on the
  host CPU, whose load jitter legitimately exceeds the 10% device
  envelope (PERFORMANCE.md §13) — they get their own, looser CV limit
  instead of silently skipping the check.

Exit code 0 = all envelopes hold; 1 = any violation (listed on stdout).
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
from typing import List, Optional, Tuple

MARKER_START = "BENCHMARK_RESULT_JSON_START"
MARKER_END = "BENCHMARK_RESULT_JSON_END"

# mean_loss over the first ~100 steps must land inside (FLOOR, ln(V) + SLACK).
# A mean below FLOOR at benchmark step counts means the loss collapsed (data
# leak / targets bug); above the ceiling means it never trained or diverged.
LOSS_FLOOR = 0.05
LOSS_CEIL_SLACK = 0.5
STEP_CV_LIMIT_PCT = 10.0
# utils/memory.py's documented accuracy claim for the analytic model,
# validated here against the measured column whenever one exists. The band
# is asymmetric: an UNDERestimate is the dangerous direction (the
# pre-flight would wave through a config that OOMs), so it keeps the tight
# band; an OVERestimate is conservative (refuses early, never OOMs) and
# gets a wider one — at long sequences with full remat, XLA's scheduling
# lets the fp32 logits cotangent alias the logits buffer, landing the
# measured peak one logits-size below the model (32K row: est 15.9 GB vs
# measured 11.3 GB).
EST_VS_MEASURED_TOL = 0.35          # measured > est (underestimate)
EST_VS_MEASURED_TOL_OVER = 0.60     # est > measured (conservative)
# ...with an absolute-slack floor: at tiny footprints (tier-S smoke runs,
# heavily-sharded per-device peaks) the analytic model's ignored constants
# (runtime buffers, padding) dominate, so a pure relative band would flag
# noise. A violation requires BOTH the relative band and this many GB of
# absolute divergence. Tier-S smoke artifacts skip the check entirely.
EST_VS_MEASURED_ABS_SLACK_GB = 0.25
# Published-row MFU floors (% of v5e peak), a few points under the measured
# single-chip tier-A table so real regressions trip while run-to-run noise
# (±1.5% observed) does not: 2K 38.2%, 4K 33.6%, 8K 28.8%, 16K 24.6%
# measured (docs/PERFORMANCE.md §9/§12).
MFU_FLOORS_TIER_A = {2048: 36.0, 4096: 31.0, 8192: 26.0, 16384: 22.0,
                     32768: 15.5}
# The published MoE row (tier A base + E=8 top-2, bf16 params, measured
# 29.0% — MoE MFU counts only the top-k active experts' FLOPs).
MFU_FLOOR_MOE8 = 26.0
# The published causal 2K row (measured 34.2% against the causal FLOP
# count — attention work halves under the mask, so the denominator is not
# the bidirectional rows').
MFU_FLOOR_CAUSAL_2K = 31.0
# The published Llama-family rows (models.llama tier A: head_dim 128,
# GQA, SwiGLU, no dropout; measured 2K 45.2%, 8K 54.4%, 16K 42.0% — the
# wide-head shape clears the D=64 score-tile wall documented in
# PERFORMANCE.md §15/§16, and at long sequences holds ~2x the TinyGPT
# rows' MFU because the attention fraction grows on the family's more
# MXU-efficient kernel shape).
MFU_FLOORS_LLAMA = {2048: 42.0, 8192: 50.0, 16384: 38.0}
# Routing-health envelope for MoE rows: the capacity discipline drops SOME
# assignments (cf 1.25 < top-k worst case), but beyond this bound routing
# has collapsed onto a few experts (or capacity accounting broke).
EXPERT_OVERFLOW_MAX_PCT = 60.0
# Host-CPU AdamW step-time jitter under host load (PERFORMANCE.md §13
# documents p50 varying 3.6-6.2 s run-to-run; within-run CV stays well
# under this).
OFFLOAD_STEP_CV_LIMIT_PCT = 25.0
# Loss-descent envelope: rows long enough to have visibly trained
# (>= this many steps) must show loss_last_window <= loss_first_window -
# delta(family, steps). The mean-loss band alone cannot catch a FROZEN run
# (a flat line at 6.0 has a healthy-looking mean); this one does. Deltas
# are conservative fractions of the measured 100-step descents (tinygpt
# tier A descends ~5 nats in 100 steps; the llama family's measured slow
# trajectory still descends ~0.49 — see docs/PERFORMANCE.md §16), scaled
# linearly below 100 steps. Rows without the window keys (pre-round-6
# artifacts) skip the check.
LOSS_DESCENT_MIN_STEPS = 50
LOSS_DESCENT_DELTA = {"tinygpt": 0.25, "llama": 0.15}
# Resume-continuity envelope (chaos round, docs/FAULT_TOLERANCE.md): a
# resumed row records the loss its checkpoint was saved at
# (resume_baseline_loss); the post-resume first window must land near it.
# A cold restart POSING as a resume starts back at the ~ln(V) random-init
# ceiling — several nats above any mid-training checkpoint — so a modest
# absolute slack separates the two cleanly while tolerating the genuine
# wobble of an optimizer restart.
RESUME_LOSS_CONT_SLACK = 1.5
# Flight-recorder phase-attribution envelope (round 8): the recorder's
# phases are sequential and disjoint by construction, so the published
# time_in_* fields must be non-negative and their sum must not exceed the
# run's wall time (2% relative + 50 ms absolute slack for clock rounding).
# Rows from before the telemetry round carry no wall_time_total_sec and
# skip the check.
PHASE_TIME_FIELDS = (
    "time_in_init_sec", "time_in_compile_sec", "time_in_warmup_sec",
    "time_in_timed_sec", "time_in_checkpoint_sec", "time_in_trace_sec",
)
PHASE_SUM_REL_TOL = 1.02
PHASE_SUM_ABS_SLACK_SEC = 0.05
# Step-anatomy envelope (analysis/step_anatomy.py): the trace-derived
# fractions are each in [0, 1], and the three ADDITIVE step components
# (compute + exposed comms + idle) sum to the step — never beyond it
# (small slack for interval-arithmetic rounding). Roofline positions are
# percentages of a hardware peak: a value past ~110% means the cost or
# peak accounting broke, not that the chip beat its spec. Rows without
# the fields (no --profile-dir, pre-anatomy artifacts) skip the check.
ANATOMY_FRAC_FIELDS = (
    "anatomy_compute_frac", "comms_exposed_frac", "comms_overlap_frac",
    "anatomy_idle_frac", "bubble_frac",
)
ANATOMY_COMPONENT_SUM_TOL = 1.02
ROOFLINE_PCT_MAX = 110.0
# Streaming-data-path coherence envelope (data/stream.py, streaming
# round): rows with data_mode == "stream" must carry an internally
# coherent input ledger — data_stall_frac in [0, 1] (the waits happen
# inside the published step times, so the fraction is structural),
# cursor_end - cursor_start == records_consumed == steps_run x
# records/step (stream-position continuity: no replayed or skipped
# records across a stitch; the per-step record count is closed-form from
# the row's own batch geometry), and a same-geometry resume must start
# exactly where the restored checkpoint's sidecar left off. A
# geometry-change resume changes records/step, so only the within-run
# arithmetic is checkable there. records_skipped is additionally
# cross-checked against the telemetry quarantine events in
# validate_telemetry.
# Memory-anatomy envelope (analysis/memory_anatomy.py): rows carrying the
# reconciliation must be internally coherent — the persisted estimate and
# the measured column must COEXIST (hbm_measured may be null only with an
# explicit reason), every attribution class except the signed residual is
# non-negative, and the classes must close the books on the reference
# peak (that is the reconciliation's defining invariant; a gap means the
# engine and the stored row drifted). Rows without the fields
# (pre-memory-anatomy artifacts) skip every check.
HBM_BOOKS_CLOSE_TOL_GIB = 0.002


def _check(ok: bool, label: str, detail: str, failures: List[str]) -> None:
    if not ok:
        failures.append(f"{label}: {detail}")


def validate_result(r: dict, name: str) -> List[str]:
    """Envelope-check one result dict; returns a list of violations."""
    f: List[str] = []
    for key in (
        "strategy", "world_size", "seq_len", "tokens_per_sec",
        "mean_step_time_sec", "mean_loss", "peak_vram_gb", "h2d_gbps_per_gpu",
    ):
        _check(key in r, name, f"missing reference-schema key {key!r}", f)
    if f:
        return f

    _check(r["tokens_per_sec"] > 0, name,
           f"tokens_per_sec={r['tokens_per_sec']} (must be > 0)", f)
    _check(r["mean_step_time_sec"] > 0, name,
           f"mean_step_time_sec={r['mean_step_time_sec']} (must be > 0)", f)

    loss = r["mean_loss"]
    # Reference tiers A/B share the 32000 vocab; tier S (CPU smoke) is 512 —
    # its random-init ceiling is ~4.6 nats lower (tinygpt.get_model_config).
    vocab = 512 if r.get("tier") == "S" else 32000
    ceil = math.log(vocab) + LOSS_CEIL_SLACK
    _check(
        LOSS_FLOOR < loss < ceil, name,
        f"mean_loss={loss:.4f} outside ({LOSS_FLOOR}, ln({vocab})+"
        f"{LOSS_CEIL_SLACK}={ceil:.2f}) — not training or diverged", f,
    )
    _check(loss == loss, name, "mean_loss is NaN", f)

    # Descent envelope (see LOSS_DESCENT_DELTA): a non-training run must not
    # pass validation on a plausible mean alone. Resumed rows are exempt —
    # a run restored from a well-trained checkpoint legitimately starts
    # near its converged loss, with no from-scratch descent left to show.
    first_w = r.get("loss_first_window", 0.0) or 0.0
    last_w = r.get("loss_last_window", 0.0) or 0.0
    if (
        r.get("steps", 0) >= LOSS_DESCENT_MIN_STEPS
        and first_w > 0
        and last_w > 0
        and not r.get("resumed")
    ):
        fam = r.get("model_family", "tinygpt")
        base = LOSS_DESCENT_DELTA.get(fam, min(LOSS_DESCENT_DELTA.values()))
        delta = base * min(r["steps"], 100) / 100.0
        _check(
            last_w <= first_w - delta, name,
            f"loss_last_window={last_w:.4f} not below loss_first_window="
            f"{first_w:.4f} - {delta:.3f} ({fam} descent envelope at "
            f"{r['steps']} steps) — the run did not train", f,
        )

    # Resumed (stitched) rows: the first timed window after a restore
    # folds in the recompile (the loop's timed-first-step shape), so the
    # CV envelope is not a device-stability signal there. The stitch is
    # policed by its own continuity check below — and resumed rows are
    # never regression baselines anyway (regress.store).
    if (
        r.get("sync_every", 1) == 1 and r.get("step_time_cv_pct", 0) > 0
        and not r.get("resumed")
    ):
        cv = r["step_time_cv_pct"]
        cv_limit = (
            OFFLOAD_STEP_CV_LIMIT_PCT if r.get("offload_opt_state")
            else STEP_CV_LIMIT_PCT
        )
        _check(
            cv < cv_limit, name,
            f"step-time cv {cv:.1f}% >= {cv_limit}% envelope"
            + (" (offload allowance)" if r.get("offload_opt_state") else ""), f,
        )

    # Stitched-run honesty (chaos round): a row claiming resumed=true must
    # carry a coherent restart ledger, and its post-resume loss must be
    # CONTINUOUS with the checkpoint it claims to extend — a cold restart
    # mislabeled as a resume restarts at the random-init ceiling and is
    # rejected here.
    if r.get("resumed"):
        if "n_restarts" in r:
            _check(
                int(r.get("n_restarts") or 0) >= 1, name,
                f"resumed=true but n_restarts={r.get('n_restarts')} "
                "(the restart ledger must count at least the one resume)", f,
            )
        baseline = r.get("resume_baseline_loss", 0.0) or 0.0
        if baseline > 0 and first_w > 0:
            _check(
                first_w <= baseline + RESUME_LOSS_CONT_SLACK, name,
                f"loss_first_window={first_w:.4f} is discontinuous with "
                f"resume_baseline_loss={baseline:.4f} (+{RESUME_LOSS_CONT_SLACK} "
                "slack) — the run did not actually continue from its "
                "checkpoint", f,
            )
    elif int(r.get("n_restarts") or 0) > 0:
        f.append(
            f"{name}: n_restarts={r.get('n_restarts')} on a row with "
            "resumed=false — restart accounting is incoherent"
        )

    # Sentinel-rollback coherence (self-healing round, docs/
    # FAULT_TOLERANCE.md): a healed row's ledger must hang together —
    # every rollback replays at least the step its trip poisoned (the
    # checkpoint-save guard makes restore_step < trip_step structural),
    # and replayed steps without a rollback mean the accounting broke.
    n_rb = int(r.get("n_rollbacks") or 0)
    n_replayed = int(r.get("rollback_steps_replayed") or 0)
    if n_rb > 0:
        _check(
            n_replayed >= n_rb, name,
            f"n_rollbacks={n_rb} but rollback_steps_replayed={n_replayed} "
            "— every rollback replays at least one step; the sentinel "
            "ledger is incoherent", f,
        )
    elif n_replayed > 0:
        f.append(
            f"{name}: rollback_steps_replayed={n_replayed} on a row with "
            "n_rollbacks=0 — replayed steps without a rollback; the "
            "sentinel ledger is incoherent"
        )

    # Elastic-resume coherence: a geometry-changed stitch IS a resume —
    # the flag without resumed=true means the accounting (and therefore
    # the never-baseline exclusion downstream) is broken.
    if r.get("resume_geometry_changed") and not r.get("resumed"):
        f.append(
            f"{name}: resume_geometry_changed=true on a row with "
            "resumed=false — a resharded restore is a resume; the "
            "stitch accounting is incoherent"
        )

    # Supervision-stamp coherence (elastic fleet supervisor, runtime/
    # supervisor.py): the stamp exists only on RECOVERED rows, so
    # n_attempts must say so, and a recorded shrink leg means the final
    # attempt restored a checkpoint on a different geometry — the row
    # must carry the elastic-resume accounting too.
    sup = r.get("supervision")
    if sup is not None:
        n_att = int(sup.get("n_attempts") or 0)
        _check(
            n_att > 1, name,
            f"supervision stamp with n_attempts={n_att} — the supervisor "
            "stamps only recovered rows (attempt > 1); the recovery "
            "ledger is incoherent", f,
        )
        if sup.get("shrink_legs") and not r.get("resume_geometry_changed"):
            f.append(
                f"{name}: supervision.shrink_legs={sup.get('shrink_legs')} "
                "but resume_geometry_changed=false — a shrink leg IS a "
                "resharded resume; the recovery accounting is incoherent"
            )

    # MFU floors for the published-arm geometry only: tier A, single chip,
    # v5e, flash attention, dense model, device-resident optimizer, and
    # windowed timing (sync_every > 1 — the per-step block_until_ready
    # diagnostic runs legitimately sit ~11 points lower). Any other
    # geometry is exploratory and gets no floor.
    # Shared base: the published-arm geometry minus the causal/offload
    # axes (each floor below adds its own) — one predicate to update when
    # e.g. a v6 device kind joins the published set.
    family_geometry = (
        r.get("tier") == "A"
        and r.get("world_size") == 1
        and "v5" in str(r.get("device_kind", ""))
        and r.get("attention_impl") == "flash"
        and r.get("sync_every", 1) > 1
        and not r.get("offload_opt_state")
        and r.get("mfu_pct", 0) > 0
    )
    base_geometry = (
        family_geometry and r.get("model_family", "tinygpt") == "tinygpt"
    )
    llama_floor = MFU_FLOORS_LLAMA.get(r.get("seq_len"))
    if (
        family_geometry
        and r.get("model_family") == "llama"
        and llama_floor is not None
        and r.get("n_experts", 0) == 0
    ):
        _check(
            r["mfu_pct"] >= llama_floor, name,
            f"mfu_pct={r['mfu_pct']:.1f}% below the {llama_floor}% "
            "llama-family floor (published-row regression)", f,
        )
    published_geometry = base_geometry and not r.get("causal")
    floor = MFU_FLOORS_TIER_A.get(r.get("seq_len"))
    if floor is not None and published_geometry and r.get("n_experts", 0) == 0:
        _check(
            r["mfu_pct"] >= floor, name,
            f"mfu_pct={r['mfu_pct']:.1f}% below the {floor}% floor for "
            f"seq_len={r['seq_len']} (published-row regression)", f,
        )
    if (
        published_geometry
        and r.get("n_experts", 0) == 8
        and r.get("seq_len") == 2048
    ):
        _check(
            r["mfu_pct"] >= MFU_FLOOR_MOE8, name,
            f"mfu_pct={r['mfu_pct']:.1f}% below the {MFU_FLOOR_MOE8}% MoE "
            "floor (published-row regression)", f,
        )
    if (
        base_geometry
        and r.get("causal")
        and r.get("n_experts", 0) == 0
        and r.get("seq_len") == 2048
    ):
        _check(
            r["mfu_pct"] >= MFU_FLOOR_CAUSAL_2K, name,
            f"mfu_pct={r['mfu_pct']:.1f}% below the {MFU_FLOOR_CAUSAL_2K}% "
            "causal floor (published-row regression)", f,
        )
    ov = r.get("expert_overflow_pct")
    if ov is not None:
        _check(
            0.0 <= ov <= EXPERT_OVERFLOW_MAX_PCT, name,
            f"expert_overflow_pct={ov} outside [0, "
            f"{EXPERT_OVERFLOW_MAX_PCT}] — routing collapsed or capacity "
            "accounting broke", f,
        )

    est = r.get("est_hbm_gb", 0.0)
    measured = r.get("peak_hbm_gb", 0.0)
    method = r.get("peak_hbm_method", "unavailable")
    if (
        est > 0
        and measured > 0
        and r.get("tier") != "S"
        and method in ("allocator", "xla_buffer_assignment")
    ):
        rel = abs(measured - est) / measured
        tol = EST_VS_MEASURED_TOL_OVER if est > measured else EST_VS_MEASURED_TOL
        _check(
            rel <= tol
            or abs(measured - est) <= EST_VS_MEASURED_ABS_SLACK_GB, name,
            f"analytic est {est:.2f} GB vs measured {measured:.2f} GB "
            f"({method}) differ by {100*rel:.0f}% > "
            f"{100*tol:.0f}% tolerance", f,
        )
    cap = _hbm_capacity_gb(r.get("device_kind", ""))
    if cap is not None:
        for label, val in (("measured peak", measured), ("estimate", est)):
            _check(
                val <= cap, name,
                f"{label} {val:.2f} GB exceeds {cap:.1f} GB {r['device_kind']} HBM", f,
            )

    # Phase-time attribution envelope (PHASE_TIME_FIELDS above).
    wall = r.get("wall_time_total_sec", 0.0) or 0.0
    if wall > 0:
        phase_sum = 0.0
        for key in PHASE_TIME_FIELDS:
            val = r.get(key, 0.0) or 0.0
            _check(val >= 0, name, f"{key}={val} is negative", f)
            phase_sum += max(val, 0.0)
        _check(
            phase_sum <= wall * PHASE_SUM_REL_TOL + PHASE_SUM_ABS_SLACK_SEC,
            name,
            f"phase times sum to {phase_sum:.3f}s > wall_time_total_sec="
            f"{wall:.3f}s — phases must be disjoint", f,
        )
        _check(
            r.get("n_anomalies", 0) >= 0, name,
            f"n_anomalies={r.get('n_anomalies')} is negative", f,
        )

    # Step-anatomy envelope (ANATOMY_FRAC_FIELDS above).
    def _finite(key):
        v = r.get(key)
        return v if isinstance(v, (int, float)) and v == v else None

    for key in ANATOMY_FRAC_FIELDS:
        v = _finite(key)
        if v is not None:
            _check(
                -1e-6 <= v <= 1.0 + 1e-6, name,
                f"{key}={v} outside [0, 1] — the trace decomposition "
                "broke", f,
            )
    components = [_finite(k) for k in (
        "anatomy_compute_frac", "comms_exposed_frac", "anatomy_idle_frac",
    )]
    if all(v is not None for v in components):
        total = sum(components)
        _check(
            total <= ANATOMY_COMPONENT_SUM_TOL, name,
            f"step-anatomy components sum to {total:.4f} > 1 — compute + "
            "exposed comms + idle must not exceed the step time", f,
        )
    for key in ("roofline_flops_pct_of_peak", "roofline_hbm_pct_of_peak"):
        v = _finite(key)
        if v is not None:
            _check(
                0.0 <= v <= ROOFLINE_PCT_MAX, name,
                f"{key}={v} outside [0, {ROOFLINE_PCT_MAX}] — achieved "
                "past peak means the cost or peak table broke", f,
            )
    skew = _finite("straggler_skew_pct")
    if skew is not None:
        _check(skew >= 0.0, name,
               f"straggler_skew_pct={skew} is negative", f)

    # Streaming-data-path coherence envelope (see the constants note).
    if r.get("data_mode") == "stream":
        dsf = r.get("data_stall_frac")
        _check(
            isinstance(dsf, (int, float)) and dsf == dsf
            and -1e-9 <= dsf <= 1.0 + 1e-9, name,
            f"data_stall_frac={dsf} missing or outside [0, 1] on a "
            "stream row — the starvation accounting broke", f,
        )
        skipped = r.get("records_skipped")
        _check(
            isinstance(skipped, int) and skipped >= 0, name,
            f"records_skipped={skipped} must be a non-negative count", f,
        )
        consumed = int(r.get("records_consumed") or 0)
        cs = int(r.get("stream_cursor_start", -1))
        ce = int(r.get("stream_cursor_end", -1))
        _check(
            cs >= 0 and ce >= cs, name,
            f"stream cursors [{cs}, {ce}] incoherent on a stream row", f,
        )
        if cs >= 0 and ce >= cs:
            _check(
                ce - cs == consumed, name,
                f"stream_cursor_end - stream_cursor_start = {ce - cs} but "
                f"records_consumed={consumed} — the stream ledger is "
                "incoherent", f,
            )
            denom = max(
                int(r.get("tensor_parallel") or 1)
                * int(r.get("sequence_parallel") or 1)
                * int(r.get("pipeline_parallel") or 1)
                * int(r.get("expert_parallel") or 1), 1,
            )
            dp = max(int(r["world_size"]) // denom, 1)
            rps = (
                int(r["per_device_batch"]) * int(r["grad_accum"]) * dp
                * int(r.get("expert_parallel") or 1)
            )
            # NOT `or -1`: resume_step=0 is a legitimate restore (a run
            # stalled/preempted at step 1 checkpoints step 0) and must
            # not collapse to the falsy default.
            rs = r.get("resume_step")
            start = (int(rs) + 1
                     if r.get("resumed") and rs is not None else 0)
            expected = (int(r.get("steps") or 0) - start) * rps
            _check(
                consumed == expected, name,
                f"records_consumed={consumed} != (steps-{start}) x "
                f"{rps} records/step = {expected} — records were "
                "replayed or skipped across the run", f,
            )
            if (
                r.get("resumed")
                and not r.get("resume_geometry_changed")
                and int(r.get("n_restarts") or 0) == 1
            ):
                # Cross-run cursor continuity is closed-form only when
                # the WHOLE checkpoint lineage ran this geometry: on the
                # first resume, a same-geometry stitch means the prior
                # run was a cold start with this records/step. A later
                # restart (n_restarts > 1) may sit downstream of an
                # earlier geometry-change resume whose era consumed a
                # different records/step — there the sidecar cursor is
                # authoritative and only the within-run arithmetic above
                # is checkable.
                _check(
                    cs == start * rps, name,
                    f"stream_cursor_start={cs} but a same-geometry "
                    f"first resume from step {start - 1} must start at "
                    f"{start * rps} — the stitch replayed or skipped "
                    "records", f,
                )
            elif not r.get("resumed"):
                _check(
                    cs == 0, name,
                    f"stream_cursor_start={cs} on a non-resumed stream "
                    "row (must be 0)", f,
                )
    else:
        # Synthetic rows must stay inert: a stall fraction or skip count
        # on the zero-IO table means the accounting leaked across paths.
        if r.get("data_stall_frac") is not None:
            f.append(
                f"{name}: data_stall_frac={r['data_stall_frac']} on a "
                "non-stream row — the input accounting leaked"
            )
        if int(r.get("records_skipped") or 0) > 0:
            f.append(
                f"{name}: records_skipped={r['records_skipped']} on a "
                "non-stream row — the quarantine accounting leaked"
            )

    # Memory-anatomy envelope (HBM_BOOKS_CLOSE_TOL_GIB above).
    attr = r.get("hbm_attribution")
    if isinstance(attr, dict):
        _check(
            isinstance(r.get("hbm_estimate"), dict)
            and r["hbm_estimate"].get("total_gib") is not None, name,
            "hbm_attribution present without the hbm_estimate breakdown "
            "— the estimate and measurement must coexist so drift is "
            "computable offline", f,
        )
        _check(
            "hbm_measured" in r, name,
            "hbm_attribution present without an hbm_measured key (null "
            "is legal, absence is not)", f,
        )
        if r.get("hbm_measured") is None:
            _check(
                bool(r.get("hbm_measured_reason")), name,
                "hbm_measured is null without an hbm_measured_reason — "
                "an unmeasured peak must say why", f,
            )
        else:
            _check(
                r.get("hbm_model_drift_frac") is not None, name,
                "hbm_measured present but hbm_model_drift_frac is null "
                "— a measured peak beside an estimate must yield a "
                "drift", f,
            )
        for cls, val in attr.items():
            if cls == "unattributed":
                continue  # the signed book-closing residual
            _check(
                isinstance(val, (int, float)) and val >= 0, name,
                f"hbm_attribution[{cls}]={val} is negative — only the "
                "unattributed residual may be signed", f,
            )
        ref = r.get("hbm_reference_gib")
        if isinstance(ref, (int, float)):
            total = sum(
                v for v in attr.values() if isinstance(v, (int, float))
            )
            _check(
                abs(total - ref) <= HBM_BOOKS_CLOSE_TOL_GIB
                + 0.0005 * len(attr), name,
                f"hbm_attribution classes sum to {total:.4f} GiB but "
                f"hbm_reference_gib={ref:.4f} — the reconciliation must "
                "close the books exactly", f,
            )
        drift = _finite("hbm_model_drift_frac")
        if drift is not None:
            _check(drift >= 0.0, name,
                   f"hbm_model_drift_frac={drift} is negative", f)
    return f


def validate_telemetry(result_path: str, r: dict, name: str) -> List[str]:
    """Cross-check a result row against its flight-recorder JSONL.

    The harness writes ``telemetry_<arm>.jsonl`` beside
    ``result_<arm>.json``; when the sibling exists, a published row must
    come from a run whose recorder CLOSED cleanly (``run_end`` present —
    an aborted run's partial row belongs in partial_<arm>.json, not here)
    with no unresolved anomaly (NaN loss / open step-time spike) events.
    Log-scraped ``result.json`` copies have no sibling and skip the check.
    """
    f: List[str] = []
    base = os.path.basename(result_path)
    if not (base.startswith("result_") and base.endswith(".json")):
        return f
    arm = base[len("result_"):-len(".json")]
    tpath = os.path.join(os.path.dirname(result_path), f"telemetry_{arm}.jsonl")
    if not os.path.exists(tpath):
        return f
    try:
        from ..telemetry import read_events
    except ImportError:  # run as a standalone script
        from distributed_llm_training_benchmark_framework_tpu.telemetry import (
            read_events,
        )
    try:
        events = read_events(tpath)
    except ValueError as e:
        return [f"{name}: telemetry JSONL corrupt ({e})"]
    end = [e for e in events if e.get("event") == "run_end"]
    _check(
        len(end) == 1, name,
        f"result row exists but telemetry has {len(end)} run_end events "
        "(crashed runs must not publish result rows)", f,
    )
    if end:
        unresolved = end[0].get("n_unresolved_anomalies", 0) or 0
        _check(
            unresolved == 0, name,
            f"telemetry shows {unresolved} unresolved anomaly event(s) "
            "(NaN loss / open step-time spike) — row rejected", f,
        )
    if r.get("data_mode") == "stream":
        # The quarantine ledger must match the telemetry trail exactly:
        # one data_corrupt_record event per healed record. A mismatch in
        # either direction means the skip accounting (or the event drain)
        # broke — the "honest records_skipped ledger" contract.
        n_events = sum(
            1 for e in events if e.get("event") == "data_corrupt_record"
        )
        row_skipped = int(r.get("records_skipped") or 0)
        _check(
            n_events == row_skipped, name,
            f"records_skipped={row_skipped} but telemetry holds "
            f"{n_events} data_corrupt_record event(s) — the quarantine "
            "ledger and the telemetry trail disagree", f,
        )
    return f


def _hbm_capacity_gb(device_kind: str) -> Optional[float]:
    if not device_kind:
        return None
    try:
        from ..utils.memory import device_hbm_bytes
    except ImportError:  # run as a standalone script
        from distributed_llm_training_benchmark_framework_tpu.utils.memory import (
            device_hbm_bytes,
        )
    b = device_hbm_bytes(device_kind)
    return b / 1e9 if b else None


def validate_log(path: str) -> List[str]:
    """Check the stdout-marker contract in one captured run log."""
    name = os.path.basename(path)
    f: List[str] = []
    text = open(path, errors="replace").read()
    n_start, n_end = text.count(MARKER_START), text.count(MARKER_END)
    _check(
        n_start == 1 and n_end == 1, name,
        f"expected exactly one marker pair, found {n_start} start / {n_end} end", f,
    )
    if n_start >= 1 and n_end >= 1:
        payload = text.split(MARKER_START, 1)[1].split(MARKER_END, 1)[0]
        try:
            json.loads(payload)
        except json.JSONDecodeError as e:
            f.append(f"{name}: marker payload is not valid JSON ({e})")
    return f


def collect(results_dir: str, logs_dir: Optional[str]) -> Tuple[List[str], int]:
    failures: List[str] = []
    result_files = sorted(
        glob.glob(os.path.join(results_dir, "**", "result*.json"), recursive=True)
    )
    n = 0
    for path in result_files:
        name = os.path.relpath(path, results_dir)
        try:
            r = json.load(open(path))
        except json.JSONDecodeError as e:
            failures.append(f"{name}: invalid JSON ({e})")
            continue
        failures.extend(validate_result(r, name))
        failures.extend(validate_telemetry(path, r, name))
        n += 1
    if logs_dir and os.path.isdir(logs_dir):
        for path in sorted(glob.glob(os.path.join(logs_dir, "*.log"))):
            failures.extend(validate_log(path))
            n += 1
    return failures, n


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--results-dir", required=True,
                   help="directory searched recursively for result*.json")
    p.add_argument("--logs-dir", default=None,
                   help="optional directory of captured run logs (marker check)")
    args = p.parse_args(argv)
    failures, n = collect(args.results_dir, args.logs_dir)
    if n == 0:
        print(f"VALIDATE: no results found under {args.results_dir}")
        return 1
    for msg in failures:
        print(f"VALIDATE FAIL {msg}")
    verdict = "FAIL" if failures else "PASS"
    print(f"VALIDATE {verdict}: {n} artifacts checked, {len(failures)} violations")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
