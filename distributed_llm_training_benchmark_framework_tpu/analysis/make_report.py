#!/usr/bin/env python
"""Generate BENCHMARK_REPORT.md from metrics.csv.

Structure parity with the reference report generator
(``scripts/make_report.py``): summary table, per-strategy tables, key findings
(best throughput / best scaling efficiency / lowest peak memory), strategy
trade-off prose, embedded plot links — adapted to TPU terminology.
"""

from __future__ import annotations

import argparse
import os
from typing import List

import pandas as pd

# The memory-anatomy attribution classes, straight from the engine
# (parse_metrics flattens them into hbm_attr_<class> columns) — one
# list, so a class added there can never silently vanish from the
# report table.
from .memory_anatomy import ATTRIBUTION_CLASSES as _HBM_CLASSES

TRADEOFFS = {
    "ddp": (
        "Data parallel (replicated)",
        "Params and optimizer state replicated on every chip; XLA all-reduces "
        "gradients over ICI. Lowest communication volume per step at small "
        "scale; highest memory per chip.",
    ),
    "fsdp": (
        "Fully-sharded data parallel",
        "Params, gradients and optimizer state sharded across the 'data' mesh "
        "axis; XLA all-gathers weights per use and reduce-scatters gradients. "
        "Lowest steady-state memory; more collective traffic per step.",
    ),
    "zero2": (
        "ZeRO-2 (sharded optimizer state)",
        "Params replicated, gradients reduce-scattered, Adam moments sharded. "
        "Cuts optimizer memory ~per-chip by world size while keeping forward/"
        "backward free of weight gathers — often the throughput sweet spot.",
    ),
    "zero3": (
        "ZeRO-3 (fully sharded + remat)",
        "Fully-sharded like fsdp plus per-layer rematerialization: lowest "
        "memory of all arms at the cost of recompute in backward.",
    ),
}


def _fmt_params(n) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return ""
    if n != n or n <= 0:  # NaN or absent
        return ""
    return f"{n/1e9:.2f}B" if n >= 1e9 else f"{n/1e6:.0f}M"


def _composition_label(r) -> str:
    """Slug of the non-default composition axes of one run row, so roster
    arms sharing (strategy, world_size) stay distinguishable in the tables
    (e.g. 'tp2', 'pp2-interleaved-v2', 'sp2', 'ep2x4e'); '-' for a pure
    data-parallel row."""

    def val(key, default=0):
        v = r.get(key, default)
        try:
            f = float(v)
        except (TypeError, ValueError):
            return default
        return default if f != f else int(f)  # NaN -> default

    bits = []
    if val("tensor_parallel", 1) > 1:
        bits.append(f"tp{val('tensor_parallel', 1)}")
    if val("sequence_parallel", 1) > 1:
        bits.append(f"sp{val('sequence_parallel', 1)}")
    if val("pipeline_parallel", 1) > 1:
        sched = r.get("pipeline_schedule") or "gpipe"
        pp = f"pp{val('pipeline_parallel', 1)}-{sched}"
        if sched == "interleaved" and val("virtual_stages", 0) > 0:
            pp += f"-v{val('virtual_stages', 0)}"
        bits.append(pp)
    if val("n_experts", 0) > 0:
        bits.append(f"ep{max(val('expert_parallel', 1), 1)}x{val('n_experts', 0)}e")
    if r.get("param_dtype") == "bf16":
        bits.append("bf16-params")
    if str(r.get("offload_opt_state")).lower() == "true":
        bits.append("opt-offload")
    return "+".join(bits) if bits else "-"


def fmt_table(df: pd.DataFrame, cols: List[str]) -> str:
    header = "| " + " | ".join(cols) + " |"
    sep = "|" + "|".join(["---"] * len(cols)) + "|"
    rows = []
    for _, r in df.iterrows():
        cells = []
        for c in cols:
            v = r[c]
            cells.append(f"{v:,.1f}" if isinstance(v, float) else str(v))
        rows.append("| " + " | ".join(cells) + " |")
    return "\n".join([header, sep] + rows)


def trend_section(registry_root: str, limit: int = 5) -> List[str]:
    """Per-arm run-over-run history from the regress registry.

    One table per arm: the newest ``limit`` records with delta vs the
    previous ok run. Partial (heartbeat-salvaged) records appear flagged
    but never anchor deltas or the best-run marker — the same exclusion
    the summary superlatives apply to partial rows.
    """
    from ..regress import compare as regress_compare
    from ..regress import store as regress_store

    # SchemaDrift can surface at open (newer registry meta) OR while
    # loading any single record ingested by a newer writer (mixed-version
    # fleet) — either way the report must degrade to an "unavailable"
    # note, never die with a traceback and take BENCHMARK_REPORT.md down
    # with it.
    try:
        reg = regress_store.Registry(registry_root)
        if not reg.exists():
            return []
        out = ["## Per-arm trend (registry)", "",
               f"Run-over-run history from "
               f"`{os.path.basename(registry_root)}` "
               f"(newest {limit}; delta vs previous ok run; `regress trend "
               "<arm>` for the full history and a PNG).", ""]
        for arm in reg.arms():
            rows = regress_compare.trend_rows(reg, arm, limit=limit)
            if not rows:
                continue
            out.append(f"### {arm}")
            out.append("")
            out.append("| record | value | metric | delta vs prev | status |")
            out.append("|---|---|---|---|---|")
            for r in rows:
                val = f"{r['value']:,.2f}" if r["value"] is not None else "-"
                delta = (f"{r['delta_pct_vs_prev']:+.2f}%"
                         if r["delta_pct_vs_prev"] is not None else "-")
                status = r["status"] + (" (best)" if r["best"] else "")
                out.append(
                    f"| `{r['record_id']}` | {val} "
                    f"| {r['metric_name'] or '-'} | {delta} | {status} |"
                )
            out.append("")
        return out
    except regress_store.SchemaDrift as e:
        return ["## Per-arm trend (registry)", "", f"_unavailable: {e}_", ""]


#: Frontier row order: zero recompute -> full recompute, the probe last.
_REMAT_ORDER = {"none": 0, "dots": 1, "full": 2, "auto": 3}


def remat_frontier_section(registry_root: str) -> List[str]:
    """The HBM-vs-recompute frontier from ``bench.py --remat-sweep`` records.

    One table per swept arm: the newest record per remat policy —
    tokens/sec/chip vs measured peak HBM (with the per-chip headroom the
    memory estimator prints), delta vs the no-remat point. Records are
    identified by a non-null ``remat_policy`` in their result row (the
    sweep stamps it; ordinary bench/flagship rows never carry it).

    The table only mixes records from ONE config lineage (the newest
    sweep record's ``store.config_key`` with the policy axis
    neutralized): a later ``--steps 12`` smoke sweep must not lend its
    'none' base to an older full-length sweep's rows — the exact
    cross-lineage comparison the config key exists to prevent. Omitted
    older-lineage sweep records are counted in a note, never silent.
    """
    from ..regress import store as regress_store

    def lineage(rec):
        # The config key with remat_policy neutralized: rows of one
        # sweep share it, sweeps at different run shapes do not.
        r = dict(rec.get("result") or {})
        r.pop("remat_policy", None)
        return regress_store.config_key({**rec, "result": r})

    try:
        reg = regress_store.Registry(registry_root)
        if not reg.exists():
            return []
        by_arm: dict = {}
        omitted = 0
        for arm in reg.arms():
            sweep = [rec for rec in reg.records(arm)  # oldest -> newest
                     if (rec.get("result") or {}).get("remat_policy")]
            if not sweep:
                continue
            lin = lineage(sweep[-1])
            for rec in sweep:
                if lineage(rec) == lin:  # newest wins within the lineage
                    by_arm.setdefault(arm, {})[
                        rec["result"]["remat_policy"]] = rec
                else:
                    omitted += 1
        if not by_arm:
            return []
        out = ["## Remat/HBM frontier (`bench.py --remat-sweep`)", "",
               "Tokens/sec vs peak HBM per rematerialization policy — the "
               "recompute-for-memory trade (docs/PERFORMANCE.md). Each "
               "policy is its own regress lineage (the policy is part of "
               "the registry config key); *headroom* is per-chip HBM "
               "capacity minus the measured peak (blank off-TPU).", ""]
        if omitted:
            out.append(f"_{omitted} older-lineage sweep record(s) "
                       "(different run shape) omitted from the tables._")
            out.append("")
        for arm in sorted(by_arm):
            pols = by_arm[arm]
            out.append(f"### {arm}")
            out.append("")
            out.append("| policy | resolved | tokens/sec/chip | vs none "
                       "| peak HBM GB | headroom GB | MFU % | est GiB "
                       "| xla-temp GiB | drift % |")
            out.append("|---|---|---|---|---|---|---|---|---|---|")
            base = ((pols.get("none") or {}).get("metric") or {}).get("value")
            for pol in sorted(pols, key=lambda p: _REMAT_ORDER.get(p, 9)):
                rec = pols[pol]
                row = rec.get("result") or {}
                val = (rec.get("metric") or {}).get("value")
                delta = (f"{100.0 * (val - base) / base:+.1f}%"
                         if val is not None and base else "-")

                def num(key, fmt="{:,.2f}"):
                    v = row.get(key)
                    return fmt.format(v) if isinstance(v, (int, float)) else "-"

                # Memory-anatomy columns (memory round): the sweep's rows
                # now carry the measured+attributed HBM — the frontier
                # reads observed, not just estimated. Pre-anatomy records
                # render "-".
                attr = row.get("hbm_attribution") or {}
                drift_v = row.get("hbm_model_drift_frac")
                drift_s = (
                    f"{100.0 * drift_v:.1f}"
                    if isinstance(drift_v, (int, float)) else "-"
                )
                xt = attr.get("xla_temp")
                out.append(
                    f"| {pol} | {row.get('remat_policy_resolved') or '-'} "
                    f"| {f'{val:,.2f}' if val is not None else '-'} "
                    f"| {delta} | {num('peak_hbm_gb')} "
                    f"| {num('hbm_headroom_gb')} | {num('mfu_pct')} "
                    f"| {num('hbm_estimate_gib')} "
                    f"| {f'{xt:,.2f}' if isinstance(xt, (int, float)) else '-'} "
                    f"| {drift_s} |"
                )
            out.append("")
        return out
    except regress_store.SchemaDrift as e:
        return ["## Remat/HBM frontier (`bench.py --remat-sweep`)", "",
                f"_unavailable: {e}_", ""]


def anatomy_section(df: pd.DataFrame) -> List[str]:
    """Step-anatomy table for every row that carries the trace-derived
    attribution (arms run with --profile-dir; analysis/step_anatomy.py).

    The compute / exposed-comms / overlap / idle split plus the roofline
    position — the report's answer to "is this arm communication-bound,
    and is the communication hidden".
    """
    if "comms_exposed_frac" not in df.columns:
        return []
    rows = df[df["comms_exposed_frac"].notna()]
    if not len(rows):
        return []
    out = [
        "## Step anatomy (trace-derived)", "",
        "Per traced device step: compute vs collective time (exposed on "
        "the critical path vs overlapped under compute) vs idle/host gap, "
        "with the roofline position (% of peak FLOP/s and HBM bandwidth) "
        "and, for pipeline arms, the schedule's bubble fraction "
        "(`analysis/step_anatomy.py`, docs/OBSERVABILITY.md). The "
        "compute/exposed/idle columns are fractions OF THE STEP and sum "
        "to 100%; *overlap %comms* is the fraction OF COLLECTIVE TIME "
        "hidden under compute (overlapped time is already inside the "
        "compute column).", "",
        "| strategy | ws | seq | compute % | exposed comms % "
        "| overlap %comms | idle % | bubble % | FLOPs %peak | HBM %peak "
        "| skew % |",
        "|---|---|---|---|---|---|---|---|---|---|---|",
    ]

    def pct(row, key):
        v = row.get(key)
        try:
            v = float(v)
        except (TypeError, ValueError):
            return "-"
        return f"{100.0 * v:.1f}" if v == v else "-"

    def raw(row, key):
        v = row.get(key)
        try:
            v = float(v)
        except (TypeError, ValueError):
            return "-"
        return f"{v:.1f}" if v == v else "-"

    for _, r in rows.iterrows():
        out.append(
            f"| {r['strategy']} | {int(r['world_size'])} "
            f"| {int(r['seq_len'])} "
            f"| {pct(r, 'anatomy_compute_frac')} "
            f"| {pct(r, 'comms_exposed_frac')} "
            f"| {pct(r, 'comms_overlap_frac')} "
            f"| {pct(r, 'anatomy_idle_frac')} "
            f"| {pct(r, 'bubble_frac')} "
            f"| {raw(r, 'roofline_flops_pct_of_peak')} "
            f"| {raw(r, 'roofline_hbm_pct_of_peak')} "
            f"| {raw(r, 'straggler_skew_pct')} |"
        )
    out.append("")
    return out




def memory_section(df: pd.DataFrame) -> List[str]:
    """Per-arm HBM waterfall beside the time waterfall: the attributed
    peak (params/grads/opt/activations/dataset/XLA-temp + signed
    residual), the analytic estimate, the measured column (or its
    explicit unavailability reason) and the gated model drift —
    ``analysis/memory_anatomy.py``, docs/OBSERVABILITY.md."""
    cols = [f"hbm_attr_{c}" for c in _HBM_CLASSES]
    if not all(c in df.columns for c in cols):
        return []
    rows = df[df[cols[0]].notna()]
    if not len(rows):
        return []
    out = [
        "## Memory anatomy (HBM peak, attributed)", "",
        "Per-chip peak attribution from the three-source reconciliation "
        "(`analysis/memory_anatomy.py`): analytic estimate + XLA "
        "compile-time accounting + allocator measurement. *source* names "
        "which peak is being attributed (`allocator` measured > "
        "`xla_buffer_assignment` > `analytic`); *residual* is the signed "
        "book-closing remainder; *drift* = |reference − analytic| / "
        "analytic, gated as `hbm_model_drift_frac`.", "",
        "| strategy | ws | seq | source | peak GiB | est GiB | params "
        "| grads | opt | act | data | xla-temp | residual | drift % |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]

    def num(row, key, fmt="{:.2f}"):
        v = row.get(key)
        try:
            v = float(v)
        except (TypeError, ValueError):
            return "-"
        return fmt.format(v) if v == v else "-"

    for _, r in rows.iterrows():
        drift = r.get("hbm_model_drift_frac")
        try:
            drift = (f"{100.0 * float(drift):.1f}"
                     if drift is not None and float(drift) == float(drift)
                     else "-")
        except (TypeError, ValueError):
            drift = "-"
        out.append(
            f"| {r['strategy']} | {int(r['world_size'])} "
            f"| {int(r['seq_len'])} "
            f"| {r.get('hbm_attribution_source') or '-'} "
            f"| {num(r, 'hbm_reference_gib')} "
            f"| {num(r, 'hbm_est_total_gib')} "
            f"| {num(r, 'hbm_attr_params')} | {num(r, 'hbm_attr_grads')} "
            f"| {num(r, 'hbm_attr_opt_state')} "
            f"| {num(r, 'hbm_attr_activations')} "
            f"| {num(r, 'hbm_attr_dataset')} "
            f"| {num(r, 'hbm_attr_xla_temp')} "
            f"| {num(r, 'hbm_attr_unattributed', '{:+.2f}')} "
            f"| {drift} |"
        )
    out.append("")
    return out


def build_report(
    df: pd.DataFrame, plots_dir: str = "../plots", plots_root: str = "",
    registry_root: str = "", step_anatomy_txt: str = "",
) -> str:
    df = df.copy()
    cols = [
        "strategy", "world_size", "seq_len", "tokens_per_sec",
        "mean_step_time_sec", "peak_vram_gb", "scaling_efficiency_pct",
    ]
    # Tier + parameter count: without these the tier-B row is
    # indistinguishable from a catastrophically slow tier-A row.
    if "tier" in df.columns:
        cols.insert(1, "tier")
        if "n_params" in df.columns:
            df["params"] = df["n_params"].map(_fmt_params)
            cols.insert(2, "params")
    # Composition axes: roster arms share (strategy, world_size) with the
    # pure arms; a config slug keeps every row identifiable.
    comp = df.apply(_composition_label, axis=1)
    if (comp != "-").any():
        df["config"] = comp
        cols.insert(1, "config")
    # TPU-additive columns, surfaced when the data carries them: attention
    # impl (reference vs flash rows share a table) and MFU.
    if "attention_impl" in df.columns and df["attention_impl"].nunique() > 1:
        cols.insert(cols.index("tokens_per_sec"), "attention_impl")
    if "mfu_pct" in df.columns and (df["mfu_pct"] > 0).any():
        cols.insert(cols.index("mean_step_time_sec") + 1, "mfu_pct")
    if "est_hbm_gb" in df.columns and (
        "peak_vram_gb" not in df.columns or (df["peak_vram_gb"] == 0).all()
    ):
        # Measurement unavailable on this platform; show the pre-flight
        # estimate instead of an all-zero measured column.
        cols = [c for c in cols if c != "peak_vram_gb"]
        cols.insert(-1, "est_hbm_gb")
    # Partial rows (heartbeat salvage from runs that died before their
    # final marker — scripts/collect_results.sh): kept in the tables with
    # an explicit flag column, excluded from the key-findings superlatives
    # (a truncated run's throughput is not a best-of anything).
    has_partial = "partial" in df.columns and df["partial"].fillna(False).any()
    if has_partial:
        cols.append("partial")
        full = df[~df["partial"].fillna(False).astype(bool)]
    else:
        full = df
    # Sentinel-healed rows (n_rollbacks > 0, self-healing round): complete
    # and validated, but the run hit a numerics incident and replayed
    # steps — show the column so the heal is visible in the table.
    if "n_rollbacks" in df.columns and (
        df["n_rollbacks"].fillna(0) > 0
    ).any():
        cols.append("n_rollbacks")
    # Supervisor-recovered rows (elastic fleet supervisor,
    # runtime/supervisor.py): the arm died and the supervisor restarted
    # it — possibly through a geometry shrink leg — until it finished.
    # Show the recovery history (attempt count, actions taken, shrink
    # legs) beside the healed/partial accounting; like those rows, they
    # are excluded from scaling-efficiency baselines upstream.
    has_supervised = "supervised_attempts" in df.columns and (
        df["supervised_attempts"].fillna(0).astype(float) > 1
    ).any()
    if has_supervised:
        df["supervised_attempts"] = (
            df["supervised_attempts"].fillna(1).astype(int)
        )
        cols.append("supervised_attempts")
        for c in ("supervised_actions", "supervised_shrink_legs"):
            if c in df.columns:
                df[c] = df[c].fillna("").replace("", "-")
                cols.append(c)
    cols = [c for c in cols if c in df.columns]
    out = ["# TPU Distributed Training Benchmark Report", ""]

    if "device_kind" in df.columns and df["device_kind"].notna().any():
        kinds = ", ".join(sorted(set(str(k) for k in df["device_kind"].dropna() if k)))
        out += [f"Hardware: {kinds}", ""]

    out += ["## Summary", "", fmt_table(df[cols], cols), ""]

    out += ["## Per-strategy results", ""]
    for strategy, g in sorted(df.groupby("strategy")):
        title, blurb = TRADEOFFS.get(strategy, (strategy, ""))
        out += [f"### {strategy} — {title}", "", blurb, "",
                fmt_table(g[cols], cols), ""]

    out += ["## Key findings", ""]
    if len(full):
        best_tps = full.loc[full["tokens_per_sec"].idxmax()]
        out.append(
            f"- **Best throughput:** {best_tps['strategy']} at "
            f"{best_tps['tokens_per_sec']:,.0f} tokens/sec "
            f"({int(best_tps['world_size'])} chips, seq {int(best_tps['seq_len'])})"
        )
    if "scaling_efficiency_pct" in full.columns and len(full) > 1:
        multi = full[full["world_size"] > full["world_size"].min()]
        if len(multi):
            best_eff = multi.loc[multi["scaling_efficiency_pct"].idxmax()]
            out.append(
                f"- **Best scaling efficiency:** {best_eff['strategy']} at "
                f"{best_eff['scaling_efficiency_pct']:.1f}% "
                f"({int(best_eff['world_size'])} chips)"
            )
    if "peak_vram_gb" in full.columns and full["peak_vram_gb"].max() > 0:
        low_mem = full.loc[full["peak_vram_gb"].idxmin()]
        out.append(
            f"- **Lowest peak HBM:** {low_mem['strategy']} at "
            f"{low_mem['peak_vram_gb']:.2f} GB/chip"
        )
    if "mfu_pct" in full.columns and (full["mfu_pct"] > 0).any():
        best_mfu = full.loc[full["mfu_pct"].idxmax()]
        impl = (
            f", {best_mfu['attention_impl']} attention"
            if "attention_impl" in full.columns else ""
        )
        out.append(
            f"- **Best MFU:** {best_mfu['strategy']} at "
            f"{best_mfu['mfu_pct']:.1f}% of bf16 peak"
            f" (seq {int(best_mfu['seq_len'])}{impl})"
        )
    if "tokens_per_dollar" in full.columns and (full["tokens_per_dollar"] > 0).any():
        # Cost-efficiency headline (reference README.md:270-276 analogue).
        best_cost = full.loc[full["tokens_per_dollar"].idxmax()]
        out.append(
            f"- **Best cost efficiency:** {best_cost['strategy']} at "
            f"{best_cost['tokens_per_dollar']/1e6:,.1f}M tokens/$ "
            f"(${best_cost['usd_per_chip_hour']:.2f}/chip-hr on-demand, "
            f"seq {int(best_cost['seq_len'])})"
        )
    if has_partial:
        is_partial = df["partial"].fillna(False).astype(bool)
        n_partial = int(is_partial.sum())
        # Death classification (chaos + self-healing rounds): a preempted
        # arm left an emergency checkpoint and resumes on retry; a hung
        # arm was aborted by the in-process watchdog (exit 76, stack dump
        # in its telemetry hang_dump event) and also resumes on retry; a
        # crashed one needs triage. An input-starved arm (streaming
        # round) was classified reason=data_stall by the loop itself
        # (exit 78, emergency checkpoint + stream sidecar — resumes on
        # retry like a preemption, but the triage target is the DATA
        # source, not the device). The collect script stamps `reason`
        # from the final heartbeat (emergency heartbeats carry
        # reason=preempted|hang|data_stall).
        death = ""
        if "reason" in df.columns:
            reasons = df.loc[is_partial, "reason"]
            n_pre = int((reasons == "preempted").sum())
            n_hang = int((reasons == "hang").sum())
            n_stall = int((reasons == "data_stall").sum())
            stall_txt = (
                f"{n_stall} input-starved (data_stall: checkpointed, "
                "triage the data source), " if n_stall else ""
            )
            death = (f" ({n_pre} preempted with an emergency checkpoint, "
                     f"{n_hang} hung (watchdog abort, stack dump in "
                     "telemetry), " + stall_txt +
                     f"{n_partial - n_pre - n_hang - n_stall} crashed)")
        out.append(
            f"- **Partial rows:** {n_partial} arm(s) died before their "
            "final result marker; their rows come from heartbeat salvage "
            f"(last sync window){death} — see the `partial` column."
        )
    if has_supervised:
        sup = df[df["supervised_attempts"] > 1]
        n_shrunk = int((sup["supervised_shrink_legs"] != "-").sum()) if (
            "supervised_shrink_legs" in sup.columns
        ) else 0
        shrink_txt = (
            f", {n_shrunk} via a geometry shrink leg "
            "(resumed on fewer chips from the checkpoint's geometry "
            "sidecar)" if n_shrunk else ""
        )
        out.append(
            f"- **Supervised recoveries:** {len(sup)} arm(s) finished "
            "only after the fleet supervisor restarted them"
            f"{shrink_txt} — attempt counts and actions in the "
            "`supervised_*` columns; full per-attempt ledger in each "
            "arm's `supervision.json`."
        )
    out.append("")

    out += anatomy_section(df)
    out += memory_section(df)
    if step_anatomy_txt and os.path.exists(step_anatomy_txt):
        # The suite's per-arm step-anatomy CLI tables (full component
        # breakdown incl. top collectives), shipped verbatim.
        body = open(step_anatomy_txt).read().strip()
        if body:
            out += ["### Per-arm anatomy tables", "", "```", body, "```",
                    ""]

    if registry_root:
        from .scaling import scaling_section

        out += scaling_section(registry_root)
        out += remat_frontier_section(registry_root)
        out += trend_section(registry_root)

    out += ["## Plots", ""]
    for name, caption in [
        ("tokens_per_sec_vs_gpu.png", "Throughput vs chip count"),
        ("step_time_vs_gpu.png", "Step time vs chip count"),
        ("scaling_efficiency.png", "Scaling efficiency vs chip count"),
        ("vram_vs_seqlen.png", "Peak HBM vs sequence length"),
        ("hbm_anatomy.png", "HBM peak attribution (memory anatomy)"),
        ("gbps_vs_gpu.png", "H2D transfer proxy"),
        ("tokens_per_sec_by_strategy.png",
         "Throughput by strategy and attention impl"),
        ("mfu_by_strategy.png", "MFU by strategy"),
        ("tokens_vs_seqlen.png", "Throughput vs sequence length"),
    ]:
        # Skip links to figures the plotter didn't render for this dataset
        # (when we can see the plots directory; embed unconditionally if not).
        if plots_root and not os.path.exists(os.path.join(plots_root, name)):
            continue
        out.append(f"![{caption}]({plots_dir}/{name})")
    out.append("")
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--csv", required=True, help="path to metrics.csv")
    p.add_argument("--out", required=True, help="output directory")
    p.add_argument("--plots-dir", default="../plots")
    p.add_argument("--registry", default=None,
                   help="regress registry root: adds the per-arm trend "
                        "section (run-over-run history)")
    p.add_argument("--step-anatomy", default=None,
                   help="step_anatomy CLI output file: embedded verbatim "
                        "under the step-anatomy section")
    args = p.parse_args(argv)
    df = pd.read_csv(args.csv)
    os.makedirs(args.out, exist_ok=True)
    path = os.path.join(args.out, "BENCHMARK_REPORT.md")
    plots_root = os.path.normpath(os.path.join(args.out, args.plots_dir))
    with open(path, "w") as f:
        f.write(build_report(df, args.plots_dir, plots_root=plots_root,
                             registry_root=args.registry or "",
                             step_anatomy_txt=args.step_anatomy or ""))
    print(f"Wrote {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
