"""Pipeline parallelism — GPipe microbatch schedule over a 'pipe' mesh axis.

Absent from the reference (SURVEY §2.3: PP is future-work prose in its README
only). TPU-native design: the stacked layer weights are sharded on their
leading 'layers' axis across the 'pipe' mesh axis (L/P contiguous layers per
stage), and activations flow stage-to-stage via ``ppermute`` on neighbor ICI
links. The schedule is the classic GPipe fill-drain: with M microbatches and
P stages, T = M + P - 1 ticks; at tick t stage s runs microbatch t - s.

Implementation notes:
- runs inside ``jax.shard_map`` manual ONLY over 'pipe' (``axis_names``):
  the 'data'/'model' axes stay auto, so data-parallel batch sharding and
  Megatron tensor parallelism compose with the pipeline for free;
- embeddings, final LN and the tied LM head are replicated across stages;
  every stage computes the (cheap) embed/head for schedule uniformity and a
  predicate selects the real producer — the fill/drain bubble, not this, is
  the dominant overhead;
- the whole schedule is differentiable (``ppermute`` transposes to the
  reverse permutation), so one ``jax.value_and_grad`` around the pipelined
  loss drives the backward schedule automatically;
- microbatches double as gradient accumulation: the step's (accum, batch,
  seq) input feeds the pipeline as its M microbatches.

Constraint: n_layer % pipe == 0; ring (sequence-parallel) attention does not
compose with the pipeline in this version (nested manual axes) — use
dp/tp/pp.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models import tinygpt

AXIS = "pipe"


def pipeline_param_specs(params, mesh: Mesh):
    """Manual-axis ('pipe'-only) specs: block stacks sharded on layers axis."""

    def spec(path, leaf):
        is_block = any(getattr(p, "key", None) == "blocks" for p in path)
        if is_block:
            return P(AXIS, *([None] * (leaf.ndim - 1)))
        return P(*([None] * leaf.ndim))

    return jax.tree_util.tree_map_with_path(spec, params)


def pipeline_loss_fn(
    config: tinygpt.TinyGPTConfig,
    mesh: Mesh,
    params,
    batch: jax.Array,  # (M, mb, S) microbatches; targets are the inputs
    base_key: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> jax.Array:
    """Mean loss over M microbatches, computed on the GPipe schedule."""
    n_stages = mesh.shape[AXIS]
    if config.n_layer % n_stages != 0:
        raise ValueError(
            f"n_layer={config.n_layer} not divisible by pipe={n_stages}"
        )
    if config.n_experts > 0:
        raise ValueError(
            "MoE does not compose with pipeline parallelism in this version "
            "(per-stage aux-loss accounting); use dp/tp/ep"
        )
    layers_per_stage = config.n_layer // n_stages
    n_micro = batch.shape[0]
    ticks = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def staged(params, batch):
        stage = lax.axis_index(AXIS)
        blocks = params["blocks"]  # local slice: (L/P, ...)
        mb, S = batch.shape[1], batch.shape[2]
        D = config.n_embd
        state = jnp.zeros((mb, S, D), config.compute_dtype)
        loss_sum = jnp.zeros((), jnp.float32)

        emb_key = (
            jax.random.fold_in(base_key, 1_000_003) if base_key is not None else None
        )
        offset = stage * layers_per_stage

        for t in range(ticks):
            # Stage 0 ingests a fresh microbatch while the schedule is filling;
            # downstream stages consume what the previous tick permuted in.
            if t < n_micro:
                ek = (
                    jax.random.fold_in(emb_key, t)
                    if emb_key is not None and not deterministic
                    else None
                )
                inject = tinygpt.embed(config, params, batch[t], ek, deterministic)
                state_in = jnp.where(stage == 0, inject, state)
            else:
                state_in = state
            bk = (
                jax.random.fold_in(base_key, t)
                if base_key is not None and not deterministic
                else None
            )
            state_out, _ = tinygpt.apply_blocks(
                config, blocks, state_in, bk, deterministic, layer_offset=offset
            )

            # The last stage drains: at tick t it finishes microbatch
            # t - (P-1). The LM head is a (mb,S,D)x(V,D) einsum — layer-scale
            # compute — so on TPU a cond (legal per-device control flow inside
            # the manual region) skips it entirely on non-final stages. The
            # CPU backend compute-and-masks instead: XLA's CPU-only
            # AllReducePromotion pass aborts on the collectives the cond
            # lowering produces (same bug class as the pp x tp guard).
            li = t - (n_stages - 1)
            if 0 <= li < n_micro:
                if jax.default_backend() == "cpu":
                    logits = tinygpt.head(config, params, state_out)
                    l = tinygpt._cross_entropy(logits, batch[li])
                    loss_sum = loss_sum + jnp.where(stage == n_stages - 1, l, 0.0)
                else:
                    loss_sum = loss_sum + lax.cond(
                        stage == n_stages - 1,
                        lambda so=state_out, tgt=batch[li]: tinygpt._cross_entropy(
                            tinygpt.head(config, params, so), tgt
                        ),
                        # pcast marks the zero as device-varying over 'pipe'
                        # so both branches carry the same manual-axes type.
                        lambda: lax.pcast(
                            jnp.zeros((), jnp.float32), (AXIS,), to="varying"
                        ),
                    )

            if t < ticks - 1:
                state = lax.ppermute(state_out, AXIS, perm)

        # Only the last stage accumulated loss; broadcast it to every stage.
        return lax.psum(loss_sum, AXIS) / n_micro

    fn = jax.shard_map(
        staged,
        mesh=mesh,
        in_specs=(pipeline_param_specs(params, mesh), P()),
        out_specs=P(),
        axis_names=frozenset({AXIS}),
    )
    return fn(params, batch)
