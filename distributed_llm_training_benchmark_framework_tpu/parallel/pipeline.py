"""Pipeline parallelism — GPipe microbatch schedule over a 'pipe' mesh axis.

Absent from the reference (SURVEY §2.3: PP is future-work prose in its README
only). TPU-native design: the stacked layer weights are sharded on their
leading 'layers' axis across the 'pipe' mesh axis (L/P contiguous layers per
stage), and activations flow stage-to-stage via ``ppermute`` on neighbor ICI
links. The schedule is the classic GPipe fill-drain: with M microbatches and
P stages, T = M + P - 1 ticks; at tick t stage s runs microbatch t - s.

Implementation notes:
- runs inside ``jax.shard_map`` manual ONLY over 'pipe' (``axis_names``):
  the 'data'/'model' axes stay auto, so data-parallel batch sharding and
  Megatron tensor parallelism compose with the pipeline for free;
- embeddings, final LN and the tied LM head are replicated across stages;
  every stage computes the (cheap) embed/head for schedule uniformity and a
  predicate selects the real producer — the fill/drain bubble, not this, is
  the dominant overhead;
- the whole schedule is differentiable (``ppermute`` transposes to the
  reverse permutation), so one ``jax.value_and_grad`` around the pipelined
  loss drives the backward schedule automatically;
- microbatches double as gradient accumulation: the step's (accum, batch,
  seq) input feeds the pipeline as its M microbatches.

Constraint: n_layer % pipe == 0. Sequence parallelism composes: with a >1
'seq' mesh axis the schedules go manual over ('pipe', 'seq') and attention
runs the sharded ring/Ulysses bodies inside each stage (see ``_seq_setup``).
MoE composes too — per-stage aux-loss accounting masks fill/drain ticks and
psums stage contributions.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models import tinygpt
from ..utils.vma import pcast_varying

AXIS = "pipe"


#: The pipeline compile-fix switch: the typed-key boundary crossing AND
#: the legacy data-manual lowering are two halves of the same repair of
#: the seed-old pipeline compile failures. graftcheck's ``--inject
#: bad-pipeline-spec`` flips this off to resurrect the original lowering
#: (typed key closed over a partial-auto shard_map beside a REAL auto
#: 'data' axis -> the u32 tile-assignment XLA rejection) and prove the
#: schedule auditor catches it; nothing else may touch it.
_TYPED_KEY_BOUNDARY_FIX = True


def _key_data_or_none(base_key):
    """Raw uint32 key data for a typed PRNG key (None passes through).

    Typed key arrays must not cross the ``shard_map`` boundary here — on
    pre-vma runtimes the partial-auto lowering builds the boundary sharding
    from the rank-0 key aval but validates it against the rank-1 physical
    u32 key data, which XLA rejects ("Number of tile assignment dimensions
    ... is different than the input rank", the seed-old interleaved compile
    failure). Raw key data is an ordinary u32 array whose rank the boundary
    handles on every runtime; the body rebuilds the key with
    :func:`_rebuild_key`.
    """
    if not _TYPED_KEY_BOUNDARY_FIX:
        return base_key
    return None if base_key is None else jax.random.key_data(base_key)


def _rebuild_key(key_data):
    """The body-side half of the key boundary crossing (see above)."""
    if key_data is None:
        return None
    if not _TYPED_KEY_BOUNDARY_FIX:
        return key_data  # the typed key itself crossed — the old bug
    return jax.random.wrap_key_data(key_data)


def _stage_iota(n_stages: int) -> jax.Array:
    """Per-stage index fed through the shard_map as a P('pipe') operand.

    ``lax.axis_index`` inside a PARTIALLY-manual region lowers to a bare
    partition-id instruction that XLA's SPMD partitioner refuses whenever a
    real auto axis exists ("PartitionId instruction is not supported for
    SPMD partitioning"), which broke every pipeline x dp>1 composition on
    the pre-vma runtime. A sharded iota derives the same value from data:
    each stage's local shard of arange(P) is exactly its stage index.
    """
    return jnp.arange(n_stages, dtype=jnp.int32)


def _legacy_partial_auto() -> bool:
    """True on pre-vma runtimes (no ``lax.pcast``), where the legacy
    partial-auto shard_map lowering cannot partition a REAL (size>1) auto
    axis around the pipeline's collectives: a ppermute beside a >1 auto
    axis dies in XLA's SPMD partitioner (manual-subgroup CHECK failure),
    and ``lax.axis_index`` lowers to a bare partition-id the partitioner
    refuses. Size-1 auto axes are fine (the sp ring arms run that shape),
    so on these runtimes the pipeline region additionally goes manual over
    'data' and the schedules reduce over it explicitly — the same
    reductions GSPMD would have inserted for an auto data axis."""
    from jax import lax as _lax

    return not hasattr(_lax, "pcast")


def _seq_setup(config: tinygpt.TinyGPTConfig, mesh: Mesh):
    """Manual-axes composition for a pipeline schedule's shard_map.

    Sequence parallel: a >1 'seq' mesh axis goes manual beside 'pipe' —
    activations hold local sequence chunks, attention runs the sharded
    ring/Ulysses bodies communicating over 'seq' (see
    tinygpt.TinyGPTConfig.seq_manual_axis), and losses/aux psum over 'seq'.

    Data parallel on legacy runtimes (:func:`_legacy_partial_auto`): a >1
    'data' axis ALSO goes manual — each shard runs the schedule on its
    local microbatch rows and the schedules psum losses/grads over
    'data' explicitly (scaled by ``dp`` for the means). On vma runtimes
    'data' stays auto and ``data_ax`` is None — byte-identical lowering to
    before.

    Returns (config, seq_axis_or_None, sp, data_axis_or_None, dp,
    manual_axes, batch_in_spec) — ``dp`` is the data-shard count the
    schedule must normalize its means by, so it is 1 whenever 'data'
    stays auto (GSPMD owns the normalization there).
    """
    sp = mesh.shape.get("seq", 1)
    seq_ax = None
    manual = {AXIS}
    if sp > 1:
        config = dataclasses.replace(config, seq_manual_axis="seq")
        seq_ax = "seq"
        manual.add("seq")
    data_ax = None
    dp = 1
    if (
        mesh.shape.get("data", 1) > 1 and _legacy_partial_auto()
        and _TYPED_KEY_BOUNDARY_FIX
    ):
        data_ax = "data"
        dp = mesh.shape["data"]
        manual.add("data")
    if seq_ax is None and data_ax is None:
        batch_spec = P()
    else:
        batch_spec = P(None, data_ax, seq_ax)
    return config, seq_ax, sp, data_ax, dp, frozenset(manual), batch_spec


def pipeline_param_specs(params, mesh: Mesh):
    """Manual-axis ('pipe'-only) specs: block stacks sharded on layers axis."""

    def spec(path, leaf):
        is_block = any(getattr(p, "key", None) == "blocks" for p in path)
        if is_block:
            return P(AXIS, *([None] * (leaf.ndim - 1)))
        return P(*([None] * leaf.ndim))

    return jax.tree_util.tree_map_with_path(spec, params)


def pipeline_loss_fn(
    config: tinygpt.TinyGPTConfig,
    mesh: Mesh,
    params,
    batch: jax.Array,  # (M, mb, S) microbatches; targets are the inputs
    base_key: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> jax.Array:
    """Mean loss over M microbatches, computed on the GPipe schedule."""
    n_stages = mesh.shape[AXIS]
    if config.n_layer % n_stages != 0:
        raise ValueError(
            f"n_layer={config.n_layer} not divisible by pipe={n_stages}"
        )
    config, seq_ax, sp, data_ax, dp, manual_axes, batch_spec = _seq_setup(
        config, mesh
    )
    layers_per_stage = config.n_layer // n_stages
    n_micro = batch.shape[0]
    ticks = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    key_data = _key_data_or_none(base_key)
    # Axes the scalar reductions span: 'pipe' always; 'data' too when the
    # legacy runtime made it manual (each shard saw 1/dp of the batch, so
    # the psum'd means divide by dp).
    reduce_axes = (AXIS,) + ((data_ax,) if data_ax else ())

    def staged(params, batch, stage_arr):
        stage = stage_arr[0]
        base_key = _rebuild_key(key_data)
        blocks = params["blocks"]  # local slice: (L/P, ...)
        mb, S = batch.shape[1], batch.shape[2]
        D = config.n_embd
        state = jnp.zeros((mb, S, D), config.compute_dtype)
        loss_sum = jnp.zeros((), jnp.float32)
        # MoE load-balance aux: each stage accumulates its own layers' aux for
        # the microbatches it actually processes (fill/drain ticks run on
        # dummy state for schedule uniformity — their aux is masked out).
        aux_sum = jnp.zeros((), jnp.float32)

        emb_key = (
            jax.random.fold_in(base_key, 1_000_003) if base_key is not None else None
        )
        offset = stage * layers_per_stage

        for t in range(ticks):
            # Stage 0 ingests a fresh microbatch while the schedule is filling;
            # downstream stages consume what the previous tick permuted in.
            if t < n_micro:
                ek = (
                    jax.random.fold_in(emb_key, t)
                    if emb_key is not None and not deterministic
                    else None
                )
                inject = tinygpt.embed(config, params, batch[t], ek, deterministic)
                state_in = jnp.where(stage == 0, inject, state)
            else:
                state_in = state
            bk = (
                jax.random.fold_in(base_key, t)
                if base_key is not None and not deterministic
                else None
            )
            state_out, aux_t = tinygpt.apply_blocks(
                config, blocks, state_in, bk, deterministic, layer_offset=offset
            )
            if config.n_experts > 0:
                if seq_ax is not None:
                    # Per-shard load-balance stats averaged across sequence
                    # shards (the standard local-aux formulation); also makes
                    # aux seq-invariant for the loss.
                    aux_t = lax.psum(aux_t, seq_ax) / sp
                fi = t - stage  # the microbatch this stage processed this tick
                aux_valid = (fi >= 0) & (fi < n_micro)
                aux_sum = aux_sum + jnp.where(aux_valid, aux_t, 0.0)

            # The last stage drains: at tick t it finishes microbatch
            # t - (P-1). The LM head is a (mb,S,D)x(V,D) einsum — layer-scale
            # compute — so on TPU a cond (legal per-device control flow inside
            # the manual region) skips it entirely on non-final stages. The
            # CPU backend compute-and-masks instead: XLA's CPU-only
            # AllReducePromotion pass aborts on the collectives the cond
            # lowering produces (same bug class as the pp x tp guard).
            li = t - (n_stages - 1)
            if 0 <= li < n_micro:
                if jax.default_backend() == "cpu":
                    logits = tinygpt.head(config, params, state_out)
                    l = tinygpt._cross_entropy(logits, batch[li], seq_axis=seq_ax)
                    loss_sum = loss_sum + jnp.where(stage == n_stages - 1, l, 0.0)
                else:
                    loss_sum = loss_sum + lax.cond(
                        stage == n_stages - 1,
                        lambda so=state_out, tgt=batch[li]: tinygpt._cross_entropy(
                            tinygpt.head(config, params, so), tgt, seq_axis=seq_ax
                        ),
                        # pcast marks the zero as device-varying over 'pipe'
                        # so both branches carry the same manual-axes type.
                        lambda: pcast_varying(
                            jnp.zeros((), jnp.float32), (AXIS,)
                        ),
                    )

            if t < ticks - 1:
                state = lax.ppermute(state_out, AXIS, perm)

        # Only the last stage accumulated loss; broadcast it to every stage
        # (and average across data shards when 'data' is manual).
        loss = lax.psum(loss_sum, reduce_axes) / (n_micro * dp)
        if config.n_experts > 0:
            # Every (stage, microbatch) pair contributed its layers' aux once:
            # psum over stages = sum over all n_layer layers for all M
            # microbatches. Same normalization as tinygpt.forward
            # (coef * aux / n_layer), averaged over microbatches.
            loss = loss + config.router_aux_coef * lax.psum(
                aux_sum, reduce_axes
            ) / (config.n_layer * n_micro * dp)
        return loss

    fn = jax.shard_map(
        staged,
        mesh=mesh,
        in_specs=(pipeline_param_specs(params, mesh), batch_spec, P(AXIS)),
        out_specs=P(),
        axis_names=manual_axes,
    )
    return fn(params, batch, _stage_iota(n_stages))


def pipeline_loss_and_grads_1f1b(
    config: tinygpt.TinyGPTConfig,
    mesh: Mesh,
    params,
    batch: jax.Array,  # (M, mb, S) microbatches; targets are the inputs
    base_key: Optional[jax.Array] = None,
    deterministic: bool = True,
):
    """1F1B-interleaved pipeline schedule with a hand-scheduled backward.

    Returns ``(loss, grads)`` directly — the backward is NOT generated by
    ``jax.grad`` over the forward schedule. That distinction is the point:
    autodiff of the GPipe loop above reverses the whole program, so every
    ppermute of the backward sits after every ppermute of the forward in
    program order and all M microbatches' residuals are live at the
    fwd/bwd boundary — O(M) activation memory per stage. Here each tick
    interleaves one forward with one backward (the Megatron-LM 1F1B idea,
    lockstep variant), so a microbatch's residual dies 2*(P-1-s) ticks after
    its forward: peak liveness is O(P) regardless of M, which is what lets
    long accumulation chains (M >> P) train without activation OOM.

    Schedule (P stages, M microbatches, T = M + 2(P-1) ticks): at tick t,
    stage s forwards microbatch ``t - s`` (exactly GPipe) and backwards
    microbatch ``t - 2(P-1) + s``. The last stage's backward of microbatch i
    starts the same tick its forward drains (its loss gradient is computed
    in place); gradients flow stage-to-stage over the reverse ppermute ring,
    one hop per tick, meeting each stage precisely 2(P-1-s) ticks after it
    forwarded that microbatch. Both the fill and drain bubbles are 2(P-1)
    ticks — the same fraction as GPipe; 1F1B's win is memory, not bubble
    (only *interleaved* virtual stages shrink the bubble).

    Residuals: instead of storing per-microbatch VJP closures (not SPMD-able —
    the tick a stage needs them at differs per stage), each stage keeps a
    rolling buffer of its last 2P-1 forward *inputs* and rematerializes the
    stage forward under ``jax.vjp`` at backward time (per-stage activation
    recompute, the standard Megatron configuration). Dropout keys are derived
    from the originating tick index, so the recompute replays the forward
    bit-for-bit.
    """
    n_stages = mesh.shape[AXIS]
    if config.n_layer % n_stages != 0:
        raise ValueError(
            f"n_layer={config.n_layer} not divisible by pipe={n_stages}"
        )
    config, seq_ax, sp, data_ax, dp, manual_axes, batch_spec = _seq_setup(
        config, mesh
    )
    layers_per_stage = config.n_layer // n_stages
    n_micro = batch.shape[0]
    ticks = n_micro + 2 * (n_stages - 1)
    depth = 2 * n_stages - 1  # rolling residual-buffer depth
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    perm_bwd = [(i, (i - 1) % n_stages) for i in range(n_stages)]
    # The loss is the mean over microbatches AND data shards (dp=1 when
    # 'data' stays auto); every hand-seeded cotangent uses the same scale
    # so the backward stays consistent with the published loss.
    inv_m = 1.0 / (n_micro * dp)
    key_data = _key_data_or_none(base_key)
    reduce_axes = (AXIS,) + ((data_ax,) if data_ax else ())
    legacy_vma = _legacy_partial_auto()
    # Axes replicated-parameter grads sum over in the LEGACY explicit
    # reductions (vma runtimes never take these branches for 'seq': the
    # implicit invariant->varying transpose covers it, and pcast_missing
    # skips already-varying axes).
    grad_axes = reduce_axes + (
        (seq_ax,) if (seq_ax and legacy_vma) else ()
    )
    # Legacy cotangent-seed scale: pre-vma jax transposes psum to psum, so
    # differentiating through the CE/aux internal psum over 'seq' inflates
    # a hand-seeded cotangent by sp. Seeding 1/sp of the true cotangent
    # cancels it exactly (verified against plain-model ground truth); the
    # explicit grad_axes psums then restore the cross-shard sums.
    ct_scale = 1.0 / sp if (legacy_vma and sp > 1) else 1.0

    def staged(params, batch, stage_arr):
        stage = stage_arr[0]
        base_key = _rebuild_key(key_data)
        is_last = stage == n_stages - 1
        blocks = params["blocks"]  # local slice: (L/P, ...)
        mb, S = batch.shape[1], batch.shape[2]
        D = config.n_embd
        state = jnp.zeros((mb, S, D), config.compute_dtype)
        g_recv = jnp.zeros((mb, S, D), config.compute_dtype)
        buf = jnp.zeros((depth, mb, S, D), config.compute_dtype)
        loss_sum = jnp.zeros((), jnp.float32)

        d_blocks = jax.tree.map(jnp.zeros_like, blocks)
        hp = {k: params[k] for k in tinygpt.head_param_names(config)}
        ep = {k: params[k] for k in tinygpt.embed_param_names(config)}
        d_ep = jax.tree.map(jnp.zeros_like, ep)

        # Head strategy mirrors pipeline_loss_fn: on TPU a lax.cond skips the
        # layer-scale head fwd+vjp on non-final stages entirely; on CPU (where
        # XLA's AllReducePromotion pass aborts on cond-lowered collectives)
        # every stage computes it and dl=0 masks the cotangents. For the cond
        # path hp is pre-cast to 'varying' so the head vjp stays collective-
        # free inside the divergent branch (an invariant primal would make the
        # transpose insert a psum there — deadlock); the one psum that makes
        # d_hp invariant again runs after the tick loop.
        head_cond = jax.default_backend() != "cpu"
        if head_cond:
            hp_in = jax.tree.map(
                lambda x: pcast_varying(x, (AXIS,)), hp
            )
        else:
            hp_in = hp
        d_hp = jax.tree.map(jnp.zeros_like, hp_in)

        emb_key = (
            jax.random.fold_in(base_key, 1_000_003) if base_key is not None else None
        )
        offset = stage * layers_per_stage
        live_keys = base_key is not None and not deterministic

        # MoE: the load-balance aux is a second differentiable output of the
        # stage forward; its cotangent is the constant coef/(n_layer*n_micro)
        # (the aux term's weight in the final loss) whenever the backward
        # unit's microbatch is valid.
        moe = config.n_experts > 0
        aux_sum = jnp.zeros((), jnp.float32)
        aux_ct_const = (
            config.router_aux_coef * ct_scale / (config.n_layer * n_micro * dp)
            if moe else 0.0
        )

        def stage_fwd(blk, x, key):
            y, aux = tinygpt.apply_blocks(
                config, blk, x, key, deterministic, layer_offset=offset
            )
            if moe and seq_ax is not None:
                # Shard-local aux averaged over sequence shards (seq-invariant
                # so the loss and its constant cotangent stay uniform).
                aux = lax.psum(aux, seq_ax) / sp
            return (y, aux) if moe else y

        for t in range(ticks):
            # ---- forward unit: stage s runs microbatch t - s (as GPipe) ----
            if t < n_micro:
                ek = jax.random.fold_in(emb_key, t) if live_keys else None
                inject = tinygpt.embed(config, params, batch[t], ek, deterministic)
                state_in = jnp.where(stage == 0, inject, state)
            else:
                state_in = state
            # Circular residual buffer: write slot t % depth (no O(depth)
            # shift-copy per tick).
            buf = lax.dynamic_update_index_in_dim(buf, state_in, t % depth, 0)
            if t < n_micro + n_stages - 1:  # fwd window; later ticks drain only
                bk = jax.random.fold_in(base_key, t) if live_keys else None
                out = stage_fwd(blocks, state_in, bk)
                if moe:
                    state_out, aux_t = out
                    fi = t - stage
                    aux_sum = aux_sum + jnp.where(
                        (fi >= 0) & (fi < n_micro), aux_t, 0.0
                    )
                else:
                    state_out = out
            else:
                state_out = state_in

            # ---- loss + its gradient, in place, on the last stage ----
            li = t - (n_stages - 1)
            d_x_head = jnp.zeros_like(state_out)
            if 0 <= li < n_micro:
                def head_loss(hp_arg, x):
                    return tinygpt._cross_entropy(
                        tinygpt.head(config, hp_arg, x), batch[li], seq_axis=seq_ax
                    )

                if head_cond:
                    def head_work(so=state_out, fn=head_loss):
                        l, vjp_head = jax.vjp(fn, hp_in, so)
                        dl = pcast_varying(
                            jnp.asarray(inv_m * ct_scale, jnp.float32),
                            (AXIS,),
                        )
                        d_hp_t, d_xh = vjp_head(dl)
                        return l, d_hp_t, d_xh

                    def head_zero(so=state_out):
                        var = lambda z: pcast_varying(z, (AXIS,))
                        # The state cotangent is additionally seq-varying
                        # (it is a local sequence chunk's gradient).
                        var_x = lambda z: pcast_varying(
                            z, (AXIS,) + ((seq_ax,) if seq_ax else ())
                        )
                        return (
                            var(jnp.zeros((), jnp.float32)),
                            jax.tree.map(lambda x: var(jnp.zeros(x.shape, x.dtype)), hp),
                            var_x(jnp.zeros_like(so)),
                        )

                    l, d_hp_t, d_x_head = lax.cond(is_last, head_work, head_zero)
                    loss_sum = loss_sum + l
                else:
                    # compute-and-mask: dl = 0 on non-final stages zeroes both
                    # cotangents, so no cross-stage control flow is needed
                    l, vjp_head = jax.vjp(head_loss, hp_in, state_out)
                    loss_sum = loss_sum + jnp.where(is_last, l, 0.0)
                    dl = jnp.where(is_last, inv_m * ct_scale, 0.0)
                    d_hp_t, d_x_head = vjp_head(dl)
                d_hp = jax.tree.map(jnp.add, d_hp, d_hp_t)

            # ---- backward unit: stage s runs microbatch t - 2(P-1) + s ----
            if t >= n_stages - 1:  # before this no stage has backward work
                bi = t - 2 * (n_stages - 1) + stage
                vb = (bi >= 0) & (bi < n_micro)
                g_in = jnp.where(is_last, d_x_head.astype(g_recv.dtype), g_recv)
                g_in = jnp.where(vb, g_in, jnp.zeros((), g_in.dtype))
                # Residual: this stage forwarded microbatch bi at tick
                # t - 2(P-1) + 2s, i.e. 2(P-1-s) writes ago.
                k_back = jnp.clip(2 * (n_stages - 1) - 2 * stage, 0, depth - 1)
                x_saved = lax.dynamic_index_in_dim(
                    buf, jnp.mod(t - k_back, depth), 0, keepdims=False
                )
                bk_orig = (
                    jax.random.fold_in(base_key, t - 2 * (n_stages - 1) + 2 * stage)
                    if live_keys else None
                )
                _, vjp_blk = jax.vjp(
                    lambda blk, x: stage_fwd(blk, x, bk_orig), blocks, x_saved
                )
                if moe:
                    aux_ct = jnp.where(vb, aux_ct_const, 0.0).astype(jnp.float32)
                    d_blk_t, d_x = vjp_blk((g_in, aux_ct))
                else:
                    d_blk_t, d_x = vjp_blk(g_in)
                d_blocks = jax.tree.map(jnp.add, d_blocks, d_blk_t)

                # Stage 0's input cotangent belongs to the embedding. Its
                # backward microbatch index is static (bi at s=0), so the
                # embed recompute uses a static batch row.
                bi0 = t - 2 * (n_stages - 1)
                if 0 <= bi0 < n_micro:
                    ek0 = jax.random.fold_in(emb_key, bi0) if live_keys else None
                    # pcast marks the (stage-invariant) embed output as
                    # varying over 'pipe' so it accepts the varying cotangent;
                    # pcast's transpose is a psum, so d_ep_t comes back
                    # already reduced across stages (invariant) — the final
                    # grads need no further psum for wte/wpe.
                    # Legacy runtime: NO pcast here. Its transpose would
                    # psum the cotangent BEFORE the embed transpose, but
                    # under sp>1 the wpe scatter offset differs per seq
                    # shard, so the reduction only commutes with the
                    # scatter when it runs AFTER — on the accumulated d_ep
                    # below (the interleaved executor's structure). On vma
                    # runtimes the pipe-psum transpose commutes (offsets
                    # are pipe-uniform) and 'seq' is handled implicitly.
                    _, vjp_emb = jax.vjp(
                        lambda ep: pcast_varying(
                            tinygpt.embed(config, ep, batch[bi0], ek0, deterministic),
                            () if legacy_vma else (AXIS,),
                        ),
                        ep,
                    )
                    (d_ep_t,) = vjp_emb(
                        jnp.where(stage == 0, d_x, jnp.zeros((), d_x.dtype))
                    )
                    d_ep = jax.tree.map(jnp.add, d_ep, d_ep_t)

                if t < ticks - 1:
                    g_recv = lax.ppermute(d_x, AXIS, perm_bwd)

            if t < n_micro + n_stages - 2:
                state = lax.ppermute(state_out, AXIS, perm_fwd)

        loss = lax.psum(loss_sum, reduce_axes) * inv_m
        if moe:
            # Same accounting as the GPipe schedule: psum over stages covers
            # all n_layer layers once per microbatch (and over data shards
            # when 'data' is manual — dp normalizes the mean).
            loss = loss + config.router_aux_coef * lax.psum(
                aux_sum, reduce_axes
            ) / (config.n_layer * n_micro * dp)
        if head_cond:
            # cond path kept d_hp varying (nonzero on the last stage only);
            # one psum re-replicates it — over 'data' too when that axis
            # is manual (reduce_axes == (AXIS,) on vma runtimes), or the
            # legacy data-manual path on a non-CPU backend would lose the
            # head grads' cross-shard sum.
            d_hp = jax.tree.map(lambda x: lax.psum(x, reduce_axes), d_hp)
        elif legacy_vma:
            # Pre-vma runtime: the compute-and-mask path's d_hp relies on
            # the vma autodiff inserting the invariant->varying transpose
            # psum inside jax.vjp — machinery the legacy shard_map does not
            # have, so each stage still holds only its own (masked)
            # contribution. Reduce explicitly; on vma runtimes this branch
            # must NOT run or d_hp would double-count.
            d_hp = jax.tree.map(lambda x: lax.psum(x, grad_axes), d_hp)
        # Otherwise d_hp is already pipe-invariant: the vjp of using an
        # invariant primal (hp) in a varying computation transposes the
        # implicit broadcast into a psum. On vma runtimes d_ep likewise
        # came back invariant through the embed's explicit pcast — no
        # further reduction, it would double-count. The legacy runtime
        # skipped that pcast (see the vjp_emb note) and reduces here,
        # after the scatter.
        if legacy_vma:
            d_ep = jax.tree.map(lambda x: lax.psum(x, grad_axes), d_ep)
        blk_axes = tuple(
            a for a in (data_ax, seq_ax if ct_scale != 1.0 else None) if a
        )
        if blk_axes:
            # Block grads are per-stage (out_spec P('pipe', ...)) but must
            # still SUM across the manual data shards' local batches — and
            # across 'seq' on the legacy runtime, where the 1/sp-scaled
            # seeds leave per-shard partials (vma runtimes reduce
            # implicitly inside the vjp).
            d_blocks = jax.tree.map(
                lambda x: lax.psum(x, blk_axes), d_blocks
            )
        grads = {"blocks": d_blocks}
        for _dtree in (d_hp, d_ep):  # wte appears in both when tied: sum
            for _k, _v in _dtree.items():
                grads[_k] = grads[_k] + _v if _k in grads else _v
        return loss, grads

    specs = pipeline_param_specs(params, mesh)
    fn = jax.shard_map(
        staged,
        mesh=mesh,
        in_specs=(specs, batch_spec, P(AXIS)),
        out_specs=(P(), specs),
        axis_names=manual_axes,
    )
    return fn(params, batch, _stage_iota(n_stages))
