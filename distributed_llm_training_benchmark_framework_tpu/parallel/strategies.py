"""Distributed-training strategies as *sharding specifications*.

The reference implements its four strategy arms as four divergent wrapper code
paths — torch DDP, torch FSDP, and two DeepSpeed engines (reference
``benchmarking/train_harness.py:207-275``). On TPU/XLA the idiomatic design
collapses all four into data: one shared jitted train step, four
(param-sharding, grad-sharding, optimizer-state-sharding) specifications over
a ``jax.sharding.Mesh``. XLA/GSPMD then *derives* the collective schedule the
reference hand-picks libraries for:

- **ddp**   params+opt replicated, batch sharded on 'data'  -> XLA inserts a
  gradient all-reduce over ICI (what NCCL ring all-reduce does in DDP backward
  hooks, reference ``train_harness.py:217-222``).
- **fsdp**  params, grads and opt state all sharded on 'data' -> XLA inserts
  per-use all-gather of weights and reduce-scatter of grads (the FSDP
  schedule, reference ``train_harness.py:231-237``).
- **zero2** params replicated, grads+opt state sharded -> grads reduce-scatter
  into the shard, the Adam update runs on 1/N of the state, and the updates
  all-gather back into replicated params (DeepSpeed ZeRO stage-2 semantics,
  reference ``configs/deepspeed/zero2.json:10-25``). This is the arm XLA does
  not give you for free — the explicit sharding constraints below ask for it.
- **zero3** like fsdp plus per-layer rematerialization: DeepSpeed stage 3's
  live-parameter windowing (``configs/deepspeed/zero3.json:20-26``) trades
  memory for re-compute/re-gather; ``jax.checkpoint`` on the scanned block is
  the XLA-native expression of the same trade.

Every knob here is *live* (loaded from ``configs/strategies/*.json``) — unlike
the reference, where ``--fsdp-config`` is accepted but never read and
``--grad-accum`` is silently inert for DDP/FSDP (SURVEY §2.1 C8/C9).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = Any


@dataclasses.dataclass(frozen=True)
class StrategyConfig:
    """One strategy arm = optimizer recipe + sharding layout + remat policy."""

    name: str
    # optimizer (parity: AdamW lr=1e-4 wd=0.01, reference train_harness.py:328-331
    # and configs/deepspeed/zero2.json:27-36)
    learning_rate: float = 1e-4
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.01
    # DeepSpeed arms use WarmupLR(5) + grad clip 1.0 (zero2.json:2,37-44);
    # the torch arms use neither.
    warmup_steps: int = 0
    grad_clip: Optional[float] = None
    # sharding layout over the 'data' mesh axis
    shard_params: bool = False
    shard_grads: bool = False
    shard_opt_state: bool = False
    # per-layer rematerialization policy inside the block scan:
    # "none" | "dots" (save matmul outputs) | "full" | "auto" (pick the
    # cheapest policy whose memory estimate fits the device — resolved by
    # utils.memory.resolve_auto_remat before training). Legacy bools accepted
    # in JSON configs (True = "full").
    remat: str = "none"
    # compute precision for matmuls ('bf16' | 'f32')
    precision: str = "bf16"
    # parameter (and therefore Adam-state) storage dtype: 'f32' (default —
    # fp32 master weights, the training-quality choice) or 'bf16', which
    # halves params+grads+moments. bf16 state is what makes tier B (1.68B
    # params, ~25 GiB of fp32 state) runnable on a single 16 GiB chip —
    # DeepSpeed's fp16 master-weightless mode plays the same role. Expect
    # bf16-rounded Adam updates (a stress-tier trade, documented in
    # docs/TROUBLESHOOTING.md).
    param_dtype: str = "f32"
    # Host-offloaded optimizer (TPU-native analogue of DeepSpeed's
    # ZeRO-Offload, reference configs/deepspeed/zero3.json offload_optimizer):
    # fp32 MASTER params + Adam moments live permanently in pinned host
    # memory and the full update runs ON THE HOST CPU
    # (jax.experimental.compute_on inside the jitted step); the device holds
    # only a bf16 compute copy of the params, whose grads stream down and
    # whose refresh streams back each step. The quality-preserving
    # alternative to param_dtype='bf16' for models whose fp32 state exceeds
    # HBM: Adam runs in full fp32 against master weights. Costs per-step
    # PCIe traffic (~2 x bf16-param bytes); see docs/PERFORMANCE.md.
    offload_opt_state: bool = False
    # Delayed parameter update for the offload arm (DeepSpeed's
    # delayed_param_update analogue, opt-in): the host consumes the
    # PREVIOUS step's gradients (parked in pinned host memory) while the
    # device runs the CURRENT step's forward/backward — the two have no
    # data dependency inside one program, so XLA's scheduler overlaps the
    # multi-second host Adam with device compute instead of serializing
    # behind it. Params are one step stale (training-semantics change —
    # hence opt-in); step 0 performs no update (its grads become step 1's).
    offload_delayed_update: bool = False

    def describe(self) -> str:
        bits = [
            f"params={'sharded' if self.shard_params else 'replicated'}",
            f"grads={'reduce-scatter' if self.shard_grads else 'all-reduce'}",
            f"opt_state={'sharded' if self.shard_opt_state else 'replicated'}",
        ]
        if self.remat != "none":
            bits.append(f"remat={self.remat}")
        if self.param_dtype != "f32":
            bits.append(f"param_dtype={self.param_dtype}")
        if self.offload_opt_state:
            bits.append("opt_offload=pinned_host")
        if self.offload_delayed_update:
            bits.append("delayed_update")
        return f"{self.name}: " + ", ".join(bits)


STRATEGIES: Dict[str, StrategyConfig] = {
    "ddp": StrategyConfig(name="ddp"),
    "fsdp": StrategyConfig(
        name="fsdp", shard_params=True, shard_grads=True, shard_opt_state=True
    ),
    "zero2": StrategyConfig(
        name="zero2",
        shard_grads=True,
        shard_opt_state=True,
        warmup_steps=5,
        grad_clip=1.0,
    ),
    "zero3": StrategyConfig(
        name="zero3",
        shard_params=True,
        shard_grads=True,
        shard_opt_state=True,
        warmup_steps=5,
        grad_clip=1.0,
        # DeepSpeed stage 3 pays a recompute/gather tax only when memory
        # pressure demands it; blanket per-layer remat measured a ~20%
        # single-chip throughput tax where the arm fit comfortably without
        # it (docs/PERFORMANCE.md). "auto" picks the cheapest fitting policy.
        remat="auto",
    ),
}


def get_strategy(name: str) -> StrategyConfig:
    if name not in STRATEGIES:
        raise ValueError(f"Unknown strategy {name!r} (expected one of {sorted(STRATEGIES)})")
    return STRATEGIES[name]


def _normalize_remat_field(value: Any) -> str:
    """JSON remat field: bool (legacy, True="full"), a model policy string,
    or "auto" (resolved against the memory model before reaching the model —
    the one value tinygpt.normalize_remat deliberately rejects)."""
    if value == "auto":
        return value
    from ..models.tinygpt import normalize_remat

    try:
        return normalize_remat(value)
    except ValueError:
        raise ValueError(
            f"invalid remat value {value!r} in strategy config "
            "(expected bool or one of 'none'/'dots'/'full'/'auto')"
        )


def load_strategy_config(path: str) -> StrategyConfig:
    """Load a strategy arm from a JSON config file (configs/strategies/*.json).

    File format (every field live — this replaces both the reference's
    DeepSpeed JSONs, which were loaded and mutated at runtime
    (train_harness.py:246-262), and its FSDP YAML, which was dead config):

        {"strategy": "zero2",
         "optimizer": {"lr": 1e-4, "betas": [0.9, 0.999], "eps": 1e-8,
                        "weight_decay": 0.01},
         "scheduler": {"warmup_steps": 5},
         "grad_clip": 1.0,
         "precision": "bf16",
         "sharding": {"params": false, "grads": true, "opt_state": true},
         "remat": false}
    """
    with open(path) as f:
        raw = json.load(f)
    name = raw.get("strategy")
    base = get_strategy(name) if name in STRATEGIES else StrategyConfig(name=name or os.path.basename(path))
    opt = raw.get("optimizer", {})
    sched = raw.get("scheduler", {})
    shard = raw.get("sharding", {})
    pdtype = raw.get("param_dtype", base.param_dtype)
    if pdtype not in ("f32", "bf16"):
        raise ValueError(
            f"invalid param_dtype {pdtype!r} in strategy config "
            "(expected 'f32' or 'bf16')"
        )
    return dataclasses.replace(
        base,
        learning_rate=float(opt.get("lr", base.learning_rate)),
        betas=tuple(opt.get("betas", base.betas)),
        eps=float(opt.get("eps", base.eps)),
        weight_decay=float(opt.get("weight_decay", base.weight_decay)),
        warmup_steps=int(sched.get("warmup_steps", base.warmup_steps)),
        grad_clip=raw.get("grad_clip", base.grad_clip),
        precision=raw.get("precision", base.precision),
        param_dtype=pdtype,
        shard_params=bool(shard.get("params", base.shard_params)),
        shard_grads=bool(shard.get("grads", base.shard_grads)),
        shard_opt_state=bool(shard.get("opt_state", base.shard_opt_state)),
        remat=_normalize_remat_field(raw.get("remat", base.remat)),
        offload_opt_state=bool(
            raw.get("offload_opt_state", base.offload_opt_state)
        ),
    )


def is_deepspeed_config(raw: Any) -> bool:
    """True when a JSON dict looks like a DeepSpeed config rather than our
    native strategy format (which always carries a "strategy" key)."""
    if not isinstance(raw, dict) or "strategy" in raw:
        return False
    return any(
        k in raw
        for k in (
            "zero_optimization",
            "train_micro_batch_size_per_gpu",
            "gradient_clipping",
            "bf16",
            "fp16",
        )
    )


def from_deepspeed_config(raw: Dict[str, Any], strategy_name: str) -> StrategyConfig:
    """Translate a DeepSpeed-format JSON into a live StrategyConfig.

    The reference *reads and mutates* its DeepSpeed JSONs at runtime
    (reference ``train_harness.py:246-262``) — so a user pointing
    ``--deepspeed-config`` at their own file expects its optimizer/scheduler/
    clipping values to take effect. Mapping (reference
    ``configs/deepspeed/zero2.json:2,7-9,27-44``):

    - ``optimizer.params.{lr,betas,eps,weight_decay}`` -> AdamW recipe;
    - ``scheduler.params.warmup_num_steps`` (WarmupLR) -> linear warmup;
    - ``gradient_clipping``                -> global-norm clip;
    - ``bf16.enabled`` / ``fp16.enabled``  -> bf16 compute (fp16 maps to bf16:
      the TPU fast path — same role the reference's AMP plays);
    - ``zero_optimization.stage``          -> cross-checked against the CLI
      strategy arm (stage 2 != zero3 is a user error worth failing loudly on).

    Batch-size keys (``train_micro_batch_size_per_gpu`` etc.) are *not* read:
    like the reference, batch geometry comes from the CLI and is injected over
    whatever the file says (reference ``train_harness.py:250-262``).
    """
    base = get_strategy(strategy_name)

    def section(key):
        """A config section must be a dict (or absent); fail naming the key
        rather than AttributeError-ing on shorthand like {"bf16": true}."""
        val = raw.get(key, {})
        if not isinstance(val, dict):
            raise ValueError(
                f"DeepSpeed config section {key!r} must be an object, got {val!r}"
            )
        return val

    def num(container, key, fallback, cast=float):
        """Read a numeric field; HF-Trainer-style "auto" (ubiquitous in real
        DeepSpeed JSONs) falls back to the arm default; anything else
        non-numeric fails naming the offending key."""
        val = container.get(key, None)
        if val is None or val == "auto":
            return fallback
        try:
            return cast(val)
        except (TypeError, ValueError):
            raise ValueError(
                f"DeepSpeed config field {key!r} has non-numeric value {val!r}"
            )

    zero = section("zero_optimization")
    stage = num(zero, "stage", None, int)
    expected = {"zero2": 2, "zero3": 3}.get(strategy_name)
    if stage is not None and expected is not None and stage != expected:
        raise ValueError(
            f"--strategy {strategy_name} but DeepSpeed config sets "
            f"zero_optimization.stage={stage}"
        )
    opt_section = section("optimizer")
    opt_type = opt_section.get("type", "AdamW")
    if str(opt_type).lower() not in ("adam", "adamw"):
        # The framework's optimizer recipe is AdamW (reference parity);
        # silently running AdamW under an SGD/Lamb config would be wrong
        # semantics at a likely-diverging lr.
        raise ValueError(
            f"DeepSpeed optimizer type {opt_type!r} is not supported "
            "(only Adam/AdamW map onto this framework's optimizer)"
        )
    opt = opt_section.get("params", {})
    if not isinstance(opt, dict):
        raise ValueError(
            f"DeepSpeed config field 'optimizer.params' must be an object, got {opt!r}"
        )
    sched = section("scheduler")
    sched_params = sched.get("params", {})
    if not isinstance(sched_params, dict):
        raise ValueError(
            f"DeepSpeed config field 'scheduler.params' must be an object, "
            f"got {sched_params!r}"
        )
    warmup = base.warmup_steps
    # Only warmup-family schedulers carry warmup_num_steps semantics we map.
    if sched.get("type", "WarmupLR") in ("WarmupLR", "WarmupDecayLR"):
        warmup = num(sched_params, "warmup_num_steps", base.warmup_steps, int)
    betas = opt.get("betas", None)
    if betas is None or betas == "auto":
        betas = base.betas
    elif not (
        isinstance(betas, (list, tuple))
        and len(betas) == 2
        and all(isinstance(b, (int, float)) for b in betas)
    ):
        raise ValueError(f"DeepSpeed config field 'betas' must be [b1, b2], got {betas!r}")
    precision = base.precision
    if section("bf16").get("enabled") or section("fp16").get("enabled"):
        precision = "bf16"
    grad_clip = num(raw, "gradient_clipping", base.grad_clip)
    if grad_clip is not None and grad_clip <= 0:
        # DeepSpeed semantics: gradient_clipping 0 means *disabled*, not
        # "clip everything to zero norm".
        grad_clip = None
    # ZeRO-Offload: zero_optimization.offload_optimizer.device cpu/nvme
    # maps onto the pinned-host optimizer offload (reference
    # configs/deepspeed/zero3.json:12-14 ships the section with "none").
    # An explicit device (incl. "none") overrides the base strategy in
    # both directions, like gradient_clipping=0 disables clipping above.
    ds_off = section("zero_optimization").get("offload_optimizer")
    if isinstance(ds_off, dict) and "device" in ds_off:
        offload = ds_off["device"] not in (None, "none")
    else:
        offload = base.offload_opt_state
    return dataclasses.replace(
        base,
        learning_rate=num(opt, "lr", base.learning_rate),
        betas=tuple(betas),
        eps=num(opt, "eps", base.eps),
        weight_decay=num(opt, "weight_decay", base.weight_decay),
        warmup_steps=warmup,
        grad_clip=grad_clip,
        precision=precision,
        offload_opt_state=offload,
    )


def _adamw_only(strategy: StrategyConfig) -> optax.GradientTransformation:
    """AdamW with the arm's warmup schedule, WITHOUT the clip stage."""
    if strategy.warmup_steps > 0:
        lr = optax.linear_schedule(
            init_value=0.0,
            end_value=strategy.learning_rate,
            transition_steps=strategy.warmup_steps,
        )
    else:
        lr = strategy.learning_rate
    return optax.adamw(
        learning_rate=lr,
        b1=strategy.betas[0],
        b2=strategy.betas[1],
        eps=strategy.eps,
        weight_decay=strategy.weight_decay,
    )


def _base_optimizer(strategy: StrategyConfig) -> optax.GradientTransformation:
    """The plain AdamW chain (+ optional clip + warmup) for one arm."""
    tx = _adamw_only(strategy)
    if strategy.grad_clip is not None:
        tx = optax.chain(optax.clip_by_global_norm(float(strategy.grad_clip)), tx)
    return tx


def make_optimizer(strategy: StrategyConfig) -> optax.GradientTransformation:
    """AdamW (+ optional global-norm clip + optional linear warmup).

    Mirrors the reference recipes: bare AdamW(1e-4, wd=0.01) for ddp/fsdp
    (train_harness.py:328-331); AdamW + WarmupLR(5) + clip 1.0 for the ZeRO
    arms (configs/deepspeed/zero2.json:2,27-44).

    For ``offload_opt_state`` arms the returned transformation's state is
    ``(fp32_master_params, adamw_state)`` — the ZeRO-Offload layout: the
    fp32 master weights live WITH the moments in pinned host memory
    (``opt_state_shardings``), the device keeps only a bf16 compute copy of
    the params, and the whole update executes on the host
    (``offload_update_and_apply``). Its ``update`` is deliberately not
    callable — the step must use ``offload_update_and_apply``.
    """
    tx = _base_optimizer(strategy)
    if not strategy.offload_opt_state:
        return tx

    def init(params):
        # Masters are upcast from the bf16 device init, so they START
        # bf16-rounded (immaterial: the init is random noise); the arm's
        # quality edge is that every subsequent Adam update ACCUMULATES in
        # fp32, where the bf16-state arm rounds each step's small update.
        master = jax.tree.map(
            lambda p: p.astype(jnp.float32), params
        )
        state = (master, tx.init(master))
        if strategy.offload_delayed_update:
            # Delayed update: the state additionally parks last step's
            # (pre-scaled) gradients in pinned host memory, plus their clip
            # scale. Step 0 consumes these zeros: with warmup (the ZeRO
            # arms' schedule starts at lr=0) that is an exact no-op on the
            # masters; without warmup it applies one weight-decay-only
            # micro-step (documented DPU semantics).
            pending = jax.tree.map(
                lambda p: jnp.zeros(p.shape, p.dtype), params
            )
            state = state + ((pending, jnp.zeros((), jnp.float32)),)
        return state

    def update(grads, state, params=None):
        raise ValueError(
            "offload_opt_state optimizer state updates on the host — call "
            "strategies.offload_update_and_apply, not optimizer.update"
        )

    return optax.GradientTransformation(init, update)


def opt_state_shardings(mesh: Mesh, opt_specs, strategy: StrategyConfig):
    """NamedShardings for the optimizer state, honoring the offload layout:
    with ``offload_opt_state`` the WHOLE state (clip state, Adam moments,
    schedule count) lives in pinned host memory; otherwise device HBM."""
    shardings = named(mesh, opt_specs)
    if not strategy.offload_opt_state:
        return shardings
    if jax.default_backend() != "tpu":
        # XLA:CPU's SPMD partitioner RET_CHECKs on the pinned_host
        # placement annotation ("Side-effect HLO must have sharding" on
        # annotate_device_placement), so the offload arm is TPU-only —
        # fail with the remedy instead of a partitioner crash.
        raise ValueError(
            "offload_opt_state requires a TPU runtime (pinned_host memory "
            "space + host compute); this backend "
            f"({jax.default_backend()!r}) cannot partition host-placed "
            "state. Drop --offload-opt-state, or use --param-dtype bf16 "
            "for the memory relief."
        )
    return jax.tree.map(lambda s: s.with_memory_kind("pinned_host"), shardings)


def offload_update_and_apply(
    strategy: StrategyConfig,
    grads,
    opt_state,
    params,
    mesh: Mesh,
    grad_specs,
    param_specs,
):
    """Optimizer update + apply for ``offload_opt_state`` arms: the
    ZeRO-Offload architecture (reference ``configs/deepspeed/zero3.json``
    offload_optimizer analogue), TPU-native.

    The fp32 master params and the Adam moments live permanently in pinned
    host memory; the device holds a bf16 compute copy of the params (the
    memory win) whose gradients stream down once per step. AdamW (+warmup
    schedule) and ``apply_updates`` run on the host CPU via
    ``compute_on("device_host")`` in fp32 against the master weights —
    full-precision Adam, unlike ``--param-dtype bf16`` whose moments and
    updates round to bf16 — and only the refreshed bf16 compute copy
    streams back. Per-step PCIe traffic: ~2x bf16-params (grads down +
    compute copy up). Device HBM never holds moments, masters, or update
    tensors.

    Round-5 changes (PERFORMANCE.md §13):
    - global-norm CLIPPING moved to the device: the norm is a cheap fused
      reduction over grads that are already in HBM; only the resulting
      scale scalar crosses to the host, where it folds into the fp32
      upcast pass the host math does anyway. The checkpointed state keeps
      the full optax chain structure (clip state is ``EmptyState``).
    - ``offload_delayed_update``: the host consumes LAST step's grads
      (parked in pinned host memory with their own clip scale) while this
      step's fresh grads stream down beside it — inside one program the
      host call has no dependency on this step's forward/backward, so
      XLA's latency-hiding scheduler overlaps the multi-second host Adam
      with device compute. Params lag one step (DeepSpeed
      delayed_param_update semantics, opt-in via --offload-delayed-update).
    """
    from jax.experimental.compute_on import compute_on

    adamw = _adamw_only(strategy)
    is_spec = lambda x: isinstance(x, P)
    host = lambda specs: jax.tree.map(
        lambda spec: NamedSharding(mesh, spec).with_memory_kind("pinned_host"),
        specs, is_leaf=is_spec,
    )
    dev = lambda specs: jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), specs, is_leaf=is_spec
    )

    # Device-side clip: exact optax.clip_by_global_norm semantics
    # (scale = 1 when the norm is under the limit, limit/norm otherwise).
    if strategy.grad_clip is not None:
        gnorm = optax.global_norm(
            jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        )
        limit = jnp.float32(strategy.grad_clip)
        # = optax.clip_by_global_norm's scale: 1 under the limit,
        # limit/gnorm above it; no inf in either where-branch.
        scale = limit / jnp.maximum(gnorm, limit)
    else:
        scale = jnp.float32(1.0)

    delayed = strategy.offload_delayed_update
    if delayed:
        master, inner, (g_use, scale_use) = opt_state
    else:
        master, inner = opt_state
        g_use = jax.device_put(grads, host(grad_specs))
        scale_use = scale
    if strategy.grad_clip is not None:
        clip_state, adamw_state = inner
    else:
        clip_state, adamw_state = None, inner

    # The compute-copy dtype is the device params' dtype — static trace-time
    # metadata, so no param data crosses to the host for this.
    param_dtypes = jax.tree.map(lambda p: p.dtype, params)

    def host_math(g, s, master, adamw_state):
        # Clip scale folds into the fp32 upcast the update needs anyway —
        # zero extra passes over the gradient tree.
        g32 = jax.tree.map(lambda x: x.astype(jnp.float32) * s, g)
        u, adamw_state2 = adamw.update(g32, adamw_state, master)
        master2 = optax.apply_updates(master, u)
        compute = jax.tree.map(
            lambda m, dt: m.astype(dt), master2, param_dtypes
        )
        return compute, master2, adamw_state2

    compute, master2, adamw_state2 = compute_on("device_host")(
        jax.jit(host_math)
    )(g_use, scale_use, master, adamw_state)
    inner2 = (
        (clip_state, adamw_state2) if strategy.grad_clip is not None
        else adamw_state2
    )
    new_state = (master2, inner2)
    if delayed:
        # Park this step's (unscaled) grads + their clip scale for the next
        # step's host update.
        new_state = new_state + (
            (jax.device_put(grads, host(grad_specs)), scale),
        )
    return jax.device_put(compute, dev(param_specs)), new_state


# ---------------------------------------------------------------------------
# PartitionSpec derivation
# ---------------------------------------------------------------------------

# Megatron-style tensor-parallel layout over the 'model' mesh axis, keyed by
# parameter leaf path. Column-parallel QKV/FC1 (output features sharded),
# row-parallel attention-out/FC2 (input features sharded; XLA inserts the
# all-reduce the row-parallel matmul needs), vocab-sharded tied embedding
# (the logits einsum + cross-entropy become Megatron's parallel softmax —
# GSPMD derives the collectives from the sharding).
_TP_RULES = {
    "wte": (0,),        # vocab
    "lm_head": (0,),    # untied head: vocab-sharded like wte
    "blocks/wqkv": (3,),  # per-head output features
    "blocks/bqkv": (2,),
    # GQA split projections: column-parallel q and k/v (the consecutive-block
    # kv repeat in the model keeps each query-head shard paired with its own
    # kv-head shard as long as the 'model' degree divides kv_heads)
    "blocks/wq": (2,),
    "blocks/bq": (1,),
    "blocks/wkv": (3,),
    "blocks/bkv": (2,),
    "blocks/wo": (1,),  # row-parallel input (merged heads)
    "blocks/wfc": (2,),  # column-parallel output
    "blocks/bfc": (1,),
    # SwiGLU gate/up stack: column-parallel output features
    "blocks/wgu": (3,),
    "blocks/bgu": (2,),
    "blocks/wproj": (1,),  # row-parallel input
    # MoE experts: column-parallel w1, row-parallel w2 inside each expert
    "blocks/moe_w1": (3,),
    "blocks/moe_b1": (2,),
    "blocks/moe_w2": (2,),
}

# Expert parallelism over the 'expert' mesh axis: each device group owns a
# slice of the expert set; the dispatch/combine einsums in models.moe become
# the all-to-all. The router stays replicated (it is tiny and every token
# needs all scores).
_EP_RULES = {
    "blocks/moe_w1": 1,
    "blocks/moe_b1": 1,
    "blocks/moe_w2": 1,
    "blocks/moe_b2": 1,
}


def _leaf_name(path) -> str:
    return "/".join(str(getattr(p, "key", p)) for p in path)


#: Self-test escape hatch (graftcheck `--inject bad-fsdp-axis`): False
#: reverts to the pre-round-8 unrestricted largest-free-axis placement,
#: reintroducing the llama-fsdp-dp4-tp2 transposed-tiling reshard fallback
#: so CI can prove the HLO auditor catches it.
_COMPOSED_FSDP_HYGIENE = True

#: Leaves smaller than this (total elements) are not worth FSDP-sharding in
#: a composed dp x tp mesh: norm scales and biases are a few hundred
#: elements per layer, and 'data'-sharding them buys ~nothing in HBM while
#: costing an all-gather per use — measured 10 extra all-gathers per step
#: on the llama-fsdp-dp4-tp2 arm (docs/PERFORMANCE.md round 8). Pure-dp
#: meshes keep the old behavior (their frozen budgets pin it, and without
#: a 'model' axis the gathers never risk the transposed-order permutes).
_COMPOSED_MIN_SHARD_ELEMENTS = 4096

#: Round-15 scan-carry kill: stacked column-parallel leaves whose ONLY
#: hygiene-legal 'data' axis is the embed (contraction) axis stay
#: model-only sharded in composed dp x tp meshes — under the SCANNED layer
#: loop only (``param_partition_specs(scan_stacked=True)``); the unrolled
#: lowering has no stacked stash and keeps the round-8 placement, so the
#: suite's measured llama-fsdp-dp4-tp2 budget stays byte-identical.
#: Data-sharding the
#: contraction dim makes GSPMD lower the projection as contraction-partial
#: matmuls whose scanned activation/grad stash reshards between tilings
#: with collective-permute chains — measured on llama-fsdp-dp4-tp2-scan,
#: where 'blocks/wq' was the source of the banked 4 reshard suspects
#: (together with the scan-carry pin, 4 -> 0). Scoped to the measured
#: leaf: wkv/wgu data-shard the same axis without tripping the stash
#: (and wgu is the largest block leaf — its fsdp split is the memory win
#: worth keeping); the unexercised tinygpt siblings (wqkv/wfc) keep the
#: old placement until a composed-mesh tinygpt arm joins the roster.
_COMPOSED_CONTRACTION_DATA_SKIP = frozenset({"blocks/wq"})


def _shard_largest_free_axis(
    spec: list, shape: Tuple[int, ...], n_shards: int, is_block_leaf: bool,
    composed: bool = False,
) -> None:
    """FSDP-style: put 'data' on the largest unsharded divisible axis.

    For stacked block leaves (leading 'layers' scan axis) we prefer a tensor
    axis over the layers axis: sharding inside the layer keeps the scan body's
    dynamic-slice local and lets XLA all-gather exactly one layer's shard per
    scan iteration (the FSDP/ZeRO-3 schedule). The layers axis is the fallback.

    ``composed`` (a >1 'model' axis coexists with >1 'data') adds the
    round-8 tile-order hygiene rules:

    - 'data' only lands on an axis BEFORE the leaf's 'model' axis. The mesh
      is data-major, so [.., 'data', .., 'model', ..] tiles enumerate
      devices in iota order while the reverse order enumerates them
      transposed — and GSPMD can only reshard between the two orders with
      collective-permute chains. Row-parallel and vocab-sharded leaves
      ('model' leads: wo/wproj/wte/lm_head) therefore keep model-only
      sharding; column-parallel leaves (wq/wgu/wfc: 'model' trails) keep
      their fsdp 'data' split. Measured on llama-fsdp-dp4-tp2 (unrolled):
      13 replication-reshard suspects -> 0.
    - vector-like leaves (< _COMPOSED_MIN_SHARD_ELEMENTS elements) stay
      replicated over 'data' (see the constant's comment).
    """
    if composed and _COMPOSED_FSDP_HYGIENE:
        # Vector-likeness is a PER-LAYER property: block leaves are
        # stacked (L, ...), and counting the layers axis would let a
        # deep model's norm scales (L x D elements) dodge the rule the
        # comment above sizes in per-layer units.
        per_layer = shape[1:] if is_block_leaf and len(shape) > 1 else shape
        size = 1
        for d in per_layer:
            size *= d
        if "model" not in spec and size < _COMPOSED_MIN_SHARD_ELEMENTS:
            return
    axes = list(range(len(shape)))
    candidates = axes[1:] + axes[:1] if is_block_leaf and len(shape) > 1 else axes
    if composed and _COMPOSED_FSDP_HYGIENE and "model" in spec:
        model_ax = spec.index("model")
        candidates = [ax for ax in candidates if ax < model_ax]
    best = None
    for ax in candidates:
        if spec[ax] is None and shape[ax] % n_shards == 0 and shape[ax] >= n_shards:
            if best is None or shape[ax] > shape[best]:
                best = ax
    if best is not None:
        spec[best] = "data"


def param_partition_specs(
    params: Params, mesh: Mesh, shard: bool, kv_heads: Optional[int] = None,
    scan_stacked: bool = False,
) -> Params:
    """PartitionSpec pytree for the params under a given strategy + mesh.

    Applies tensor-parallel rules first (when the mesh has a >1 'model' axis),
    then — for sharded strategies — FSDP-style 'data' sharding on the largest
    remaining axis of each leaf. The two compose: a 2-D (data, model) mesh
    gives e.g. wfc the spec P(None, 'data', 'model').

    ``kv_heads`` (the model config's KV-head count, passed by config-bearing
    callers) gates the GQA kv projections' 'model' sharding: the column
    split is only head-aligned when the 'model' degree divides ``kv_heads``.
    A misaligned split shards WITHIN each kv head's feature block, and the
    consecutive-block kv repeat in the model then needs a layout the
    partitioner cannot produce in place — it falls back to
    full-replicate-then-repartition of every per-layer k/v tensor (measured:
    +10 all-gathers and +6 collective-permutes per step on a tp=2 llama-S
    compile; on newer XLA the same fallback logs "[SPMD] Involuntary full
    rematerialization"). Keeping wkv/bkv replicated over 'model' instead
    duplicates only the small kv projection einsum (2/(2+q_heads/kv_heads)
    of one attention projection) and emits zero resharding collectives —
    the Megatron choice for tp > kv_heads.

    Composed dp x tp meshes additionally apply the round-8 tile-order
    hygiene rules (see ``_shard_largest_free_axis``): 'data' never lands
    after a leaf's 'model' axis (the transposed tile order is the
    llama-fsdp-dp4-tp2 collective-permute fallback) and vector-like leaves
    stay replicated over 'data'.

    ``scan_stacked`` (round 15) says the caller compiles the SCANNED layer
    loop: composed meshes then keep the
    :data:`_COMPOSED_CONTRACTION_DATA_SKIP` leaves model-only — the scan's
    stacked activation/grad stash is what reshards with permute chains
    when those leaves data-shard their contraction axis. The unrolled
    lowering has no stacked stash and keeps the round-8 placement (its
    frozen budgets stay byte-identical).
    """
    n_data = mesh.shape.get("data", 1)
    n_model = mesh.shape.get("model", 1)
    n_pipe = mesh.shape.get("pipe", 1)
    n_expert = mesh.shape.get("expert", 1)
    kv_misaligned = kv_heads is not None and kv_heads % n_model != 0

    def spec(path, leaf):
        s = [None] * len(leaf.shape)
        name = _leaf_name(path)
        is_block = name.startswith("blocks/")
        if n_pipe > 1 and is_block:
            # Pipeline stages own contiguous slices of the stacked layers axis.
            s[0] = "pipe"
        if n_expert > 1 and name in _EP_RULES:
            ax = _EP_RULES[name]
            if leaf.shape[ax] % n_expert == 0:
                s[ax] = "expert"
        if n_model > 1:
            for ax in _TP_RULES.get(name, ()):
                if name in ("blocks/wkv", "blocks/bkv") and kv_misaligned:
                    # kv-head-aligned rule (see docstring): replicate the kv
                    # projection over 'model' rather than split inside a head.
                    continue
                if name in ("wte", "lm_head") and n_pipe > 1:
                    # Pipeline runs keep the tied embedding replicated over
                    # 'model': the schedule already replicates embed/head
                    # across stages (every stage computes them for schedule
                    # uniformity), and a vocab-sharded embedding gather inside
                    # the partially-manual pipe region trips an XLA SPMD
                    # partitioner CHECK (spmd_partitioner_util.cc:495) when
                    # 'data' also shards the indices — the dp x tp x pp
                    # triple. Megatron-LM likewise special-cases the
                    # embedding's placement under pipeline parallelism.
                    continue
                if s[ax] is None and leaf.shape[ax] % n_model == 0:
                    s[ax] = "model"
        if shard and n_data > 1:
            if (
                scan_stacked
                and n_model > 1
                and _COMPOSED_FSDP_HYGIENE
                and name in _COMPOSED_CONTRACTION_DATA_SKIP
            ):
                # Round-15 scan-carry rule: keep the leaf model-only (see
                # _COMPOSED_CONTRACTION_DATA_SKIP) — the same posture the
                # hygiene rules already give the row-parallel leaves, whose
                # leading 'model' axis leaves no legal 'data' slot either.
                pass
            else:
                _shard_largest_free_axis(
                    s, leaf.shape, n_data, is_block, composed=n_model > 1
                )
        return P(*s)

    return jax.tree_util.tree_map_with_path(spec, params)


def opt_state_partition_specs(
    optimizer: optax.GradientTransformation,
    params: Params,
    param_specs: Params,
    mesh: Mesh,
    shard: bool,
    kv_heads: Optional[int] = None,
    scan_stacked: bool = False,
) -> Any:
    """PartitionSpec pytree for the optimizer state.

    Param-shaped leaves (Adam mu/nu, weight-decay masks, ...) inherit either
    the param's own spec (fsdp/zero3) or an FSDP-style sharded spec of their
    own (zero2: replicated params but *sharded* moments — the defining ZeRO-2
    layout). Non-param leaves (step counts) are replicated.
    """
    state_shapes = jax.eval_shape(optimizer.init, params)
    if shard:
        moment_specs = param_partition_specs(
            params, mesh, shard=True, kv_heads=kv_heads,
            scan_stacked=scan_stacked,
        )
    else:
        moment_specs = param_specs
    return optax.tree_map_params(
        optimizer,
        lambda _, spec: spec,
        state_shapes,
        moment_specs,
        transform_non_params=lambda _: P(),
    )


def batch_partition_spec(mesh: Mesh) -> P:
    """Global batch (batch, seq): batch dim sharded on 'data' — AND on
    'expert' when an expert-parallel axis exists — sequence dim on 'seq'
    when a sequence-parallel axis exists (ring attention consumes it).

    Expert parallelism rides the batch dim (DeepSpeed-MoE style): each of
    the dp x ep device groups processes a DISTINCT batch shard, and the MoE
    layer exchanges tokens across 'expert' with an explicit all-to-all
    (models.moe). The round-4 layout kept the batch replicated over
    'expert', which silently duplicated all non-expert compute ep times —
    half the machine re-deriving the same activations at ep=2."""
    axes = tuple(ax for ax in ("data", "expert") if mesh.shape.get(ax, 1) > 1)
    batch_axis = axes if axes else None
    seq_axis = "seq" if mesh.shape.get("seq", 1) > 1 else None
    if seq_axis is None:
        return P(batch_axis) if batch_axis else P()
    return P(batch_axis, seq_axis)


def named(mesh: Mesh, spec_tree: Any) -> Any:
    """PartitionSpec pytree -> NamedSharding pytree."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )
