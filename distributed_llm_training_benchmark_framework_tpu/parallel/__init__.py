from .mesh import make_mesh, MeshAxes
from .strategies import (
    StrategyConfig,
    STRATEGIES,
    get_strategy,
    load_strategy_config,
    param_partition_specs,
    opt_state_partition_specs,
    batch_partition_spec,
)

__all__ = [
    "make_mesh",
    "MeshAxes",
    "StrategyConfig",
    "STRATEGIES",
    "get_strategy",
    "load_strategy_config",
    "param_partition_specs",
    "opt_state_partition_specs",
    "batch_partition_spec",
]
