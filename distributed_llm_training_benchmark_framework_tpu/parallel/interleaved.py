"""Interleaved 1F1B — virtual pipeline stages that actually shrink the bubble.

The plain 1F1B schedule in ``parallel.pipeline`` is the lockstep variant: its
fill/drain bubble is identical to GPipe's (2*(P-1) full-stage units); the win
is memory only. This module implements the Megatron-LM *interleaved* schedule
(Narayanan et al. 2021, "Efficient Large-Scale Language Model Training on GPU
Clusters"): each device owns V non-contiguous layer chunks — global pipeline
position j in [0, P*V) maps to device j % P, chunk j // P — so a microbatch
rides the ring V times through chunks 1/V the size. Fill/drain cost drops to
2*(P-1) *chunk* units versus the non-interleaved 2*(P-1)*V: the bubble
fraction falls by ~V.

TPU-native construction (nothing like Megatron's process-per-stage runtime):

- **Static schedule, SPMD execution.** A greedy list scheduler
  (``build_schedule``, plain numpy at trace time) simulates the whole run —
  each device executes ONE chunk-forward or ONE chunk-backward per tick,
  messages take one tick per ring hop — and emits per-(tick, stage) tables:
  which (microbatch, chunk) to run, which buffer slots to read/write, what to
  send. The executor replays the tables with a ``lax.scan`` over the stacked
  table rows inside a ``shard_map`` manual over 'pipe' — ONE compiled tick
  body regardless of how long the accumulation chain is. Per tick, a
  ``lax.switch`` on the device's scheduled kind runs exactly one unit
  (device-varying control flow — legal in the manual region), then ONE fwd
  ``ppermute`` and ONE bwd ``ppermute`` move whatever was produced (zeros on
  idle links). Collectives stay unconditional and uniform — no deadlock
  surface.
- **Rolling buffers, slot-allocated by the scheduler.** Arriving activations
  / gradients park in pending buffers; forward inputs persist in a residual
  buffer until their backward rematerializes the chunk under ``jax.vjp``
  (same per-stage recompute policy as the plain 1F1B). Smallest-free-slot
  allocation bounds every buffer at its true max concurrency — O(P*V),
  independent of M (tests assert both properties).
- **No forward unit at the last position.** The final chunk's output is only
  ever consumed by its own backward, which rematerializes the chunk from its
  input anyway — so position P*V-1 schedules no F unit at all: its backward
  (the "head" unit) consumes the parked incoming activation directly and
  computes loss value + chunk/head/input cotangents in ONE vjp. Saves M
  chunk-forwards per step and their schedule slots.
- **Permuted layer stacking.** Device d must own global layers of chunks
  {v*P + d}: ``layer_permutation`` reorders the stacked block weights so the
  contiguous 'pipe' sharding of ``pipeline_param_specs`` lands each chunk on
  its device. Params (and grads, and Adam state) live in this layout for the
  whole run — checkpoints record the layout and refuse a mismatched resume.
  Dropout keys use GLOBAL layer indices, so the math is layout-independent.

Constraints: n_layer % (pipe * virtual) == 0. MoE composes: each chunk's
forward returns its layers' Switch load-balance aux alongside the
activation, F units (and the head unit, whose chunk has no F) accumulate
the primal aux, and every chunk backward seeds the constant aux cotangent
coef/(n_layer*n_micro) — the same accounting gpipe/1f1b use, per chunk
instead of per stage. Sequence parallelism composes the same way as the
other schedules (manual
over ('pipe','seq'), sharded ring/Ulysses attention, CE psum over 'seq') —
with one backend-specific execution detail. With sp>1 the unit bodies
contain 'seq'-axis collectives, and the per-tick ``lax.switch`` index varies
across pipe stages. Each 'seq' collective's participants all share a pipe
stage, so every participant takes the same branch — uniform-across-
participants, which is what the SPMD model requires — but XLA:CPU's thunk
runtime rendezvouses ALL local devices per collective instruction, so pipe
stage 0 sitting in the FWD branch's ring ppermute while stage 1 sits in the
BWD branch's CE psum aborts the process (rendezvous timeout, observed as
SIGABRT with "Expected 4 threads to join the rendezvous, but only 2
arrived"). On CPU with sp>1 the executor therefore runs every unit kind
unconditionally and selects outputs by mask — one uniform collective
sequence on every device, at the price of ~2-3x per-tick compute. That
price is paid only where it buys testability; the TPU path keeps the
single-unit switch.
"""

from __future__ import annotations

import dataclasses
import heapq
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models import tinygpt
from .pipeline import (
    AXIS, _key_data_or_none, _rebuild_key, _seq_setup, _stage_iota,
    pipeline_param_specs,
)

IDLE, FWD, BWD = 0, 1, 2

# Table names stacked into the executor's lax.scan xs, in order.
_TABLES = (
    "kind", "unit_m", "unit_v", "f_src", "b_src", "b_head",
    "resid_rw", "park_f", "park_b", "send_f", "send_b",
)


@dataclasses.dataclass(frozen=True)
class Schedule:
    """Static interleaved-1F1B schedule for (P stages, V chunks, M micro).

    All tables are (T, P) int32; -1 means "not applicable this tick".
    """

    P: int
    V: int
    M: int
    ticks: int
    kind: np.ndarray          # IDLE/FWD/BWD
    unit_m: np.ndarray        # microbatch index of this tick's unit
    unit_v: np.ndarray        # chunk index of this tick's unit
    f_src: np.ndarray         # FWD: pend_f slot to read (-2 = embed injection)
    b_src: np.ndarray         # BWD: pend_b slot (b_head=0) / pend_f slot (=1)
    b_head: np.ndarray        # 1 iff this BWD unit is the last position
    resid_rw: np.ndarray      # FWD: slot to write x_in / BWD: slot to read
    park_f: np.ndarray        # slot to park the arriving fwd message (-1 none)
    park_b: np.ndarray        # slot to park the arriving bwd message (-1 none)
    send_f: np.ndarray        # 1 iff this tick's F output goes on the fwd ring
    send_b: np.ndarray        # 1 iff this tick's B output goes on the bwd ring
    pend_f_slots: int
    pend_b_slots: int
    resid_slots: int

    @property
    def bubble_fraction(self) -> float:
        """Idle fraction of the schedule (unit-ticks wasted / total)."""
        work = self.M * (self.P * self.V - 1) + self.M * self.P * self.V
        return 1.0 - work / float(self.ticks * self.P)


def build_schedule(P: int, V: int, M: int) -> Schedule:
    """Greedy lockstep list-scheduler (the 'alternate' policy).

    Per tick each device picks one ready unit: after a backward it prefers a
    forward (the 1F1B steady-state alternation — strict backward-greedy
    measures 1-14 ticks worse at P=4); forwards prefer the DEEPEST ready
    position (drain in-flight microbatches before injecting new ones, which
    bounds residual liveness), backwards the oldest microbatch.

    Readiness: F(m,0) is always ready (embed is local); F(m,j) one tick after
    F(m,j-1) ran on the previous ring device. Position PV-1 has NO forward
    unit — B(m, PV-1) becomes ready one tick after F(m, PV-2) (its input has
    arrived) and does loss + chunk vjp in place; B(m,j) one tick after
    B(m,j+1).
    """
    PV = P * V
    fwd_done: Dict[Tuple[int, int], int] = {}
    bwd_done: Dict[Tuple[int, int], int] = {}
    last_was_b = [False] * P

    rows: List[dict] = []  # per tick: {d: (kind, m, j)}
    t = 0
    while len(bwd_done) < M * PV:
        if t > 8 * (2 * M * V + 4 * PV) + 64:
            raise RuntimeError(
                f"interleaved schedule did not converge (P={P}, V={V}, M={M})"
            )
        sel = {}
        for d in range(P):
            fcands, bcands = [], []
            for m in range(M):
                for v in range(V):
                    j = v * P + d
                    if j != PV - 1 and (m, j) not in fwd_done:
                        if j == 0:
                            fcands.append((m, j))
                        else:
                            pm = fwd_done.get((m, j - 1))
                            if pm is not None and pm + 1 <= t:
                                fcands.append((m, j))
                    if (m, j) not in bwd_done:
                        if j == PV - 1:
                            pm = fwd_done.get((m, j - 1))
                            if pm is not None and pm + 1 <= t:
                                bcands.append((m, j))
                        elif (m, j) in fwd_done:
                            nb = bwd_done.get((m, j + 1))
                            if nb is not None and nb + 1 <= t:
                                bcands.append((m, j))
            fcands.sort(key=lambda mj: (-mj[1], mj[0]))
            bcands.sort(key=lambda mj: (mj[0], -mj[1]))
            if last_was_b[d] and fcands:
                sel[d] = (FWD, *fcands[0])
            elif bcands:
                sel[d] = (BWD, *bcands[0])
            elif fcands:
                sel[d] = (FWD, *fcands[0])
        for d, (kind, m, j) in sel.items():
            if kind == FWD:
                fwd_done[(m, j)] = t
                last_was_b[d] = False
            else:
                bwd_done[(m, j)] = t
                last_was_b[d] = True
        rows.append(sel)
        t += 1
    T = t

    # --- second pass: buffer-slot allocation from the committed schedule ---
    shape = (T, P)
    kind = np.zeros(shape, np.int32)
    unit_m = np.full(shape, -1, np.int32)
    unit_v = np.full(shape, -1, np.int32)
    f_src = np.full(shape, -1, np.int32)
    b_src = np.full(shape, -1, np.int32)
    b_head = np.zeros(shape, np.int32)
    resid_rw = np.full(shape, -1, np.int32)
    park_f = np.full(shape, -1, np.int32)
    park_b = np.full(shape, -1, np.int32)
    send_f = np.zeros(shape, np.int32)
    send_b = np.zeros(shape, np.int32)

    # Smallest-free-slot allocation so the high-watermark equals the true
    # max concurrency (the buffer-size claim tests assert O(P*V)).
    pend_f_free = [list(range(4 * PV + 4)) for _ in range(P)]
    pend_b_free = [list(range(4 * PV + 4)) for _ in range(P)]
    resid_free = [list(range(4 * PV + 4)) for _ in range(P)]
    pend_f_of: Dict[Tuple[int, int], int] = {}  # (m, j-consumer) -> slot
    pend_b_of: Dict[Tuple[int, int], int] = {}
    resid_of: Dict[Tuple[int, int], int] = {}
    hi_f = hi_b = hi_r = 0

    for t, sel in enumerate(rows):
        # arrivals first: a message sent at t-1 parks at t (possibly consumed
        # later the same tick).
        if t > 0:
            for d, (k, m, j) in rows[t - 1].items():
                if k == FWD:  # every scheduled F unit sends (PV-1 has none)
                    dst = (d + 1) % P
                    slot = heapq.heappop(pend_f_free[dst])
                    hi_f = max(hi_f, slot + 1)
                    pend_f_of[(m, j + 1)] = slot
                    park_f[t, dst] = slot
                elif k == BWD and j != 0:
                    dst = (d - 1) % P
                    slot = heapq.heappop(pend_b_free[dst])
                    hi_b = max(hi_b, slot + 1)
                    pend_b_of[(m, j - 1)] = slot
                    park_b[t, dst] = slot
        for d, (k, m, j) in sel.items():
            kind[t, d] = k
            unit_m[t, d] = m
            unit_v[t, d] = j // P
            if k == FWD:
                if j == 0:
                    f_src[t, d] = -2
                else:
                    slot = pend_f_of.pop((m, j))
                    f_src[t, d] = slot
                    heapq.heappush(pend_f_free[d], slot)
                rslot = heapq.heappop(resid_free[d])
                hi_r = max(hi_r, rslot + 1)
                resid_of[(m, j)] = rslot
                resid_rw[t, d] = rslot
                send_f[t, d] = 1
            elif j == PV - 1:
                # Head unit: consumes the parked incoming activation directly
                # (no residual, no F unit existed for this position).
                slot = pend_f_of.pop((m, j))
                b_src[t, d] = slot
                b_head[t, d] = 1
                heapq.heappush(pend_f_free[d], slot)
                send_b[t, d] = 1
            else:
                slot = pend_b_of.pop((m, j))
                b_src[t, d] = slot
                heapq.heappush(pend_b_free[d], slot)
                rslot = resid_of.pop((m, j))
                resid_rw[t, d] = rslot
                heapq.heappush(resid_free[d], rslot)
                send_b[t, d] = int(j != 0)

    return Schedule(
        P=P, V=V, M=M, ticks=T, kind=kind, unit_m=unit_m, unit_v=unit_v,
        f_src=f_src, b_src=b_src, b_head=b_head, resid_rw=resid_rw,
        park_f=park_f, park_b=park_b, send_f=send_f, send_b=send_b,
        pend_f_slots=max(hi_f, 1), pend_b_slots=max(hi_b, 1),
        resid_slots=max(hi_r, 1),
    )


def layer_permutation(n_layer: int, P: int, V: int) -> np.ndarray:
    """perm such that stacked row r holds global layer perm[r] when the stack
    is contiguously sharded over 'pipe': device d's rows (v*Lc + i within its
    shard) hold chunk (v*P + d)'s layers."""
    if n_layer % (P * V) != 0:
        raise ValueError(
            f"n_layer={n_layer} not divisible by pipe*virtual={P}*{V}"
        )
    Lc = n_layer // (P * V)
    perm = np.empty(n_layer, np.int64)
    for d in range(P):
        for v in range(V):
            for i in range(Lc):
                r = d * (n_layer // P) + v * Lc + i
                perm[r] = (v * P + d) * Lc + i
    return perm


def interleaved_loss_and_grads(
    config: tinygpt.TinyGPTConfig,
    mesh: Mesh,
    params,
    batch: jax.Array,  # (M, mb, S) microbatches; targets are the inputs
    virtual: int = 2,
    base_key: Optional[jax.Array] = None,
    deterministic: bool = True,
):
    """Run one interleaved-1F1B step -> (loss, grads).

    ``params['blocks']`` must already be stacked in ``layer_permutation``
    order (create_train_state does this for pipeline_schedule='interleaved');
    returned grads are in the same layout.
    """
    n_stages = mesh.shape[AXIS]
    V = virtual
    if config.n_layer % (n_stages * V) != 0:
        raise ValueError(
            f"n_layer={config.n_layer} not divisible by pipe*virtual="
            f"{n_stages}*{V}"
        )
    config, seq_ax, sp, data_ax, dp, manual_axes, batch_spec = _seq_setup(
        config, mesh
    )
    # See the module docstring: XLA:CPU's collective rendezvous spans all
    # local devices per instruction, so 'seq' collectives inside the
    # device-varying switch deadlock there. Run all unit kinds and mask.
    # Keyed on backend != 'tpu' (not == 'cpu'): only TPU's per-core SPMD
    # rendezvous is validated for collectives inside lax.switch, so any
    # other backend (e.g. GPU) gets the conservative uniform path too.
    uniform_units = sp > 1 and jax.default_backend() != "tpu"
    PV = n_stages * V
    Lc = config.n_layer // PV
    n_micro = batch.shape[0]
    sched = build_schedule(n_stages, V, n_micro)
    perm_fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    perm_bwd = [(i, (i - 1) % n_stages) for i in range(n_stages)]
    # Mean over microbatches AND manual data shards (dp=1 when 'data' is
    # auto); the hand-seeded loss cotangent uses the same scale.
    inv_m = 1.0 / (n_micro * dp)
    var_axes = (AXIS,) + ((seq_ax,) if seq_ax else ())
    # Scalar (loss/aux) reductions: CE/aux are already seq-invariant when
    # sp>1 (psum'd inside), so they span pipe + the manual data axis.
    reduce_axes = (AXIS,) + ((data_ax,) if data_ax else ())
    # Parameter-grad reductions: var_axes plus the manual data axis (on
    # vma runtimes data stays auto and this equals var_axes exactly).
    grad_axes = var_axes + ((data_ax,) if data_ax else ())
    # Legacy cotangent-seed scale — pre-vma jax transposes psum to psum,
    # so differentiating through the CE/aux internal 'seq' psum inflates a
    # hand-seeded cotangent by sp; seed 1/sp to cancel (the explicit
    # grad psums below restore the cross-shard sums). See the identical
    # note in pipeline.pipeline_loss_and_grads_1f1b.
    from .pipeline import _legacy_partial_auto

    ct_scale = 1.0 / sp if (_legacy_partial_auto() and sp > 1) else 1.0
    moe = config.n_experts > 0
    key_data = _key_data_or_none(base_key)

    def staged(params, batch, stage_arr):
        stage = stage_arr[0]
        # The typed key must not cross the shard_map boundary (the seed-old
        # u32 tile-assignment compile failure — see _key_data_or_none);
        # rebuild it from the raw data inside the manual region.
        base_key = _rebuild_key(key_data)
        blocks = params["blocks"]  # local rows: V chunks x Lc layers
        mb, S = batch.shape[1], batch.shape[2]
        D = config.n_embd
        cd = config.compute_dtype

        from ..utils.vma import pcast_missing

        def var(x):
            # Activations and head/embed cotangents vary over every manual
            # axis (pipe, and seq when sequence-parallel).
            return pcast_missing(x, var_axes)

        def var_p(x):
            # Block grads and scalar loss terms are pipe-varying only: the
            # block-param primal is seq-invariant (its vjp psums over 'seq'
            # implicitly) and the CE psums over 'seq' explicitly.
            return pcast_missing(x, (AXIS,))

        zeros_act = lambda n: var(jnp.zeros((n, mb, S, D), cd))
        pend_f = zeros_act(sched.pend_f_slots)
        pend_b = zeros_act(sched.pend_b_slots)
        resid = zeros_act(sched.resid_slots)
        fwd_msg = var(jnp.zeros((mb, S, D), cd))
        bwd_msg = var(jnp.zeros((mb, S, D), cd))
        d_blocks = jax.tree.map(lambda x: var_p(jnp.zeros_like(x)), blocks)
        loss_sum = var_p(jnp.zeros((), jnp.float32))
        # MoE: chunk forwards return their layers' Switch load-balance aux;
        # F units (and the head unit, whose chunk never runs an F) add the
        # primal aux, every chunk backward seeds the constant cotangent —
        # the weight of the aux term in the final loss. Every scheduled
        # unit is a real (microbatch, chunk), so no validity masking is
        # needed (unlike the lockstep schedules' fill/drain ticks).
        aux_sum = var_p(jnp.zeros((), jnp.float32))
        aux_ct_const = (
            config.router_aux_coef * ct_scale / (config.n_layer * n_micro * dp)
            if moe else 0.0
        )

        hp = {k: params[k] for k in tinygpt.head_param_names(config)}
        ep = {k: params[k] for k in tinygpt.embed_param_names(config)}
        # Pre-cast the head/embed params to device-varying so their vjps stay
        # collective-free inside the switch branches (an invariant primal
        # would make the transpose insert a psum there — deadlock inside
        # divergent control flow); ONE psum after the tick loop re-reduces.
        hp_in = jax.tree.map(var, hp)
        ep_in = jax.tree.map(var, ep)
        d_hp = jax.tree.map(lambda x: var(jnp.zeros(x.shape, x.dtype)), hp)
        d_ep = jax.tree.map(lambda x: var(jnp.zeros(x.shape, x.dtype)), ep)

        live_keys = base_key is not None and not deterministic
        emb_key = (
            jax.random.fold_in(base_key, 1_000_003) if live_keys else None
        )

        def chunk_slice(tree, v):
            return jax.tree.map(
                lambda x: lax.dynamic_slice_in_dim(x, v * Lc, Lc, axis=0), tree
            )

        def chunk_update_add(tree, upd, v):
            def one(x, u):
                cur = lax.dynamic_slice_in_dim(x, v * Lc, Lc, axis=0)
                return lax.dynamic_update_slice_in_dim(
                    x, cur + u, v * Lc, axis=0
                )
            return jax.tree.map(one, tree, upd)

        def chunk_fwd(blk_c, x, m, v):
            # Dropout keys: base fold m + (gpipe stage owning these layers) +
            # per-layer fold of the GLOBAL layer index inside apply_blocks —
            # exactly the keys the GPipe/plain-1F1B schedules derive for the
            # same (microbatch, layer), so the three schedules produce
            # bit-identical dropout masks; the backward rematerialization
            # derives the same key from (m, j), replaying the forward exactly.
            j = v * n_stages + stage
            key = (
                jax.random.fold_in(base_key, m + j // V) if live_keys
                else None
            )
            y, aux = tinygpt.apply_blocks(
                config, blk_c, x, key, deterministic,
                layer_offset=j * Lc,
            )
            if moe:
                if seq_ax is not None:
                    # Shard-local aux averaged over sequence shards
                    # (seq-invariant, matching pipeline.stage_fwd).
                    aux = lax.psum(aux, seq_ax) / sp
            else:
                # Dense: apply_blocks' zero aux carries the activation's
                # full (seq,pipe) vma, which would widen the aux carry and
                # the final loss; a fresh zero stays pipe-varying only.
                # Its vjp cotangent (constant 0.0) reaches nothing.
                aux = jnp.zeros((), jnp.float32)
            # Always (y, aux): the uniform shape keeps the dense and MoE
            # vjp/seeding code identical (one copy, not four).
            return y, var_p(aux)

        def tick(carry, row):
            (pend_f, pend_b, resid, fwd_msg, bwd_msg,
             d_blocks, d_hp, d_ep, loss_sum, aux_sum) = carry
            t = dict(zip(_TABLES, [r[stage] for r in row]))

            # Park arrivals (messages sent on the rings last tick).
            pend_f = jnp.where(
                t["park_f"] >= 0,
                lax.dynamic_update_index_in_dim(
                    pend_f, fwd_msg, jnp.maximum(t["park_f"], 0), 0
                ),
                pend_f,
            )
            pend_b = jnp.where(
                t["park_b"] >= 0,
                lax.dynamic_update_index_in_dim(
                    pend_b, bwd_msg, jnp.maximum(t["park_b"], 0), 0
                ),
                pend_b,
            )

            m_s = jnp.maximum(t["unit_m"], 0)
            v_s = jnp.maximum(t["unit_v"], 0)
            blk_c = chunk_slice(blocks, v_s)
            tgt = jnp.take(batch, m_s, axis=0)
            zero_out = var(jnp.zeros((mb, S, D), cd))
            zb = jax.tree.map(lambda x: var_p(jnp.zeros_like(x)), blk_c)
            zh = jax.tree.map(lambda x: var(jnp.zeros(x.shape, x.dtype)), hp)
            ze = jax.tree.map(lambda x: var(jnp.zeros(x.shape, x.dtype)), ep)
            zl = var_p(jnp.zeros((), jnp.float32))

            def f_unit():
                inject = tinygpt.embed(
                    config, ep_in, tgt,
                    jax.random.fold_in(emb_key, m_s) if live_keys else None,
                    deterministic,
                )
                parked = lax.dynamic_index_in_dim(
                    pend_f, jnp.maximum(t["f_src"], 0), 0, keepdims=False
                )
                x_in = jnp.where(t["f_src"] == -2, inject, parked)
                resid2 = lax.dynamic_update_index_in_dim(
                    resid, x_in, jnp.maximum(t["resid_rw"], 0), 0
                )
                y, aux_t = chunk_fwd(blk_c, x_in, m_s, v_s)
                return (resid2, y, zero_out, zb, zh, ze, zl, aux_t)

            def b_unit():
                is_head = t["b_head"] == 1
                from_pend_f = lax.dynamic_index_in_dim(
                    pend_f, jnp.maximum(t["b_src"], 0), 0, keepdims=False
                )
                from_resid = lax.dynamic_index_in_dim(
                    resid, jnp.maximum(t["resid_rw"], 0), 0, keepdims=False
                )
                x_saved = jnp.where(is_head, from_pend_f, from_resid)
                g_parked = lax.dynamic_index_in_dim(
                    pend_b, jnp.maximum(t["b_src"], 0), 0, keepdims=False
                )
                ek = (
                    jax.random.fold_in(emb_key, m_s) if live_keys else None
                )

                def head_vjp():
                    # The head position (PV-1) never runs an F unit, so its
                    # chunk's primal aux is accumulated HERE, alongside the
                    # loss; every other chunk's aux came from its F unit.
                    def fn(blk_a, hp_a, x):
                        y, aux = chunk_fwd(blk_a, x, m_s, v_s)
                        l = tinygpt._cross_entropy(
                            tinygpt.head(config, hp_a, y), tgt, seq_axis=seq_ax
                        )
                        return l, aux
                    (l, aux_p), vjp = jax.vjp(fn, blk_c, hp_in, x_saved)
                    dl = var_p(jnp.asarray(inv_m * ct_scale, jnp.float32))
                    d_blk, d_hp_t, d_x = vjp(
                        (dl, jnp.zeros_like(aux_p) + aux_ct_const)
                    )
                    return l, d_blk, d_hp_t, d_x, aux_p

                def plain_vjp():
                    # Chunk backward: seed the constant aux cotangent (its
                    # weight in the final loss — 0.0 for dense); the primal
                    # aux was already counted by this unit's F.
                    (_, aux_p), vjp = jax.vjp(
                        lambda blk_a, x: chunk_fwd(blk_a, x, m_s, v_s),
                        blk_c, x_saved,
                    )
                    d_blk, d_x = vjp(
                        (g_parked, jnp.zeros_like(aux_p) + aux_ct_const)
                    )
                    return zl, d_blk, zh, d_x, zl

                if uniform_units:
                    l, d_blk, d_hp_t, d_x, aux_p = jax.tree.map(
                        lambda h, p: jnp.where(is_head, h, p),
                        head_vjp(), plain_vjp(),
                    )
                else:
                    l, d_blk, d_hp_t, d_x, aux_p = lax.cond(
                        is_head, head_vjp, plain_vjp
                    )

                # Position 0's input cotangent belongs to the embedding
                # (compute-and-mask: embed is cheap, and ep_in is pre-cast
                # varying so the vjp is collective-free).
                is_embed = (v_s == 0) & (stage == 0) & (t["b_head"] == 0)
                _, vjp_emb = jax.vjp(
                    lambda ep_a: tinygpt.embed(
                        config, ep_a, tgt, ek, deterministic
                    ),
                    ep_in,
                )
                (d_ep_t,) = vjp_emb(
                    jnp.where(is_embed, d_x, jnp.zeros((), d_x.dtype))
                )
                return (resid, zero_out, d_x, d_blk, d_hp_t, d_ep_t, l,
                        aux_p)

            def idle_unit():
                return (resid, zero_out, zero_out, zb, zh, ze, zl, zl)

            if uniform_units:
                k = t["kind"]
                (resid, f_out, b_out, d_blk_t, d_hp_t, d_ep_t, l_t,
                 aux_t) = jax.tree.map(
                    lambda i, f, b: jnp.where(
                        k == FWD, f, jnp.where(k == BWD, b, i)
                    ),
                    idle_unit(), f_unit(), b_unit(),
                )
            else:
                (resid, f_out, b_out, d_blk_t, d_hp_t, d_ep_t, l_t,
                 aux_t) = lax.switch(t["kind"], [idle_unit, f_unit, b_unit])
            d_blocks = chunk_update_add(d_blocks, d_blk_t, v_s)
            d_hp = jax.tree.map(jnp.add, d_hp, d_hp_t)
            d_ep = jax.tree.map(jnp.add, d_ep, d_ep_t)
            loss_sum = loss_sum + l_t
            aux_sum = aux_sum + aux_t

            fwd_msg = lax.ppermute(
                jnp.where(t["send_f"] == 1, f_out, jnp.zeros((), cd)),
                AXIS, perm_fwd,
            )
            bwd_msg = lax.ppermute(
                jnp.where(t["send_b"] == 1, b_out, jnp.zeros((), cd)),
                AXIS, perm_bwd,
            )
            return (pend_f, pend_b, resid, fwd_msg, bwd_msg,
                    d_blocks, d_hp, d_ep, loss_sum, aux_sum), None

        carry = (pend_f, pend_b, resid, fwd_msg, bwd_msg,
                 d_blocks, d_hp, d_ep, loss_sum, aux_sum)
        xs = tuple(jnp.asarray(getattr(sched, n)) for n in _TABLES)
        carry, _ = lax.scan(tick, carry, xs)

        (_, _, _, _, _, d_blocks, d_hp, d_ep, loss_sum, aux_sum) = carry
        loss = lax.psum(loss_sum, reduce_axes) * inv_m
        if moe:
            # Every (microbatch, chunk) contributed its layers' aux exactly
            # once; normalize as gpipe/1f1b do: coef * mean per layer per
            # microbatch (averaged over manual data shards when present).
            loss = loss + config.router_aux_coef * lax.psum(
                aux_sum, reduce_axes
            ) / (config.n_layer * n_micro * dp)
        d_hp = jax.tree.map(lambda x: lax.psum(x, grad_axes), d_hp)
        d_ep = jax.tree.map(lambda x: lax.psum(x, grad_axes), d_ep)
        blk_axes = tuple(
            a for a in (data_ax, seq_ax if ct_scale != 1.0 else None) if a
        )
        if blk_axes:
            # Block grads stay per-stage (out_spec P('pipe', ...)) but sum
            # across the manual data shards' local batches — and across
            # 'seq' on the legacy runtime, where the 1/sp-scaled seeds
            # leave per-shard partials (vma runtimes reduce implicitly).
            d_blocks = jax.tree.map(
                lambda x: lax.psum(x, blk_axes), d_blocks
            )
        grads = {"blocks": d_blocks}
        for _dtree in (d_hp, d_ep):  # wte appears in both when tied: sum
            for _k, _v in _dtree.items():
                grads[_k] = grads[_k] + _v if _k in grads else _v
        return loss, grads

    specs = pipeline_param_specs(params, mesh)
    fn = jax.shard_map(
        staged,
        mesh=mesh,
        in_specs=(specs, batch_spec, P(AXIS)),
        out_specs=(P(), specs),
        axis_names=manual_axes,
    )
    return fn(params, batch, _stage_iota(n_stages))
