"""Device mesh construction — the TPU-native replacement for process groups.

The reference's communication layer is a NCCL process group built over a TCP
rendezvous (reference ``benchmarking/train_harness.py:186-198``). On TPU the
equivalent structure is a ``jax.sharding.Mesh`` over the chips: collectives are
not library calls but XLA-inserted all-reduce / all-gather / reduce-scatter
that ride the ICI torus. Axis order matters — ``mesh_utils.create_device_mesh``
lays axes out so the innermost (fastest-varying) axis maps to physically
adjacent chips, which is the TPU analogue of the reference's
``NCCL_SOCKET_IFNAME``/ring-order tuning (``NETWORK_CONFIGURATION.md:243-248``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshAxes:
    """Canonical axis names used across the framework."""

    data: str = "data"      # data parallel / ZeRO sharding axis
    model: str = "model"    # tensor parallel axis
    seq: str = "seq"        # sequence/context parallel axis (ring attention)
    pipe: str = "pipe"      # pipeline stage axis


AXES = MeshAxes()


def make_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Tuple[str, ...] = ("data",),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh. Default: 1-D 'data' mesh over all addressable devices.

    ``shape`` like (4, 2) with axis_names ('data', 'model') builds a 2-D mesh;
    ``create_device_mesh`` chooses a device order that keeps each axis on
    contiguous ICI links when running on real TPU topologies.
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),)
    if int(np.prod(shape)) != len(devices):
        devices = devices[: int(np.prod(shape))]
    if int(np.prod(shape)) != len(devices):
        raise ValueError(
            f"Mesh shape {tuple(shape)} needs {int(np.prod(shape))} devices, "
            f"have {len(devices)}"
        )
    if len(shape) != len(axis_names):
        raise ValueError(f"shape {shape} vs axis_names {axis_names} rank mismatch")
    try:
        dev_array = mesh_utils.create_device_mesh(tuple(shape), devices=list(devices))
    except Exception:
        # CPU/virtual-device fallback: plain row-major reshape.
        dev_array = np.asarray(list(devices)).reshape(tuple(shape))
    return Mesh(dev_array, axis_names)


# ---------------------------------------------------------------------------
# Mesh / PartitionSpec (de)serialization — the checkpoint geometry contract
# ---------------------------------------------------------------------------
#
# Elastic resume (runtime/checkpoint.py, docs/FAULT_TOLERANCE.md) persists
# each checkpoint's mesh geometry and per-leaf PartitionSpecs in a JSON
# sidecar, so a later run on a DIFFERENT mesh can decide reshard-vs-refuse
# without deserializing any payload. These helpers are the one place that
# defines the JSON shape (a spec entry is None | axis name | [axis names]).


def mesh_axes_dict(mesh: Mesh) -> dict:
    """{'data': 4, 'model': 2, ...} — the geometry identity of a mesh."""
    return {str(name): int(size) for name, size in mesh.shape.items()}


def spec_to_jsonable(spec) -> list:
    """jax.sharding.PartitionSpec -> JSON-serializable entry list."""
    out = []
    for entry in tuple(spec):
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append([str(ax) for ax in entry])
        else:
            out.append(str(entry))
    return out


def jsonable_to_spec(entries):
    """Inverse of :func:`spec_to_jsonable`."""
    from jax.sharding import PartitionSpec as P

    parts = []
    for entry in entries or []:
        if entry is None:
            parts.append(None)
        elif isinstance(entry, list):
            parts.append(tuple(entry))
        else:
            parts.append(entry)
    return P(*parts)
