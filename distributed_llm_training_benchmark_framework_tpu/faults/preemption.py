"""Preemption-safe shutdown: SIGTERM -> flag -> emergency checkpoint.

Kubelet (and every sane supervisor) delivers SIGTERM, waits the grace
period, then SIGKILLs. The train loop cannot act on the signal inside a
dispatched step — and MUST not run Python in the handler beyond setting
a flag (the handler can interrupt arbitrary bytecode, including orbax's
commit path). So the protocol is:

1. :class:`PreemptionGuard` installs a SIGTERM handler OUTSIDE the timed
   loop (graftcheck rule GC106 pins that discipline) that only sets a
   flag;
2. the loop polls ``guard.requested`` at sync-window boundaries (device
   already fenced, checkpoint state coherent);
3. on a set flag it performs the emergency checkpoint, emits the
   ``run_aborted reason=preempted`` telemetry event plus a final
   heartbeat, and raises :class:`Preempted`;
4. the harness maps :class:`Preempted` to :data:`EXIT_PREEMPTED` — the
   distinct exit code the retrying orchestration keys on to resume
   instead of cold-restarting.
"""

from __future__ import annotations

import signal
from typing import Optional

#: Process exit code for a preempted-but-checkpointed run. 75 is BSD's
#: EX_TEMPFAIL ("temporary failure, retry"): distinct from crash codes
#: (1, 134, 137, 139) and from timeout(1)'s 124, so the retry loop can
#: tell "resume me" apart from "I am broken".
EXIT_PREEMPTED = 75

#: Process exit code for a --resume that found a checkpoint but no steps
#: left to run (the run already completed, or the checkpoint belongs to a
#: longer configuration). DETERMINISTIC: the retry wrappers must NOT
#: retry it — every attempt would refuse identically and the backoff
#: budget would burn on nothing. (Renumbered 76 -> 77 in the self-healing
#: round: 76 is now EXIT_HUNG — the hang watchdog's retryable-with-resume
#: abort, the semantic opposite of this never-retry refusal, so the two
#: could not share a code; faults/watchdog.py.)
EXIT_NOTHING_TO_RESUME = 77


class NothingToResume(RuntimeError):
    """--resume restored a checkpoint past the configured step range."""


class Preempted(RuntimeError):
    """Control-flow exception: the run stopped at a boundary on SIGTERM.

    ``step`` is the last completed step; ``saved_step`` the emergency
    checkpoint's step (None when no checkpointer was configured or the
    save failed — the run is then a plain partial).
    """

    def __init__(self, step: int, saved_step: Optional[int] = None):
        self.step = step
        self.saved_step = saved_step
        saved = (f"emergency checkpoint at step {saved_step}"
                 if saved_step is not None else "no checkpoint saved")
        super().__init__(f"preempted at step {step} ({saved})")


class PreemptionGuard:
    """Flag-only SIGTERM handler with install/uninstall bracketing.

    Degrades to disabled (``installed`` False) when handlers cannot be
    installed — non-main threads (embedded callers, some test runners)
    raise ValueError from ``signal.signal``; such runs simply keep the
    supervisor-kill behavior they had before this round.

    Multi-host coordination (elastic-resilience round): on a
    ``jax.distributed`` rendezvous the host-local flag alone is not enough
    — PR 5's guard only saved when *rank 0* was the SIGTERM'd host.
    :meth:`coordinate` broadcasts the flag over the coordination service's
    KV store (``runtime.distributed``) and agrees a single stop boundary
    with every peer, so ANY rank's SIGTERM produces one coherent all-host
    emergency checkpoint and a unanimous EXIT_PREEMPTED.
    """

    def __init__(self, enabled: bool = True):
        self._requested = False
        self._prev = None
        self.installed = False
        self._published = False
        self._agreed_step: Optional[int] = None
        #: True once the cross-host agreement ran (successfully or
        #: degraded) — either way it must not re-run: a dead peer would
        #: otherwise re-block every later boundary for the full ack
        #: timeout, stalling each remaining timed window.
        self._agreement_done = False
        if not enabled:
            return
        try:
            self._prev = signal.signal(signal.SIGTERM, self._on_sigterm)
            self.installed = True
        except (ValueError, OSError):
            pass

    def _on_sigterm(self, signum, frame) -> None:
        # Flag only — see module docstring. Everything else happens at
        # the loop's next sync boundary.
        self._requested = True

    @property
    def requested(self) -> bool:
        return self._requested

    def coordinate(self, boundary_step: int) -> Optional[int]:
        """Cross-host poll at one fenced sync-window boundary.

        Returns the step at which the loop must emergency-stop (stop at
        the first boundary >= it), or None to keep running. Single-process
        runs reduce to the local flag. Multi-process runs publish the
        local flag when set, poll the peers' flags (non-blocking, ~1 ms),
        and on any visible flag run the ack agreement once — the result
        (including a degraded no-agreement outcome) is cached so later
        boundaries pay only the local check.

        Call sites must be boundary-aligned across hosts (the loop's poll
        site is: pending empty, same step grid everywhere) — the blocking
        agreement assumes every peer reaches its own next boundary.
        """
        if self._agreement_done:
            # Agreement already ran. A degraded outcome (dead peer, no
            # agreed step) still honors a LATER local SIGTERM — stop at
            # this boundary best-effort rather than ignoring the signal.
            if self._agreed_step is not None:
                return self._agreed_step
            return boundary_step if self._requested else None
        import jax

        if jax.process_count() <= 1:
            return boundary_step if self._requested else None

        from ..runtime import distributed as dist

        if self._requested and not self._published:
            self._published = dist.publish_preempt_flag(boundary_step)
        if not self._requested and not dist.preempt_flag_entries():
            return None
        agreed = dist.agree_preempt_step(boundary_step)
        if agreed is None:
            # A peer never acked (died outright): degrade to a local
            # best-effort stop when WE were signalled, else keep running
            # — wedging every healthy host on a dead peer would turn one
            # preemption into a whole-job loss.
            agreed = boundary_step if self._requested else None
        self._agreed_step = agreed
        self._agreement_done = True
        return agreed

    def uninstall(self) -> None:
        """Restore the previous handler (idempotent)."""
        if not self.installed:
            return
        try:
            signal.signal(signal.SIGTERM, self._prev or signal.SIG_DFL)
        except (ValueError, OSError, TypeError):
            pass
        self.installed = False
