"""Hang watchdog: a monotonic deadline on the sync-window cadence.

A hung collective (one stalled rank, a wedged DMA, a deadlocked host
thread) is the one failure class the chaos stack could not yet classify
*in process*: the run simply stops emitting sync-window events and some
external supervisor — the k8s liveness probe, a suite timeout — kills it
minutes later with a generic 124/137 and no forensics. The watchdog turns
that into a first-class, classified abort:

- the loop **beats** the watchdog at every sync-window boundary (an
  attribute write — no IO, no device work; the same call-site discipline
  as :class:`~.preemption.PreemptionGuard`'s boundary poll, GC105/GC106
  clean by construction);
- a daemon thread checks the deadline. When no boundary arrives within
  ``timeout_sec`` it dumps **all-thread stacks** plus the last beat into a
  ``hang_dump`` telemetry event (the JSONL is line-buffered, so the dump
  survives the process), prints the same dump to stderr, emits
  ``run_aborted reason=hang`` + a final ``reason=hang`` heartbeat, and
  exits with the distinct :data:`EXIT_HUNG` code the retrying
  orchestration treats as retryable-with-resume;
- on a ``jax.distributed`` rendezvous the firing rank first publishes a
  hang flag on the coordination-service KV store
  (``runtime.distributed.publish_hang_flag``). Peers see it — the watchdog
  thread polls the flag namespace, and ranks still reaching boundaries
  poll it there too — and abort with the *same* exit code and a dump of
  their own stacks, so one stuck rank yields a coherent all-host abort
  instead of N staggered timeouts.

Scope: the watchdog guards the *step loop's* sync-window cadence. It arms
at the first beat (init/XLA compile legitimately run many minutes with no
boundaries — the same posture scripts/liveness_probe.sh takes before the
first telemetry event) and is disarmed before the finalize tail. Hangs
outside that bracket stay the liveness probe's job; the probe's grace
window must therefore EXCEED ``timeout_sec`` so the in-process dump wins
the race against the probe's forensics-free pod kill
(docs/FAULT_TOLERANCE.md).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

#: Process exit code for a run aborted by the hang watchdog. Distinct from
#: crash codes (1, 134, 137, 139), timeout(1)'s 124 and EXIT_PREEMPTED
#: (75); the retry wrappers treat it as retryable-with-resume — the
#: checkpoints on disk are intact, only the process wedged.
EXIT_HUNG = 76


class Hung(RuntimeError):
    """Control-flow exception: a PEER rank's hang flag was seen at a
    boundary (this rank is healthy — the stuck one already dumped and
    exited). The harness maps it to :data:`EXIT_HUNG` so the abort is
    unanimous across ranks."""

    def __init__(self, step: int, peer: Optional[int] = None):
        self.step = step
        self.peer = peer
        who = f"rank {peer}" if peer is not None else "a peer rank"
        super().__init__(
            f"aborting at boundary step {step}: {who} reported a hang"
        )


def _scan_peer_flags() -> Optional[Tuple[int, int]]:
    """(rank, step) of another rank's published hang flag, or None.

    The ONE peer-flag scan behind both halves of the coherent abort —
    the loop's fenced boundary poll and the watchdog thread — so the
    process-count guard and own-rank filtering can never diverge.
    """
    import jax

    if jax.process_count() <= 1:
        return None
    from ..runtime import distributed as dist

    for rank, step in dist.hang_flag_entries():
        if rank != jax.process_index():
            return rank, step
    return None


def format_all_stacks() -> List[str]:
    """One formatted stack per live thread — the hang_dump payload.

    ``sys._current_frames`` is a snapshot, not a stop-the-world: good
    enough for "where was everyone when the deadline passed", which is
    the question a hung collective leaves unanswered.
    """
    names = {t.ident: t.name for t in threading.enumerate()}
    out: List[str] = []
    for ident, frame in sorted(sys._current_frames().items()):
        name = names.get(ident, "?")
        stack = "".join(traceback.format_stack(frame))
        out.append(f"Thread {name} (ident {ident}):\n{stack}")
    return out


class HangWatchdog:
    """Deadline timer over the loop's sync-window beats.

    Parameters
    ----------
    timeout_sec:
        Max seconds between beats before the run is declared hung.
        ``<= 0`` disables the watchdog entirely (``armed`` False) — the
        default, so benchmark runs pay one attribute check per boundary
        and nothing else.
    recorder:
        The run's flight recorder (telemetry.TelemetryRecorder) — the
        ``hang_dump`` event, the ``run_aborted reason=hang`` trail and
        the final heartbeat go through it. Optional for direct users.
    """

    def __init__(
        self,
        timeout_sec: float = 0.0,
        *,
        recorder=None,
        is_main: bool = True,
        rank: int = 0,
        poll_interval_sec: Optional[float] = None,
        _exit: Callable[[int], Any] = os._exit,
    ):
        self.timeout_sec = float(timeout_sec or 0.0)
        self.recorder = recorder
        self.is_main = is_main
        self.rank = rank
        self._exit = _exit
        self.poll_interval_sec = (
            poll_interval_sec
            if poll_interval_sec is not None
            else max(min(self.timeout_sec / 4.0, 5.0), 0.05)
        )
        self._last_beat: Optional[float] = None  # monotonic; None = unarmed
        self._last_step: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = False

    @property
    def armed(self) -> bool:
        return self.timeout_sec > 0

    # -- loop-facing surface (boundary call sites only) --------------------

    def beat(self, step: int) -> None:
        """Feed the deadline: a sync-window boundary arrived. Attribute
        writes only — safe at any cadence, sanctioned at boundaries."""
        self._last_beat = time.monotonic()
        self._last_step = step

    def peer_hang(self) -> Optional[Tuple[int, int]]:
        """Non-blocking boundary poll: (rank, step) of a peer's published
        hang flag, or None. The *healthy*-rank half of the coherent
        all-host abort — a rank still reaching boundaries (process-local
        dryrun meshes, or a stall that only wedges some ranks) learns of
        the hang here and raises :class:`Hung` from its own main thread
        instead of waiting out its own timeout. Unlike the thread-side
        :meth:`_poll_peer_flag` this lets errors PROPAGATE — the main
        thread's caller owns the failure."""
        if not self.armed:
            return None
        return _scan_peer_flags()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if not self.armed or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="hang-watchdog", daemon=True
        )
        self._thread.start()

    def disarm(self) -> None:
        """Stop the deadline thread (idempotent). Called before the
        finalize tail — post-loop work (final checkpoint, barrier, AOT
        memory accounting) has no sync-window cadence to guard."""
        self._stop.set()
        thread = self._thread
        self._thread = None
        if thread is not None and thread.is_alive():
            thread.join(timeout=2.0)

    # -- the deadline thread ----------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_sec):
            last = self._last_beat
            if last is None:
                # Not yet armed: init/compile may legitimately take longer
                # than any sane hang timeout (liveness-probe posture).
                continue
            stalled = time.monotonic() - last
            if stalled > self.timeout_sec:
                self._fire(
                    reason=(
                        f"no sync-window boundary for {stalled:.1f}s "
                        f"(> --hang-timeout-sec {self.timeout_sec:g}; last "
                        f"boundary step {self._last_step})"
                    ),
                )
                return
            peer = self._poll_peer_flag()
            if peer is not None:
                self._fire(
                    reason=(
                        f"peer rank {peer[0]} reported a hang at its "
                        f"boundary step {peer[1]} (this rank last beat "
                        f"{stalled:.1f}s ago)"
                    ),
                    peer=peer[0],
                )
                return

    def _poll_peer_flag(self) -> Optional[Tuple[int, int]]:
        """Thread-side peer poll, best-effort: a rank blocked inside a
        collective never reaches another boundary, so its MAIN thread
        cannot learn of the peer's flag — this thread can. Errors degrade
        to the local timeout (which is also ticking)."""
        try:
            return _scan_peer_flags()
        except Exception:
            return None

    def _fire(self, reason: str, peer: Optional[int] = None) -> None:
        """Dump, publish, record, exit 76. Runs on the watchdog thread —
        the main thread is by definition stuck, so nothing here may wait
        on it; ``os._exit`` skips interpreter teardown deliberately (the
        telemetry file is line-buffered, every event already reached the
        OS)."""
        if self.fired:
            return
        self.fired = True
        stacks = format_all_stacks()
        # Publish FIRST (cheap host RPC): even if the dump below wedges on
        # a broken recorder, the peers must learn of the hang.
        if peer is None:
            try:
                from ..runtime import distributed as dist

                dist.publish_hang_flag(self._last_step or 0)
            except Exception:
                pass
        header = (
            f"HANG WATCHDOG (rank {self.rank}): {reason} — dumping "
            f"{len(stacks)} thread stack(s) and exiting {EXIT_HUNG}"
        )
        try:
            print(header, file=sys.stderr, flush=True)
            for s in stacks:
                print(s, file=sys.stderr, flush=True)
        except Exception:
            pass
        if self.recorder is not None:
            try:
                self.recorder.note(
                    "hang_dump",
                    reason=reason,
                    last_beat_step=self._last_step,
                    timeout_sec=self.timeout_sec,
                    peer_rank=peer,
                    stacks=stacks,
                )
                self.recorder.emergency_heartbeat(
                    reason="hang",
                    extra={"last_beat_step": self._last_step},
                )
                self.recorder.abort("hang")
            except Exception:
                pass
        _linger_for_coherent_exit(self.poll_interval_sec)
        self._exit(EXIT_HUNG)

    # -- context sugar -----------------------------------------------------

    def __enter__(self) -> "HangWatchdog":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.disarm()


def _linger_for_coherent_exit(poll_interval_sec: float) -> None:
    """Multi-host abort ordering: every aborting rank LINGERS before
    dying, and the coordination-service HOST (process 0) lingers longest
    so it provably exits LAST. Two failure modes this prevents, both
    observed on the dryrun: (a) the origin exiting before its peers
    polled the hang flag — they then die of a coordination heartbeat
    timeout's uncatchable FATAL (crash code, no classification); (b) a
    healthy peer on process 0 exiting FIRST after seeing the flag —
    tearing the KV store down under the still-lingering origin. The
    stack dump is already on disk before any linger, so the wait risks
    nothing. Single-process runs skip it entirely."""
    try:
        import jax

        if jax.process_count() > 1:
            linger = min(max(2 * poll_interval_sec, 2.0), 10.0)
            if jax.process_index() == 0:
                linger += 2.0
            time.sleep(linger)
    except Exception:
        pass


def abort_on_peer_hang(recorder, step: int, peer: Tuple[int, int]) -> None:
    """Main-thread half of the coherent abort: emit the (stackless) dump
    trail for a peer-reported hang and raise :class:`Hung`. Shared by the
    loop's boundary poll so the telemetry shape matches the thread path —
    collect/parse classify both as ``reason=hang``."""
    rank, peer_step = peer
    if recorder is not None:
        try:
            recorder.note(
                "hang_dump",
                reason=(f"peer rank {rank} reported a hang at its boundary "
                        f"step {peer_step}; this rank is healthy at "
                        f"boundary {step}"),
                last_beat_step=step,
                peer_rank=rank,
                stacks=format_all_stacks(),
            )
            recorder.emergency_heartbeat(
                reason="hang", extra={"last_beat_step": step},
            )
            recorder.abort("hang")
        except Exception:
            pass
    # Same exit-ordering discipline as the thread path: this rank's
    # unwind tears down its jax.distributed client, and on process 0
    # that is the coordination service itself.
    _linger_for_coherent_exit(1.0)
    raise Hung(step, peer=rank)
