"""Chaos harness: deterministic fault injection + preemption machinery.

Production TPU fleets live with preemption as a steady-state event, and a
benchmark that cannot survive one publishes a lie by omission: every
mid-run death becomes a vanished (or, since the flight recorder, a
partial) row, and nothing ever *proves* the recovery path works. This
package is the proving ground:

- :mod:`.injection` — a registry of injectable faults (``sigkill@N``,
  ``sigterm@N``, ``nan-loss@N``, ``hang@N``, ``stall-rank@N:R``,
  ``bitflip@N``, ``grad-explode@N``, ``torn-checkpoint``,
  ``enospc-on-save``, plus the streaming-data kinds
  ``data-stall@N[:SECS]`` / ``data-corrupt-record@N`` /
  ``data-slow-reader@N:MS`` / ``data-missing-shard@K``), armed via the
  harness ``--inject-fault`` flag or the ``INJECT_FAULT`` env var, each
  firing at an exact sync-window boundary (or an exact record/shard
  index for the data kinds) so a chaos run aborts at the same point
  every time.
- :mod:`.preemption` — the SIGTERM-to-emergency-checkpoint guard the
  train loop polls at sync boundaries, the :class:`Preempted` control
  exception, and the distinct ``EXIT_PREEMPTED`` process exit code the
  retrying orchestration keys on.
- :mod:`.watchdog` — the hang watchdog (self-healing round): a monotonic
  deadline on the sync-window cadence that dumps all-thread stacks into a
  ``hang_dump`` telemetry event, coordinates a coherent all-host abort
  over the coordination-service KV store, and exits ``EXIT_HUNG`` (76,
  retryable-with-resume).
- :mod:`.sentinel` — the numerics sentinel: boundary-cadence guards
  (loss envelope, global grad-norm, per-N-steps parameter checksum) that
  on trip roll the run back IN PROCESS to the last validated checkpoint,
  reseed the data stream and replay, with ``n_rollbacks``/
  ``rollback_steps_replayed`` accounting end to end.

``scripts/chaos_suite.sh`` drives the full fault matrix end to end and
asserts every class lands in a completed, validated result (after
resume) or a correctly classified partial — docs/FAULT_TOLERANCE.md is
the operator contract.
"""

from ..data.stream import EXIT_DATA_STALL  # noqa: F401  (central registry)
from .injection import (  # noqa: F401
    DATA_KINDS,
    FAULT_KINDS,
    FaultInjector,
    FaultSpec,
    parse_fault_spec,
)
from .preemption import (  # noqa: F401
    EXIT_NOTHING_TO_RESUME,
    EXIT_PREEMPTED,
    NothingToResume,
    Preempted,
    PreemptionGuard,
)
from .sentinel import (  # noqa: F401
    NumericsSentinel,
    SentinelTripped,
)
from .watchdog import (  # noqa: F401
    EXIT_HUNG,
    HangWatchdog,
    Hung,
)

__all__ = [
    "DATA_KINDS",
    "FAULT_KINDS",
    "FaultInjector",
    "FaultSpec",
    "parse_fault_spec",
    "EXIT_DATA_STALL",
    "EXIT_NOTHING_TO_RESUME",
    "EXIT_PREEMPTED",
    "EXIT_HUNG",
    "HangWatchdog",
    "Hung",
    "NothingToResume",
    "NumericsSentinel",
    "Preempted",
    "PreemptionGuard",
    "SentinelTripped",
]
