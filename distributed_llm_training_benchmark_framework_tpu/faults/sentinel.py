"""Numerics sentinel: detect -> roll back -> replay, without losing the run.

The telemetry anomaly engine (PR 3) *screens* numerics failures — a NaN
loss opens an anomaly event and validate_results later rejects the row —
but the run itself either dies or keeps training on garbage, and the whole
measurement is lost. The sentinel closes the loop in process:

- **Guards** (host-side floats, evaluated only at sync-window boundaries
  where the device is already fenced — the GC105 discipline):

  * non-finite loss, or a loss that *jumps* past the rolling-median
    envelope (a frozen run descends; a poisoned one explodes);
  * non-finite or exploding **global grad-norm** — computed INSIDE the
    jitted step when the sentinel is armed (``train.step.make_train_step
    (sentinel=True)`` returns it as a fourth output; one replicated f32
    scalar, a reduction XLA fuses into the existing grad pass), so the
    guard costs no extra device round-trip;
  * a per-N-steps **parameter-tree checksum** (global L2 norm) for silent
    data corruption: params move slowly step-to-step, so a bit flip that
    lands in an exponent moves the norm by orders of magnitude (or to
    inf/NaN) between two checksums.

- **On trip** the run does NOT die: the loop rolls back in-process to the
  last *validated* checkpoint (``runtime.checkpoint`` digest-verified
  restore), reseeds the data stream past the poisoned region (the replay
  uses a shifted step fold, so the same rows/dropout keys are never
  re-consumed), and replays. ``MAX_ROLLBACKS`` bounds the loop: a
  persistent numerics bug aborts loudly instead of replaying forever.

- **Honest accounting**: every trip emits a ``sentinel_trip`` telemetry
  event and every rollback a ``rollback`` event; the result row carries
  ``n_rollbacks``/``rollback_steps_replayed``; replayed windows are
  excluded from the timed distributions; validate_results checks the
  accounting coheres; and rolled-back records join resumed/partial rows
  in the regress never-baseline set (docs/FAULT_TOLERANCE.md).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

#: A boundary loss must stay under ``median + LOSS_ENVELOPE_NATS`` of the
#: rolling window (and under FACTOR x median) to pass. Both conditions:
#: early training legitimately wobbles whole nats while the median is
#: still high, and tiny late-run medians would make a pure factor twitchy.
LOSS_ENVELOPE_NATS = 2.0
LOSS_SPIKE_FACTOR = 2.0
#: Grad-norm guard: trip when the step's global grad-norm exceeds
#: FACTOR x the rolling median (gradient explosion), or is non-finite.
GRAD_SPIKE_FACTOR = 10.0
#: Param-checksum guard: trip when the parameter-tree L2 norm moves by
#: more than this fraction between consecutive checksums (params move at
#: ~lr per step; an SDC bit flip in an exponent moves them by orders of
#: magnitude), or is non-finite.
PARAM_NORM_JUMP_FRAC = 0.5
#: Minimum rolling-window history before the envelope guards judge — the
#: same warm-up posture as the telemetry spike screen.
MIN_HISTORY = 3
#: Rolling-window length for the loss / grad-norm medians.
WINDOW = 16
#: Rollbacks after which the sentinel stops healing and aborts the run
#: loudly — a trip that survives this many replays is a persistent bug
#: (or a poisoned checkpoint), not a transient.
MAX_ROLLBACKS = 3


def _median(vals: List[float]) -> float:
    return sorted(vals)[len(vals) // 2]


class SentinelTripped(RuntimeError):
    """The sentinel tripped but could not (or may no longer) roll back —
    no validated checkpoint behind the run, or MAX_ROLLBACKS exhausted.
    The harness maps it to a plain failure: the run is garbage and says
    so, rather than publishing it."""

    def __init__(self, kind: str, step: int, detail: str):
        self.kind = kind
        self.step = step
        super().__init__(
            f"numerics sentinel tripped ({kind}) at step {step} with no "
            f"rollback available: {detail}"
        )


class NumericsSentinel:
    """Boundary-cadence numerics guards + rollback accounting.

    The loop owns the actual rollback (it holds params/opt_state and the
    checkpointer); the sentinel owns detection and the honest ledger.
    All inputs are host floats the loop already synced — the sentinel
    itself performs no device work and no IO beyond recorder events.
    """

    def __init__(
        self,
        *,
        recorder=None,
        is_main: bool = True,
        max_rollbacks: int = MAX_ROLLBACKS,
        window: int = WINDOW,
    ):
        self.recorder = recorder
        self.is_main = is_main
        self.max_rollbacks = max_rollbacks
        self.window = window
        self._loss_hist: List[float] = []
        self._gnorm_hist: List[float] = []
        self._last_pnorm: Optional[float] = None
        #: The open trip ({kind, step, detail}) awaiting the loop's
        #: rollback decision, or None.
        self.trip: Optional[Dict[str, Any]] = None
        self.n_trips = 0
        self.n_rollbacks = 0
        self.rollback_steps_replayed = 0
        #: How many data-stream reseeds are in effect: the loop folds
        #: ``data_reseeds * total_steps`` into the step index it hands the
        #: jitted step, so replayed steps draw fresh batch rows and
        #: dropout keys instead of re-consuming the poisoned sequence.
        self.data_reseeds = 0

    # -- guards (sync-window boundaries only) -------------------------------

    def observe(
        self, step: int, loss: float, grad_norm: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        """Judge one synced step's loss (and grad-norm, when armed).

        Returns the trip dict when a guard fires (also stored on
        ``self.trip`` for the loop's boundary handler), else None. While
        a trip is open further observations are no-ops — the poisoned
        tail must not mint N events for one incident.
        """
        if self.trip is not None:
            return None
        if loss != loss or math.isinf(loss):
            return self._trip("nan_loss", step, "non-finite loss")
        if grad_norm is not None:
            if grad_norm != grad_norm or math.isinf(grad_norm):
                return self._trip(
                    "grad_explode", step, "non-finite global grad-norm"
                )
            if len(self._gnorm_hist) >= MIN_HISTORY:
                med = _median(self._gnorm_hist)
                if med > 0 and grad_norm > GRAD_SPIKE_FACTOR * med:
                    return self._trip(
                        "grad_explode", step,
                        f"global grad-norm {grad_norm:.4g} > "
                        f"{GRAD_SPIKE_FACTOR:g}x rolling median {med:.4g}",
                    )
        if len(self._loss_hist) >= MIN_HISTORY:
            med = _median(self._loss_hist)
            if (
                loss > med + LOSS_ENVELOPE_NATS
                and loss > LOSS_SPIKE_FACTOR * med
            ):
                return self._trip(
                    "loss_spike", step,
                    f"loss {loss:.4g} > rolling median {med:.4g} + "
                    f"{LOSS_ENVELOPE_NATS:g} nats",
                )
            # The envelope is two-sided: a COLLAPSE is the other poisoned
            # shape — saturated logits land on the gold token and the
            # loss free-falls to ~0 in one window (real descent moves
            # fractions of a nat per window, never whole nats).
            if (
                loss < med - LOSS_ENVELOPE_NATS
                and loss < med / LOSS_SPIKE_FACTOR
            ):
                return self._trip(
                    "loss_collapse", step,
                    f"loss {loss:.4g} < rolling median {med:.4g} - "
                    f"{LOSS_ENVELOPE_NATS:g} nats — saturated/corrupted "
                    "logits, not descent",
                )
        # Healthy values join the rolling windows (tripped ones never do —
        # one incident must not drag the median up and mask the next).
        self._loss_hist.append(loss)
        if grad_norm is not None:
            self._gnorm_hist.append(grad_norm)
        del self._loss_hist[: -self.window]
        del self._gnorm_hist[: -self.window]
        return None

    def observe_param_checksum(
        self, step: int, value: float,
    ) -> Optional[Dict[str, Any]]:
        """Judge one parameter-tree checksum (global L2 norm) sample."""
        if self.trip is not None:
            return None
        if value != value or math.isinf(value):
            return self._trip(
                "sdc", step, "non-finite parameter-tree checksum"
            )
        prev = self._last_pnorm
        if prev is not None and prev > 0:
            jump = abs(value - prev) / prev
            if jump > PARAM_NORM_JUMP_FRAC:
                return self._trip(
                    "sdc", step,
                    f"parameter-tree norm moved {100 * jump:.1f}% between "
                    f"checksums ({prev:.6g} -> {value:.6g}) — silent "
                    "corruption envelope is "
                    f"{100 * PARAM_NORM_JUMP_FRAC:.0f}%",
                )
        self._last_pnorm = value
        return None

    def _trip(self, kind: str, step: int, detail: str) -> Dict[str, Any]:
        self.n_trips += 1
        self.trip = {"kind": kind, "step": step, "detail": detail}
        if self.recorder is not None:
            try:
                self.recorder.note("sentinel_trip", **self.trip)
            except Exception:
                pass
        if self.is_main:
            print(f"SENTINEL: {kind} tripped at step {step} — {detail}",
                  flush=True)
        return self.trip

    # -- rollback ledger -----------------------------------------------------

    @property
    def rollback_allowed(self) -> bool:
        return self.n_rollbacks < self.max_rollbacks

    def note_rollback(self, *, from_step: int, to_step: int) -> None:
        """Record one executed rollback and clear the open trip.

        ``from_step`` is the boundary the trip was detected at;
        ``to_step`` the checkpoint step the loop restored. The steps in
        between get replayed — counted here, and excluded from the timed
        distributions by the loop.
        """
        replayed = max(from_step - to_step, 0)
        self.n_rollbacks += 1
        self.rollback_steps_replayed += replayed
        self.data_reseeds += 1
        # The poisoned tail's values never joined the histories, but the
        # checksum baseline may predate the restore point — reset it so
        # the restored (older) params are not themselves judged a jump.
        self._last_pnorm = None
        trip = self.trip or {}
        self.trip = None
        if self.recorder is not None:
            try:
                self.recorder.note(
                    "rollback",
                    from_step=from_step,
                    to_step=to_step,
                    steps_replayed=replayed,
                    n_rollbacks=self.n_rollbacks,
                    data_reseeds=self.data_reseeds,
                    trip_kind=trip.get("kind"),
                )
            except Exception:
                pass
        if self.is_main:
            print(
                f"SENTINEL: rolling back to validated checkpoint step "
                f"{to_step} (trip at {from_step}; {replayed} step(s) to "
                f"replay, reseeded data stream; rollback "
                f"#{self.n_rollbacks}/{self.max_rollbacks})",
                flush=True,
            )
