"""Deterministic fault injection for the chaos harness.

Every fault fires at an exact, *reproducible* point in the run:

- the stepped kinds (``sigkill@N``, ``sigterm@N``, ``hang@N``,
  ``stall-rank@N:R``) fire at the first sync-window boundary whose last
  completed step is >= N — the loop is already fenced there, so the
  abort step in the telemetry trail is the same on every run of the same
  spec;
- ``nan-loss@N`` corrupts exactly step N's loss at dispatch (the NaN
  surfaces at that step's sync window and trips the recorder's anomaly
  screen);
- ``bitflip@N`` / ``grad-explode@N`` poison the parameter tree exactly
  before step N dispatches (one huge element / one scaled leaf) — the
  numerics-sentinel proof faults (``faults/sentinel.py``): the run must
  detect, roll back to the last validated checkpoint and replay;
- ``torn-checkpoint`` fires after the first checkpoint save that leaves
  a *previous* committed step behind it: it tears the newest step's
  payload (truncates one file) and SIGKILLs, so resume must quarantine
  the torn step and fall back;
- ``enospc-on-save`` raises ``OSError(ENOSPC)`` from every checkpoint
  save — the run must degrade (warn + telemetry event) and still finish.

The injector is inert (``armed`` False) when constructed without a spec,
so the hot loop pays one attribute check per boundary and nothing else.
Faults announce themselves with a ``fault_injected`` telemetry event
*before* firing — the JSONL stream is line-buffered, so even the SIGKILL
trail records what killed it.
"""

from __future__ import annotations

import dataclasses
import errno
import os
import signal
import time
from typing import Optional

#: kind -> one-line contract, the registry --inject-fault validates against.
FAULT_KINDS = {
    "sigkill": "SIGKILL self at the first sync boundary with step >= N "
               "(the honest crash: no handlers, no flushes)",
    "sigterm": "SIGTERM self at the first sync boundary with step >= N "
               "(exercises the preemption handler end to end)",
    "sigterm-rank": "sigterm-rank@N:R — SIGTERM self at the first sync "
                    "boundary with step >= N, but ONLY on rank R "
                    "(exercises the cross-host preempt-soon broadcast: "
                    "every OTHER rank must learn of the preemption via "
                    "the coordination-service flag, not a signal)",
    "nan-loss": "corrupt step N's loss to NaN (trips the recorder's "
                "anomaly screen; validate_results must reject the row)",
    "hang": "sleep at the first sync boundary with step >= N "
            "(hang@N:SECS overrides the default stall; exercises the "
            "in-process hang watchdog / the liveness probe)",
    "stall-rank": "stall-rank@N:R[:SECS] — sleep at the first sync "
                  "boundary with step >= N, but ONLY on rank R "
                  "(exercises the cross-host hang broadcast: every OTHER "
                  "rank must learn of the stall from the "
                  "coordination-service hang flag and join the coherent "
                  "EXIT_HUNG abort)",
    "bitflip": "bitflip@N — corrupt one element of one parameter leaf "
               "before step N dispatches (silent-data-corruption "
               "analogue; the numerics sentinel's checksum/grad guards "
               "must trip and roll back)",
    "grad-explode": "grad-explode@N — scale one parameter leaf by a large "
                    "factor before step N dispatches, so the step's "
                    "global grad-norm explodes (the sentinel's grad-norm "
                    "guard must trip and roll back)",
    "opt-moments": "opt-moments@N — collapse the optimizer's second-"
                   "moment (Adam nu) accumulators toward zero before "
                   "step N dispatches: step N's update explodes (m/"
                   "(sqrt(nu)+eps) with a vanishing denominator) while "
                   "step N's own loss/grads stay healthy, so step N+1's "
                   "global grad-norm spikes FIRST — the sentinel's "
                   "grad-norm guard must trip before the loss/checksum "
                   "guards and roll back (the ROADMAP carry-forward "
                   "fault class no other spec exercises)",
    "torn-checkpoint": "tear the newest checkpoint after a save that has "
                       "a previous committed step, then SIGKILL (restore "
                       "must quarantine and fall back)",
    "enospc-on-save": "every checkpoint save raises OSError(ENOSPC); the "
                      "run must degrade and still finish",
    "data-stall": "data-stall@N[:SECS] — the streaming input source goes "
                  "silent before the batch for step N (default stall "
                  "3600 s): the prefetch producer sleeps, the timed loop "
                  "starves, and the run must classify reason=data_stall "
                  "(exit 78, retryable-with-resume) — NOT the watchdog's "
                  "hang. Requires --data-path",
    "data-corrupt-record": "data-corrupt-record@N — flip one byte of "
                           "global record N's payload as it is read "
                           "(emulated disk bit-rot; the files are never "
                           "mutated): the CRC check must catch it, the "
                           "slot heals by substitution, and the "
                           "records_skipped ledger + data_corrupt_record "
                           "telemetry event record the quarantine. "
                           "Requires --data-path",
    "data-slow-reader": "data-slow-reader@N:MS — every record read from "
                        "global record N onward takes MS extra "
                        "milliseconds (a degraded mount): the run must "
                        "COMPLETE with an honest, elevated "
                        "data_stall_frac — degrade, never die. Requires "
                        "--data-path",
    "data-missing-shard": "data-missing-shard@K — shard K is withheld "
                          "from discovery (a hole in the corpus): the "
                          "stream must REFUSE loudly naming the shard "
                          "before any device work — training on a "
                          "silently truncated corpus is the failure this "
                          "proves impossible. Requires --data-path",
}

#: Kinds that take a mandatory ``@N`` step (for the data kinds, N is a
#: global record index / shard index rather than an optimizer step — the
#: same "a fault without a firing point is not reproducible" rule).
STEPPED_KINDS = frozenset(
    {"sigkill", "sigterm", "sigterm-rank", "nan-loss", "hang",
     "stall-rank", "bitflip", "grad-explode", "opt-moments",
     "data-stall", "data-corrupt-record", "data-slow-reader",
     "data-missing-shard"}
)

#: Kinds whose ``@N:R`` suffix names a target rank.
RANKED_KINDS = frozenset({"sigterm-rank", "stall-rank"})

#: Data-path kinds (fire inside data/stream.py + data/prefetch.py via the
#: injector's data_* hooks; require --data-path to have any consumer).
DATA_KINDS = frozenset(
    {"data-stall", "data-corrupt-record", "data-slow-reader",
     "data-missing-shard"}
)

#: The bitflip magnitude: large enough that a squared-norm reduction in
#: f32 overflows to inf (1e30^2 > f32 max), so the sentinel's checksum /
#: grad-norm guards trip deterministically on the very next boundary.
BITFLIP_VALUE = 1e30
#: grad-explode scales one leaf by this factor — logits saturate, the
#: loss and the global grad-norm jump orders of magnitude, but nothing
#: goes non-finite (the *envelope* guards must catch it, not a NaN
#: screen).
GRAD_EXPLODE_SCALE = 1e3
#: opt-moments: the exponent-burst scales for the Adam moment buffers.
#: The second moments (nu) collapse toward zero and the paired first
#: moments (mu) flip UP — one SDC burst across the adjacent moment
#: state. Both halves are needed for a physical reason worth recording:
#: a pure nu collapse CANNOT spike the next step's gradients, because
#: optax updates the moments BEFORE computing the step — the
#: ``(1 - b2) * g^2`` refill rebuilds the denominator within the very
#: corrupted step, bounding the update inflation at ``1/sqrt(1-b2)``
#: (~31x, and only ~3x at early step counts under bias correction):
#: a 31x-effective-lr drift, not an explosion. The corrupted mu has the
#: opposite refill asymmetry — ``b1 * mu`` RETAINS the corruption — so
#: the update explodes ~1e4x through the numerator while the step's own
#: loss/grads stay healthy: the first observable symptom is the NEXT
#: step's global grad-norm, which is exactly the guard this spec exists
#: to prove fires before the loss/checksum guards.
MOMENT_COLLAPSE_SCALE = 1e-8
MOMENT_BURST_SCALE = 1e4

#: Default stall for ``hang`` when the spec carries no ``:SECS``. Long
#: enough that any sane per-run timeout (or the k8s liveness probe) fires
#: first; the chaos suite passes a short override.
HANG_DEFAULT_SEC = 3600.0


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One parsed ``--inject-fault`` value."""

    kind: str
    step: Optional[int] = None
    hang_sec: Optional[float] = None
    # sigterm-rank@N:R — the one rank that receives the signal. Every rank
    # parses the same spec (the suite passes one value to every worker);
    # the injector compares against its own rank at fire time.
    rank: Optional[int] = None
    # data-slow-reader@N:MS — per-record extra read latency in
    # milliseconds (its own field so the spec string round-trips in the
    # unit the operator wrote; hang_sec stays seconds).
    delay_ms: Optional[float] = None

    def __str__(self) -> str:
        s = self.kind
        if self.step is not None:
            s += f"@{self.step}"
        if self.rank is not None:
            # Ranked grammar: KIND@N:R[:SECS] — the rank rides first.
            s += f":{self.rank}"
        if self.hang_sec is not None:
            s += f":{self.hang_sec:g}"
        if self.delay_ms is not None:
            s += f":{self.delay_ms:g}"
        return s


def parse_fault_spec(spec: Optional[str]) -> Optional[FaultSpec]:
    """``"sigkill@10"`` -> FaultSpec; None/empty -> None; junk raises.

    Grammar: ``KIND`` | ``KIND@STEP`` | ``hang@STEP:SECS`` |
    ``sigterm-rank@STEP:RANK`` | ``stall-rank@STEP:RANK[:SECS]``.
    Stepped kinds *require* the step (a fault with no defined firing
    point would not be reproducible) — the ranked kinds additionally
    require the target rank; the save-path kinds refuse one (they fire on
    save events, not steps).
    """
    if not spec:
        return None
    spec = spec.strip()
    kind, _, rest = spec.partition("@")
    if kind not in FAULT_KINDS:
        raise ValueError(
            f"unknown fault kind {kind!r} (expected one of "
            f"{sorted(FAULT_KINDS)})"
        )
    if kind in STEPPED_KINDS:
        if not rest:
            raise ValueError(
                f"fault {kind!r} needs an explicit step: {kind}@N "
                "(a fault without a firing step is not reproducible)"
            )
        step_str, _, suffix = rest.partition(":")
        if suffix and kind not in (
            "hang", "data-stall", "data-slow-reader", *RANKED_KINDS
        ):
            raise ValueError(
                f"only 'hang', 'data-stall', 'data-slow-reader' and the "
                f"ranked kinds ({sorted(RANKED_KINDS)}) take a suffix, "
                f"got {spec!r}"
            )
        if kind in RANKED_KINDS and not suffix:
            raise ValueError(
                f"{kind} needs a target rank: {kind}@N:R (without one the "
                f"fault is rankless — which rank it hits is the whole "
                "point of the spec)"
            )
        if kind == "data-slow-reader" and not suffix:
            raise ValueError(
                f"data-slow-reader needs a per-record latency: "
                f"data-slow-reader@N:MS (without one the degradation it "
                f"injects is undefined), got {spec!r}"
            )
        try:
            step = int(step_str)
        except ValueError:
            raise ValueError(f"fault step must be an integer, got {spec!r}")
        if step < 0:
            raise ValueError(f"fault step must be >= 0, got {spec!r}")
        hang_sec = None
        rank = None
        delay_ms = None
        if suffix and kind == "data-slow-reader":
            try:
                delay_ms = float(suffix)
            except ValueError:
                raise ValueError(
                    f"data-slow-reader latency must be a number of "
                    f"milliseconds, got {spec!r}"
                )
            if delay_ms <= 0:
                raise ValueError(
                    f"data-slow-reader latency must be > 0, got {spec!r}"
                )
            return FaultSpec(kind=kind, step=step, delay_ms=delay_ms)
        if suffix and kind in RANKED_KINDS:
            rank_str, _, secs_str = suffix.partition(":")
            if secs_str and kind != "stall-rank":
                raise ValueError(
                    f"only stall-rank takes a duration suffix, got {spec!r}"
                )
            try:
                rank = int(rank_str)
            except ValueError:
                raise ValueError(
                    f"{kind} target must be an integer rank, got {spec!r}"
                )
            if rank < 0:
                raise ValueError(f"fault rank must be >= 0, got {spec!r}")
            if secs_str:
                try:
                    hang_sec = float(secs_str)
                except ValueError:
                    raise ValueError(
                        f"stall duration must be a number, got {spec!r}"
                    )
                if hang_sec <= 0:
                    raise ValueError(
                        f"stall duration must be > 0, got {spec!r}"
                    )
        elif suffix:
            try:
                hang_sec = float(suffix)
            except ValueError:
                raise ValueError(
                    f"hang duration must be a number, got {spec!r}"
                )
            if hang_sec <= 0:
                raise ValueError(f"hang duration must be > 0, got {spec!r}")
        return FaultSpec(kind=kind, step=step, hang_sec=hang_sec, rank=rank)
    if rest:
        raise ValueError(
            f"fault {kind!r} fires on checkpoint saves and takes no @step "
            f"(got {spec!r})"
        )
    return FaultSpec(kind=kind)


def _tear_newest_file(step_dir: str) -> Optional[str]:
    """Truncate the first (sorted) non-empty file under ``step_dir``.

    Deterministic pick so the torn artifact is the same every run; returns
    the torn path (repo of the chaos trail) or None when nothing tearable.
    """
    candidates = []
    for dirpath, _dirnames, filenames in os.walk(step_dir):
        for fn in sorted(filenames):
            path = os.path.join(dirpath, fn)
            try:
                if os.path.getsize(path) > 0:
                    candidates.append(path)
            except OSError:
                continue
    if not candidates:
        return None
    victim = sorted(candidates)[0]
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.truncate(max(size // 2, 1) - 1 if size > 1 else 0)
    return victim


class FaultInjector:
    """Arms one :class:`FaultSpec` against the train loop's boundaries.

    Call sites (all at device-fenced points — the injector never adds a
    sync of its own):

    - :meth:`at_boundary` from ``sync_window`` after the window's
      telemetry, with the window's last completed step;
    - :meth:`corrupt_loss` on each step's freshly dispatched loss;
    - :meth:`maybe_fail_save` just before a checkpoint save;
    - :meth:`after_save` just after a committed checkpoint save.
    """

    def __init__(self, spec: Optional[FaultSpec] = None, recorder=None,
                 is_main: bool = True, rank: int = 0):
        self.spec = spec
        self.recorder = recorder
        self.is_main = is_main
        # This process's rank — the sigterm-rank kind fires only when it
        # matches the spec's target (every worker of a multi-host run is
        # handed the same spec string).
        self.rank = rank
        self.fired = False

    @property
    def armed(self) -> bool:
        return self.spec is not None

    def _announce(self, detail: str) -> None:
        if self.recorder is not None:
            try:
                self.recorder.note(
                    "fault_injected", fault=str(self.spec), detail=detail,
                )
            except Exception:
                pass
        if self.is_main:
            print(f"CHAOS: injecting fault {self.spec} — {detail}",
                  flush=True)

    # -- boundary faults ---------------------------------------------------

    def at_boundary(self, last_step: int) -> None:
        """Fire sigkill/sigterm/hang/stall at the first boundary past N."""
        if (
            self.spec is None or self.fired
            or self.spec.kind not in (
                "sigkill", "sigterm", "sigterm-rank", "hang", "stall-rank"
            )
            or last_step < (self.spec.step or 0)
        ):
            return
        if self.spec.kind == "stall-rank" and self.rank != (self.spec.rank or 0):
            # Not this worker's stall: THIS rank must learn of the hang
            # from the coordination-service broadcast (the watchdog's
            # hang flag), not from its own stopped clock — that asymmetry
            # is what the spec exists to prove. Stay armed (fired False):
            # a healthy rank never fires anything.
            return
        self.fired = True
        if self.spec.kind == "sigkill":
            self._announce(f"SIGKILL at sync boundary, step {last_step}")
            os.kill(os.getpid(), signal.SIGKILL)
        elif self.spec.kind == "sigterm":
            self._announce(f"SIGTERM at sync boundary, step {last_step}")
            os.kill(os.getpid(), signal.SIGTERM)
        elif self.spec.kind == "sigterm-rank":
            if self.rank != (self.spec.rank or 0):
                # Not this worker's fault to fire: the kill lands on rank
                # R only, and THIS rank must learn of the preemption from
                # the cross-host broadcast — that asymmetry is what the
                # spec exists to prove.
                return
            self._announce(
                f"SIGTERM (rank {self.rank}) at sync boundary, step {last_step}"
            )
            os.kill(os.getpid(), signal.SIGTERM)
        elif self.spec.kind == "stall-rank":
            secs = self.spec.hang_sec or HANG_DEFAULT_SEC
            self._announce(
                f"stall (rank {self.rank}, {secs:g}s) at sync boundary, "
                f"step {last_step}"
            )
            time.sleep(secs)
        else:  # hang
            secs = self.spec.hang_sec or HANG_DEFAULT_SEC
            self._announce(
                f"hang ({secs:g}s stall) at sync boundary, step {last_step}"
            )
            time.sleep(secs)

    # -- loss corruption ---------------------------------------------------

    def corrupt_loss(self, step: int, loss):
        """NaN exactly step N's loss for ``nan-loss@N`` (else passthrough)."""
        if (
            self.spec is None or self.fired
            or self.spec.kind != "nan-loss" or step != self.spec.step
        ):
            return loss
        self.fired = True
        self._announce(f"NaN loss injected at step {step}")
        # Multiplying keeps shape/dtype/sharding; no host sync, no
        # device fence — the NaN just rides the normal loss handle.
        return loss * float("nan")

    # -- parameter corruption (numerics-sentinel proofs) -------------------

    def corrupt_params(self, step: int, params):
        """Poison the parameter tree before step N dispatches (else
        passthrough) — the SDC / gradient-explosion injection point.

        ``bitflip@N`` sets one element of one leaf (the LARGEST leaf —
        deterministically the embedding table, whose poison flows into
        every logit rather than being washed out by the next LayerNorm;
        ties break on path) to :data:`BITFLIP_VALUE`; ``grad-explode@N``
        scales that whole leaf by :data:`GRAD_EXPLODE_SCALE`. Pure device
        ops on the fenced pre-dispatch handle: no host sync, no
        shape/dtype/sharding change — the poison just rides the normal
        params into the step, exactly like a real corrupted HBM word
        would.
        """
        if (
            self.spec is None or self.fired
            or self.spec.kind not in ("bitflip", "grad-explode")
            or step != self.spec.step
        ):
            return params
        self.fired = True
        import jax
        import jax.numpy as jnp

        leaves = jax.tree_util.tree_flatten_with_path(params)[0]
        if self.spec.kind == "grad-explode":
            # Prefer the embedding table (weight-tied LM head): scaling it
            # multiplies every logit, so the loss and the backward pass
            # explode THROUGH the normalization layers instead of being
            # washed out by the next LayerNorm. Fall back to the largest
            # leaf on head-less trees.
            named = [e for e in leaves if "wte" in str(e[0])]
            leaves = named or leaves
        victim_path, victim = sorted(
            leaves, key=lambda e: (-getattr(e[1], "size", 0), str(e[0]))
        )[0]
        name = jax.tree_util.keystr(victim_path)
        if self.spec.kind == "bitflip":
            poisoned = victim.at[(0,) * victim.ndim].set(
                jnp.asarray(BITFLIP_VALUE, victim.dtype)
            )
            self._announce(
                f"bitflip: params{name}[0...] = {BITFLIP_VALUE:g} before "
                f"step {step}"
            )
        else:
            poisoned = victim * jnp.asarray(GRAD_EXPLODE_SCALE, victim.dtype)
            self._announce(
                f"grad-explode: params{name} scaled x{GRAD_EXPLODE_SCALE:g} "
                f"before step {step}"
            )

        def swap(path, leaf):
            return poisoned if path == victim_path else leaf

        return jax.tree_util.tree_map_with_path(swap, params)

    def corrupt_opt_state(self, step: int, opt_state):
        """Corrupt the Adam moment buffers before step N dispatches
        (``opt-moments@N``; else passthrough).

        One exponent burst across the optimizer's moment state: every
        leaf under a ``nu`` field (optax's ``ScaleByAdamState.nu`` —
        matched by the exact attribute name in the tree path, so a
        parameter coincidentally containing 'nu' can never be hit)
        collapses by :data:`MOMENT_COLLAPSE_SCALE`, and the paired
        ``mu`` leaves flip up by :data:`MOMENT_BURST_SCALE` (see the
        constants' note for why the mu half is load-bearing: the nu
        refill self-heals within the corrupted step). The corrupted
        step itself computes HEALTHY loss and gradients — the poison
        only enters through the optimizer update — which is what makes
        this the one fault class whose first observable symptom is the
        NEXT step's exploding grad-norm: the sentinel's grad-norm guard
        must trip before the loss/checksum guards ever see anything.
        Pure device ops on the fenced pre-dispatch handle, like
        ``corrupt_params``. The moment buffers are also the state no
        other guard covers at rest — the checkpoint digests protect
        them on disk, but in HBM a flipped moment is invisible until
        the update fires.
        """
        if (
            self.spec is None or self.fired
            or self.spec.kind != "opt-moments" or step != self.spec.step
        ):
            return opt_state
        self.fired = True
        import jax
        import jax.numpy as jnp

        flat = jax.tree_util.tree_flatten_with_path(opt_state)[0]

        def moment_field(path):
            for e in path:
                if getattr(e, "name", None) in ("mu", "nu"):
                    return e.name
            return None

        n_nu = sum(1 for path, leaf in flat
                   if moment_field(path) == "nu" and hasattr(leaf, "dtype"))
        if n_nu == 0:
            # An optimizer layout without Adam moments (e.g. a future
            # SGD arm): the fault has nothing to corrupt — say so
            # loudly rather than silently passing a healthy run off as
            # a survived injection.
            self._announce(
                "opt-moments: no Adam moment (mu/nu) leaves in this "
                "optimizer state — fault inert"
            )
            return opt_state
        self._announce(
            f"opt-moments: collapsing {n_nu} second-moment (nu) leaves "
            f"x{MOMENT_COLLAPSE_SCALE:g} and bursting the paired mu "
            f"leaves x{MOMENT_BURST_SCALE:g} before step {step}"
        )

        def scale(path, leaf):
            field = moment_field(path)
            if field is None or not hasattr(leaf, "dtype"):
                return leaf
            factor = (MOMENT_COLLAPSE_SCALE if field == "nu"
                      else MOMENT_BURST_SCALE)
            return leaf * jnp.asarray(factor, leaf.dtype)

        return jax.tree_util.tree_map_with_path(scale, opt_state)

    # -- save-path faults --------------------------------------------------

    def maybe_fail_save(self) -> None:
        """Raise ENOSPC from the save path for ``enospc-on-save``."""
        if self.spec is None or self.spec.kind != "enospc-on-save":
            return
        self._announce("OSError(ENOSPC) raised from checkpoint save")
        raise OSError(errno.ENOSPC, "No space left on device (injected)")

    def after_save(self, ckpt, step: int) -> None:
        """Tear the newest checkpoint + SIGKILL for ``torn-checkpoint``.

        Waits until a committed *previous* step exists, so the resume has
        a good step to fall back to — the whole point of the fault class.
        """
        if (
            self.spec is None or self.fired
            or self.spec.kind != "torn-checkpoint"
        ):
            return
        steps = ckpt.all_steps()
        if len(steps) < 2:
            return
        self.fired = True
        victim = ckpt.step_dir(max(steps))
        torn = _tear_newest_file(victim)
        self._announce(
            f"tore checkpoint step {max(steps)} ({torn}); SIGKILL"
        )
        os.kill(os.getpid(), signal.SIGKILL)

    # -- data-path faults (consumed by data/stream.py + data/prefetch.py) --

    def data_missing_shard(self) -> Optional[int]:
        """Shard index to withhold from discovery (``data-missing-shard@K``),
        or None. Fires at stream construction — pre-dispatch, so the
        refusal it provokes never wastes device time."""
        if self.spec is None or self.spec.kind != "data-missing-shard":
            return None
        if not self.fired:
            self.fired = True
            self._announce(
                f"shard {self.spec.step} withheld from discovery — the "
                "stream must refuse loudly naming it"
            )
        return self.spec.step

    def data_stall_sec(self, step: int) -> float:
        """Seconds the prefetch producer sleeps before the batch for step
        N (``data-stall@N[:SECS]``); 0.0 otherwise. Runs on the prefetch
        thread — the announce reaches the JSONL before the consumer
        starves, so the trail records what stalled it."""
        if (
            self.spec is None or self.fired
            or self.spec.kind != "data-stall" or step != self.spec.step
        ):
            return 0.0
        self.fired = True
        secs = self.spec.hang_sec or HANG_DEFAULT_SEC
        self._announce(
            f"input source silent for {secs:g}s before the batch for "
            f"step {step}"
        )
        return secs

    def data_corrupt_payload(self, global_index: int, payload: bytes) -> bytes:
        """Flip one byte of global record N's payload as read
        (``data-corrupt-record@N``; passthrough otherwise). Emulates disk
        bit-rot deterministically WITHOUT mutating the shard files — the
        CRC check downstream must catch it."""
        if (
            self.spec is None or self.fired
            or self.spec.kind != "data-corrupt-record"
            or global_index != self.spec.step
        ):
            return payload
        self.fired = True
        self._announce(
            f"flipped one payload byte of global record {global_index} "
            "(CRC must catch it; slot heals by substitution)"
        )
        return bytes([payload[0] ^ 0xFF]) + payload[1:]

    def data_read_delay_sec(self, global_index: int) -> float:
        """Extra per-record read latency from record N on
        (``data-slow-reader@N:MS``); 0.0 otherwise. ``fired`` only gates
        the announce — the degradation persists for the rest of the run,
        which is what makes data_stall_frac measurable."""
        if (
            self.spec is None or self.spec.kind != "data-slow-reader"
            or global_index < (self.spec.step or 0)
        ):
            return 0.0
        if not self.fired:
            self.fired = True
            self._announce(
                f"every record read from global record {self.spec.step} "
                f"on takes +{self.spec.delay_ms:g} ms (degraded mount)"
            )
        return (self.spec.delay_ms or 0.0) / 1000.0
