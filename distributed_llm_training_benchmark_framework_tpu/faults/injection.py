"""Deterministic fault injection for the chaos harness.

Every fault fires at an exact, *reproducible* point in the run:

- the stepped kinds (``sigkill@N``, ``sigterm@N``, ``hang@N``) fire at
  the first sync-window boundary whose last completed step is >= N — the
  loop is already fenced there, so the abort step in the telemetry trail
  is the same on every run of the same spec;
- ``nan-loss@N`` corrupts exactly step N's loss at dispatch (the NaN
  surfaces at that step's sync window and trips the recorder's anomaly
  screen);
- ``torn-checkpoint`` fires after the first checkpoint save that leaves
  a *previous* committed step behind it: it tears the newest step's
  payload (truncates one file) and SIGKILLs, so resume must quarantine
  the torn step and fall back;
- ``enospc-on-save`` raises ``OSError(ENOSPC)`` from every checkpoint
  save — the run must degrade (warn + telemetry event) and still finish.

The injector is inert (``armed`` False) when constructed without a spec,
so the hot loop pays one attribute check per boundary and nothing else.
Faults announce themselves with a ``fault_injected`` telemetry event
*before* firing — the JSONL stream is line-buffered, so even the SIGKILL
trail records what killed it.
"""

from __future__ import annotations

import dataclasses
import errno
import os
import signal
import time
from typing import Optional

#: kind -> one-line contract, the registry --inject-fault validates against.
FAULT_KINDS = {
    "sigkill": "SIGKILL self at the first sync boundary with step >= N "
               "(the honest crash: no handlers, no flushes)",
    "sigterm": "SIGTERM self at the first sync boundary with step >= N "
               "(exercises the preemption handler end to end)",
    "sigterm-rank": "sigterm-rank@N:R — SIGTERM self at the first sync "
                    "boundary with step >= N, but ONLY on rank R "
                    "(exercises the cross-host preempt-soon broadcast: "
                    "every OTHER rank must learn of the preemption via "
                    "the coordination-service flag, not a signal)",
    "nan-loss": "corrupt step N's loss to NaN (trips the recorder's "
                "anomaly screen; validate_results must reject the row)",
    "hang": "sleep at the first sync boundary with step >= N "
            "(hang@N:SECS overrides the default stall; exercises "
            "timeouts / the liveness probe)",
    "torn-checkpoint": "tear the newest checkpoint after a save that has "
                       "a previous committed step, then SIGKILL (restore "
                       "must quarantine and fall back)",
    "enospc-on-save": "every checkpoint save raises OSError(ENOSPC); the "
                      "run must degrade and still finish",
}

#: Kinds that take a mandatory ``@N`` step.
STEPPED_KINDS = frozenset(
    {"sigkill", "sigterm", "sigterm-rank", "nan-loss", "hang"}
)

#: Default stall for ``hang`` when the spec carries no ``:SECS``. Long
#: enough that any sane per-run timeout (or the k8s liveness probe) fires
#: first; the chaos suite passes a short override.
HANG_DEFAULT_SEC = 3600.0


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One parsed ``--inject-fault`` value."""

    kind: str
    step: Optional[int] = None
    hang_sec: Optional[float] = None
    # sigterm-rank@N:R — the one rank that receives the signal. Every rank
    # parses the same spec (the suite passes one value to every worker);
    # the injector compares against its own rank at fire time.
    rank: Optional[int] = None

    def __str__(self) -> str:
        s = self.kind
        if self.step is not None:
            s += f"@{self.step}"
        if self.hang_sec is not None:
            s += f":{self.hang_sec:g}"
        if self.rank is not None:
            s += f":{self.rank}"
        return s


def parse_fault_spec(spec: Optional[str]) -> Optional[FaultSpec]:
    """``"sigkill@10"`` -> FaultSpec; None/empty -> None; junk raises.

    Grammar: ``KIND`` | ``KIND@STEP`` | ``hang@STEP:SECS`` |
    ``sigterm-rank@STEP:RANK``. Stepped kinds *require* the step (a fault
    with no defined firing point would not be reproducible) —
    ``sigterm-rank`` additionally requires the target rank; the save-path
    kinds refuse one (they fire on save events, not steps).
    """
    if not spec:
        return None
    spec = spec.strip()
    kind, _, rest = spec.partition("@")
    if kind not in FAULT_KINDS:
        raise ValueError(
            f"unknown fault kind {kind!r} (expected one of "
            f"{sorted(FAULT_KINDS)})"
        )
    if kind in STEPPED_KINDS:
        if not rest:
            raise ValueError(
                f"fault {kind!r} needs an explicit step: {kind}@N "
                "(a fault without a firing step is not reproducible)"
            )
        step_str, _, secs_str = rest.partition(":")
        if secs_str and kind not in ("hang", "sigterm-rank"):
            raise ValueError(
                f"only 'hang' and 'sigterm-rank' take a suffix, got {spec!r}"
            )
        if kind == "sigterm-rank" and not secs_str:
            raise ValueError(
                "sigterm-rank needs a target rank: sigterm-rank@N:R "
                "(without one the fault is 'sigterm' — which rank dies is "
                "the whole point of the spec)"
            )
        try:
            step = int(step_str)
        except ValueError:
            raise ValueError(f"fault step must be an integer, got {spec!r}")
        if step < 0:
            raise ValueError(f"fault step must be >= 0, got {spec!r}")
        hang_sec = None
        rank = None
        if secs_str and kind == "sigterm-rank":
            try:
                rank = int(secs_str)
            except ValueError:
                raise ValueError(
                    f"sigterm-rank target must be an integer rank, got {spec!r}"
                )
            if rank < 0:
                raise ValueError(f"fault rank must be >= 0, got {spec!r}")
        elif secs_str:
            try:
                hang_sec = float(secs_str)
            except ValueError:
                raise ValueError(
                    f"hang duration must be a number, got {spec!r}"
                )
            if hang_sec <= 0:
                raise ValueError(f"hang duration must be > 0, got {spec!r}")
        return FaultSpec(kind=kind, step=step, hang_sec=hang_sec, rank=rank)
    if rest:
        raise ValueError(
            f"fault {kind!r} fires on checkpoint saves and takes no @step "
            f"(got {spec!r})"
        )
    return FaultSpec(kind=kind)


def _tear_newest_file(step_dir: str) -> Optional[str]:
    """Truncate the first (sorted) non-empty file under ``step_dir``.

    Deterministic pick so the torn artifact is the same every run; returns
    the torn path (repo of the chaos trail) or None when nothing tearable.
    """
    candidates = []
    for dirpath, _dirnames, filenames in os.walk(step_dir):
        for fn in sorted(filenames):
            path = os.path.join(dirpath, fn)
            try:
                if os.path.getsize(path) > 0:
                    candidates.append(path)
            except OSError:
                continue
    if not candidates:
        return None
    victim = sorted(candidates)[0]
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.truncate(max(size // 2, 1) - 1 if size > 1 else 0)
    return victim


class FaultInjector:
    """Arms one :class:`FaultSpec` against the train loop's boundaries.

    Call sites (all at device-fenced points — the injector never adds a
    sync of its own):

    - :meth:`at_boundary` from ``sync_window`` after the window's
      telemetry, with the window's last completed step;
    - :meth:`corrupt_loss` on each step's freshly dispatched loss;
    - :meth:`maybe_fail_save` just before a checkpoint save;
    - :meth:`after_save` just after a committed checkpoint save.
    """

    def __init__(self, spec: Optional[FaultSpec] = None, recorder=None,
                 is_main: bool = True, rank: int = 0):
        self.spec = spec
        self.recorder = recorder
        self.is_main = is_main
        # This process's rank — the sigterm-rank kind fires only when it
        # matches the spec's target (every worker of a multi-host run is
        # handed the same spec string).
        self.rank = rank
        self.fired = False

    @property
    def armed(self) -> bool:
        return self.spec is not None

    def _announce(self, detail: str) -> None:
        if self.recorder is not None:
            try:
                self.recorder.note(
                    "fault_injected", fault=str(self.spec), detail=detail,
                )
            except Exception:
                pass
        if self.is_main:
            print(f"CHAOS: injecting fault {self.spec} — {detail}",
                  flush=True)

    # -- boundary faults ---------------------------------------------------

    def at_boundary(self, last_step: int) -> None:
        """Fire sigkill/sigterm/hang at the first boundary past the step."""
        if (
            self.spec is None or self.fired
            or self.spec.kind not in (
                "sigkill", "sigterm", "sigterm-rank", "hang"
            )
            or last_step < (self.spec.step or 0)
        ):
            return
        self.fired = True
        if self.spec.kind == "sigkill":
            self._announce(f"SIGKILL at sync boundary, step {last_step}")
            os.kill(os.getpid(), signal.SIGKILL)
        elif self.spec.kind == "sigterm":
            self._announce(f"SIGTERM at sync boundary, step {last_step}")
            os.kill(os.getpid(), signal.SIGTERM)
        elif self.spec.kind == "sigterm-rank":
            if self.rank != (self.spec.rank or 0):
                # Not this worker's fault to fire: the kill lands on rank
                # R only, and THIS rank must learn of the preemption from
                # the cross-host broadcast — that asymmetry is what the
                # spec exists to prove.
                return
            self._announce(
                f"SIGTERM (rank {self.rank}) at sync boundary, step {last_step}"
            )
            os.kill(os.getpid(), signal.SIGTERM)
        else:  # hang
            secs = self.spec.hang_sec or HANG_DEFAULT_SEC
            self._announce(
                f"hang ({secs:g}s stall) at sync boundary, step {last_step}"
            )
            time.sleep(secs)

    # -- loss corruption ---------------------------------------------------

    def corrupt_loss(self, step: int, loss):
        """NaN exactly step N's loss for ``nan-loss@N`` (else passthrough)."""
        if (
            self.spec is None or self.fired
            or self.spec.kind != "nan-loss" or step != self.spec.step
        ):
            return loss
        self.fired = True
        self._announce(f"NaN loss injected at step {step}")
        # Multiplying keeps shape/dtype/sharding; no host sync, no
        # device fence — the NaN just rides the normal loss handle.
        return loss * float("nan")

    # -- save-path faults --------------------------------------------------

    def maybe_fail_save(self) -> None:
        """Raise ENOSPC from the save path for ``enospc-on-save``."""
        if self.spec is None or self.spec.kind != "enospc-on-save":
            return
        self._announce("OSError(ENOSPC) raised from checkpoint save")
        raise OSError(errno.ENOSPC, "No space left on device (injected)")

    def after_save(self, ckpt, step: int) -> None:
        """Tear the newest checkpoint + SIGKILL for ``torn-checkpoint``.

        Waits until a committed *previous* step exists, so the resume has
        a good step to fall back to — the whole point of the fault class.
        """
        if (
            self.spec is None or self.fired
            or self.spec.kind != "torn-checkpoint"
        ):
            return
        steps = ckpt.all_steps()
        if len(steps) < 2:
            return
        self.fired = True
        victim = ckpt.step_dir(max(steps))
        torn = _tear_newest_file(victim)
        self._announce(
            f"tore checkpoint step {max(steps)} ({torn}); SIGKILL"
        )
        os.kill(os.getpid(), signal.SIGKILL)
