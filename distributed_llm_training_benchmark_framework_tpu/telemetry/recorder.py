"""The flight recorder: crash-resilient JSONL events + stdout heartbeats.

Design constraints (see package docstring and docs/OBSERVABILITY.md):

- **Crash resilience over buffering.** The JSONL file is opened
  line-buffered (``buffering=1``): every event reaches the OS when its
  line completes, so a SIGKILL'd process keeps everything up to its last
  sync boundary. The recorder never buffers events in memory.
- **Zero device syncs.** The recorder is host-side bookkeeping only. It is
  *called* at sync-window boundaries (where the loop already blocked on
  the device), and its one device-adjacent read — the allocator HBM
  high-water mark via ``utils.metrics.peak_hbm_bytes()`` — is a host-side
  stats query, not a fence. graftcheck rule GC105 (analysis/static/lint.py)
  pins the call-site discipline in train/loop.py.
- **Best-effort everywhere.** A full disk or torn-down results dir must
  degrade telemetry, never fail the benchmark: every write path swallows
  ``OSError``.

Timestamps: ``ts`` is unix wall time (joinable against profiler traces and
pod logs), ``rel`` is seconds since recorder creation on the monotonic
clock (durable arithmetic — wall time can step).
"""

from __future__ import annotations

import atexit
import json
import math
import os
import re
import sys
import time
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1

#: The stdout scrape marker. scripts/collect_results.sh greps this literal
#: (and tests/test_telemetry.py pins that the script and this constant
#: agree), so partial progress survives in pod logs when the final
#: BENCHMARK_RESULT_JSON markers never print.
HEARTBEAT_MARKER = "BENCHMARK_HEARTBEAT"

#: Canonical phase names, in their natural run order. ``begin_phase``
#: accepts only these — a typo'd phase would silently fork the attribution.
PHASES = (
    "init", "compile", "warmup", "timed", "trace", "checkpoint", "finalize",
)

#: A window whose mean step time exceeds SPIKE_FACTOR x the median of the
#: preceding windows opens a ``step_time_spike`` anomaly; a later window
#: back under SPIKE_RESOLVE_FACTOR x median resolves it. A spike that
#: persists for SPIKE_REBASELINE_WINDOWS consecutive windows is a
#: sustained slowdown, not a stall: it resolves as "rebaselined" and its
#: level becomes the new median — otherwise the frozen history could
#: never catch up and a successfully completed (if slower) run would be
#: rejected by the validator as an open anomaly. NaN losses are never
#: resolved.
SPIKE_FACTOR = 3.0
SPIKE_RESOLVE_FACTOR = 1.5
SPIKE_MIN_HISTORY = 3
SPIKE_REBASELINE_WINDOWS = 5


def telemetry_filename(arm: str, rank: int = 0) -> str:
    """Rank 0 owns the canonical ``telemetry_<arm>.jsonl`` (paired with the
    result row by slug); every other rank of a multi-host run streams its
    own ``telemetry_<arm>.rank<r>.jsonl`` beside it — a straggling or
    preempted non-zero rank is then visible directly instead of only
    through rank 0's window times (telemetry follow-up (a))."""
    if rank and rank > 0:
        return f"telemetry_{arm}.rank{rank}.jsonl"
    return f"telemetry_{arm}.jsonl"


#: The rank-sibling suffix contract, in one place: telemetry_filename
#: builds it, rank_telemetry_files and is_rank_sibling match it.
_RANK_SIBLING_RE = re.compile(r"\.rank(\d+)\.jsonl$")


def is_rank_sibling(path: str) -> bool:
    """True for a non-zero rank's ``telemetry_<arm>.rank<r>.jsonl`` file
    (which reports under its rank-0 file, never as a standalone run)."""
    return _RANK_SIBLING_RE.search(os.path.basename(path)) is not None


def rank_telemetry_files(path: str) -> Dict[int, str]:
    """{rank: path} for a rank-0 telemetry file and its rank siblings.

    ``path`` is the canonical ``telemetry_<arm>.jsonl``; the rank files
    live beside it. Used by analysis.telemetry_report to merge a
    multi-host run's per-rank streams into one straggler view.
    """
    import glob as _glob

    out: Dict[int, str] = {0: path}
    base = os.path.basename(path)
    if not (base.startswith("telemetry_") and base.endswith(".jsonl")):
        return out
    stem = base[:-len(".jsonl")]
    pattern = os.path.join(
        os.path.dirname(path) or ".", f"{stem}.rank*.jsonl"
    )
    for sibling in sorted(_glob.glob(pattern)):
        m = _RANK_SIBLING_RE.search(sibling)
        if m:
            out[int(m.group(1))] = sibling
    return out


def spike_mask_intervals(
    events: List[Dict[str, Any]],
) -> List[tuple]:
    """Step intervals during which a ``step_time_spike`` anomaly was open.

    Returns ``[(open_step, resolve_step | None), ...]`` — a window whose
    step satisfies ``open_step <= step < resolve_step`` ran while the
    recorder's spike screen was tripped (the resolving window itself
    measured back under the threshold and stays unmasked — EXCEPT for a
    ``rebaselined`` resolution, where the resolving window was still at
    the elevated level so the interval extends one step past it; ``None``
    means the spike never resolved, masking to the end of the run). The
    shared source of truth for window-level anomaly masking:
    ``regress.stats`` excludes these windows from comparison samples, and
    the masking is surfaced as a ``masked_windows`` count so it is never
    silent.
    """
    out: List[tuple] = []
    open_step: Optional[int] = None
    for e in events:
        if (
            e.get("event") == "anomaly"
            and e.get("kind") == "step_time_spike"
            and open_step is None
        ):
            open_step = e.get("step")
        elif (
            e.get("event") == "anomaly_resolved"
            and e.get("kind") == "step_time_spike"
            and open_step is not None
        ):
            hi = e.get("step")
            if e.get("rebaselined") and hi is not None:
                hi = hi + 1
            out.append((open_step, hi))
            open_step = None
    if open_step is not None:
        out.append((open_step, None))
    return out


def step_in_spike(step: Optional[int], intervals: List[tuple]) -> bool:
    """True when ``step`` falls inside any open-spike interval."""
    if step is None:
        return False
    for lo, hi in intervals:
        if lo is not None and step >= lo and (hi is None or step < hi):
            return True
    return False


def parse_heartbeat_line(line: str) -> Optional[Dict[str, Any]]:
    """Decode one ``BENCHMARK_HEARTBEAT {json}`` stdout line (or None).

    The single shared parser: the collect script's grep/sed pipeline and
    the tests both anchor on the same ``MARKER + space + JSON`` shape this
    function accepts.
    """
    line = line.strip()
    if not line.startswith(HEARTBEAT_MARKER + " "):
        return None
    try:
        payload = json.loads(line[len(HEARTBEAT_MARKER) + 1:])
    except json.JSONDecodeError:
        return None
    return payload if isinstance(payload, dict) else None


def read_events(path: str) -> List[Dict[str, Any]]:
    """Load a telemetry JSONL file, tolerating a torn final line.

    A process killed mid-write legitimately leaves a truncated last line;
    every complete line before it is still a valid event. A malformed line
    anywhere *else* raises — that is corruption, not a crash artifact.
    """
    events: List[Dict[str, Any]] = []
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            events.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break  # torn tail from a mid-write kill
            raise
    return events


class TelemetryRecorder:
    """Streams run telemetry; tracks phase-time attribution for the result.

    Parameters
    ----------
    arm:
        Run slug — the same stem as the result filename, so
        ``result_<arm>.json`` and ``telemetry_<arm>.jsonl`` pair up.
    results_dir:
        Where the JSONL lands; ``None`` (bench.py in-process arms) keeps
        the recorder alive for phase accounting but writes no file.
    is_main:
        Only rank 0 writes the file and prints heartbeats; other ranks
        still track phases so their (unpublished) results stay coherent.
    heartbeat_every_sec:
        Minimum wall seconds between heartbeat lines. ``0`` prints one per
        step window (tests); the first window always prints one so even a
        run killed in its second window left a scrapeable line.
    tokens_per_step:
        Global tokens consumed per optimizer step — turns window step
        times into the cumulative tokens/sec the heartbeat advertises.
    meta:
        Run-identity dict echoed into ``run_meta`` and every heartbeat
        (strategy/world_size/seq_len/tier/... — what collect_results.sh
        needs to synthesize a partial result row).
    """

    def __init__(
        self,
        arm: str,
        *,
        results_dir: Optional[str] = None,
        is_main: bool = True,
        enabled: bool = True,
        heartbeat_every_sec: float = 30.0,
        tokens_per_step: int = 0,
        total_steps: int = 0,
        rank: int = 0,
        meta: Optional[Dict[str, Any]] = None,
    ):
        self.arm = arm
        self.is_main = is_main
        self.enabled = enabled
        self.rank = int(rank)
        self.heartbeat_every_sec = heartbeat_every_sec
        self.tokens_per_step = tokens_per_step
        self.total_steps = total_steps
        self.meta = dict(meta or {})
        self._t0 = time.perf_counter()
        self._phase: Optional[str] = None
        self._phase_t0 = self._t0
        self._phase_times: Dict[str, float] = {}
        self._file = None
        self._closed = False
        self._last_step: Optional[int] = None
        self._last_loss: Optional[float] = None
        self._last_hb_t: Optional[float] = None
        self._cum_tokens = 0
        self._cum_window_sec = 0.0
        self._window_dts: List[float] = []
        self._n_anomalies = 0
        self._nan_anomalies = 0
        self._last_hbm_peak_gib: Optional[float] = None
        # Streaming-data accounting (data/prefetch.py): cumulative wait
        # the loop spent starved for input vs the window wall it happened
        # in, plus the quarantine ledger total. None-gated: synthetic
        # runs never pass the fields, so their telemetry/heartbeat bytes
        # are unchanged.
        self._has_data_path = False
        self._cum_data_wait_sec = 0.0
        self._cum_data_window_sec = 0.0
        self._records_skipped: Optional[int] = None
        self._open_spike: Optional[int] = None  # step that opened the spike
        self._spike_dts: List[float] = []  # window dts while a spike is open
        self.path: Optional[str] = None
        # Rank 0 writes the canonical file; non-zero ranks of a multi-host
        # run stream their own rank-suffixed sibling (per-rank telemetry —
        # heartbeats stay rank-0-only below, the stdout scrape channel has
        # exactly one writer).
        writes_file = is_main or self.rank > 0
        if enabled and writes_file and results_dir:
            try:
                os.makedirs(results_dir, exist_ok=True)
                self.path = os.path.join(
                    results_dir, telemetry_filename(arm, rank=self.rank)
                )
                # buffering=1: line-buffered — each event line reaches the
                # OS as soon as it is written (the crash-resilience core).
                self._file = open(self.path, "w", buffering=1)
            except OSError as e:
                self._file = None
                print(f"WARNING: telemetry file unavailable: {e}",
                      file=sys.stderr)
        self._emit("run_meta", arm=arm, schema_version=SCHEMA_VERSION,
                   tokens_per_step=tokens_per_step, total_steps=total_steps,
                   **self.meta)
        # Backstop flushers for crash paths the loop's try/except never
        # sees (interpreter teardown, uncaught errors outside the loop).
        # The loop's own abort() remains the primary path and wins the
        # _closed race.
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._excepthook
        atexit.register(self._atexit_flush)

    # ------------------------------------------------------------------
    # Event plumbing
    # ------------------------------------------------------------------

    def _rel(self) -> float:
        return time.perf_counter() - self._t0

    def _emit(self, event: str, **fields: Any) -> None:
        if self._file is None:
            return
        rec = {"event": event, "ts": round(time.time(), 6),
               "rel": round(self._rel(), 6)}
        rec.update(fields)
        try:
            self._file.write(json.dumps(rec) + "\n")
        except (OSError, ValueError):
            pass  # telemetry must never fail the run

    def note(self, event: str, **fields: Any) -> None:
        """Emit one ad-hoc event into the JSONL stream.

        The public hook for loop-adjacent machinery (fault injection,
        checkpoint-save failures) that has something worth recording but
        no schema claim of its own. Same best-effort semantics as every
        other emit: a failed write never fails the run. Callers are
        bound by the same cadence discipline as step_window — sync
        boundaries only (graftcheck GC105).
        """
        self._emit(event, **fields)

    def note_resume(
        self, *, step: int, n_restarts: int, baseline_loss: Optional[float] = None,
        geometry_changed: bool = False,
        source_geometry: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Record that this run restored a checkpoint and continued.

        Emits a ``resume`` event and folds ``resumed``/``n_restarts``
        into the run-identity meta, so every subsequent heartbeat — and
        the final ``run_end``/``run_aborted`` summary — carries the
        stitch. A resumed run must never be mistakable for a clean
        baseline anywhere downstream (regress registry, partial rows).
        ``geometry_changed`` marks an elastic (cross-mesh) resume; the
        source mesh rides the event for the audit trail.
        """
        self.meta["resumed"] = True
        self.meta["n_restarts"] = int(n_restarts)
        if geometry_changed:
            self.meta["resume_geometry_changed"] = True
        self._emit(
            "resume", step=step, n_restarts=int(n_restarts),
            baseline_loss=(
                round(baseline_loss, 6)
                if baseline_loss is not None and math.isfinite(baseline_loss)
                else None
            ),
            geometry_changed=bool(geometry_changed),
            source_geometry=source_geometry,
        )

    # ------------------------------------------------------------------
    # Phases
    # ------------------------------------------------------------------

    @property
    def phase(self) -> Optional[str]:
        return self._phase

    def begin_phase(self, name: str) -> None:
        """End the current phase (if any) and begin ``name``.

        Phases are sequential and non-overlapping by construction, so
        their durations sum to the covered wall time — the property the
        telemetry_report attribution and the validate_results envelope
        both rely on.
        """
        if name not in PHASES:
            raise ValueError(f"unknown telemetry phase {name!r} "
                             f"(expected one of {PHASES})")
        now = time.perf_counter()
        if self._phase is not None:
            dur = now - self._phase_t0
            self._phase_times[self._phase] = (
                self._phase_times.get(self._phase, 0.0) + dur
            )
            self._emit("phase_end", phase=self._phase, dur_sec=round(dur, 6))
        self._phase = name
        self._phase_t0 = now
        self._emit("phase_begin", phase=name)

    def phase_times(self) -> Dict[str, float]:
        """Per-phase accumulated seconds, including the open phase so far."""
        out = dict(self._phase_times)
        if self._phase is not None:
            out[self._phase] = (
                out.get(self._phase, 0.0)
                + (time.perf_counter() - self._phase_t0)
            )
        return out

    def wall_time_total(self) -> float:
        return self._rel()

    @property
    def n_anomalies(self) -> int:
        return self._n_anomalies

    @property
    def n_unresolved_anomalies(self) -> int:
        return self._nan_anomalies + (1 if self._open_spike is not None else 0)

    # ------------------------------------------------------------------
    # Step windows (called at sync boundaries only)
    # ------------------------------------------------------------------

    @property
    def data_stall_frac(self) -> Optional[float]:
        """Fraction of the streamed windows' wall time spent starved for
        input so far (None on synthetic runs)."""
        if not self._has_data_path:
            return None
        if self._cum_data_window_sec <= 0:
            return 0.0
        return max(
            0.0,
            min(self._cum_data_wait_sec / self._cum_data_window_sec, 1.0),
        )

    def step_window(
        self,
        *,
        last_step: int,
        losses: List[float],
        window_mean_step_time_sec: float,
        data_wait_sec: Optional[float] = None,
        records_skipped: Optional[int] = None,
    ) -> None:
        """Record one synced window: per-window stats + anomaly screening.

        Call ONLY after the loop blocked on the window's last loss (the
        values are real, and the device is already fenced — no extra
        sync). Samples the allocator HBM high-water mark, updates the
        cumulative-throughput accounting, screens for NaN losses and
        step-time spikes, and prints a heartbeat when the interval is due.
        """
        n = len(losses)
        if n == 0:
            return
        self._last_step = last_step
        loss = losses[-1]
        self._last_loss = loss
        self._cum_tokens += n * self.tokens_per_step
        self._cum_window_sec += n * window_mean_step_time_sec
        tps = (self._cum_tokens / self._cum_window_sec
               if self._cum_window_sec > 0 else 0.0)
        hbm = None
        hbm_now = None
        try:
            from ..utils.metrics import hbm_bytes_in_use, peak_hbm_bytes

            hbm = peak_hbm_bytes()
            hbm_now = hbm_bytes_in_use()
        except Exception:
            pass
        if hbm is not None:
            # Live high-water mark for the heartbeat channel (memory
            # anatomy round): the liveness probe surfaces memory
            # pressure mid-run instead of only post-mortem.
            self._last_hbm_peak_gib = round(hbm / 2**30, 3)
        # Streaming-data fields (additive, stream runs only): the
        # per-window input-starvation wait and the quarantine total make
        # the stall timeline reconstructible from the JSONL alone.
        data_fields: Dict[str, Any] = {}
        if data_wait_sec is not None:
            self._has_data_path = True
            self._cum_data_wait_sec += max(data_wait_sec, 0.0)
            self._cum_data_window_sec += n * window_mean_step_time_sec
            data_fields["data_wait_sec"] = round(data_wait_sec, 6)
        if records_skipped is not None:
            self._has_data_path = True
            self._records_skipped = int(records_skipped)
            data_fields["records_skipped"] = int(records_skipped)
        self._emit(
            "step_window",
            step=last_step,
            steps_in_window=n,
            # Non-finite -> null: json.dumps would otherwise write the
            # non-spec NaN/Infinity tokens and break strict consumers.
            loss=round(loss, 6) if math.isfinite(loss) else None,
            window_mean_step_time_sec=round(window_mean_step_time_sec, 6),
            cum_tokens=self._cum_tokens,
            tokens_per_sec=round(tps, 3),
            peak_hbm_bytes=hbm,
            hbm_bytes_in_use=hbm_now,
            phase=self._phase,
            **data_fields,
        )
        self._screen_anomalies(last_step, losses, window_mean_step_time_sec)
        self._heartbeat(last_step, loss, tps, window_mean_step_time_sec)

    def _screen_anomalies(
        self, last_step: int, losses: List[float], dt: float
    ) -> None:
        for l in losses:
            if l != l or math.isinf(l):
                self._n_anomalies += 1
                self._nan_anomalies += 1
                self._emit("anomaly", kind="nan_loss", step=last_step,
                           detail="non-finite loss in window")
                break  # one nan event per window is signal enough
        history = self._window_dts
        if len(history) >= SPIKE_MIN_HISTORY:
            med = sorted(history)[len(history) // 2]
            if self._open_spike is None and dt > SPIKE_FACTOR * med:
                self._n_anomalies += 1
                self._open_spike = last_step
                self._spike_dts = [dt]
                self._emit(
                    "anomaly", kind="step_time_spike", step=last_step,
                    detail=(f"window mean {dt:.4f}s > {SPIKE_FACTOR}x "
                            f"median {med:.4f}s"),
                )
            elif self._open_spike is not None:
                if dt <= SPIKE_RESOLVE_FACTOR * med:
                    self._emit("anomaly_resolved", kind="step_time_spike",
                               step=last_step,
                               opened_at_step=self._open_spike)
                    self._open_spike = None
                else:
                    self._spike_dts.append(dt)
                    if len(self._spike_dts) >= SPIKE_REBASELINE_WINDOWS:
                        # Sustained slowdown, not a stall: adopt the new
                        # level as the baseline so the run can still close
                        # with zero open anomalies (the published step-time
                        # stats carry the slowdown honestly either way).
                        self._emit(
                            "anomaly_resolved", kind="step_time_spike",
                            step=last_step,
                            opened_at_step=self._open_spike,
                            rebaselined=True,
                            detail=(f"rebaselined after "
                                    f"{len(self._spike_dts)} windows at "
                                    "the new level"),
                        )
                        self._open_spike = None
                        # The trailing append below re-adds this window.
                        self._window_dts = list(self._spike_dts[:-1])
        # Spike windows stay out of the history so one stall cannot drag
        # the median up and mask the next stall.
        if self._open_spike is None:
            self._window_dts.append(dt)

    def _heartbeat(self, step: int, loss: float, tps: float, dt: float) -> None:
        if not (self.enabled and self.is_main):
            return
        now = time.perf_counter()
        if (self._last_hb_t is not None
                and now - self._last_hb_t < self.heartbeat_every_sec):
            return
        self._last_hb_t = now
        payload = {
            "arm": self.arm,
            "step": step,
            "total_steps": self.total_steps,
            "loss": round(loss, 4) if math.isfinite(loss) else None,
            "tokens_per_sec": round(tps, 1),
            "window_mean_step_time_sec": round(dt, 4),
            "phase": self._phase,
            "ts": round(time.time(), 3),
        }
        if self._last_hbm_peak_gib is not None:
            # Live memory pressure in the scrape channel (memory-anatomy
            # round): scripts/liveness_probe.sh surfaces it mid-run.
            payload["hbm_peak_gib"] = self._last_hbm_peak_gib
        if self._has_data_path:
            # Streaming-data pressure in the scrape channel: an
            # input-bound run is visible mid-run, and a salvaged partial
            # row carries the honest stall/skip accounting.
            payload["data_stall_frac"] = round(self.data_stall_frac or 0.0, 4)
            payload["records_skipped"] = self._records_skipped or 0
        payload.update(self.meta)
        # flush=True: heartbeats must reach a pipe/pod log immediately —
        # a block-buffered stdout would hold them hostage past a SIGKILL.
        print(f"{HEARTBEAT_MARKER} {json.dumps(payload)}", flush=True)

    def emergency_heartbeat(
        self, *, reason: str, extra: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Print one final heartbeat NOW, ignoring the cadence.

        The preemption path's last word on stdout: carries ``reason``
        (e.g. ``preempted``) plus whatever the emergency stop knows
        (``emergency_checkpoint_step``), so collect_results.sh stamps the
        salvaged partial row from the emergency checkpoint's metadata
        rather than an older cadenced heartbeat.
        """
        if not (self.enabled and self.is_main):
            return
        self._last_hb_t = time.perf_counter()
        loss = self._last_loss
        payload = {
            "arm": self.arm,
            "step": self._last_step,
            "total_steps": self.total_steps,
            "loss": (round(loss, 4)
                     if loss is not None and math.isfinite(loss) else None),
            "tokens_per_sec": round(
                self._cum_tokens / self._cum_window_sec
                if self._cum_window_sec > 0 else 0.0, 1),
            "phase": self._phase,
            "reason": reason,
            "ts": round(time.time(), 3),
        }
        if self._last_hbm_peak_gib is not None:
            payload["hbm_peak_gib"] = self._last_hbm_peak_gib
        if self._has_data_path:
            payload["data_stall_frac"] = round(self.data_stall_frac or 0.0, 4)
            payload["records_skipped"] = self._records_skipped or 0
        payload.update(self.meta)
        payload.update(extra or {})
        print(f"{HEARTBEAT_MARKER} {json.dumps(payload)}", flush=True)

    # ------------------------------------------------------------------
    # Shutdown
    # ------------------------------------------------------------------

    def _summary_fields(self) -> Dict[str, Any]:
        fields = {
            "last_step": self._last_step,
            "phase": self._phase,
            "phase_times": {k: round(v, 6)
                            for k, v in self.phase_times().items()},
            "wall_time_total_sec": round(self.wall_time_total(), 6),
            "n_anomalies": self._n_anomalies,
            "n_unresolved_anomalies": self.n_unresolved_anomalies,
        }
        if self._has_data_path:
            # Streaming-data runs carry the input-path accounting into
            # the terminal event too: a JSONL alone (no result row) still
            # shows whether the run was input-bound or healed records.
            fields["data_stall_frac"] = round(self.data_stall_frac or 0.0, 6)
            fields["records_skipped"] = self._records_skipped or 0
        if self.meta.get("resumed"):
            # Stitched runs carry their accounting into the terminal
            # event too, so a JSONL alone (no result row) still shows
            # the run was not a clean single-attempt measurement.
            fields["resumed"] = True
            fields["n_restarts"] = self.meta.get("n_restarts", 1)
            if self.meta.get("resume_geometry_changed"):
                fields["resume_geometry_changed"] = True
        return fields

    def discard(self) -> None:
        """Close WITHOUT a terminal event and delete the JSONL. Idempotent.

        For refusal paths that must leave no trail: opening the recorder
        truncated ``telemetry_<arm>.jsonl``, so a refused re-invocation
        (e.g. a resume with nothing left to run) would otherwise replace
        a completed run's telemetry with a ``run_aborted`` stub — and the
        validator would then reject the completed run's published row as
        "crashed". Only sane before any step windows were recorded.
        """
        if self._closed:
            return
        self._closed = True
        path = self.path
        self._teardown()
        if path is not None:
            try:
                os.remove(path)
            except OSError:
                pass

    def abort(self, reason: str) -> None:
        """Emit ``run_aborted`` and release the hooks. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._emit("run_aborted", reason=reason, **self._summary_fields())
        self._teardown()

    def close(self, status: str = "ok") -> Dict[str, float]:
        """End the open phase, emit ``run_end``, return the phase times."""
        if self._closed:
            return dict(self._phase_times)
        now = time.perf_counter()
        if self._phase is not None:
            dur = now - self._phase_t0
            self._phase_times[self._phase] = (
                self._phase_times.get(self._phase, 0.0) + dur
            )
            self._emit("phase_end", phase=self._phase, dur_sec=round(dur, 6))
            self._phase = None
        self._closed = True
        self._emit("run_end", status=status, **self._summary_fields())
        self._teardown()
        return dict(self._phase_times)

    def _teardown(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        if sys.excepthook is self._excepthook:
            sys.excepthook = self._prev_excepthook
        try:
            atexit.unregister(self._atexit_flush)
        except Exception:
            pass

    def _excepthook(self, etype, value, tb) -> None:
        self.abort(f"exception:{etype.__name__}: {value}")
        self._prev_excepthook(etype, value, tb)

    def _atexit_flush(self) -> None:
        # Reached only when neither close() nor abort() ran (e.g. a
        # sys.exit mid-run): record that the run ended without a verdict.
        try:
            self.abort("atexit:process exited before run_end")
        except Exception:
            pass
