"""Flight-recorder telemetry: streaming per-step JSONL + heartbeat markers.

The result-marker protocol (utils.metrics) only speaks AFTER a successful
run — a pod that hangs, OOMs or is preempted at step 173/200 leaves nothing
in ``kubectl logs`` for ``scripts/collect_results.sh`` to scrape, and a
single wall-clock number never explains where a run's time went. This
package is the in-flight channel (docs/OBSERVABILITY.md):

- :class:`TelemetryRecorder` streams structured JSONL events (``run_meta``,
  ``phase_begin``/``phase_end``, ``step_window``, ``anomaly``,
  ``run_aborted``, ``run_end``) to ``<results_dir>/telemetry_<arm>.jsonl``
  with line-buffered writes, so a killed process keeps every event up to
  its last sync boundary;
- periodic single-line ``BENCHMARK_HEARTBEAT {json}`` markers on stdout
  (rank 0, sync boundaries only — never a device sync inside a timed
  window) make partial progress recoverable from pod logs alone;
- an excepthook/atexit flusher emits a final ``run_aborted`` event with
  the phase and last step on any crash the process survives long enough
  to report.

Consumed by ``analysis.telemetry_report`` (timeline + phase attribution)
and ``analysis.validate_results`` (anomaly/phase envelopes).
"""

from .recorder import (  # noqa: F401
    HEARTBEAT_MARKER,
    PHASES,
    SCHEMA_VERSION,
    TelemetryRecorder,
    is_rank_sibling,
    parse_heartbeat_line,
    rank_telemetry_files,
    read_events,
    spike_mask_intervals,
    step_in_spike,
    telemetry_filename,
)
