"""Ring attention — sequence/context parallelism over a mesh axis.

Nothing like this exists in the reference (SURVEY §5.7: longest tested
sequence is 2048, no sequence parallelism anywhere); it is a first-class
capability here because long-context is where TPU ICI topology shines.

Mechanism: with the sequence dimension sharded over the mesh axis ``seq``,
each device keeps its local Q block resident and the K/V blocks *rotate*
around the ring via ``ppermute`` — after N-1 hops every device has attended
its queries to every key. Online-softmax statistics (running max / running
sum) merge each incoming block, so the full (S, S) score matrix never exists
anywhere and per-device attention memory is O(S_local * S_local). Communication
rides neighbor-to-neighbor ICI links — exactly the topology ppermute maps to.

Usable two ways:
- ``ring_attention(q, k, v)`` inside a jitted function running under a mesh
  that has a ``seq`` axis (it shard_maps itself over that axis);
- ``ring_attention_sharded`` directly inside an existing ``shard_map``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _block_attend(
    q, k, v, q_off, k_off, causal,
    bh0=None, dropout_rate=0.0, dropout_seed=None,
):
    """One (local-Q x one-KV-block) pass -> (scores-exp sum stats, weighted V).

    Returns (m, l, o): running-max (Sq,H,1), exp-sum (Sq,H,1), accumulator
    (Sq,H,D) for this block alone, with global-position causal masking.

    Attention-probability dropout uses the SAME absolute-coordinate hash as
    the flash kernel (flash_attention._dropout_keep) keyed by global
    (batch*head, row, col): with equal seeds, ring and flash produce
    bitwise-identical keep masks regardless of how the ring shards the
    sequence. The exp-sum ``l`` accumulates the un-dropped probabilities
    (dropout acts after normalization; normalization is linear), the
    accumulator sees the dropped+rescaled ones.
    """
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("qhd,khd->hqk", q, k, preferred_element_type=jnp.float32) * scale
    Sq, Sk = q.shape[0], k.shape[0]
    rows = q_off + lax.broadcasted_iota(jnp.int32, (Sq, 1), 0)
    cols = k_off + lax.broadcasted_iota(jnp.int32, (1, Sk), 1)
    if causal:
        s = jnp.where((rows >= cols)[None, :, :], s, NEG_INF)
    m = jnp.max(s, axis=-1)                          # (H, Sq)
    p = jnp.exp(s - m[..., None])                    # (H, Sq, Sk)
    if causal:
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)                          # (H, Sq)
    if dropout_rate > 0.0 and dropout_seed is not None:
        from .flash_attention import _dropout_keep, _dropout_threshold

        H = q.shape[1]
        bh = (bh0 + jnp.arange(H))[:, None, None]    # (H, 1, 1)
        keep = _dropout_keep(
            dropout_seed, bh, rows[None], cols[None],
            _dropout_threshold(dropout_rate),
        )                                            # (H, Sq, Sk)
        p_acc = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
    else:
        p_acc = p
    o = jnp.einsum("hqk,khd->qhd", p_acc, v.astype(jnp.float32))
    return m, l, o


def ring_attention_sharded(
    q: jax.Array,  # (B, S_local, H, D) — this device's sequence shard
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = False,
    dropout_rate: float = 0.0,
    dropout_seed: Optional[jax.Array] = None,
    batch_axis: Optional[str] = None,
    heads_axis: Optional[str] = None,
) -> jax.Array:
    """Ring attention body; call inside shard_map with seq sharded on axis_name.

    ``batch_axis``/``heads_axis`` name the mesh axes (if any) the batch and
    head dims are sharded over, so dropout-mask coordinates are GLOBAL
    (batch, head) indices — without them, same-local-index examples on
    different data shards would share masks.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Sl, H, D = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]
    if dropout_seed is None:
        from .flash_attention import _warn_seedless_dropout

        _warn_seedless_dropout(dropout_rate, "ring_attention_sharded")
        dropout_rate = 0.0
    b_off = lax.axis_index(batch_axis) * B if batch_axis else 0
    h_off = lax.axis_index(heads_axis) * H if heads_axis else 0
    n_heads = H * (lax.axis_size(heads_axis) if heads_axis else 1)

    def one_batch(qb, kb, vb, bidx):
        q_off = my * Sl
        # n is a static mesh-axis size, so the ring unrolls as a Python loop:
        # no permute is issued after the final block (the rotated K/V would be
        # discarded), saving one neighbor exchange per call.
        m_run = jnp.full((H, Sl), NEG_INF, jnp.float32)
        l_run = jnp.zeros((H, Sl), jnp.float32)
        o_run = jnp.zeros((Sl, H, D), jnp.float32)
        k_cur, v_cur = kb, vb
        for t in range(n):
            # After t forward hops the resident block originated on (my - t) % n.
            src = (my - t) % n
            m_b, l_b, o_b = _block_attend(
                qb, k_cur, v_cur, q_off, src * Sl, causal,
                # global (batch*heads) base: matches flash's b*H + h keying
                bh0=(b_off + bidx) * n_heads + h_off,
                dropout_rate=dropout_rate, dropout_seed=dropout_seed,
            )
            # Merge online-softmax statistics (m_*: (H,Sq), o_*: (Sq,H,D)).
            m_new = jnp.maximum(m_run, m_b)
            a_run = jnp.exp(m_run - m_new)
            a_b = jnp.exp(m_b - m_new)
            l_run = l_run * a_run + l_b * a_b
            o_run = (
                o_run * a_run.transpose(1, 0)[:, :, None]
                + o_b * a_b.transpose(1, 0)[:, :, None]
            )
            m_run = m_new
            if t < n - 1:
                k_cur = lax.ppermute(k_cur, axis_name, perm)
                v_cur = lax.ppermute(v_cur, axis_name, perm)
        l_f = jnp.where(l_run == 0.0, 1.0, l_run)
        return (o_run / l_f.transpose(1, 0)[:, :, None]).astype(qb.dtype)

    return jax.vmap(one_batch)(q, k, v, jnp.arange(B))


def ring_attention(
    q: jax.Array,  # (B, S, H, D) — full (mesh-visible) arrays
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    axis_name: str = "seq",
    mesh: Optional[jax.sharding.Mesh] = None,
    dropout_rate: float = 0.0,
    dropout_seed: Optional[jax.Array] = None,
) -> jax.Array:
    """Shard the sequence over ``axis_name`` and run the ring. Falls back to
    flash attention when no such mesh axis is in scope (so models configured
    with attention_impl='ring' still run on a plain data mesh).

    Attention-probability dropout (``dropout_rate`` + uint32 ``dropout_seed``)
    uses the flash kernel's global-coordinate hash: for equal seeds the mask
    is identical to flash's, independent of the ring's sequence sharding.
    """
    if mesh is None:
        m = jax.sharding.get_abstract_mesh()
        mesh = m if m is not None and axis_name in getattr(m, "axis_names", ()) else None
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        from .flash_attention import flash_attention

        return flash_attention(
            q, k, v, causal=causal,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )

    # Compose with whatever other parallelism the mesh carries: batch stays
    # sharded on 'data', heads stay sharded on 'model' (tensor parallel) —
    # the ring only ever communicates along the 'seq' axis.
    batch_ax = "data" if mesh.shape.get("data", 1) > 1 else None
    model_ax = "model" if mesh.shape.get("model", 1) > 1 else None
    spec = P(batch_ax, axis_name, model_ax, None)
    if dropout_seed is None:
        from .flash_attention import _warn_seedless_dropout

        _warn_seedless_dropout(dropout_rate, "ring_attention")
        seed = jnp.zeros((), jnp.uint32)
        dropout_rate = 0.0
    else:
        seed = jnp.asarray(dropout_seed, jnp.uint32).reshape(())
    def body(qs, ks, vs, seed_s):
        return ring_attention_sharded(
            qs, ks, vs, axis_name=axis_name, causal=causal,
            dropout_rate=dropout_rate, dropout_seed=seed_s,
            batch_axis=batch_ax, heads_axis=model_ax,
        )

    fn = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec, P()), out_specs=spec
    )
    return fn(q, k, v, seed)
