"""Ring attention — sequence/context parallelism over a mesh axis.

Nothing like this exists in the reference (SURVEY §5.7: longest tested
sequence is 2048, no sequence parallelism anywhere); it is a first-class
capability here because long-context is where TPU ICI topology shines.

Mechanism: with the sequence dimension sharded over the mesh axis ``seq``,
each device keeps its local Q block resident and the K/V blocks *rotate*
around the ring via ``ppermute`` — after N-1 hops every device has attended
its queries to every key. Online-softmax statistics (running max / running
sum) merge each incoming block, so the full (S, S) score matrix never exists
anywhere and per-device attention memory is O(S_local * S_local). Communication
rides neighbor-to-neighbor ICI links — exactly the topology ppermute maps to.

Usable two ways:
- ``ring_attention(q, k, v)`` inside a jitted function running under a mesh
  that has a ``seq`` axis (it shard_maps itself over that axis);
- ``ring_attention_sharded`` directly inside an existing ``shard_map``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, q_off, k_off, causal):
    """One (local-Q x one-KV-block) pass -> (scores-exp sum stats, weighted V).

    Returns (m, l, o): running-max (Sq,H,1), exp-sum (Sq,H,1), accumulator
    (Sq,H,D) for this block alone, with global-position causal masking.
    """
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("qhd,khd->hqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        Sq, Sk = q.shape[0], k.shape[0]
        rows = q_off + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        cols = k_off + lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where((rows >= cols)[None, :, :], s, NEG_INF)
    m = jnp.max(s, axis=-1)                          # (H, Sq)
    p = jnp.exp(s - m[..., None])                    # (H, Sq, Sk)
    if causal:
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)                          # (H, Sq)
    o = jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32))
    return m, l, o


def ring_attention_sharded(
    q: jax.Array,  # (B, S_local, H, D) — this device's sequence shard
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = False,
) -> jax.Array:
    """Ring attention body; call inside shard_map with seq sharded on axis_name."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Sl, H, D = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    def one_batch(qb, kb, vb):
        q_off = my * Sl
        # n is a static mesh-axis size, so the ring unrolls as a Python loop:
        # no permute is issued after the final block (the rotated K/V would be
        # discarded), saving one neighbor exchange per call.
        m_run = jnp.full((H, Sl), NEG_INF, jnp.float32)
        l_run = jnp.zeros((H, Sl), jnp.float32)
        o_run = jnp.zeros((Sl, H, D), jnp.float32)
        k_cur, v_cur = kb, vb
        for t in range(n):
            # After t forward hops the resident block originated on (my - t) % n.
            src = (my - t) % n
            m_b, l_b, o_b = _block_attend(qb, k_cur, v_cur, q_off, src * Sl, causal)
            # Merge online-softmax statistics (m_*: (H,Sq), o_*: (Sq,H,D)).
            m_new = jnp.maximum(m_run, m_b)
            a_run = jnp.exp(m_run - m_new)
            a_b = jnp.exp(m_b - m_new)
            l_run = l_run * a_run + l_b * a_b
            o_run = (
                o_run * a_run.transpose(1, 0)[:, :, None]
                + o_b * a_b.transpose(1, 0)[:, :, None]
            )
            m_run = m_new
            if t < n - 1:
                k_cur = lax.ppermute(k_cur, axis_name, perm)
                v_cur = lax.ppermute(v_cur, axis_name, perm)
        l_f = jnp.where(l_run == 0.0, 1.0, l_run)
        return (o_run / l_f.transpose(1, 0)[:, :, None]).astype(qb.dtype)

    return jax.vmap(one_batch)(q, k, v)


def ring_attention(
    q: jax.Array,  # (B, S, H, D) — full (mesh-visible) arrays
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    axis_name: str = "seq",
    mesh: Optional[jax.sharding.Mesh] = None,
) -> jax.Array:
    """Shard the sequence over ``axis_name`` and run the ring. Falls back to
    flash attention when no such mesh axis is in scope (so models configured
    with attention_impl='ring' still run on a plain data mesh)."""
    if mesh is None:
        m = jax.sharding.get_abstract_mesh()
        mesh = m if m is not None and axis_name in getattr(m, "axis_names", ()) else None
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        from .flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)

    # Compose with whatever other parallelism the mesh carries: batch stays
    # sharded on 'data', heads stay sharded on 'model' (tensor parallel) —
    # the ring only ever communicates along the 'seq' axis.
    batch_ax = "data" if mesh.shape.get("data", 1) > 1 else None
    model_ax = "model" if mesh.shape.get("model", 1) > 1 else None
    spec = P(batch_ax, axis_name, model_ax, None)
    fn = jax.shard_map(
        functools.partial(ring_attention_sharded, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
