"""Ring attention — sequence/context parallelism over a mesh axis.

Nothing like this exists in the reference (SURVEY §5.7: longest tested
sequence is 2048, no sequence parallelism anywhere); it is a first-class
capability here because long-context is where TPU ICI topology shines.

Mechanism: with the sequence dimension sharded over the mesh axis ``seq``,
each device keeps its local Q block resident and the K/V blocks *rotate*
around the ring via ``ppermute`` — after N-1 hops every device has attended
its queries to every key. Online-softmax statistics (running max / running
sum) merge each incoming block, so the full (S, S) score matrix never exists
anywhere and per-device attention memory is O(S_local * S_local). Communication
rides neighbor-to-neighbor ICI links — exactly the topology ppermute maps to.

Per-hop block compute is the SAME Pallas flash kernel machinery as
``flash_attention`` — a variant that takes global (row, col) offsets from
SMEM and emits the *unnormalized* online-softmax triple (m, l, o) instead of
a normalized output, so the ring merge happens outside the kernel while the
(S_local, S_local) score tile still never leaves VMEM. Measured single-chip
at the parity config, the kernel is ~2x the einsum path the ring used
before (flash 42.0k vs materialized-path 19.5k tok/s/chip —
docs/PERFORMANCE.md), and that per-block gap is what multi-chip sequence
parallelism inherits.

The backward is a second ring pass (Liu et al. 2023, "Ring Attention with
Blockwise Transformers"): each device recomputes its block's attention
probabilities from the saved GLOBAL logsumexp (standard flash backward
identity), accumulates dq locally, and rotates (k, v, dk, dv) around the
ring so after N hops every block's dk/dv arrive back at their home device
fully accumulated. Per-block compute follows the measured S-dependent
backward crossover (docs/PERFORMANCE.md §11-12): XLA-fused blockwise
einsum tiles for S_local < 4096, offset-aware Pallas dq / dk+dv kernels
(1.6-2.1x per block) from 4096 up — the regime multi-chip sequence
parallelism actually runs in.

Attention-probability dropout uses the flash kernel's absolute-coordinate
hash (``flash_attention._dropout_keep``) keyed by global (batch*head, row,
col): with equal seeds, ring and flash produce bitwise-identical keep masks
regardless of how the ring shards the sequence, and the ring backward
regenerates the same mask from coordinates alone.

Usable two ways:
- ``ring_attention(q, k, v)`` inside a jitted function running under a mesh
  that has a ``seq`` axis (it shard_maps itself over that axis);
- ``ring_attention_sharded`` directly inside an existing ``shard_map``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from .flash_attention import (
    _bwd_dq_kernel,
    _bwd_dkv_kernel,
    _dropout_keep,
    _dropout_threshold,
    _pick_block,
    _vma_struct,
    _warn_seedless_dropout,
    _FWD_BLOCK_Q,
    _FWD_BLOCK_K,
    _BWD_BLOCK_K,
    _PALLAS_BWD_MIN_SEQ,
)

NEG_INF = -1e30


def _ring_fwd_block_kernel(
    seed_ref, qoff_ref, koff_ref, bhv_ref, q_ref, k_ref, v_ref,
    m_ref, l_ref, o_ref, m_scr, l_scr, acc_scr,
    *, bq: int, bk: int, scale: float, causal: bool, dropout_rate: float,
):
    """Flash forward tile pass emitting UNNORMALIZED (m, l, o) for one ring
    block: identical math to ``flash_attention._flash_fwd_kernel`` except
    (a) row/col coordinates come from per-TILE global base vectors in SMEM
    (``qoff_ref`` (nq,) / ``koff_ref`` (nk,) — shard offset + arange for
    contiguous ring blocks, per-half-chunk bases for the zigzag layout) so
    causal masking and the dropout hash see absolute coordinates, (b) the
    per-grid-row global batch*head index comes from the SMEM vector
    ``bhv_ref`` (data/tensor-parallel shards feed their global offsets in),
    and (c) no normalization — the ring merge outside combines blocks,
    exactly like the kernel's own k-block accumulation combines tiles."""
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    q_off = qoff_ref[qi]
    k_off = koff_ref[ki]

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal skip by GLOBAL position: a k tile strictly above the diagonal
    # contributes nothing. With ring offsets this also skips every tile of a
    # block that sits entirely in this Q shard's future.
    live = True if not causal else (q_off + bq - 1 >= k_off)

    @pl.when(live)
    def _accumulate():
        q = q_ref[0]  # (bq, d) input dtype
        k = k_ref[0]  # (bk, d)
        v = v_ref[0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (bq, bk) fp32

        rows = q_off + lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
        cols = k_off + lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        if causal:
            mask = rows >= cols
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)

        # Normalizer accumulates UN-dropped p (dropout acts after
        # normalization; normalization is linear); the output accumulator
        # sees the dropped+rescaled p — same convention as the flash kernel.
        if dropout_rate > 0.0:
            keep = _dropout_keep(
                seed_ref[0], bhv_ref[bh], rows, cols,
                _dropout_threshold(dropout_rate),
            )
            p_acc = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        else:
            p_acc = p

        l_prev = l_scr[:, :1]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + lax.dot_general(
            p_acc.astype(q.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        # Stats are logically (bq,); stored sublane-broadcast as (8, bq)
        # because TPU output blocks must tile to (8, 128). o stays fp32 and
        # unnormalized — the ring merge divides once at the very end.
        m_ref[0] = jnp.broadcast_to(m_scr[:, :1].T, (8, bq))
        l_ref[0] = jnp.broadcast_to(l_scr[:, :1].T, (8, bq))
        o_ref[0] = acc_scr[:]


def _block_stats_kernel(
    q3, k3, v3, seed, qoffs, koffs, bh_vec,
    causal: bool, dropout_rate: float, bq: int, bk: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pallas path: (BH, Sq, D) x (BH, Sk, D) -> m, l (BH, Sq) f32 and
    unnormalized o (BH, Sq, D) f32. ``qoffs``/``koffs`` are per-tile global
    base vectors ((Sq//bq,) / (Sk//bk,) int32)."""
    BH, Sq, D = q3.shape
    Sk = k3.shape[1]
    scale = 1.0 / (D ** 0.5)
    m, l, o = pl.pallas_call(
        functools.partial(
            _ring_fwd_block_kernel, bq=bq, bk=bk, scale=scale,
            causal=causal, dropout_rate=dropout_rate,
        ),
        out_shape=[
            _vma_struct((BH, 8, Sq), jnp.float32, q3, k3, v3),
            _vma_struct((BH, 8, Sq), jnp.float32, q3, k3, v3),
            _vma_struct((BH, Sq, D), jnp.float32, q3, k3, v3),
        ],
        grid=(BH, Sq // bq, Sk // bk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # seed (1,) uint32
            pl.BlockSpec(memory_space=pltpu.SMEM),  # q tile bases (nq,)
            pl.BlockSpec(memory_space=pltpu.SMEM),  # k tile bases (nk,)
            pl.BlockSpec(memory_space=pltpu.SMEM),  # global bh ids (BH,)
            pl.BlockSpec((1, bq, D), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, bk, D), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, bk, D), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 8, bq), lambda b, qi, ki: (b, 0, qi)),
            pl.BlockSpec((1, 8, bq), lambda b, qi, ki: (b, 0, qi)),
            pl.BlockSpec((1, bq, D), lambda b, qi, ki: (b, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),  # running max
            pltpu.VMEM((bq, 128), jnp.float32),  # running sum
            pltpu.VMEM((bq, D), jnp.float32),    # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(seed, qoffs, koffs, bh_vec, q3, k3, v3)
    return m[:, 0, :], l[:, 0, :], o


def _block_bwd_kernel(
    q3, k_b, v_b, do3, lse, delta, seed, qoffs, koffs, bh_vec,
    causal: bool, dropout_rate: float, bq: int, bk: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pallas path for one resident ring block's backward ->
    (dq_partial, dk_b, dv_b), all fp32 (BH, Sl, D). Runs the SHARED
    offset-aware flash backward kernels (flash_attention._bwd_dq_kernel /
    _bwd_dkv_kernel) with this block's global offsets and batch*head
    indices in SMEM — one kernel implementation serves flash and ring."""
    BH, Sq, D = q3.shape
    Sk = k_b.shape[1]
    scale = 1.0 / (D ** 0.5)
    lse3 = jnp.broadcast_to(lse[:, None, :], (BH, 8, Sq))
    delta3 = jnp.broadcast_to(delta[:, None, :], (BH, 8, Sq))
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    row = dict(
        q=pl.BlockSpec((1, bq, D), lambda b, qi, ki: (b, qi, 0)),
        k=pl.BlockSpec((1, bk, D), lambda b, qi, ki: (b, ki, 0)),
        stat=pl.BlockSpec((1, 8, bq), lambda b, qi, ki: (b, 0, qi)),
    )
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, bq=bq, bk=bk, scale=scale, causal=causal,
            dropout_rate=dropout_rate,
        ),
        out_shape=_vma_struct((BH, Sq, D), jnp.float32, q3, k_b, v_b, do3),
        grid=(BH, Sq // bq, Sk // bk),
        in_specs=[smem, smem, smem, smem, row["q"], row["k"], row["k"],
                  row["q"], row["stat"], row["stat"]],
        out_specs=row["q"],
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(seed, qoffs, koffs, bh_vec, q3, k_b, v_b, do3, lse3, delta3)

    col = dict(
        q=pl.BlockSpec((1, bq, D), lambda b, ki, qi: (b, qi, 0)),
        k=pl.BlockSpec((1, bk, D), lambda b, ki, qi: (b, ki, 0)),
        stat=pl.BlockSpec((1, 8, bq), lambda b, ki, qi: (b, 0, qi)),
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, bq=bq, bk=bk, scale=scale, causal=causal,
            dropout_rate=dropout_rate,
        ),
        out_shape=[
            _vma_struct((BH, Sk, D), jnp.float32, q3, k_b, v_b, do3),
            _vma_struct((BH, Sk, D), jnp.float32, q3, k_b, v_b, do3),
        ],
        grid=(BH, Sk // bk, Sq // bq),
        in_specs=[smem, smem, smem, smem, col["q"], col["k"], col["k"],
                  col["q"], col["stat"], col["stat"]],
        out_specs=[col["k"], col["k"]],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(seed, qoffs, koffs, bh_vec, q3, k_b, v_b, do3, lse3, delta3)
    return dq, dk, dv


def _block_stats_jnp(
    q3, k3, v3, seed, row_idx, col_idx, bh_vec,
    causal: bool, dropout_rate: float,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Einsum path with the kernel's exact semantics, for backends where the
    Pallas interpreter cannot run inside vma-carrying manual regions (the
    CPU test meshes — same limitation flash_attention._jnp_reference_forward
    covers). ``row_idx``/``col_idx`` are per-row GLOBAL index vectors
    ((Sq,) / (Sk,) int32) — contiguous or zigzag."""
    BH, Sq, D = q3.shape
    Sk = k3.shape[1]
    scale = 1.0 / (D ** 0.5)
    s = jnp.einsum(
        "bqd,bkd->bqk", q3, k3, preferred_element_type=jnp.float32
    ) * scale
    rows = row_idx.astype(jnp.int32)[:, None]
    cols = col_idx.astype(jnp.int32)[None, :]
    if causal:
        mask = (rows >= cols)[None]
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    if causal:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    if dropout_rate > 0.0:
        keep = _dropout_keep(
            seed[0], bh_vec[:, None, None], rows[None], cols[None],
            _dropout_threshold(dropout_rate),
        )
        p_acc = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
    else:
        p_acc = p
    o = jnp.einsum(
        "bqk,bkd->bqd", p_acc.astype(q3.dtype), v3,
        preferred_element_type=jnp.float32,
    )
    return m, l, o



def _zig_chunk_bases(c, n, h):
    """Global start rows of device ``c``'s two zigzag half-chunks: chunk c
    and chunk 2n-1-c (h tokens each). ``c`` may be traced."""
    return (c * h, (2 * n - 1 - c) * h)


def _bases_to_tiles(bases, h: int, b: int):
    """Per-tile global base vector from per-chunk bases (each chunk h rows,
    tile size b, b | h): concat over chunks of base + arange(h//b)*b."""
    per = h // b
    return jnp.concatenate([
        jnp.asarray(base, jnp.int32) + jnp.arange(per, dtype=jnp.int32) * b
        for base in bases
    ])


def _bases_to_rows(bases, h: int):
    """Per-row global index vector from per-chunk bases."""
    return jnp.concatenate([
        jnp.asarray(base, jnp.int32) + jnp.arange(h, dtype=jnp.int32)
        for base in bases
    ])


def _zig_exchange(x3, axis_name: str, n: int, my, inverse: bool = False):
    """Redistribute (BH, Sl, D) half-chunks between the contiguous layout
    (device c holds chunks 2c, 2c+1) and the zigzag layout (device c holds
    chunks c, 2n-1-c — Brandon et al. 2023 "striped"/zigzag causal load
    balancing): each device's triangular work becomes ~equal, so no ring
    hop waits on the last device's full diagonal. Two ppermutes each way
    (one per half), ~one extra hop-equivalent of traffic per exchange.
    """
    zig = lambda g: g if g < n else 2 * n - 1 - g
    h = x3.shape[1] // 2
    lo, hi = x3[:, :h], x3[:, h:]  # axis 1 = rows; trailing dims pass through
    even = (my % 2) == 0
    if not inverse:
        # contiguous -> zigzag: device c sends chunk 2c on ring A, chunk
        # 2c+1 on ring B; zigzag device d's low chunk (d) arrives on A iff
        # d is even, and its high chunk (2n-1-d) on the other.
        perm_a = [(c, zig(2 * c)) for c in range(n)]
        perm_b = [(c, zig(2 * c + 1)) for c in range(n)]
        recv_a = lax.ppermute(lo, axis_name, perm_a)
        recv_b = lax.ppermute(hi, axis_name, perm_b)
        new_lo = jnp.where(even, recv_a, recv_b)
        new_hi = jnp.where(even, recv_b, recv_a)
    else:
        # zigzag -> contiguous: ring A carries the EVEN global chunk each
        # device holds (its low chunk if the device index is even, else its
        # high chunk), ring B the odd one; contiguous device c receives
        # chunk 2c on A (its low half) and 2c+1 on B.
        perm_a = [(zig(2 * c), c) for c in range(n)]
        perm_b = [(zig(2 * c + 1), c) for c in range(n)]
        send_a = jnp.where(even, lo, hi)
        send_b = jnp.where(even, hi, lo)
        new_lo = lax.ppermute(send_a, axis_name, perm_a)
        new_hi = lax.ppermute(send_b, axis_name, perm_b)
    return jnp.concatenate([new_lo, new_hi], axis=1)


def _global_bh_vec(B: int, H: int, b_off, h_off, n_heads: int) -> jax.Array:
    """(B*H,) int32 of GLOBAL batch*heads indices — matches flash's b*H + h
    keying when batch/heads are themselves sharded over mesh axes."""
    return (
        (b_off + jnp.arange(B, dtype=jnp.int32))[:, None] * n_heads
        + h_off + jnp.arange(H, dtype=jnp.int32)[None, :]
    ).reshape(B * H)


def _ring_offsets(axis_name, batch_axis, heads_axis, B, H):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b_off = lax.axis_index(batch_axis) * B if batch_axis else 0
    h_off = lax.axis_index(heads_axis) * H if heads_axis else 0
    n_heads = H * (lax.axis_size(heads_axis) if heads_axis else 1)
    return n, my, b_off, h_off, n_heads


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ring(opts: Tuple, q, k, v, seed):
    out, _ = _ring_fwd(opts, q, k, v, seed)
    return out


def _ring_fwd(opts, q, k, v, seed):
    """Forward ring pass over (B, Sl, H, D) local shards -> normalized out
    plus the (BH, Sl) global logsumexp residual the backward needs."""
    (axis_name, causal, rate, batch_axis, heads_axis,
     interpret, bq, bk, bk_bwd, zig) = opts
    B, Sl, H, D = q.shape
    n, my, b_off, h_off, n_heads = _ring_offsets(
        axis_name, batch_axis, heads_axis, B, H
    )
    bh_vec = _global_bh_vec(B, H, b_off, h_off, n_heads)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def to3(t):  # (B, Sl, H, D) -> (B*H, Sl, D)
        return t.transpose(0, 2, 1, 3).reshape(B * H, Sl, D)

    q3, k3, v3 = to3(q), to3(k), to3(v)
    if zig:
        # Causal load balancing: redistribute to the zigzag layout so every
        # device's triangle work is ~equal (see _zig_exchange). Global
        # coordinates flow through the per-chunk base vectors, so masking
        # and dropout stay bit-identical to flash.
        q3 = _zig_exchange(q3, axis_name, n, my)
        k3 = _zig_exchange(k3, axis_name, n, my)
        v3 = _zig_exchange(v3, axis_name, n, my)
        h = Sl // 2
        q_bases = _zig_chunk_bases(my, n, h)
    else:
        h = Sl
        q_bases = (my * Sl,)
    m_run = jnp.full((B * H, Sl), NEG_INF, jnp.float32)
    l_run = jnp.zeros((B * H, Sl), jnp.float32)
    o_run = jnp.zeros((B * H, Sl, D), jnp.float32)
    k_cur, v_cur = k3, v3
    # n is a static mesh-axis size, so the ring unrolls as a Python loop: no
    # permute is issued after the final block (the rotated K/V would be
    # discarded), saving one neighbor exchange per call.
    for t in range(n):
        # After t forward hops the resident block originated on (my - t) % n.
        src = (my - t) % n
        k_bases = _zig_chunk_bases(src, n, h) if zig else (src * Sl,)
        if interpret:
            m_b, l_b, o_b = _block_stats_jnp(
                q3, k_cur, v_cur, seed, _bases_to_rows(q_bases, h),
                _bases_to_rows(k_bases, h), bh_vec, causal, rate,
            )
        else:
            m_b, l_b, o_b = _block_stats_kernel(
                q3, k_cur, v_cur, seed, _bases_to_tiles(q_bases, h, bq),
                _bases_to_tiles(k_bases, h, bk), bh_vec, causal,
                rate, bq, bk,
            )
        # Merge online-softmax statistics, exactly as the kernel merges its
        # own k tiles: rescale both accumulators to the joint max.
        m_new = jnp.maximum(m_run, m_b)
        a_run = jnp.exp(m_run - m_new)
        a_b = jnp.exp(m_b - m_new)
        l_run = l_run * a_run + l_b * a_b
        o_run = o_run * a_run[..., None] + o_b * a_b[..., None]
        m_run = m_new
        if t < n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    l_safe = jnp.where(l_run == 0.0, 1.0, l_run)
    out3 = (o_run / l_safe[..., None]).astype(q.dtype)
    lse = m_run + jnp.log(l_safe)  # (BH, Sl) fp32, zigzag-ordered when zig
    if zig:
        out3 = _zig_exchange(out3, axis_name, n, my, inverse=True)
    out = out3.reshape(B, H, Sl, D).transpose(0, 2, 1, 3)
    return out, (q, k, v, out, lse, seed)


def _ring_bwd(opts, res, do):
    """Backward ring pass: recompute per-block probabilities from the saved
    global logsumexp, accumulate dq locally, rotate (k, v, dk, dv) a full
    cycle so every block's dk/dv land home fully summed. Per-block compute
    follows the measured S-dependent crossover: einsum tiles below
    _PALLAS_BWD_MIN_SEQ-sized local shards, the shared offset-aware Pallas
    backward kernels from there up (docs/PERFORMANCE.md §11)."""
    (axis_name, causal, rate, batch_axis, heads_axis,
     interpret, bq, bk, bk_bwd, zig) = opts
    q, k, v, out, lse, seed = res
    B, Sl, H, D = q.shape
    n, my, b_off, h_off, n_heads = _ring_offsets(
        axis_name, batch_axis, heads_axis, B, H
    )
    bh_vec = _global_bh_vec(B, H, b_off, h_off, n_heads)
    perm = [(j, (j + 1) % n) for j in range(n)]
    f32 = jnp.float32
    cd = q.dtype
    scale = 1.0 / (D ** 0.5)
    import numpy as np

    from ..utils.vma import pcast_like

    def to3(t):
        return t.transpose(0, 2, 1, 3).reshape(B * H, Sl, D)

    q3, k3, v3, out3, do3 = to3(q), to3(k), to3(v), to3(out), to3(do)
    # delta is a per-row reduction, invariant to row reordering — compute
    # it in the contiguous layout and exchange the (BH, Sl) result, D times
    # cheaper than exchanging the full out3 activation.
    delta = jnp.sum(do3.astype(f32) * out3.astype(f32), axis=-1)  # (BH, Sl)
    if zig:
        # The forward computed (and saved lse) in the zigzag row order;
        # re-enter it for the backward and leave it again at the end.
        q3 = _zig_exchange(q3, axis_name, n, my)
        k3 = _zig_exchange(k3, axis_name, n, my)
        v3 = _zig_exchange(v3, axis_name, n, my)
        do3 = _zig_exchange(do3, axis_name, n, my)
        delta = _zig_exchange(delta, axis_name, n, my)
        h = Sl // 2
        q_bases = _zig_chunk_bases(my, n, h)
    else:
        h = Sl
        q_bases = (my * Sl,)
    dof = do3.astype(cd)
    rows = _bases_to_rows(q_bases, h)
    threshold = _dropout_threshold(rate)
    tile = min(bk_bwd, h)
    # Same S-dependent backward crossover as flash_attention (measured,
    # docs/PERFORMANCE.md §12): the einsum tiles win at short blocks, the
    # Pallas kernels from _PALLAS_BWD_MIN_SEQ-sized local shards up — the
    # regime multi-chip sequence parallelism actually runs in.
    use_kernels = (not interpret) and Sl >= _PALLAS_BWD_MIN_SEQ

    def block_bwd(k_b, v_b, k_rows):
        """One resident block's (dq_partial, dk_b, dv_b), tiled over K so
        only (Sl, tile) score tiles materialize — flash_attention.
        _jnp_blockwise_bwd restricted to this block, with global row/col
        index vectors (contiguous or zigzag)."""
        nt = Sl // tile
        ks = k_b.reshape(B * H, nt, tile, D).transpose(1, 0, 2, 3)
        vs = v_b.reshape(B * H, nt, tile, D).transpose(1, 0, 2, 3)
        col_tiles = k_rows.reshape(nt, tile)

        def one_tile(dq_acc, blk):
            ti, k_t, v_t = blk
            cols = jnp.take(col_tiles, ti, axis=0)
            s = jnp.einsum(
                "bqd,bkd->bqk", q3, k_t, preferred_element_type=f32
            ) * scale
            if causal:
                mask = rows[:, None] >= cols[None, :]
                s = jnp.where(mask[None], s, NEG_INF)
            p = jnp.exp(s - lse[:, :, None])  # (BH, Sl, tile) fp32
            if causal:
                p = jnp.where(mask[None], p, 0.0)
            if rate > 0.0:
                keep = _dropout_keep(
                    seed[0], bh_vec[:, None, None], rows[None, :, None],
                    cols[None, None, :], threshold,
                )
                inv = 1.0 / (1.0 - rate)
                pd = jnp.where(keep, p * inv, 0.0)
                dp_scale = jnp.where(keep, inv, 0.0)
            else:
                pd = p
                dp_scale = None
            dv_t = jnp.einsum(
                "bqk,bqd->bkd", pd.astype(cd), dof, preferred_element_type=f32
            )
            dp = jnp.einsum(
                "bqd,bkd->bqk", dof, v_t, preferred_element_type=f32
            )
            if dp_scale is not None:
                dp = dp * dp_scale
            ds = (p * (dp - delta[:, :, None]) * scale).astype(cd)
            dq_acc = dq_acc + jnp.einsum(
                "bqk,bkd->bqd", ds, k_t, preferred_element_type=f32
            )
            dk_t = jnp.einsum(
                "bqk,bqd->bkd", ds, q3, preferred_element_type=f32
            )
            return dq_acc, (dk_t, dv_t)

        dq0 = pcast_like(jnp.zeros((B * H, Sl, D), f32), q3, k_b, v_b, do3)
        dq_p, (dk_tiles, dv_tiles) = lax.scan(
            one_tile, dq0, (jnp.arange(nt), ks, vs)
        )
        dk_b = dk_tiles.transpose(1, 0, 2, 3).reshape(B * H, Sl, D)
        dv_b = dv_tiles.transpose(1, 0, 2, 3).reshape(B * H, Sl, D)
        return dq_p, dk_b, dv_b

    dq3 = pcast_like(jnp.zeros((B * H, Sl, D), f32), q3, k3, v3, do3)
    k_cur, v_cur = k3, v3
    dk_cur = pcast_like(jnp.zeros((B * H, Sl, D), f32), q3, k3, v3, do3)
    dv_cur = pcast_like(jnp.zeros((B * H, Sl, D), f32), q3, k3, v3, do3)
    for t in range(n):
        # Same visit order as the forward: at step t the resident K/V block
        # originated on (my - t) % n, and so did the dk/dv accumulators
        # riding along with it.
        src = (my - t) % n
        k_bases = _zig_chunk_bases(src, n, h) if zig else (src * Sl,)
        if use_kernels:
            dq_p, dk_b, dv_b = _block_bwd_kernel(
                q3, k_cur, v_cur, do3, lse, delta, seed,
                _bases_to_tiles(q_bases, h, bq),
                _bases_to_tiles(k_bases, h, tile),
                bh_vec, causal, rate, bq, tile,
            )
        else:
            dq_p, dk_b, dv_b = block_bwd(
                k_cur, v_cur, _bases_to_rows(k_bases, h)
            )
        dq3 = dq3 + dq_p
        dk_cur = dk_cur + dk_b
        dv_cur = dv_cur + dv_b
        # dk/dv must complete the full cycle (n hops) to land home with
        # every device's contribution; k/v are not needed after their last
        # block pass, saving one exchange.
        if t < n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_cur = lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur, axis_name, perm)

    if zig:
        dq3 = _zig_exchange(dq3, axis_name, n, my, inverse=True)
        dk_cur = _zig_exchange(dk_cur, axis_name, n, my, inverse=True)
        dv_cur = _zig_exchange(dv_cur, axis_name, n, my, inverse=True)

    def back4(t3, dtype):  # (B*H, Sl, D) -> (B, Sl, H, D)
        return t3.reshape(B, H, Sl, D).transpose(0, 2, 1, 3).astype(dtype)

    seed_ct = np.zeros((1,), jax.dtypes.float0)  # integral: no tangent
    return (
        back4(dq3, q.dtype), back4(dk_cur, k.dtype), back4(dv_cur, v.dtype),
        seed_ct,
    )


def _ring_fwd_rule(opts, q, k, v, seed):
    return _ring_fwd(opts, q, k, v, seed)


_ring.defvjp(_ring_fwd_rule, _ring_bwd)


def ring_attention_sharded(
    q: jax.Array,  # (B, S_local, H, D) — this device's sequence shard
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = False,
    dropout_rate: float = 0.0,
    dropout_seed: Optional[jax.Array] = None,
    batch_axis: Optional[str] = None,
    heads_axis: Optional[str] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    block_k_bwd: Optional[int] = None,
    zigzag: Optional[bool] = None,
) -> jax.Array:
    """Ring attention body; call inside shard_map with seq sharded on axis_name.

    ``batch_axis``/``heads_axis`` name the mesh axes (if any) the batch and
    head dims are sharded over, so dropout-mask coordinates are GLOBAL
    (batch, head) indices — without them, same-local-index examples on
    different data shards would share masks.

    ``zigzag`` (default: auto — on when ``causal``, the ring has >1
    device, the local shard is even, and any explicit block sizes divide
    the half-chunk) redistributes half-chunks so device c owns global chunks
    (c, 2n-1-c): causal triangle work becomes ~equal per device per hop
    instead of the contiguous layout's last-device-does-everything skew
    (~2x wall-clock at large rings). Purely internal — inputs/outputs stay
    in the contiguous layout, and global coordinates keep dropout masks
    bit-identical to flash. Pass ``zigzag=False`` to force contiguous.

    On TPU each ring hop runs the Pallas flash block kernel (VMEM-resident
    score tiles); elsewhere (CPU test meshes, where the Pallas interpreter
    cannot run inside vma-carrying manual regions) an einsum path with
    identical semantics. Gradients flow through a custom VJP that makes a
    second ring pass (see module docstring).
    """
    B, Sl, H, D = q.shape
    if dropout_seed is None:
        _warn_seedless_dropout(dropout_rate, "ring_attention_sharded")
        dropout_rate = 0.0
        seed = jnp.zeros((1,), jnp.uint32)
    else:
        seed = jnp.asarray(dropout_seed, jnp.uint32).reshape((1,))
    interpret = jax.default_backend() != "tpu"
    n = lax.axis_size(axis_name)
    if zigzag is None:
        zig = causal and n > 1 and Sl % 2 == 0
        # Auto mode must never turn a previously-valid config into an
        # error: explicit block sizes that divide the shard but not the
        # half-chunk fall back to the contiguous layout.
        if zig and any(
            b is not None and (Sl // 2) % b != 0
            for b in (block_q, block_k, block_k_bwd)
        ):
            zig = False
    else:
        zig = bool(zigzag) and n > 1
        if zig and Sl % 2 != 0:
            raise ValueError(
                f"zigzag=True needs an even local shard, got S/sp={Sl} "
                f"over '{axis_name}' (the layout splits each shard into "
                "two half-chunks)"
            )
    # Blocks tile one CHUNK: the whole shard normally, a half-chunk under
    # zigzag (tiles must not straddle the half boundary — their rows would
    # not be globally contiguous).
    chunk = Sl // 2 if zig else Sl
    bq = block_q or _pick_block(chunk, _FWD_BLOCK_Q)
    bk = block_k or _pick_block(chunk, _FWD_BLOCK_K)
    bk_bwd = block_k_bwd or _pick_block(chunk, _BWD_BLOCK_K)
    if chunk % bq != 0 or chunk % bk != 0 or chunk % bk_bwd != 0:
        # Same contract as flash_attention, against the LOCAL chunk: a
        # non-dividing (or oversized) block would silently truncate the
        # kernel grid and compute wrong attention.
        raise ValueError(
            f"block sizes (block_q={bq}, block_k={bk}, block_k_bwd="
            f"{bk_bwd}) must divide the local chunk {chunk} "
            f"(S/sp={Sl} over '{axis_name}'"
            + (", halved by the zigzag causal layout)" if zig else ")")
        )
    opts = (
        axis_name, causal, dropout_rate, batch_axis, heads_axis,
        interpret, bq, bk, bk_bwd, zig,
    )
    return _ring(opts, q, k, v, seed)


def ring_attention(
    q: jax.Array,  # (B, S, H, D) — full (mesh-visible) arrays
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    axis_name: str = "seq",
    mesh: Optional[jax.sharding.Mesh] = None,
    dropout_rate: float = 0.0,
    dropout_seed: Optional[jax.Array] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    block_k_bwd: Optional[int] = None,
    zigzag: Optional[bool] = None,
) -> jax.Array:
    """Shard the sequence over ``axis_name`` and run the ring. Falls back to
    flash attention when no such mesh axis is in scope (so models configured
    with attention_impl='ring' still run on a plain data mesh).

    Attention-probability dropout (``dropout_rate`` + uint32 ``dropout_seed``)
    uses the flash kernel's global-coordinate hash: for equal seeds the mask
    is identical to flash's, independent of the ring's sequence sharding.
    """
    if mesh is None:
        m = jax.sharding.get_abstract_mesh()
        mesh = m if m is not None and axis_name in getattr(m, "axis_names", ()) else None
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        from .flash_attention import flash_attention

        return flash_attention(
            q, k, v, causal=causal,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
            block_q=block_q, block_k=block_k, block_k_bwd=block_k_bwd,
        )

    # Compose with whatever other parallelism the mesh carries: batch stays
    # sharded on 'data', heads stay sharded on 'model' (tensor parallel) —
    # the ring only ever communicates along the 'seq' axis.
    batch_ax = "data" if mesh.shape.get("data", 1) > 1 else None
    model_ax = "model" if mesh.shape.get("model", 1) > 1 else None
    spec = P(batch_ax, axis_name, model_ax, None)
    if dropout_seed is None:
        _warn_seedless_dropout(dropout_rate, "ring_attention")
        seed = jnp.zeros((), jnp.uint32)
        dropout_rate = 0.0
    else:
        seed = jnp.asarray(dropout_seed, jnp.uint32).reshape(())

    def body(qs, ks, vs, seed_s):
        return ring_attention_sharded(
            qs, ks, vs, axis_name=axis_name, causal=causal,
            dropout_rate=dropout_rate, dropout_seed=seed_s,
            batch_axis=batch_ax, heads_axis=model_ax,
            block_q=block_q, block_k=block_k, block_k_bwd=block_k_bwd,
            zigzag=zigzag,
        )

    fn = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec, P()), out_specs=spec
    )
    return fn(q, k, v, seed)
