"""Ulysses-style sequence parallelism — all-to-all head redistribution.

The second of the two canonical sequence-parallel attention schemes (the
DeepSpeed-Ulysses construction; ring attention in ``ops.ring_attention`` is
the other). Nothing like either exists in the reference (SURVEY §2.3: no
sequence parallelism anywhere; 16K+ contexts are future-work prose).

Mechanism: with the sequence dimension sharded over mesh axis ``seq`` (size
n), an ``all_to_all`` re-shards each of Q/K/V from sequence-sharded
(B, S/n, H, D) to head-sharded (B, S, H/n, D). Every device then runs the
ordinary *local* flash kernel over the FULL sequence for its 1/n of the
heads — no attention math changes at all — and a reverse all-to-all restores
sequence sharding on the output.

Trade-off vs ring (why both exist):
- Ulysses moves 4 all-to-alls of S*H*D/n elements each per call and reuses
  the peak-tuned flash kernel unchanged; parallelism is capped at
  n <= H (heads must divide).
- Ring moves (n-1) neighbor hops of 2*S*D/n (K,V) overlapped with compute,
  scales past the head count, but runs its own online-softmax merge.
On ICI both patterns map well (all_to_all uses the full torus bisection;
ppermute uses neighbor links); for moderate n and head-rich models Ulysses
usually wins on simplicity and kernel efficiency.

Attention-probability dropout: the local flash call uses the shared
coordinate-hash mask with a per-shard seed fold — the fold covers the seq
axis index AND any data/model shard indices (each attention shard in the
whole mesh draws from its own stream), so masks are unbiased and
decorrelated across head groups, batch shards, and tp shards alike, and
reproducible: the exact global mask is a pure function of (seed, shard ids)
the tests materialize and check against. (It is NOT bitwise-equal to the
mask the unsharded flash kernel would draw for the same seed — the
head-group seeding differs; flash<->ring keep that property instead.)
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _shard_seed(seed: jax.Array, shard: jax.Array) -> jax.Array:
    """Per-shard dropout seed: decorrelate attention shards across the mesh."""
    return (seed + (shard.astype(jnp.uint32) + jnp.uint32(1))
            * jnp.uint32(0x9E3779B9)).astype(jnp.uint32)


def resolve_seq_mesh(
    mesh: Optional[jax.sharding.Mesh], axis_name: str
) -> Tuple[Optional[jax.sharding.Mesh], Optional[str], Optional[str]]:
    """Shared mesh resolution for the sequence-parallel wrappers (ring and
    Ulysses): discover the ambient mesh if none given, and name the axes the
    batch and head dims ride (for specs and dropout decorrelation). Returns
    (mesh-or-None, batch_axis, heads_axis); mesh None means "no seq axis in
    scope — fall back to plain flash"."""
    if mesh is None:
        m = jax.sharding.get_abstract_mesh()
        mesh = m if m is not None and axis_name in getattr(m, "axis_names", ()) else None
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        return None, None, None
    batch_ax = "data" if mesh.shape.get("data", 1) > 1 else None
    model_ax = "model" if mesh.shape.get("model", 1) > 1 else None
    return mesh, batch_ax, model_ax


def _global_shard_index(axis_names) -> jax.Array:
    """Flatten this device's position along the given (present) mesh axes
    into one index — a unique per-attention-shard id for seed folding."""
    idx = jnp.zeros((), jnp.uint32)
    for ax in axis_names:
        if ax is None:
            continue
        idx = idx * jnp.uint32(lax.axis_size(ax)) + lax.axis_index(ax).astype(jnp.uint32)
    return idx


def ulysses_attention_sharded(
    q: jax.Array,  # (B, S_local, H, D) — this device's sequence shard
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = False,
    dropout_rate: float = 0.0,
    dropout_seed: Optional[jax.Array] = None,
    batch_axis: Optional[str] = None,
    heads_axis: Optional[str] = None,
    interpret: Optional[bool] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    block_k_bwd: Optional[int] = None,
    pallas_backward: Optional[bool] = None,
) -> jax.Array:
    """Ulysses body; call inside shard_map with seq sharded on axis_name.

    ``batch_axis``/``heads_axis`` name the mesh axes (if any) the batch and
    head dims are sharded over — folded into the dropout seed so shards at
    the same local coordinates on different dp/tp shards do NOT share masks
    (the same hazard ring_attention_sharded's global offsets prevent).
    """
    from .flash_attention import flash_attention

    n = lax.axis_size(axis_name)
    B, Sl, H, D = q.shape
    if H % n != 0:
        raise ValueError(
            f"Ulysses needs heads % seq_parallel == 0, got H={H}, n={n} "
            "(use ring attention past the head count)"
        )

    def to_heads(t):  # (B, S/n, H, D) -> (B, S, H/n, D)
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1, tiled=True)

    qg, kg, vg = to_heads(q), to_heads(k), to_heads(v)

    seed = None
    rate = 0.0
    if dropout_rate > 0.0 and dropout_seed is not None:
        shard = _global_shard_index((batch_axis, heads_axis, axis_name))
        seed = _shard_seed(
            jnp.asarray(dropout_seed, jnp.uint32).reshape(()), shard
        )
        rate = dropout_rate
    out = flash_attention(
        qg, kg, vg, causal=causal, interpret=interpret,
        block_q=block_q, block_k=block_k, block_k_bwd=block_k_bwd,
        pallas_backward=pallas_backward,
        dropout_rate=rate, dropout_seed=seed,
    )  # (B, S, H/n, D)
    # heads-sharded -> seq-sharded
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,  # (B, S, H, D) — full (mesh-visible) arrays
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    axis_name: str = "seq",
    mesh: Optional[jax.sharding.Mesh] = None,
    dropout_rate: float = 0.0,
    dropout_seed: Optional[jax.Array] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    block_k_bwd: Optional[int] = None,
    pallas_backward: Optional[bool] = None,
) -> jax.Array:
    """Shard the sequence over ``axis_name`` and run Ulysses. Falls back to
    plain flash when no such mesh axis is in scope (mirrors ring_attention's
    contract, so attention_impl='ulysses' runs anywhere). Flash tuning
    parameters pass straight through — the local compute IS the flash
    kernel, so tier-tuned tile sizes apply under Ulysses too."""
    mesh, batch_ax, model_ax = resolve_seq_mesh(mesh, axis_name)
    if mesh is None:
        from .flash_attention import flash_attention

        return flash_attention(
            q, k, v, causal=causal,
            block_q=block_q, block_k=block_k, block_k_bwd=block_k_bwd,
            pallas_backward=pallas_backward,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )

    spec = P(batch_ax, axis_name, model_ax, None)
    if dropout_seed is None:
        from .flash_attention import _warn_seedless_dropout

        _warn_seedless_dropout(dropout_rate, "ulysses_attention")
        seed = jnp.zeros((), jnp.uint32)
        dropout_rate = 0.0
    else:
        seed = jnp.asarray(dropout_seed, jnp.uint32).reshape(())

    def body(qs, ks, vs, seed_s):
        return ulysses_attention_sharded(
            qs, ks, vs, axis_name=axis_name, causal=causal,
            dropout_rate=dropout_rate, dropout_seed=seed_s,
            batch_axis=batch_ax, heads_axis=model_ax,
            block_q=block_q, block_k=block_k, block_k_bwd=block_k_bwd,
            pallas_backward=pallas_backward,
        )

    fn = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec, P()), out_specs=spec,
        # The Pallas kernel's out_shape carries no varying-axes annotation;
        # skip the vma checker for this map (the all_to_alls fix the types).
        check_vma=False,
    )
    return fn(q, k, v, seed)
