"""Flash attention — Pallas TPU forward kernel + blockwise custom VJP.

The reference benchmarks vanilla O(S^2)-materialized attention
(``nn.MultiheadAttention``, reference ``benchmarking/train_harness.py:114-116``)
and defers "Flash Attention for 16K+ sequences" to future work
(reference ``README.md:1026-1034``). This module supplies it TPU-natively.

Forward (Pallas kernel):
- never materializes the (S, S) score matrix in HBM — K/V stream through VMEM
  in blocks while running-max/running-sum (online softmax) statistics fold
  each block into the output accumulator;
- fp32 statistics and accumulation, bf16 matmul inputs on the MXU;
- grid (batch*heads, q_blocks, k_blocks) with the k axis innermost and
  sequential, so the VMEM scratch accumulator persists across k blocks
  (TPU grids execute the trailing axis as the inner sequential loop);
- also emits the per-row logsumexp, the residual the backward pass needs;
- ``causal=True`` masks by global position and skips fully-masked k blocks.

Backward (custom VJP): recomputes attention probabilities blockwise over K
from the saved logsumexp — the standard flash backward — as a ``lax.scan`` of
dense jnp blocks, so peak memory is O(S * block) instead of O(S^2) and XLA
fuses it onto the MXU on TPU. (A hand-written Pallas backward kernel is a
further optimization, not a semantic change.)

On non-TPU backends the forward kernel runs in Pallas interpret mode (slow but
bit-honest), keeping the CPU test/smoke paths real.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _pick_block(seq_len: int, preferred: int = 512) -> int:
    for b in (preferred, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        if b <= seq_len and seq_len % b == 0:
            return b
    return seq_len


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, bq: int, bk: int, scale: float, causal: bool,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # With causal masking, k blocks strictly above the diagonal contribute
    # nothing — skip their compute entirely.
    live = (not causal) or (ki * bk < (qi + 1) * bq)

    @pl.when(live)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)  # (bq, d)
        k = k_ref[0].astype(jnp.float32)  # (bk, d)
        v = v_ref[0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (bq, bk)

        if causal:
            rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            mask = rows >= cols
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]                       # (bq, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)             # (bq, 1)
        p = jnp.exp(s - m_new)                      # (bq, bk)
        if causal:
            p = jnp.where(mask, p, 0.0)

        l_prev = l_scr[:, :1]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zero output
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # lse is logically (bq,); stored sublane-broadcast as (8, bq) because
        # TPU output blocks must tile to (8, 128).
        lse = (m_scr[:, :1] + jnp.log(l_safe))[:, 0]
        lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, lse.shape[0]))


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool, interpret: bool, bq: int, bk: int,
) -> Tuple[jax.Array, jax.Array]:
    """Run the Pallas kernel on (BH, S, D) inputs -> (out, lse)."""
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)
    grid = (BH, S // bq, S // bk)
    out, lse = pl.pallas_call(
        functools.partial(
            _flash_fwd_kernel, bq=bq, bk=bk, scale=scale, causal=causal
        ),
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 8, S), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, bk, D), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, bk, D), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, 8, bq), lambda b, qi, ki: (b, 0, qi)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),  # running max (lane-broadcast)
            pltpu.VMEM((bq, 128), jnp.float32),  # running sum
            pltpu.VMEM((bq, D), jnp.float32),    # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, 0, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(opts: Tuple, q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    causal, interpret, bq, bk = opts
    out, _ = _flash_forward(q, k, v, causal, interpret, bq, bk)
    return out


def _flash_fwd_rule(opts, q, k, v):
    causal, interpret, bq, bk = opts
    out, lse = _flash_forward(q, k, v, causal, interpret, bq, bk)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(opts, res, do):
    """Blockwise flash backward from the saved logsumexp.

    Standard identities (per batch*head row block):
        p    = exp(q k^T * scale - lse)
        dv   = p^T do
        dp   = do v^T
        ds   = p * (dp - delta) * scale,  delta = rowsum(do * o)
        dq   = ds k ;  dk = ds^T q
    computed as a scan over K blocks so only (S, bk) tiles materialize.
    """
    causal, _, _, bk = opts
    q, k, v, out, lse = res
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)
    f32 = jnp.float32
    qf, kf, vf, dof = (t.astype(f32) for t in (q, k, v, do))
    delta = jnp.sum(dof * out.astype(f32), axis=-1)  # (BH, S)

    nk = S // bk
    ks = kf.reshape(BH, nk, bk, D).transpose(1, 0, 2, 3)  # (nk, BH, bk, D)
    vs = vf.reshape(BH, nk, bk, D).transpose(1, 0, 2, 3)

    rows = jnp.arange(S)

    def one_block(dq_acc, blk):
        ki, k_b, v_b = blk
        s = jnp.einsum("bqd,bkd->bqk", qf, k_b, preferred_element_type=f32) * scale
        if causal:
            cols = ki * bk + jnp.arange(bk)
            mask = rows[:, None] >= cols[None, :]
            s = jnp.where(mask[None], s, NEG_INF)
        p = jnp.exp(s - lse[:, :, None])  # (BH, S, bk)
        if causal:
            p = jnp.where(mask[None], p, 0.0)
        dv_b = jnp.einsum("bqk,bqd->bkd", p, dof, preferred_element_type=f32)
        dp = jnp.einsum("bqd,bkd->bqk", dof, v_b, preferred_element_type=f32)
        ds = p * (dp - delta[:, :, None]) * scale
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds, k_b, preferred_element_type=f32)
        dk_b = jnp.einsum("bqk,bqd->bkd", ds, qf, preferred_element_type=f32)
        return dq_acc, (dk_b, dv_b)

    dq0 = jnp.zeros((BH, S, D), f32)
    dq, (dk_blocks, dv_blocks) = lax.scan(
        one_block, dq0, (jnp.arange(nk), ks, vs)
    )
    dk = dk_blocks.transpose(1, 0, 2, 3).reshape(BH, S, D)
    dv = dv_blocks.transpose(1, 0, 2, 3).reshape(BH, S, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(
    jax.jit, static_argnames=("causal", "interpret", "block_q", "block_k")
)
def flash_attention(
    q: jax.Array,  # (B, S, H, D)
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    interpret: Optional[bool] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
) -> jax.Array:
    """Multi-head flash attention over (batch, seq, heads, head_dim) inputs."""
    B, S, H, D = q.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bq = block_q or _pick_block(S)
    bk = block_k or _pick_block(S)
    if S % bq != 0 or S % bk != 0:
        raise ValueError(
            f"block sizes (block_q={bq}, block_k={bk}) must divide seq_len={S}"
        )

    # (B, S, H, D) -> (B*H, S, D): one grid row per (batch, head) pair.
    def to_bhsd(t):
        return t.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    out = _flash((causal, interpret, bq, bk), to_bhsd(q), to_bhsd(k), to_bhsd(v))
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def reference_attention(q, k, v, causal: bool = False) -> jax.Array:
    """Materialized-softmax attention for correctness comparison (same math
    as models.tinygpt's in-model path, without dropout)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(q.dtype), v, preferred_element_type=jnp.float32
    ).astype(q.dtype)
