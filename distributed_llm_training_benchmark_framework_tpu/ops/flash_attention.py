"""Flash attention — Pallas TPU forward kernel + blockwise custom VJP.

The reference benchmarks vanilla O(S^2)-materialized attention
(``nn.MultiheadAttention``, reference ``benchmarking/train_harness.py:114-116``)
and defers "Flash Attention for 16K+ sequences" to future work
(reference ``README.md:1026-1034``). This module supplies it TPU-natively.

Forward (Pallas kernel):
- never materializes the (S, S) score matrix in HBM — K/V stream through VMEM
  in blocks while running-max/running-sum (online softmax) statistics fold
  each block into the output accumulator;
- fp32 statistics and accumulation, bf16 matmul inputs on the MXU;
- grid (batch*heads, q_blocks, k_blocks) with the k axis innermost and
  sequential, so the VMEM scratch accumulator persists across k blocks
  (TPU grids execute the trailing axis as the inner sequential loop);
- also emits the per-row logsumexp, the residual the backward pass needs;
- ``causal=True`` masks by global position and skips fully-masked k blocks.

Backward (custom VJP): recomputes attention probabilities blockwise over K
from the saved logsumexp — the standard flash backward — with two
implementations sharing the same math: an XLA-fused ``lax.scan`` of dense
jnp blocks (peak memory O(S * block)), and hand-written Pallas dq / dk+dv
kernels. Which is faster is S-dependent on v5e (einsum to S=2048, kernels
from S=4096 with margins growing to +88% at 16K — docs/PERFORMANCE.md §12);
``pallas_backward=None`` auto-selects by the measured crossover.

On non-TPU backends the forward kernel runs in Pallas interpret mode (slow but
bit-honest), keeping the CPU test/smoke paths real.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _mix32(x: jax.Array) -> jax.Array:
    """32-bit integer finalizer (murmur3-style avalanche) on uint32 lanes.

    Runs per score element in the flash kernels' hot loop, so the op count
    was scrutinized: a single-multiply xorshift variant measured faster but
    showed real adjacent-element keep correlation (pair rate 0.446 vs the
    0.490 expected at rate 0.3) — biased dropout. Two multiplies is the
    floor that passes the adjacency tests in tests/test_attention_ops.py.
    """
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


def _dropout_keep(seed, bh, rows, cols, threshold) -> jax.Array:
    """Deterministic per-element keep mask for attention-probability dropout.

    Derived from the absolute (batch*head, row, col) coordinate — NOT from
    block indices or a stateful PRNG — so the forward kernel, the jnp
    blockwise backward, and the Pallas backward kernels reproduce the exact
    same mask even though they tile the (S, S) matrix differently.
    ``seed`` is a traced uint32 scalar; ``threshold`` = keep_prob * 2^32.

    Each of (bh, row) gets its own fully-avalanched 32-bit stream base, so
    two rows (same or different heads) only ever share keep bits where two
    independent 32-bit hashes collide (~2^-32 per pair) — unlike an affine
    ``base + row*S + col`` packing, where B*H*S^2 > 2^32 forces systematic
    shifted-identical masks across heads by pigeonhole. Per-element cost is
    unchanged (one finalizer on the broadcast (rows, cols) product); the
    row mix runs on the narrow rows operand.
    """
    base = _mix32(seed + jnp.uint32(bh) * jnp.uint32(0x9E3779B9))
    rowbase = _mix32(base + rows.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B))
    h = _mix32(rowbase + cols.astype(jnp.uint32))
    return h < threshold


def _dropout_threshold(rate: float) -> jnp.uint32:
    return jnp.uint32(min(int((1.0 - rate) * 2**32), 2**32 - 1))


def _warn_seedless_dropout(dropout_rate: float, api_name: str) -> None:
    """A caller passing dropout_rate>0 without a seed gets *deterministic*
    attention; make that audible instead of silent (advisor finding r2)."""
    if dropout_rate > 0.0:
        import warnings

        warnings.warn(
            f"{api_name}: dropout_rate > 0 with dropout_seed=None — dropout "
            "is DISABLED (deterministic attention). Pass a uint32 "
            "dropout_seed to enable it.",
            stacklevel=3,
        )


def _pick_block(seq_len: int, preferred: int = 512) -> int:
    for b in (preferred, 512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        if b <= seq_len and seq_len % b == 0:
            return b
    return seq_len


# Default tile sizes, measured on v5e (tier A, S=2048, head_dim 64; see
# docs/PERFORMANCE.md): the forward kernel is fastest at 1024x1024 tiles
# (0.275 ms/layer vs 0.568 ms at 512x512 — fewer grid cells amortize per-cell
# overhead), while the blockwise backward is fastest with 512-wide K blocks
# (1024 doubles its time). Hence separate fwd/bwd defaults.
_FWD_BLOCK_Q = 1024
_FWD_BLOCK_K = 1024
_BWD_BLOCK_K = 512

# Backward implementation crossover, measured on v5e tier A (docs/
# PERFORMANCE.md §12): the XLA-fused blockwise-einsum backward wins at
# S=2048 (41.6k vs 38.4k tok/s) but the Pallas backward kernels win from
# S=4096 up, by growing margins (+14% @4K, +45% @8K, +88% @16K) — the
# einsum path's (BH, S, bk) probability tiles become HBM-bandwidth-bound
# while the kernels keep them in VMEM. pallas_backward=None picks by S.
_PALLAS_BWD_MIN_SEQ = 4096


def _flash_fwd_kernel(
    seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, bq: int, bk: int, scale: float, causal: bool,
    seq_len: int, dropout_rate: float,
):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # With causal masking, k blocks strictly above the diagonal contribute
    # nothing — skip their compute entirely.
    live = (not causal) or (ki * bk < (qi + 1) * bq)

    @pl.when(live)
    def _accumulate():
        # bf16 operands on the MXU, fp32 accumulation via
        # preferred_element_type — softmax statistics stay fp32 throughout.
        q = q_ref[0]  # (bq, d) input dtype
        k = k_ref[0]  # (bk, d)
        v = v_ref[0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (bq, bk) fp32

        # Narrow coordinate operands: the causal compare and the dropout
        # hash broadcast (bq,1)x(1,bk); the row-fold mix runs per-row only.
        rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
        cols = ki * bk + lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        if causal:
            mask = rows >= cols
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]                       # (bq, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)             # (bq, 1)
        p = jnp.exp(s - m_new)                      # (bq, bk) fp32
        if causal:
            p = jnp.where(mask, p, 0.0)

        # Attention-probability dropout (parity with the reference model,
        # train_harness.py:114-116): the softmax normalizer l accumulates the
        # UN-dropped p (dropout acts after normalization, and normalization is
        # linear, so dropping the unnormalized p against the full-l divisor is
        # exact), while the output accumulator sees the dropped+rescaled p.
        if dropout_rate > 0.0:
            keep = _dropout_keep(
                seed_ref[0], bh, rows, cols,
                _dropout_threshold(dropout_rate),
            )
            p_acc = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        else:
            p_acc = p

        l_prev = l_scr[:, :1]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + lax.dot_general(
            p_acc.astype(q.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zero output
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # lse is logically (bq,); stored sublane-broadcast as (8, bq) because
        # TPU output blocks must tile to (8, 128).
        lse = (m_scr[:, :1] + jnp.log(l_safe))[:, 0]
        lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, lse.shape[0]))


def _vma_struct(shape, dtype, *like):
    """ShapeDtypeStruct carrying the union of the inputs' varying-manual-axes.

    When the kernel runs inside a vma-checked ``shard_map`` (e.g. Ulysses
    under the sequence-manual pipeline), Pallas requires out_shapes to declare
    how outputs vary across the manual mesh axes — they vary exactly as the
    operands do (the kernel is pointwise in the shard dimension)."""
    from ..utils.vma import vma_of

    vma = vma_of(*like)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _jnp_reference_forward(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool, dropout_rate: float, seed: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Materialized-softmax forward with the kernel's exact mask/accumulation
    semantics (same ``_dropout_keep`` coordinates, same un-dropped normalizer),
    for contexts where the Pallas HLO interpreter cannot run — currently
    vma-carrying manual regions on CPU (the interpreter's internal
    dynamic_slice rejects mixed varying/invariant operands). Returns
    (out, lse) exactly as ``_flash_forward`` does."""
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)
    s = jnp.einsum(
        "bqd,bkd->bqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    rows = lax.broadcasted_iota(jnp.int32, (S, 1), 0)
    cols = lax.broadcasted_iota(jnp.int32, (1, S), 1)
    if causal:
        s = jnp.where((rows >= cols)[None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    if causal:
        p = jnp.where((rows >= cols)[None], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    if dropout_rate > 0.0:
        bh = jnp.arange(BH, dtype=jnp.uint32)[:, None, None]
        keep = _dropout_keep(
            seed[0], bh, rows[None], cols[None], _dropout_threshold(dropout_rate)
        )
        p_acc = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
    else:
        p_acc = p
    l_safe = jnp.where(l == 0.0, 1.0, l)
    acc = jnp.einsum(
        "bqk,bkd->bqd", p_acc.astype(q.dtype), v,
        preferred_element_type=jnp.float32,
    )
    out = (acc / l_safe).astype(q.dtype)
    lse = (m + jnp.log(l_safe))[:, :, 0]
    return out, lse


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool, interpret: bool, bq: int, bk: int,
    dropout_rate: float = 0.0, seed: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Run the Pallas kernel on (BH, S, D) inputs -> (out, lse)."""
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)
    grid = (BH, S // bq, S // bk)
    if seed is None:
        seed = jnp.zeros((1,), jnp.uint32)
    from ..utils.vma import vma_of

    if interpret and vma_of(q, k, v):
        return _jnp_reference_forward(q, k, v, causal, dropout_rate, seed)
    out, lse = pl.pallas_call(
        functools.partial(
            _flash_fwd_kernel, bq=bq, bk=bk, scale=scale, causal=causal,
            seq_len=S, dropout_rate=dropout_rate,
        ),
        out_shape=[
            _vma_struct((BH, S, D), q.dtype, q, k, v),
            _vma_struct((BH, 8, S), jnp.float32, q, k, v),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # dropout seed (1,) uint32
            pl.BlockSpec((1, bq, D), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, bk, D), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, bk, D), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, 8, bq), lambda b, qi, ki: (b, 0, qi)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),  # running max (lane-broadcast)
            pltpu.VMEM((bq, 128), jnp.float32),  # running sum
            pltpu.VMEM((bq, D), jnp.float32),    # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(seed, q, k, v)
    return out, lse[:, 0, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(
    opts: Tuple, q: jax.Array, k: jax.Array, v: jax.Array, seed: jax.Array
) -> jax.Array:
    causal, interpret, bq, bk, _, _, rate = opts
    out, _ = _flash_forward(q, k, v, causal, interpret, bq, bk, rate, seed)
    return out


def _flash_fwd_rule(opts, q, k, v, seed):
    causal, interpret, bq, bk, _, _, rate = opts
    out, lse = _flash_forward(q, k, v, causal, interpret, bq, bk, rate, seed)
    return out, (q, k, v, out, lse, seed)


def _bwd_dq_kernel(
    seed_ref, qoff_ref, koff_ref, bhv_ref, q_ref, k_ref, v_ref, do_ref,
    lse_ref, delta_ref, dq_ref, acc,
    *, bq: int, bk: int, scale: float, causal: bool,
    dropout_rate: float,
):
    """dq = sum over k blocks of ds @ k, ds = p * (dp - delta) * scale.

    Shared by plain flash, ring attention's per-block backward, and the
    zigzag ring layout: the SMEM vectors ``qoff_ref`` (nq,) / ``koff_ref``
    (nk,) carry each TILE's global base row/col — arange(n)*b for plain
    flash, shard-offset + arange for contiguous ring blocks, per-half-chunk
    bases for zigzag — so causal masking and the dropout hash always see
    absolute coordinates from one kernel implementation. Tiles must be
    internally contiguous (tile sizes divide the chunk size)."""
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    q_off = qoff_ref[qi]
    k_off = koff_ref[ki]

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    live = True if not causal else (q_off + bq - 1 >= k_off)

    @pl.when(live)
    def _accumulate():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][0]      # (bq,)
        delta = delta_ref[0][0]  # (bq,)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        # Narrow coordinate operands: the causal compare and the dropout
        # hash broadcast (bq,1)x(1,bk); the row-fold mix runs per-row only.
        rows = q_off + lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
        cols = k_off + lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        if causal:
            mask = rows >= cols
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if dropout_rate > 0.0:
            keep = _dropout_keep(
                seed_ref[0], bhv_ref[bh], rows, cols,
                _dropout_threshold(dropout_rate),
            )
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        acc[:] = acc[:] + lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    seed_ref, qoff_ref, koff_ref, bhv_ref, q_ref, k_ref, v_ref, do_ref,
    lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
    *, bq: int, bk: int, scale: float, causal: bool,
    dropout_rate: float,
):
    """dk = sum over q blocks of ds^T @ q; dv = sum of (D∘p)^T @ do.

    Shared with ring attention's per-block backward (contiguous and zigzag
    layouts) via the same SMEM tile-base vectors as _bwd_dq_kernel (see its
    docstring)."""
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    q_off = qoff_ref[qi]
    k_off = koff_ref[ki]

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = True if not causal else (q_off + bq - 1 >= k_off)

    @pl.when(live)
    def _accumulate():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][0]
        delta = delta_ref[0][0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        # Narrow coordinate operands: the causal compare and the dropout
        # hash broadcast (bq,1)x(1,bk); the row-fold mix runs per-row only.
        rows = q_off + lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
        cols = k_off + lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        if causal:
            mask = rows >= cols
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if dropout_rate > 0.0:
            keep = _dropout_keep(
                seed_ref[0], bhv_ref[bh], rows, cols,
                _dropout_threshold(dropout_rate),
            )
            inv = 1.0 / (1.0 - dropout_rate)
            pd = jnp.where(keep, p * inv, 0.0)
            dp = jnp.where(keep, dp * inv, 0.0)
        else:
            pd = p
        dv_acc[:] = dv_acc[:] + lax.dot_general(
            pd.astype(q.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        dk_acc[:] = dk_acc[:] + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _jnp_blockwise_bwd(causal, bk, rate, res, do):
    """Blockwise flash backward as batched einsums over a K-block scan.

    Same math as the Pallas kernels below, expressed as XLA-fused dense
    einsums: only (S, bk) tiles materialize. Measured FASTER than the Pallas
    backward on v5e (XLA schedules the batched-over-heads contractions onto
    the MXU better than the per-(head, tile) kernel grid) — hence the default.

    With dropout (out = (D∘P) @ V, D = keep/keep_prob): dV = (D∘P)^T dO, and
    the softmax-Jacobian identity dS = P∘(D∘dP - delta) still holds with
    delta = rowsum(dO∘out) because rowsum((D∘P)∘dP) = rowsum(dO∘out). The
    keep mask is regenerated from the same absolute-coordinate hash as the
    forward kernel, so the decomposition mismatch (fwd 1024-wide tiles, bwd
    ``bk``-wide) is invisible.
    """
    q, k, v, out, lse, seed = res
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)
    f32 = jnp.float32
    cd = q.dtype  # matmul operand dtype (bf16 on TPU); accumulation is fp32
    dof = do.astype(cd)
    delta = jnp.sum(
        do.astype(f32) * out.astype(f32), axis=-1
    )  # (BH, S) fp32

    nk = S // bk
    ks = k.reshape(BH, nk, bk, D).transpose(1, 0, 2, 3)  # (nk, BH, bk, D)
    vs = v.reshape(BH, nk, bk, D).transpose(1, 0, 2, 3)
    rows = jnp.arange(S)
    threshold = _dropout_threshold(rate)
    bh_idx = jnp.arange(BH)

    def one_block(dq_acc, blk):
        ki, k_b, v_b = blk
        cols = ki * bk + jnp.arange(bk)
        s = jnp.einsum("bqd,bkd->bqk", q, k_b, preferred_element_type=f32) * scale
        if causal:
            mask = rows[:, None] >= cols[None, :]
            s = jnp.where(mask[None], s, NEG_INF)
        p = jnp.exp(s - lse[:, :, None])  # (BH, S, bk) fp32
        if causal:
            p = jnp.where(mask[None], p, 0.0)
        if rate > 0.0:
            keep = _dropout_keep(
                seed[0], bh_idx[:, None, None], rows[None, :, None],
                cols[None, None, :], threshold,
            )  # (BH, S, bk)
            inv = 1.0 / (1.0 - rate)
            pd = jnp.where(keep, p * inv, 0.0)
            dp_scale = jnp.where(keep, inv, 0.0)
        else:
            pd = p
            dp_scale = None
        dv_b = jnp.einsum(
            "bqk,bqd->bkd", pd.astype(cd), dof, preferred_element_type=f32
        )
        dp = jnp.einsum("bqd,bkd->bqk", dof, v_b, preferred_element_type=f32)
        if dp_scale is not None:
            dp = dp * dp_scale
        ds = (p * (dp - delta[:, :, None]) * scale).astype(cd)
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds, k_b, preferred_element_type=f32)
        dk_b = jnp.einsum("bqk,bqd->bkd", ds, q, preferred_element_type=f32)
        return dq_acc, (dk_b, dv_b)

    # Under a vma-checked manual region the accumulator carry must match the
    # varying type the block updates produce.
    from ..utils.vma import pcast_like

    dq0 = pcast_like(jnp.zeros((BH, S, D), f32), q, k, v, do)
    dq, (dk_blocks, dv_blocks) = lax.scan(one_block, dq0, (jnp.arange(nk), ks, vs))
    dk = dk_blocks.transpose(1, 0, 2, 3).reshape(BH, S, D)
    dv = dv_blocks.transpose(1, 0, 2, 3).reshape(BH, S, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_bwd_rule(opts, res, do):
    """Flash backward: recompute attention probabilities per tile from the
    saved logsumexp. Two implementations, selected by ``pallas_backward``:
    the default XLA-fused blockwise einsum path (faster on v5e), and the
    hand-written Pallas kernel pair (dq; dk/dv) below.
    """
    causal, interpret, bq, bk_fwd, bk, pallas_bwd, rate = opts
    seed_ct = np.zeros((1,), jax.dtypes.float0)  # seed is integral: no tangent
    from ..utils.vma import vma_of

    if pallas_bwd and interpret and vma_of(*res[:3], do):
        # Same limitation the forward's _jnp_reference_forward fallback works
        # around: the Pallas HLO interpreter cannot run on vma-carrying
        # operands (seq-manual pipeline on CPU) — take the jnp backward.
        pallas_bwd = False
    if not pallas_bwd:
        return (*_jnp_blockwise_bwd(causal, bk, rate, res, do), seed_ct)
    q, k, v, out, lse, seed = res
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)

    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # (BH, S)
    # lse/delta enter the kernels sublane-broadcast as (BH, 8, S) to satisfy
    # the (8, 128) input-tile constraint (same trick as the forward's output).
    lse3 = jnp.broadcast_to(lse[:, None, :], (BH, 8, S))
    delta3 = jnp.broadcast_to(delta[:, None, :], (BH, 8, S))

    seed_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    # Plain flash = the shared tile-base-aware kernels at identity bases
    # (tile i starts at row i*b) with an identity batch*head index vector
    # (ring attention feeds global ones).
    qoffs = jnp.arange(S // bq, dtype=jnp.int32) * bq
    koffs = jnp.arange(S // bk, dtype=jnp.int32) * bk
    bhv = jnp.arange(BH, dtype=jnp.int32)
    row_specs = dict(
        q=pl.BlockSpec((1, bq, D), lambda b, qi, ki: (b, qi, 0)),
        k=pl.BlockSpec((1, bk, D), lambda b, qi, ki: (b, ki, 0)),
        stat=pl.BlockSpec((1, 8, bq), lambda b, qi, ki: (b, 0, qi)),
    )
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, bq=bq, bk=bk, scale=scale, causal=causal,
            dropout_rate=rate,
        ),
        out_shape=_vma_struct((BH, S, D), q.dtype, q, k, v, do),
        grid=(BH, S // bq, S // bk),
        in_specs=[seed_spec, seed_spec, seed_spec, seed_spec,
                  row_specs["q"], row_specs["k"], row_specs["k"],
                  row_specs["q"], row_specs["stat"], row_specs["stat"]],
        out_specs=row_specs["q"],
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(seed, qoffs, koffs, bhv, q, k, v, do, lse3, delta3)

    col_specs = dict(
        q=pl.BlockSpec((1, bq, D), lambda b, ki, qi: (b, qi, 0)),
        k=pl.BlockSpec((1, bk, D), lambda b, ki, qi: (b, ki, 0)),
        stat=pl.BlockSpec((1, 8, bq), lambda b, ki, qi: (b, 0, qi)),
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, bq=bq, bk=bk, scale=scale, causal=causal,
            dropout_rate=rate,
        ),
        out_shape=[
            _vma_struct((BH, S, D), k.dtype, q, k, v, do),
            _vma_struct((BH, S, D), v.dtype, q, k, v, do),
        ],
        grid=(BH, S // bk, S // bq),
        in_specs=[seed_spec, seed_spec, seed_spec, seed_spec,
                  col_specs["q"], col_specs["k"], col_specs["k"],
                  col_specs["q"], col_specs["stat"], col_specs["stat"]],
        out_specs=[col_specs["k"], col_specs["k"]],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(seed, qoffs, koffs, bhv, q, k, v, do, lse3, delta3)

    return dq, dk, dv, seed_ct


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "interpret", "block_q", "block_k", "block_k_bwd",
        "pallas_backward", "dropout_rate",
    ),
)
def flash_attention(
    q: jax.Array,  # (B, S, H, D)
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    interpret: Optional[bool] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    block_k_bwd: Optional[int] = None,
    pallas_backward: Optional[bool] = None,
    dropout_rate: float = 0.0,
    dropout_seed: Optional[jax.Array] = None,
) -> jax.Array:
    """Multi-head flash attention over (batch, seq, heads, head_dim) inputs.

    Forward and backward take separate K-block sizes because their optima
    differ on v5e (see _FWD_BLOCK_* notes above).

    ``dropout_rate`` > 0 (with a uint32 scalar/1-vector ``dropout_seed``)
    applies attention-probability dropout INSIDE the kernel — parity with the
    reference's ``nn.MultiheadAttention(dropout=...)`` (train_harness.py:116)
    that earlier rounds had to document as a deviation. The keep mask is a
    stateless hash of absolute coordinates, so fwd/bwd agree despite their
    different tilings. With ``dropout_seed=None`` the rate is ignored and a
    warning is emitted (the model's deterministic/no-key dropout convention).
    """
    B, S, H, D = q.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if pallas_backward is None:
        # Auto: the measured S-dependent crossover (_PALLAS_BWD_MIN_SEQ).
        # Interpret mode keeps the einsum backward — the Pallas bwd kernels
        # would run under the slow HLO interpreter for no fidelity gain.
        pallas_backward = (not interpret) and S >= _PALLAS_BWD_MIN_SEQ
    bq = block_q or _pick_block(S, _FWD_BLOCK_Q)
    bk = block_k or _pick_block(S, _FWD_BLOCK_K)
    bk_bwd = block_k_bwd or _pick_block(S, _BWD_BLOCK_K)
    if S % bq != 0 or S % bk != 0 or S % bk_bwd != 0:
        raise ValueError(
            f"block sizes (block_q={bq}, block_k={bk}, block_k_bwd={bk_bwd}) "
            f"must divide seq_len={S}"
        )
    if dropout_seed is None:
        _warn_seedless_dropout(dropout_rate, "flash_attention")
        dropout_rate = 0.0
        seed = jnp.zeros((1,), jnp.uint32)
    else:
        seed = jnp.asarray(dropout_seed, jnp.uint32).reshape((1,))
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got {dropout_rate}")

    # (B, S, H, D) -> (B*H, S, D): one grid row per (batch, head) pair.
    def to_bhsd(t):
        return t.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    out = _flash(
        (causal, interpret, bq, bk, bk_bwd, pallas_backward, dropout_rate),
        to_bhsd(q), to_bhsd(k), to_bhsd(v), seed,
    )
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def reference_attention(q, k, v, causal: bool = False) -> jax.Array:
    """Materialized-softmax attention for correctness comparison (same math
    as models.tinygpt's in-model path, without dropout)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(q.dtype), v, preferred_element_type=jnp.float32
    ).astype(q.dtype)
