"""Collective matmul — tp projection comms decomposed into a ppermute ring.

Overlap round 3 (docs/PERFORMANCE.md §20). The plain tensor-parallel
lowering keeps the residual stream replicated over 'model' and pays a bare
activation all-gather (and a bare partial-sum all-reduce) at the
projections — collectives the scheduler can only overlap with *unrelated*
work, because the gather's consumer is the very dot waiting on it. The
collective-matmul formulation (Wang et al., ASPLOS'23 "Overlap
Communication with Dependent Computation via Decomposition"; the t5x/praxis
``collective_matmul`` passes) restructures the projection itself:

- the residual stream between projections rides SEQUENCE-sharded over the
  'model' axis (Megatron sequence-parallel layout — norms, residual adds
  and dropout are elementwise over the feature dim, so they stay local);
- entering a column-parallel projection (attention qkv, MLP up), the
  activation all-gather is split into per-shard sequence chunks rotated by
  ``ppermute``: each hop's chunk feeds one partial dot while the next chunk
  is in flight, so the comms hide INSIDE the matmul
  (:func:`ag_proj`);
- leaving a row-parallel projection (attention out, MLP down), the
  reduce-scatter is likewise a rotating-accumulator ring: each hop adds the
  partial product destined for the accumulator's current owner
  (:func:`rs_proj`).

Per projection that turns one bulk collective into n-1 neighbor
``ppermute`` hops interleaved with n dots — ICI-neighbor traffic with a
dependent-compute shadow to hide in, instead of a bisection-wide barrier.
The HLO signature (pinned by the ``llama-tp2-gqa-cmm`` graftcheck budget):
tp all-gathers at the projections -> 0, replaced by the ppermute ring,
reshard suspects 0.

Usable two ways, like ``ops.ring_attention``:
- ``ag_proj``/``rs_proj`` inside a jitted function running under a mesh
  with a >1 ``axis_name`` axis (they shard_map themselves over it, and
  fall back to the plain einsum when the axis is absent or 1 — so a
  ``tp_collective_matmul`` model still runs on a pure-dp mesh);
- ``ag_proj_sharded``/``rs_proj_sharded`` directly inside an existing
  shard_map.

Numerics: every dot accumulates in fp32 (``preferred_element_type``), the
ring accumulator is fp32, and the result downcasts once at the end — at
least as accurate as the plain path, whose partial-sum all-reduce runs on
the fp32 einsum output. Equivalence against the plain tp lowering (forward
AND grads) is pinned by ``tests/test_overlap.py`` on the 8-virtual-device
CPU mesh.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

#: Self-test escape hatch (graftcheck `--inject bad-cmm-ring`): False
#: breaks the ppermute decomposition — the shard_map bodies fall back to
#: the unfused all_gather / psum_scatter forms (same math, bulk
#: collectives back in the module) so CI can prove the cmm arm's frozen
#: budget catches a silently-reverted ring.
_CMM_RING = True


def _tp_mesh(axis_name: str, mesh) -> Optional[jax.sharding.Mesh]:
    """The mesh in scope when ``axis_name`` is a >1 axis, else None."""
    if mesh is None:
        m = jax.sharding.get_abstract_mesh()
        mesh = (
            m if m is not None and axis_name in getattr(m, "axis_names", ())
            else None
        )
    if mesh is None or mesh.shape.get(axis_name, 1) <= 1:
        return None
    return mesh


def _batch_axes(mesh) -> Optional[Tuple[str, ...]]:
    """Mesh axes the activation batch dim is sharded over (cf.
    strategies.batch_partition_spec) — the ring only ever communicates
    along ``axis_name``; batch stays sharded on 'data'/'expert'."""
    axes = tuple(
        ax for ax in ("data", "expert") if mesh.shape.get(ax, 1) > 1
    )
    return axes or None


def _proj_einsum(x: jax.Array, w: jax.Array) -> jax.Array:
    """The projection contraction, fp32 accumulation, both weight ranks."""
    eq = "bsd,dcf->bscf" if w.ndim == 3 else "bsd,df->bsf"
    return jnp.einsum(eq, x, w, preferred_element_type=jnp.float32)


def ag_proj_sharded(
    x: jax.Array,  # (B, S_local, D) — this shard's sequence chunk
    w: jax.Array,  # (D, F_local) or (D, C, F_local) — local feature shard
    axis_name: str = "model",
) -> jax.Array:
    """All-gather-side collective matmul body: full-sequence output rows
    for the local feature shard, comms as a ppermute ring.

    Each of the n ring steps multiplies the currently-held sequence chunk
    with the local weight shard and writes the product into its global row
    slot; the chunk rotates one neighbor hop per step, so after n steps
    every device has computed all S rows of its F_local columns without a
    bulk all-gather ever materializing. Returns (B, S_total, F_local...)
    in x.dtype (fp32 accumulation internally).
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return _proj_einsum(x, w).astype(x.dtype)
    if not _CMM_RING:
        # Injection fallback (`--inject bad-cmm-ring`): the unfused form —
        # same math, but the bulk all-gather is back and the frozen cmm
        # budget must flag it.
        xg = lax.all_gather(x, axis_name, axis=1, tiled=True)
        return _proj_einsum(xg, w).astype(x.dtype)
    idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]
    s_local = x.shape[1]
    out = jnp.zeros(
        (x.shape[0], s_local * n) + w.shape[1:], jnp.float32
    )
    chunk = x
    for i in range(n):
        # After i hops along j -> j+1, the chunk this device holds
        # originated at device (idx - i) mod n — that is its row slot.
        src = (idx - i) % n
        out = lax.dynamic_update_slice_in_dim(
            out, _proj_einsum(chunk, w), src * s_local, axis=1
        )
        if i < n - 1:
            chunk = lax.ppermute(chunk, axis_name, perm)
    return out.astype(x.dtype)


def rs_proj_sharded(
    y: jax.Array,  # (B, S_total, F_local) — full rows, local features
    w: jax.Array,  # (F_local, D) — local row shard
    axis_name: str = "model",
) -> jax.Array:
    """Reduce-scatter-side collective matmul body: the row-parallel
    partial sums accumulate around the ring instead of in a bulk
    reduce-scatter. Returns (B, S_local, D) — this shard's sequence chunk
    of the summed projection, in y.dtype (fp32 ring accumulator).

    Schedule: at step i device j contracts the sequence chunk
    ``(j - i + n - 1) mod n`` — chosen so each accumulator hop lands on
    the device that computes the SAME chunk next, and after n-1 hops the
    accumulator sits on its destination with all n partials folded in.
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return _proj_einsum(y, w).astype(y.dtype)
    if y.shape[1] % n != 0:
        # A non-dividing sequence would silently drop the trailing rows
        # from the ring's partial sums (the rs_proj wrapper guards this;
        # the sharded entry point must be loud too — it is documented
        # public API, and the injection fallback's psum_scatter would
        # only error with an opaque tiling message).
        raise ValueError(
            f"rs_proj_sharded: sequence length {y.shape[1]} does not "
            f"divide the '{axis_name}' ring size {n}"
        )
    if not _CMM_RING:
        full = _proj_einsum(y, w)
        return lax.psum_scatter(
            full, axis_name, scatter_dimension=1, tiled=True
        ).astype(y.dtype)
    idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]
    s_local = y.shape[1] // n
    acc = jnp.zeros((y.shape[0], s_local, w.shape[-1]), jnp.float32)
    for i in range(n):
        if i:
            acc = lax.ppermute(acc, axis_name, perm)
        ci = (idx - i + n - 1) % n
        chunk = lax.dynamic_slice_in_dim(y, ci * s_local, s_local, axis=1)
        acc = acc + _proj_einsum(chunk, w)
    return acc.astype(y.dtype)


def _feature_sharded(
    w: jax.Array, n: int, aligned_units: Optional[int]
) -> bool:
    """Whether the projection's feature dim shards over the tp axis —
    MUST agree with strategies.param_partition_specs: 'model' lands on the
    feature axis iff it divides, and the GQA kv projection additionally
    demands the 'model' degree divide ``kv_heads`` (the kv-head-aligned
    rule; a misaligned split has no in-place reshard)."""
    if w.shape[-1] % n != 0:
        return False
    return aligned_units is None or aligned_units % n == 0


def ag_proj(
    x: jax.Array,  # (B, S, D) global activations
    w: jax.Array,  # (D, F) or (D, C, F) global weight
    *,
    axis_name: str = "model",
    mesh: Optional[jax.sharding.Mesh] = None,
    aligned_units: Optional[int] = None,
) -> jax.Array:
    """Column-parallel projection as a collective matmul.

    The activation enters sequence-sharded over ``axis_name`` (GSPMD
    reshards it there — a local slice when the producer was replicated,
    exact when the producer was the previous block's :func:`rs_proj`), the
    weight enters feature-sharded, and the output leaves feature-sharded
    with FULL sequence rows — what attention / the MLP nonlinearity needs.

    ``aligned_units`` gates feature sharding beyond plain divisibility
    (pass ``kv_heads`` for the GQA kv projection — the kv-head-aligned
    rule): a non-shardable weight enters replicated and the ring computes
    replicated full-feature outputs instead (each device still does one
    S x F worth of dot work — the chunks just cover all features).

    Falls back to the plain einsum when no >1 ``axis_name`` axis is in
    scope, or the sequence does not divide by it.
    """
    m = _tp_mesh(axis_name, mesh)
    n = 1 if m is None else m.shape[axis_name]
    if m is None or x.shape[1] % n != 0:
        return _proj_einsum(x, w).astype(x.dtype)
    b = _batch_axes(m)
    sharded = _feature_sharded(w, n, aligned_units)
    w_spec = P(*([None] * (w.ndim - 1)), axis_name if sharded else None)
    out_spec = P(b, None, *([None] * (w.ndim - 2)),
                 axis_name if sharded else None)
    fn = jax.shard_map(
        lambda xs, ws: ag_proj_sharded(xs, ws, axis_name=axis_name),
        mesh=m,
        in_specs=(P(b, axis_name, None), w_spec),
        out_specs=out_spec,
        check_vma=False,
    )
    return fn(x, w)


def rs_proj(
    y: jax.Array,  # (B, S, F) global, feature-sharded activations
    w: jax.Array,  # (F, D) global row-parallel weight
    *,
    axis_name: str = "model",
    mesh: Optional[jax.sharding.Mesh] = None,
) -> jax.Array:
    """Row-parallel projection as a collective matmul.

    The feature-sharded activation (a :func:`ag_proj` output, through the
    elementwise middle) contracts against the row-sharded weight; the
    partial sums fold around the ppermute ring and the output leaves
    sequence-sharded over ``axis_name`` — exactly the layout the next
    residual add and :func:`ag_proj` consume, so the stream between
    projections never re-replicates.

    Falls back to the plain einsum when no >1 ``axis_name`` axis is in
    scope, the contraction dim does not shard, or the sequence does not
    divide.
    """
    m = _tp_mesh(axis_name, mesh)
    n = 1 if m is None else m.shape[axis_name]
    if m is None or y.shape[1] % n != 0 or w.shape[0] % n != 0:
        return _proj_einsum(y, w).astype(y.dtype)
    b = _batch_axes(m)
    fn = jax.shard_map(
        lambda ys, ws: rs_proj_sharded(ys, ws, axis_name=axis_name),
        mesh=m,
        in_specs=(P(b, None, axis_name), P(axis_name, None)),
        out_specs=P(b, axis_name, None),
        check_vma=False,
    )
    return fn(y, w)
