"""``python -m distributed_llm_training_benchmark_framework_tpu.regress``."""

import sys

from .compare import main

if __name__ == "__main__":
    sys.exit(main())
