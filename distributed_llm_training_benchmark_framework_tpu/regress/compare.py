"""regress CLI: ingest / compare / trend / gate over the run registry.

    python -m distributed_llm_training_benchmark_framework_tpu.regress \
        ingest --results-dir results [--registry results/registry]
    ... regress ingest --legacy            # seed from BENCH_r*/MULTICHIP_r*
    ... regress compare <id-or-sel> <id-or-sel> [--arm ARM]
    ... regress trend <arm> [--png trend.png] [--limit N]
    ... regress gate --baseline last-good --candidate latest [--arm ARM|--all]
    ... regress bisect <good> <bad> [--arm ARM]   # first-bad git-sha boundary

Exit codes mirror graftcheck (the other standing gate): 0 clean, 1 a
significant regression (gate) or a failed comparison the caller asked to
enforce, 2 operational error (schema drift, unknown record, bad usage).

Selectors accepted wherever a record is named: a record-id prefix,
``latest`` (newest record for --arm), or ``last-good`` (newest ok,
non-partial record for --arm). The gate's contract — pinned by the
frozen-fixture proof in tests/test_regress.py — is that a regression
line names the arm, the metric, the delta and the confidence interval:

    regress gate: REGRESSION arm=<arm> metric=tokens_per_sec \
        delta=-10.12% CI95=[-10.80%, -9.45%] p=... baseline=<id> candidate=<id>
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from . import stats, store


# ---------------------------------------------------------------------------
# Record resolution
# ---------------------------------------------------------------------------


def resolve_selector(
    reg: store.Registry, selector: str, arm: Optional[str],
) -> Dict[str, Any]:
    if selector == "latest":
        if not arm:
            raise KeyError("selector 'latest' needs --arm")
        rec = reg.latest(arm)
        if rec is None:
            raise KeyError(f"no records for arm {arm!r}")
        return rec
    if selector == "last-good":
        if not arm:
            raise KeyError("selector 'last-good' needs --arm")
        rec = reg.baseline(arm)
        if rec is None:
            raise KeyError(f"no ok (non-partial) records for arm {arm!r}")
        return rec
    return reg.resolve(selector)


# ---------------------------------------------------------------------------
# Comparison / gate core
# ---------------------------------------------------------------------------


def compare_pair(
    reg: store.Registry,
    base_rec: Dict[str, Any],
    cand_rec: Dict[str, Any],
    *,
    min_effect_pct: float = stats.DEFAULT_MIN_EFFECT_PCT,
    alpha: float = stats.DEFAULT_ALPHA,
) -> Dict[str, Any]:
    """Compare two records with registry history as the noise floor.

    The report's ``verdict`` is REGRESSION when ANY comparison — primary
    throughput or a secondary metric (MFU / peak HBM /
    comms_exposed_frac, ``stats.SECONDARY_METRICS``) — verdicts one;
    otherwise it is the primary comparison's verdict.
    ``gate_comparison`` is the comparison the gate line should quote:
    the first regressed one, so a secondary-only regression fails CI
    naming ITS metric, not the healthy primary's.
    """
    arm = cand_rec.get("arm", base_rec.get("arm", "?"))
    metric_name = (cand_rec.get("metric") or {}).get("name", "tokens_per_sec")
    history = reg.history_values(
        arm, metric_name=metric_name,
        exclude_record_id=cand_rec.get("record_id"),
        match_config_of=cand_rec,
    )
    secondary_history = {
        key: reg.result_history_values(
            arm, result_key=key,
            exclude_record_id=cand_rec.get("record_id"),
            match_config_of=cand_rec,
        )
        for key, _, _, _ in stats.SECONDARY_METRICS
    }
    comparisons = stats.compare_records(
        base_rec, cand_rec, min_effect_pct=min_effect_pct, alpha=alpha,
        history=history, secondary_history=secondary_history,
    )
    regressed = [c for c in comparisons
                 if c.verdict == stats.VERDICT_REGRESSION]
    if regressed:
        verdict = stats.VERDICT_REGRESSION
        gate_comparison = regressed[0]
    else:
        verdict = (comparisons[0].verdict if comparisons
                   else stats.VERDICT_INSUFFICIENT)
        gate_comparison = comparisons[0] if comparisons else None
    return {
        "arm": arm,
        "baseline": base_rec.get("record_id"),
        "candidate": cand_rec.get("record_id"),
        "comparisons": comparisons,
        "verdict": verdict,
        "gate_comparison": gate_comparison,
    }


def format_comparison(rep: Dict[str, Any]) -> str:
    lines = [
        f"== regress compare: {rep['arm']} ==",
        f"  baseline : {rep['baseline']}",
        f"  candidate: {rep['candidate']}",
    ]
    for c in rep["comparisons"]:
        lines.append(
            f"  {c.metric}: base {c.base_mean:,.2f} -> cand "
            f"{c.cand_mean:,.2f} ({c.mode}, n={c.n_base}/{c.n_cand})"
        )
        lines.append(f"    {c.summary()}")
    lines.append(f"  VERDICT: {rep['verdict']}")
    return "\n".join(lines)


def gate_arm(
    reg: store.Registry, arm: str, *,
    baseline_sel: str = "last-good", candidate_sel: str = "latest",
    min_effect_pct: float = stats.DEFAULT_MIN_EFFECT_PCT,
    alpha: float = stats.DEFAULT_ALPHA,
    bank_regressions: bool = True,
) -> Tuple[str, str]:
    """Gate one arm; returns (verdict, human line).

    A partial candidate never verdicts (its last-window rate is not a
    run mean); a resumed (stitched) candidate never verdicts either —
    its first window folds in the restore recompile, so comparing it
    would gate the recovery machinery, not the code. A missing baseline
    is insufficient-data, not a failure — the first-ever suite run on a
    fresh registry must pass the gate.

    A REGRESSION verdict on the default last-good/latest path BANKS the
    candidate (store.Registry.bank): the next run's "last known good"
    skips the regressed record instead of adopting it, so one bad merge
    cannot silently ratchet the baseline down (ROADMAP benchreg (b)).
    """
    cand = resolve_selector(reg, candidate_sel, arm)
    if cand.get("status") != "ok":
        return (stats.VERDICT_INSUFFICIENT,
                f"regress gate: SKIP arm={arm} candidate "
                f"{cand.get('record_id')} has status="
                f"{cand.get('status')!r} (partial runs never verdict)")
    if (cand.get("result") or {}).get("resumed"):
        return (stats.VERDICT_INSUFFICIENT,
                f"regress gate: SKIP arm={arm} candidate "
                f"{cand.get('record_id')} is a resumed (stitched) run — "
                "not a clean measurement; rerun the arm for a verdict")
    if (cand.get("result") or {}).get("n_rollbacks"):
        return (stats.VERDICT_INSUFFICIENT,
                f"regress gate: SKIP arm={arm} candidate "
                f"{cand.get('record_id')} is a rolled-back (sentinel-"
                "healed) run — it hit a numerics incident and replayed "
                "steps; rerun the arm for a verdict")
    if baseline_sel == "last-good":
        base = reg.baseline(
            arm, exclude_record_id=cand.get("record_id"),
            match_config_of=cand,
        )
    else:
        base = resolve_selector(reg, baseline_sel, arm)
        if base.get("status") != "ok":
            return (stats.VERDICT_INSUFFICIENT,
                    f"regress gate: SKIP arm={arm} baseline "
                    f"{base.get('record_id')} has status="
                    f"{base.get('status')!r} (partial runs are never "
                    "baselines)")
    if base is None:
        return (stats.VERDICT_INSUFFICIENT,
                f"regress gate: SKIP arm={arm} — no prior ok record with "
                "matching config (first run on this arm)")
    rep = compare_pair(
        reg, base, cand, min_effect_pct=min_effect_pct, alpha=alpha,
    )
    # The quoted comparison is the first REGRESSED one (secondary metrics
    # included — an overlap regression fails CI by name just like a
    # tokens/sec one), falling back to the primary when nothing regressed.
    c = rep["gate_comparison"] or rep["comparisons"][0]
    line = (
        f"regress gate: {rep['verdict'].upper()} arm={arm} {c.summary()} "
        f"baseline={rep['baseline']} candidate={rep['candidate']}"
    )
    if (
        bank_regressions
        and rep["verdict"] == stats.VERDICT_REGRESSION
        and baseline_sel == "last-good" and candidate_sel == "latest"
    ):
        # Bank silently-idempotently; the bank note is its own (stable)
        # line so the REGRESSION line format stays byte-pinned.
        if reg.bank(cand.get("record_id"), reason=line):
            line += (
                f"\nregress gate: banked candidate {cand.get('record_id')} "
                "as a known regression — future last-good lookups skip it "
                "(`regress unbank` to lift)"
            )
    return rep["verdict"], line


def verdict_line_for_bench(
    reg: store.Registry, record: Dict[str, Any],
) -> str:
    """bench.py's one-line verdict vs last known good (stderr channel)."""
    arm = record["arm"]
    base = reg.baseline(
        arm, exclude_record_id=record.get("record_id"),
        match_config_of=record,
    )
    if base is None:
        return (f"regress: arm={arm} first record with this configuration "
                "— no baseline to compare against")
    rep = compare_pair(reg, base, record)
    c = rep["gate_comparison"] or rep["comparisons"][0]
    return (
        f"regress: {rep['verdict'].upper()} vs last-good arm={arm} "
        f"{c.summary()} (baseline={base.get('record_id')} from "
        f"{base.get('source', '?')})"
    )


# ---------------------------------------------------------------------------
# Bisect (benchreg follow-up (b))
# ---------------------------------------------------------------------------


def bisect_records(
    reg: store.Registry, good: Dict[str, Any], bad: Dict[str, Any],
) -> Dict[str, Any]:
    """Walk the registry between a known-good and a known-bad record and
    find the first-bad boundary, keyed by the env-fingerprint git shas.

    Both records must belong to one arm and sit in ``good`` -> ``bad``
    ingest order (the registry's ``seq`` clock). The threshold is the
    midpoint of the two endpoints' metric values (direction from the
    metric's ``higher_is_better``): each intermediate ok record is
    classified good/bad against it, and the first bad one — together
    with the last good one before it — names the git-sha boundary to
    diff. Records without the metric (partials) are listed but never
    classify.
    """
    arm = good.get("arm")
    if arm != bad.get("arm"):
        raise KeyError(
            f"bisect needs two records of one arm, got {arm!r} and "
            f"{bad.get('arm')!r}"
        )

    def _val(rec):
        m = rec.get("metric") or {}
        return m.get("value")

    g_val, b_val = _val(good), _val(bad)
    if g_val is None or b_val is None:
        raise KeyError("bisect endpoints must both carry a metric value")
    higher_better = bool(
        (good.get("metric") or {}).get("higher_is_better", True)
    )
    threshold = (float(g_val) + float(b_val)) / 2.0

    recs = reg.records(arm)
    ids = [r.get("record_id") for r in recs]
    try:
        i_good, i_bad = ids.index(good.get("record_id")), ids.index(
            bad.get("record_id")
        )
    except ValueError:
        raise KeyError("bisect endpoints must both be ingested records "
                       f"of arm {arm!r}")
    if i_good >= i_bad:
        raise KeyError(
            "bisect walks ingest order: the good record must precede the "
            f"bad one (got seq {i_good} -> {i_bad})"
        )

    rows: List[Dict[str, Any]] = []
    last_good = good
    first_bad: Optional[Dict[str, Any]] = None
    for rec in recs[i_good: i_bad + 1]:
        val = _val(rec)
        verdict = None
        if rec.get("status") == "ok" and val is not None:
            is_bad = (val < threshold) if higher_better else (val > threshold)
            verdict = "bad" if is_bad else "good"
        rows.append({
            "record_id": rec.get("record_id"),
            "git_sha": (rec.get("env") or {}).get("git_sha"),
            "value": val,
            "status": rec.get("status"),
            "verdict": verdict,
        })
        if verdict == "good" and first_bad is None:
            last_good = rec
        elif verdict == "bad" and first_bad is None:
            first_bad = rec
    return {
        "arm": arm,
        "metric": (good.get("metric") or {}).get("name"),
        "threshold": threshold,
        "rows": rows,
        "last_good": last_good,
        "first_bad": first_bad,
    }


def format_bisect(rep: Dict[str, Any]) -> str:
    lines = [
        f"== regress bisect: {rep['arm']} ({rep['metric']}, threshold "
        f"{rep['threshold']:,.2f}) ==",
    ]
    for r in rep["rows"]:
        val = f"{r['value']:,.2f}" if r["value"] is not None else "-"
        lines.append(
            f"  {r['record_id']}  sha={r['git_sha'] or '?':<10} "
            f"{val:>14}  {r['verdict'] or r['status']}"
        )
    fb = rep["first_bad"]
    lg = rep["last_good"]
    if fb is None:
        lines.append(
            "  no intermediate record classifies as bad — the regression "
            "is not reproduced between these endpoints (missing history, "
            "or a noise-level delta)"
        )
    else:
        lines.append(
            f"  FIRST BAD: {fb.get('record_id')} at git sha "
            f"{(fb.get('env') or {}).get('git_sha') or '?'} "
            f"(last good {lg.get('record_id')} at "
            f"{(lg.get('env') or {}).get('git_sha') or '?'}) — diff those "
            "shas"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Trend
# ---------------------------------------------------------------------------


def trend_rows(
    reg: store.Registry, arm: str, limit: int = 0,
) -> List[Dict[str, Any]]:
    """History table rows for one arm, oldest first.

    Delta is vs the previous OK row (partials are carried in the table —
    flagged — but neither anchor deltas nor count as best; the same
    exclusion parse_metrics applies to scaling efficiency).
    """
    recs = reg.records(arm)
    if limit:
        recs = recs[-limit:]
    banked = reg.banked_ids()
    rows: List[Dict[str, Any]] = []
    prev_ok: Optional[float] = None
    best = max(
        (r.get("metric", {}).get("value") for r in recs
         if r.get("status") == "ok"
         and r.get("metric", {}).get("value") is not None),
        default=None,
    )
    for rec in recs:
        val = rec.get("metric", {}).get("value")
        delta = None
        if rec.get("status") == "ok" and val is not None and prev_ok:
            delta = 100.0 * (val - prev_ok) / prev_ok
        rows.append({
            "record_id": rec.get("record_id"),
            "status": rec.get("status"),
            "source": rec.get("source", ""),
            "metric_name": rec.get("metric", {}).get("name"),
            "value": val,
            "delta_pct_vs_prev": delta,
            "best": (rec.get("status") == "ok" and val is not None
                     and best is not None and val == best),
            "banked": rec.get("record_id") in banked,
            "resumed": bool((rec.get("result") or {}).get("resumed")),
            "rolled_back": bool(
                (rec.get("result") or {}).get("n_rollbacks")
            ),
        })
        if rec.get("status") == "ok" and val is not None:
            prev_ok = val
    return rows


def format_trend(arm: str, rows: List[Dict[str, Any]]) -> str:
    out = [f"== regress trend: {arm} ({len(rows)} records) =="]
    for r in rows:
        val = f"{r['value']:,.2f}" if r["value"] is not None else "-"
        delta = (f"{r['delta_pct_vs_prev']:+.2f}%"
                 if r["delta_pct_vs_prev"] is not None else "      ")
        flags = ("PARTIAL" if r["status"] != "ok"
                 else "BANKED" if r.get("banked")
                 else "RESUMED" if r.get("resumed")
                 else "HEALED" if r.get("rolled_back")
                 else ("BEST" if r["best"] else ""))
        out.append(
            f"  {r['record_id']}  {val:>14} {r['metric_name'] or '':<24}"
            f" {delta:>8}  {flags:<7} {r['source']}"
        )
    return "\n".join(out)


def write_trend_png(arm: str, rows: List[Dict[str, Any]], path: str) -> str:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    xs = list(range(len(rows)))
    ys = [r["value"] for r in rows]
    ok = [i for i in xs if rows[i]["status"] == "ok" and ys[i] is not None]
    bad = [i for i in xs if rows[i]["status"] != "ok" and ys[i] is not None]
    fig, ax = plt.subplots(figsize=(6, 3.2), dpi=150)
    if ok:
        ax.plot([xs[i] for i in ok], [ys[i] for i in ok],
                marker="o", color="#2a78d6", linewidth=1.2, label="ok")
    if bad:
        ax.scatter([xs[i] for i in bad], [ys[i] for i in bad],
                   marker="x", color="#c0392b", label="partial")
        ax.legend(fontsize=7)
    ax.set_xlabel("ingest order")
    ax.set_ylabel(rows[0]["metric_name"] if rows else "value")
    ax.set_title(f"{arm} trend", fontsize=9)
    ax.grid(color="#d9d8d4", linewidth=0.5)
    for spine in ("top", "right"):
        ax.spines[spine].set_visible(False)
    fig.tight_layout()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fig.savefig(path)
    plt.close(fig)
    return path


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributed_llm_training_benchmark_framework_tpu"
             ".regress",
        description="benchreg: run registry + statistical regression gate "
                    "(docs/REGRESSION.md)",
    )
    p.add_argument("--registry", default=None,
                   help="registry root (default: $REGRESS_REGISTRY or "
                        "results/registry)")
    sub = p.add_subparsers(dest="cmd", required=True)

    pi = sub.add_parser("ingest", help="ingest run artifacts into the registry")
    pi.add_argument("--results-dir", default=None,
                    help="suite results tree (result_<arm>.json + "
                         "partial_<arm>.json + telemetry JSONL siblings)")
    pi.add_argument("--legacy", action="store_true",
                    help="seed from the repo-root BENCH_r*.json / "
                         "MULTICHIP_r*.json snapshots")
    pi.add_argument("--root", default=None,
                    help="snapshot directory for --legacy (default: repo root)")

    pc = sub.add_parser("compare", help="compare two records")
    pc.add_argument("a", help="baseline: record-id prefix | latest | last-good")
    pc.add_argument("b", help="candidate: record-id prefix | latest | last-good")
    pc.add_argument("--arm", default=None,
                    help="required when a selector is latest/last-good")
    pc.add_argument("--min-effect-pct", type=float,
                    default=stats.DEFAULT_MIN_EFFECT_PCT)
    pc.add_argument("--alpha", type=float, default=stats.DEFAULT_ALPHA)

    pt = sub.add_parser("trend", help="history table (+PNG) for one arm")
    pt.add_argument("arm")
    pt.add_argument("--png", default=None, help="write a trend PNG here")
    pt.add_argument("--limit", type=int, default=0,
                    help="only the newest N records (0 = all)")

    pg = sub.add_parser("gate", help="fail (exit 1) on significant regression")
    pg.add_argument("--baseline", default="last-good",
                    help="baseline selector (default last-good)")
    pg.add_argument("--candidate", default="latest",
                    help="candidate selector (default latest)")
    pg.add_argument("--arm", default=None, help="gate one arm")
    pg.add_argument("--all", action="store_true",
                    help="gate every arm's latest vs its last-good")
    pg.add_argument("--min-effect-pct", type=float,
                    default=stats.DEFAULT_MIN_EFFECT_PCT)
    pg.add_argument("--alpha", type=float, default=stats.DEFAULT_ALPHA)

    pbi = sub.add_parser(
        "bisect",
        help="walk env fingerprints (git shas) between a good and a bad "
             "record and print the first-bad boundary",
    )
    pbi.add_argument("good", help="known-good: record-id prefix | last-good")
    pbi.add_argument("bad", help="known-bad: record-id prefix | latest")
    pbi.add_argument("--arm", default=None,
                     help="required when a selector is latest/last-good")

    sub.add_parser("list", help="list arms and record counts")

    pb = sub.add_parser(
        "bank",
        help="mark a record as a known regression (last-good skips it)",
    )
    pb.add_argument("record_id", help="record-id prefix")
    pb.add_argument("--reason", default="operator-banked")

    pu = sub.add_parser("unbank", help="lift a bank")
    pu.add_argument("record_id", help="record-id prefix")
    pu.add_argument("--reason", default="operator-unbanked")

    args = p.parse_args(argv)

    try:
        reg = store.Registry(args.registry)
    except store.SchemaDrift as e:
        print(f"regress: {e}", file=sys.stderr)
        return 2

    try:
        if args.cmd == "ingest":
            if not args.legacy and not args.results_dir:
                p.error("ingest needs --results-dir and/or --legacy")
            ingested: List[Tuple[Dict[str, Any], bool]] = []
            if args.legacy:
                ingested += store.ingest_legacy(reg, args.root)
            if args.results_dir:
                ingested += store.ingest_results_dir(reg, args.results_dir)
            created = sum(1 for _, c in ingested if c)
            print(f"regress ingest: {len(ingested)} artifact(s) scanned, "
                  f"{created} new record(s) -> {reg.root}")
            for rec, c in ingested:
                if c:
                    print(f"  + {rec['arm']} {rec['record_id']} "
                          f"[{rec['status']}] from {rec.get('source', '')}")
            return 0

        if args.cmd == "compare":
            a = resolve_selector(reg, args.a, args.arm)
            b = resolve_selector(reg, args.b, args.arm)
            rep = compare_pair(
                reg, a, b, min_effect_pct=args.min_effect_pct,
                alpha=args.alpha,
            )
            print(format_comparison(rep))
            return 1 if rep["verdict"] == stats.VERDICT_REGRESSION else 0

        if args.cmd == "bisect":
            good = resolve_selector(reg, args.good, args.arm)
            bad = resolve_selector(reg, args.bad, args.arm)
            rep = bisect_records(reg, good, bad)
            print(format_bisect(rep))
            return 0

        if args.cmd == "trend":
            rows = trend_rows(reg, args.arm, limit=args.limit)
            if not rows:
                print(f"regress trend: no records for arm {args.arm!r} "
                      f"in {reg.root}", file=sys.stderr)
                return 2
            print(format_trend(args.arm, rows))
            if args.png:
                print(f"Wrote {write_trend_png(args.arm, rows, args.png)}")
            return 0

        if args.cmd == "gate":
            if args.all:
                arms = [a for a in reg.arms()]
            elif args.arm:
                arms = [args.arm]
            else:
                p.error("gate needs --arm or --all")
            n_regressions = 0
            for arm in arms:
                verdict, line = gate_arm(
                    reg, arm, baseline_sel=args.baseline,
                    candidate_sel=args.candidate,
                    min_effect_pct=args.min_effect_pct, alpha=args.alpha,
                )
                print(line)
                if verdict == stats.VERDICT_REGRESSION:
                    n_regressions += 1
            # The summary names the secondary-metric roster so a gate
            # transcript is self-describing about WHAT was policed —
            # scripts/regress_gate.sh surfaces this line as its verdict.
            secondaries = ", ".join(
                key for key, _hib, _eff, _scale in stats.SECONDARY_METRICS
            )
            print(f"regress gate: {len(arms)} arm(s) checked, "
                  f"{n_regressions} regression(s) "
                  f"(secondaries gated: {secondaries})")
            return 1 if n_regressions else 0

        if args.cmd == "list":
            banked = reg.banked_ids()
            for arm in reg.arms():
                lines = [l for l in reg.index_lines() if l["arm"] == arm]
                n_ok = sum(1 for l in lines if l["status"] == "ok")
                n_banked = sum(1 for l in lines
                               if l["record_id"] in banked)
                extra = f", {n_banked} banked" if n_banked else ""
                print(f"{arm}: {len(lines)} record(s) ({n_ok} ok{extra})")
            return 0

        if args.cmd in ("bank", "unbank"):
            rec = reg.resolve(args.record_id)
            if args.cmd == "bank":
                changed = reg.bank(rec["record_id"], reason=args.reason)
                verb = "banked" if changed else "already banked"
            else:
                changed = reg.unbank(rec["record_id"], reason=args.reason)
                verb = "unbanked" if changed else "was not banked"
            print(f"regress {args.cmd}: {rec['arm']} {rec['record_id']} "
                  f"{verb}")
            return 0
    except store.SchemaDrift as e:
        print(f"regress: {e}", file=sys.stderr)
        return 2
    except KeyError as e:
        print(f"regress: {e.args[0] if e.args else e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
