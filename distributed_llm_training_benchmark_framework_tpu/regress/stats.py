"""The shared statistics engine behind every regression verdict.

One module owns the math so the gate (``regress.compare``), the telemetry
comparison (``analysis.telemetry_report --compare``) and the trend tables
cannot drift apart — the ISSUE-4 acceptance contract. Everything here is
deterministic: the bootstrap is seeded, the permutation test is seeded,
and there is no wall-clock or platform dependence, so the frozen-fixture
gate proof (tests/test_regress.py) byte-reproduces everywhere.

Why distributions, not single numbers: a benchmark arm's published
tokens/sec is a mean over ~100 steps, and two means 3% apart say nothing
without the spread behind them. The flight recorder (PR 3) already
persists per-window step times at every sync boundary; those windows are
the per-run sample this module feeds into

- a **seeded bootstrap** for confidence intervals on the relative delta
  of means (percentile method — no normality assumption);
- a **Mann-Whitney U** rank test (normal approximation with tie
  correction) for windows-sized samples, falling back to a **seeded
  permutation test** of the mean difference when either side is tiny;
- a **noise floor** estimated from repeated same-arm runs in the
  registry (the legacy BENCH_r02..r05 snapshots alone pin bench-headline
  run-to-run noise at well under 1%), so the minimum effect a verdict
  requires is max(configured threshold, observed noise) — raw deltas
  never verdict on their own.

Verdicts are the closed set {regression, improvement, neutral,
insufficient-data}: a significant-but-tiny delta is *neutral* (below the
minimum effect), a large-but-unsupported delta is *neutral* (failed the
significance test), and too few samples is *insufficient-data*, never a
silent pass pretending to be evidence.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

#: Fewer timed windows than this on either side -> insufficient-data in
#: window mode (a 100-step, sync-every-10 run yields ~9; smoke runs less).
MIN_WINDOWS = 4
#: Two-sided significance level for the rank/permutation test.
DEFAULT_ALPHA = 0.05
#: Minimum relative effect (%) a verdict requires even when the noise
#: floor is lower — sub-2% deltas on these arms are weather, not climate.
DEFAULT_MIN_EFFECT_PCT = 2.0
#: Noise floor assumed when the registry holds too little same-arm
#: history to estimate one.
DEFAULT_NOISE_FLOOR_PCT = 1.0
#: Scalar mode has no within-run distribution, so its verdict leans
#: entirely on the history-derived noise floor — below this many
#: same-config history runs the floor is a guess, and a guess must not
#: mint a regression: the comparison reports insufficient-data instead.
#: (Window mode needs no history and verdicts from run #2.)
MIN_SCALAR_HISTORY = 3
#: Bootstrap resamples and the fixed seed (determinism is a feature: the
#: gate must give the same verdict on the same records every time).
BOOTSTRAP_N = 2000
BOOTSTRAP_SEED = 20260803
PERMUTATION_N = 4000
#: Below this per-side size the normal approximation is shaky; use the
#: permutation test instead.
SMALL_SAMPLE_N = 5

VERDICT_REGRESSION = "regression"
VERDICT_IMPROVEMENT = "improvement"
VERDICT_NEUTRAL = "neutral"
VERDICT_INSUFFICIENT = "insufficient-data"

#: First-class SECONDARY metrics the gate verdicts beside the primary
#: (benchreg follow-up (a) / step-anatomy round): each entry names a
#: result-row key, its direction sign, its minimum-effect floor and its
#: comparison scale. ``rel`` compares relative deltas (percent of
#: baseline); ``abs_pp`` compares fractions on an absolute
#: percentage-point scale — a comms_exposed_frac of 0.00 -> 0.05 is a
#: 5-point regression, not an undefined relative delta. All scalar-mode:
#: one value per run, noise floor learned from same-config registry
#: history (MIN_SCALAR_HISTORY applies, so sparse history reports
#: insufficient-data instead of minting verdicts).
SECONDARY_METRICS = (
    # (result key, higher_is_better, min effect, scale)
    ("mfu_pct", True, 2.0, "rel"),
    ("peak_hbm_gb", False, 5.0, "rel"),
    ("comms_exposed_frac", False, 2.0, "abs_pp"),
    # Scaling observatory: the 0-1 fraction of ideal per-chip throughput
    # vs the suite's smallest-geometry base, stamped onto clean rows by
    # analysis.scaling.stamp_results_dir before ingest. Absolute pp scale
    # like comms_exposed_frac (a 2 pp efficiency drop at ws=8 is a
    # regression even when the ws=8 absolute throughput sits inside its
    # own noise floor) — the arm slug names the geometry in the gate line.
    ("scaling_efficiency", True, 2.0, "abs_pp"),
    # Pipeline-arm bubble fraction (step-anatomy device idle — only
    # pipeline rows carry it, others skip via the both-rows-present
    # rule). Absolute pp scale like comms_exposed_frac: a schedule whose
    # bubble grew 2pp regressed even when wall-clock noise hides it —
    # the dynamic half of the schedule auditor's structural bubble
    # bound (docs/STATIC_ANALYSIS.md).
    ("bubble_frac", False, 2.0, "abs_pp"),
    # Memory-anatomy model drift (analysis/memory_anatomy.py):
    # |reference peak − analytic estimate| / analytic, where the
    # reference is the allocator's measured peak (or XLA's
    # buffer-assignment peak on backends without memory_stats). Lower is
    # better — a growing drift means utils/memory.py's analytic model is
    # decaying, which silently degrades the pre-flight OOM refusals and
    # the auto-remat resolver that trust it. Absolute pp scale (a
    # healthy drift can legitimately sit near 0); 5 pp floor because the
    # model's documented accuracy band is ±20% — the gate polices
    # DECAY, not the residual itself.
    ("hbm_model_drift_frac", False, 5.0, "abs_pp"),
    # Streaming-data-path input starvation (train/loop.py + data/
    # prefetch.py): the fraction of timed step wall the loop spent
    # starved for input. Only streaming (--data-path) rows carry it
    # (synthetic rows publish null, so the both-rows-present rule skips
    # them). Absolute pp scale like the other fractions — a healthy
    # stream legitimately sits at ~0, where a relative delta is
    # undefined; 2 pp of new input-boundedness is a regression even when
    # the wall-clock delta hides inside the throughput noise floor.
    ("data_stall_frac", False, 2.0, "abs_pp"),
)
#: Absolute-scale fallback noise floor (percentage points) below 3
#: same-config history runs.
DEFAULT_NOISE_FLOOR_PP = 1.0


# ---------------------------------------------------------------------------
# Telemetry extraction
# ---------------------------------------------------------------------------


def timed_windows(
    events: Sequence[Dict[str, Any]], *, mask_spikes: bool = False,
) -> List[Dict[str, Any]]:
    """The comparable sample: ``step_window`` events from the timed phase.

    Compile/warmup windows are excluded (their times measure XLA, not the
    step); a run that never reached the timed phase yields [] and the
    comparison degrades to scalar mode rather than comparing warmup noise.
    ``mask_spikes`` additionally drops windows the recorder flagged inside
    an open ``step_time_spike`` anomaly (see :func:`split_masked_windows`
    for the count — masking must never be silent).
    """
    kept, _ = split_masked_windows(events, mask_spikes=mask_spikes)
    return kept


def split_masked_windows(
    events: Sequence[Dict[str, Any]], *, mask_spikes: bool = True,
) -> tuple:
    """Timed windows split into (kept, spike-masked) lists.

    Window-level anomaly masking (benchreg follow-up (c)): a window that
    ran during an open recorder spike measures the stall, not the code —
    comparing it verdicts the incident. The masked windows are returned
    (not dropped on the floor) so every consumer can surface a
    ``masked_windows`` count beside its verdict.

    Sentinel rollbacks (self-healing round) mask the same way: a
    ``rollback`` event means every window in ``(to_step, from_step]`` was
    measured twice — once poisoned, once replaying over the restore — and
    neither copy is run-to-run jitter of the code under test. Both copies
    leave the comparison sample, mirroring the result row's
    replayed-steps exclusion.
    """
    from ..telemetry import spike_mask_intervals, step_in_spike

    events = list(events)
    intervals = spike_mask_intervals(events) if mask_spikes else []
    rollbacks = [
        (e.get("to_step"), e.get("from_step"))
        for e in events
        if e.get("event") == "rollback"
        and e.get("to_step") is not None and e.get("from_step") is not None
    ]

    def in_rollback(step):
        return step is not None and any(
            lo < step <= hi for lo, hi in rollbacks
        )

    kept: List[Dict[str, Any]] = []
    masked: List[Dict[str, Any]] = []
    for e in events:
        if e.get("event") != "step_window" or e.get("phase") != "timed":
            continue
        dt = e.get("window_mean_step_time_sec")
        if dt is None or dt <= 0:
            continue
        w = {
            "step": e.get("step"),
            "steps_in_window": e.get("steps_in_window", 1),
            "dt": float(dt),
            "loss": e.get("loss"),
        }
        if (
            (intervals and step_in_spike(e.get("step"), intervals))
            or in_rollback(e.get("step"))
        ):
            masked.append(w)
        else:
            kept.append(w)
    return kept, masked


def window_step_times(record: Dict[str, Any]) -> List[float]:
    return [w["dt"] for w in record.get("windows", []) if w.get("dt")]


def window_tokens_per_sec(record: Dict[str, Any]) -> List[float]:
    """Per-window throughput: tokens_per_step / window mean step time."""
    tps = record.get("tokens_per_step", 0) or 0
    if tps <= 0:
        return []
    return [tps / w["dt"] for w in record.get("windows", []) if w.get("dt")]


# ---------------------------------------------------------------------------
# Core statistics (all seeded / closed-form)
# ---------------------------------------------------------------------------


def _phi(z: float) -> float:
    """Standard normal CDF via erf (no scipy dependency)."""
    return 0.5 * (1.0 + math.erf(z / math.sqrt(2.0)))


def mann_whitney_p(a: Sequence[float], b: Sequence[float]) -> float:
    """Two-sided Mann-Whitney U p-value (normal approx, tie-corrected).

    Identical samples (zero rank variance) return p=1.0 — indistinguishable
    by construction.
    """
    n1, n2 = len(a), len(b)
    if n1 == 0 or n2 == 0:
        return 1.0
    pooled = sorted([(v, 0) for v in a] + [(v, 1) for v in b])
    # Average ranks over ties.
    ranks = [0.0] * len(pooled)
    i = 0
    while i < len(pooled):
        j = i
        while j < len(pooled) and pooled[j][0] == pooled[i][0]:
            j += 1
        avg_rank = (i + j + 1) / 2.0  # ranks are 1-based
        for k in range(i, j):
            ranks[k] = avg_rank
        i = j
    r1 = sum(r for r, (_, grp) in zip(ranks, pooled) if grp == 0)
    u1 = r1 - n1 * (n1 + 1) / 2.0
    mu = n1 * n2 / 2.0
    n = n1 + n2
    # Tie-corrected variance.
    tie_term = 0.0
    i = 0
    while i < len(pooled):
        j = i
        while j < len(pooled) and pooled[j][0] == pooled[i][0]:
            j += 1
        t = j - i
        tie_term += t**3 - t
        i = j
    var = n1 * n2 / 12.0 * ((n + 1) - tie_term / (n * (n - 1)))
    if var <= 0:
        return 1.0
    z = (u1 - mu - math.copysign(0.5, u1 - mu)) / math.sqrt(var)
    return max(min(2.0 * (1.0 - _phi(abs(z))), 1.0), 0.0)


def permutation_p(
    a: Sequence[float], b: Sequence[float],
    n_perm: int = PERMUTATION_N, seed: int = BOOTSTRAP_SEED,
) -> float:
    """Two-sided permutation test of the mean difference (seeded)."""
    n1, n2 = len(a), len(b)
    if n1 == 0 or n2 == 0:
        return 1.0
    pooled = np.asarray(list(a) + list(b), dtype=float)
    observed = abs(float(np.mean(pooled[:n1]) - np.mean(pooled[n1:])))
    rng = np.random.default_rng(seed)
    hits = 0
    for _ in range(n_perm):
        perm = rng.permutation(pooled)
        if abs(float(np.mean(perm[:n1]) - np.mean(perm[n1:]))) >= observed - 1e-15:
            hits += 1
    # +1 smoothing: a permutation p-value of exactly 0 overstates evidence.
    return (hits + 1) / (n_perm + 1)


def significance_p(a: Sequence[float], b: Sequence[float]) -> float:
    """Rank test at window sizes; permutation test for tiny samples."""
    if min(len(a), len(b)) < SMALL_SAMPLE_N:
        return permutation_p(a, b)
    return mann_whitney_p(a, b)


def bootstrap_mean_ci(
    samples: Sequence[float], *, confidence: float = 0.95,
    n_boot: int = BOOTSTRAP_N, seed: int = BOOTSTRAP_SEED,
) -> tuple:
    """Seeded percentile-bootstrap CI on the mean of one sample."""
    x = np.asarray(samples, dtype=float)
    if x.size == 0:
        return (float("nan"), float("nan"))
    rng = np.random.default_rng(seed)
    means = np.mean(
        x[rng.integers(0, x.size, size=(n_boot, x.size))], axis=1
    )
    lo = (1.0 - confidence) / 2.0
    return (float(np.quantile(means, lo)),
            float(np.quantile(means, 1.0 - lo)))


def bootstrap_delta_ci_pct(
    base: Sequence[float], cand: Sequence[float], *,
    confidence: float = 0.95, n_boot: int = BOOTSTRAP_N,
    seed: int = BOOTSTRAP_SEED,
) -> tuple:
    """Seeded CI on the relative delta of means, in percent of baseline."""
    a = np.asarray(base, dtype=float)
    b = np.asarray(cand, dtype=float)
    if a.size == 0 or b.size == 0 or float(np.mean(a)) == 0.0:
        return (float("nan"), float("nan"))
    rng = np.random.default_rng(seed)
    am = np.mean(a[rng.integers(0, a.size, size=(n_boot, a.size))], axis=1)
    bm = np.mean(b[rng.integers(0, b.size, size=(n_boot, b.size))], axis=1)
    deltas = 100.0 * (bm - am) / am
    lo = (1.0 - confidence) / 2.0
    return (float(np.quantile(deltas, lo)),
            float(np.quantile(deltas, 1.0 - lo)))


def noise_floor_pct(values: Sequence[float]) -> float:
    """Run-to-run noise estimate from repeated same-arm measurements.

    2x the coefficient of variation (~95% band under roughly-normal
    noise); falls back to DEFAULT_NOISE_FLOOR_PCT below 3 samples.
    """
    x = np.asarray(values, dtype=float)
    x = x[np.isfinite(x)]
    if x.size < 3 or float(np.mean(x)) == 0.0:
        return DEFAULT_NOISE_FLOOR_PCT
    cv = float(np.std(x) / abs(np.mean(x)))
    return max(200.0 * cv, 0.0)


def noise_floor_abs(values: Sequence[float]) -> float:
    """Absolute-scale noise floor: 2x the history's standard deviation.

    The percentage-point analogue of :func:`noise_floor_pct` for metrics
    whose baseline can legitimately be ~0 (comms_exposed_frac) — a
    relative CV there divides by nothing. Falls back to
    DEFAULT_NOISE_FLOOR_PP below 3 samples.
    """
    x = np.asarray(values, dtype=float)
    x = x[np.isfinite(x)]
    if x.size < 3:
        return DEFAULT_NOISE_FLOOR_PP
    return max(2.0 * float(np.std(x)), 0.0)


# ---------------------------------------------------------------------------
# Verdicts
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MetricComparison:
    """One metric's baseline-vs-candidate outcome, with its evidence."""

    metric: str
    higher_is_better: bool
    mode: str  # 'windows' | 'scalar'
    n_base: int
    n_cand: int
    base_mean: float
    cand_mean: float
    delta_pct: float
    ci_lo_pct: float
    ci_hi_pct: float
    p_value: Optional[float]
    threshold_pct: float
    verdict: str
    note: str = ""
    #: Unit of delta/CI/threshold: "%" (relative to baseline) or "pp"
    #: (absolute percentage points — the abs_pp secondary metrics, whose
    #: baseline can legitimately be 0 so a relative delta is undefined).
    unit: str = "%"

    def summary(self) -> str:
        u = self.unit
        ci = (f"CI95=[{self.ci_lo_pct:+.2f}{u}, {self.ci_hi_pct:+.2f}{u}]"
              if math.isfinite(self.ci_lo_pct) else "CI95=[n/a]")
        p = f" p={self.p_value:.4g}" if self.p_value is not None else ""
        return (
            f"metric={self.metric} delta={self.delta_pct:+.2f}{u} {ci}{p} "
            f"threshold={self.threshold_pct:.2f}{u} verdict={self.verdict}"
            + (f" ({self.note})" if self.note else "")
        )


def _classify(
    delta_pct: float, ci_lo: float, ci_hi: float, p: Optional[float],
    *, higher_is_better: bool, threshold_pct: float, alpha: float,
) -> str:
    """Shared verdict rule (see module docstring for the semantics)."""
    if higher_is_better:
        worse = delta_pct <= -threshold_pct
        better = delta_pct >= threshold_pct
        ci_excludes_zero_worse = math.isfinite(ci_hi) and ci_hi < 0.0
        ci_excludes_zero_better = math.isfinite(ci_lo) and ci_lo > 0.0
    else:
        worse = delta_pct >= threshold_pct
        better = delta_pct <= -threshold_pct
        ci_excludes_zero_worse = math.isfinite(ci_lo) and ci_lo > 0.0
        ci_excludes_zero_better = math.isfinite(ci_hi) and ci_hi < 0.0
    significant = p is None or p < alpha
    if worse and significant and ci_excludes_zero_worse:
        return VERDICT_REGRESSION
    if better and significant and ci_excludes_zero_better:
        return VERDICT_IMPROVEMENT
    return VERDICT_NEUTRAL


def compare_distributions(
    base: Sequence[float], cand: Sequence[float], *,
    metric: str, higher_is_better: bool,
    min_effect_pct: float = DEFAULT_MIN_EFFECT_PCT,
    alpha: float = DEFAULT_ALPHA,
    noise_pct: float = 0.0,
) -> MetricComparison:
    """Window-distribution comparison (the preferred mode)."""
    threshold = max(min_effect_pct, noise_pct)
    n1, n2 = len(base), len(cand)
    if n1 < MIN_WINDOWS or n2 < MIN_WINDOWS:
        return MetricComparison(
            metric=metric, higher_is_better=higher_is_better,
            mode="windows", n_base=n1, n_cand=n2,
            base_mean=float(np.mean(base)) if n1 else float("nan"),
            cand_mean=float(np.mean(cand)) if n2 else float("nan"),
            delta_pct=float("nan"), ci_lo_pct=float("nan"),
            ci_hi_pct=float("nan"), p_value=None,
            threshold_pct=threshold, verdict=VERDICT_INSUFFICIENT,
            note=f"need >= {MIN_WINDOWS} timed windows per side "
                 f"(have {n1} vs {n2})",
        )
    bm, cm = float(np.mean(base)), float(np.mean(cand))
    delta_pct = 100.0 * (cm - bm) / bm if bm else float("nan")
    ci_lo, ci_hi = bootstrap_delta_ci_pct(base, cand)
    p = significance_p(base, cand)
    verdict = _classify(
        delta_pct, ci_lo, ci_hi, p, higher_is_better=higher_is_better,
        threshold_pct=threshold, alpha=alpha,
    )
    return MetricComparison(
        metric=metric, higher_is_better=higher_is_better, mode="windows",
        n_base=n1, n_cand=n2, base_mean=bm, cand_mean=cm,
        delta_pct=delta_pct, ci_lo_pct=ci_lo, ci_hi_pct=ci_hi, p_value=p,
        threshold_pct=threshold, verdict=verdict,
    )


def compare_scalars(
    base_value: float, cand_value: float, *,
    metric: str, higher_is_better: bool,
    history: Sequence[float] = (),
    min_effect_pct: float = DEFAULT_MIN_EFFECT_PCT,
    absolute: bool = False,
) -> MetricComparison:
    """Scalar-vs-history comparison for runs without telemetry windows.

    One number per side means no within-run distribution, so the
    registry's same-arm history supplies the spread: the verdict band is
    the noise floor around the baseline, and the reported interval is
    the delta +/- that floor. No p-value is claimed — there is no test
    statistic to compute from two scalars.

    ``absolute=True`` compares on the values' own (pre-scaled) absolute
    scale instead of percent-of-baseline: delta/CI/threshold are then all
    in the same units as the inputs (the secondary-metric ``abs_pp``
    entries pre-scale fractions to percentage points), and a zero
    baseline is a legal value rather than a division hazard.
    """
    history = [v for v in history if v is not None]
    noise = noise_floor_abs(history) if absolute else noise_floor_pct(history)
    threshold = max(min_effect_pct, noise)
    unit = "pp" if absolute else "%"
    missing = base_value is None or cand_value is None
    if not absolute and not missing and not base_value:
        missing = True  # relative scale needs a nonzero baseline
    if missing:
        return MetricComparison(
            metric=metric, higher_is_better=higher_is_better, mode="scalar",
            n_base=1 if base_value is not None else 0,
            n_cand=1 if cand_value is not None else 0,
            base_mean=float(base_value if base_value is not None else "nan"),
            cand_mean=float(cand_value if cand_value is not None else "nan"),
            delta_pct=float("nan"), ci_lo_pct=float("nan"),
            ci_hi_pct=float("nan"), p_value=None, threshold_pct=threshold,
            verdict=VERDICT_INSUFFICIENT, note="missing metric value",
            unit=unit,
        )
    if absolute:
        delta_pct = float(cand_value) - float(base_value)
    else:
        delta_pct = 100.0 * (cand_value - base_value) / base_value
    ci_lo, ci_hi = delta_pct - noise, delta_pct + noise
    if len(history) < MIN_SCALAR_HISTORY:
        # The delta is still reported (trend/triage value) but an
        # unlearned noise floor must not verdict (see MIN_SCALAR_HISTORY).
        verdict = VERDICT_INSUFFICIENT
    else:
        verdict = _classify(
            delta_pct, ci_lo, ci_hi, None, higher_is_better=higher_is_better,
            threshold_pct=threshold, alpha=DEFAULT_ALPHA,
        )
    return MetricComparison(
        metric=metric, higher_is_better=higher_is_better, mode="scalar",
        n_base=1, n_cand=1, base_mean=float(base_value),
        cand_mean=float(cand_value), delta_pct=delta_pct,
        ci_lo_pct=ci_lo, ci_hi_pct=ci_hi, p_value=None,
        threshold_pct=threshold, verdict=verdict, unit=unit,
        note=(
            f"scalar mode, noise floor {noise:.2f}{unit} "
            f"from {len(history)} history runs"
            + (" (absolute pp scale)" if absolute else "")
            + ("" if len(history) >= MIN_SCALAR_HISTORY else
               f" — need >= {MIN_SCALAR_HISTORY} for a verdict")
        ),
    )


def secondary_comparisons(
    base_rec: Dict[str, Any], cand_rec: Dict[str, Any], *,
    secondary_history: Optional[Dict[str, Sequence[float]]] = None,
) -> List[MetricComparison]:
    """Scalar comparisons for every SECONDARY metric both rows carry.

    Benchreg follow-up (a): MFU, peak HBM and the step-anatomy
    comms_exposed_frac verdict beside the primary throughput metric, each
    with its own direction sign, minimum effect and (per-metric,
    same-config) noise-floor history. Metrics absent from either result
    row are skipped — old records stay comparable.
    """
    out: List[MetricComparison] = []
    br = base_rec.get("result") or {}
    cr = cand_rec.get("result") or {}
    hist = secondary_history or {}
    for key, hib, min_eff, scale in SECONDARY_METRICS:
        bv, cv = br.get(key), cr.get(key)
        if bv is None or cv is None:
            continue
        values = [v for v in hist.get(key, ()) if v is not None]
        if scale == "abs_pp":
            # Fractions verdict on an absolute percentage-point scale.
            bv, cv = float(bv) * 100.0, float(cv) * 100.0
            values = [float(v) * 100.0 for v in values]
        out.append(compare_scalars(
            bv, cv, metric=key, higher_is_better=hib, history=values,
            min_effect_pct=min_eff, absolute=(scale == "abs_pp"),
        ))
    return out


def compare_records(
    base_rec: Dict[str, Any], cand_rec: Dict[str, Any], *,
    min_effect_pct: float = DEFAULT_MIN_EFFECT_PCT,
    alpha: float = DEFAULT_ALPHA,
    history: Sequence[float] = (),
    secondary_history: Optional[Dict[str, Sequence[float]]] = None,
) -> List[MetricComparison]:
    """Compare two registry records; first comparison is the gate metric.

    Window mode when both records carry enough timed windows (primary:
    per-window tokens/sec; secondary: step time); scalar mode against
    registry history otherwise. Either way the SECONDARY metric
    comparisons (MFU / peak HBM / comms_exposed_frac — see
    :data:`SECONDARY_METRICS`) are appended after the primary ones.
    Partial candidates/baselines are the caller's (``regress.compare``)
    responsibility to refuse — this function compares whatever it is
    handed.
    """
    out: List[MetricComparison] = []
    b_tps = window_tokens_per_sec(base_rec)
    c_tps = window_tokens_per_sec(cand_rec)
    noise = noise_floor_pct(history) if history else 0.0
    if len(b_tps) >= MIN_WINDOWS and len(c_tps) >= MIN_WINDOWS:
        out.append(compare_distributions(
            b_tps, c_tps, metric="tokens_per_sec", higher_is_better=True,
            min_effect_pct=min_effect_pct, alpha=alpha, noise_pct=noise,
        ))
        out.append(compare_distributions(
            window_step_times(base_rec), window_step_times(cand_rec),
            metric="window_mean_step_time_sec", higher_is_better=False,
            min_effect_pct=min_effect_pct, alpha=alpha, noise_pct=noise,
        ))
    else:
        bm = (base_rec.get("metric") or {})
        cm = (cand_rec.get("metric") or {})
        name = cm.get("name") or bm.get("name") or "metric"
        out.append(compare_scalars(
            bm.get("value"), cm.get("value"), metric=name,
            higher_is_better=bool(cm.get("higher_is_better", True)),
            history=history, min_effect_pct=min_effect_pct,
        ))
    out.extend(secondary_comparisons(
        base_rec, cand_rec, secondary_history=secondary_history,
    ))
    # Window-level anomaly masking is never silent: the counts ride the
    # primary comparison's note (and so its summary()/gate line).
    masked_b = int(base_rec.get("masked_windows", 0) or 0)
    masked_c = int(cand_rec.get("masked_windows", 0) or 0)
    if out and (masked_b or masked_c):
        extra = f"masked_windows={masked_b}/{masked_c}"
        out[0].note = f"{out[0].note}, {extra}" if out[0].note else extra
    return out


# ---------------------------------------------------------------------------
# Telemetry-file comparison (analysis.telemetry_report --compare)
# ---------------------------------------------------------------------------


def compare_telemetry(
    events_a: Sequence[Dict[str, Any]], events_b: Sequence[Dict[str, Any]], *,
    min_effect_pct: float = DEFAULT_MIN_EFFECT_PCT,
    alpha: float = DEFAULT_ALPHA,
) -> Dict[str, Any]:
    """Two telemetry JSONL event streams -> per-phase + per-window deltas.

    The ROADMAP's ``telemetry_report --compare A B`` regression-triage
    mode: phase-time attribution deltas (where did the extra wall time
    go) plus the window-distribution comparisons on the timed phase
    (did the step itself get slower, with what confidence).
    """
    from ..analysis.telemetry_report import build_timeline
    from ..telemetry import PHASES

    tla, tlb = build_timeline(list(events_a)), build_timeline(list(events_b))
    phases: List[Dict[str, Any]] = []
    present = set(tla["phase_times"]) | set(tlb["phase_times"])
    ordered = [ph for ph in PHASES if ph in present] + sorted(
        present - set(PHASES)
    )
    for phase in ordered:
        a = tla["phase_times"].get(phase)
        b = tlb["phase_times"].get(phase)
        phases.append({
            "phase": phase, "a_sec": a, "b_sec": b,
            "delta_sec": (b - a) if (a is not None and b is not None) else None,
            "delta_pct": (100.0 * (b - a) / a)
            if (a and b is not None) else None,
        })
    wa, masked_a = split_masked_windows(events_a)
    wb, masked_b = split_masked_windows(events_b)
    meta_a, meta_b = tla["meta"], tlb["meta"]
    comparisons: List[MetricComparison] = [compare_distributions(
        [w["dt"] for w in wa], [w["dt"] for w in wb],
        metric="window_mean_step_time_sec", higher_is_better=False,
        min_effect_pct=min_effect_pct, alpha=alpha,
    )]
    tps_a = int(meta_a.get("tokens_per_step", 0) or 0)
    tps_b = int(meta_b.get("tokens_per_step", 0) or 0)
    if tps_a > 0 and tps_b > 0:
        comparisons.insert(0, compare_distributions(
            [tps_a / w["dt"] for w in wa], [tps_b / w["dt"] for w in wb],
            metric="tokens_per_sec", higher_is_better=True,
            min_effect_pct=min_effect_pct, alpha=alpha,
        ))
    if masked_a or masked_b:
        # The masking rides the PRIMARY comparison's note (and so its
        # summary line / the verdict line) — never silent.
        extra = f"masked_windows={len(masked_a)}/{len(masked_b)}"
        comparisons[0].note = (
            f"{comparisons[0].note}, {extra}" if comparisons[0].note
            else extra
        )
    return {
        "a": {"arm": meta_a.get("arm"), "wall": tla["wall"],
              "n_timed_windows": len(wa),
              "masked_windows": len(masked_a)},
        "b": {"arm": meta_b.get("arm"), "wall": tlb["wall"],
              "n_timed_windows": len(wb),
              "masked_windows": len(masked_b)},
        "phases": phases,
        "comparisons": comparisons,
    }
