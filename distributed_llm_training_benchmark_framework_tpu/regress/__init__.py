"""benchreg: persistent run registry + statistical regression gate.

The framework's accumulation layer (docs/REGRESSION.md). Every completed
run's evidence — the result row, its telemetry JSONL windows, an
environment fingerprint — ingests into an append-only, content-addressed
registry under ``results/registry/``; the statistics engine turns two
records (or two telemetry files) into a {regression, improvement,
neutral, insufficient-data} verdict with seeded-bootstrap confidence
intervals and a registry-derived noise floor; and the gate makes that
verdict an exit code the suite's finish path enforces.

    regress.store    the registry (schema-versioned records, partials
                     stored but never baseline-eligible, schema drift
                     refused loudly)
    regress.stats    seeded bootstrap CIs, Mann-Whitney/permutation
                     significance, noise floor, verdict classifier —
                     shared with telemetry_report --compare
    regress.compare  the CLI: ingest / compare / trend / gate
                     (python -m ...regress, scripts/regress_gate.sh)
"""

from .store import (  # noqa: F401
    REGISTRY_SCHEMA_VERSION,
    Registry,
    SchemaDrift,
    default_registry_root,
    ingest_legacy,
    ingest_results_dir,
    make_record,
    record_from_bench_row,
)
from .stats import (  # noqa: F401
    VERDICT_IMPROVEMENT,
    VERDICT_INSUFFICIENT,
    VERDICT_NEUTRAL,
    VERDICT_REGRESSION,
    MetricComparison,
)
