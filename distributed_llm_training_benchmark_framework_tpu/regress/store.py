"""The run registry: append-only, content-addressed benchmark history.

Every other layer of the framework produces evidence about ONE run — a
result row (utils.metrics), a telemetry JSONL (telemetry.recorder), a
salvaged partial (scripts/collect_results.sh) — and until this module
nothing accumulated it: suite runs were compared by eyeball against stale
markdown. The registry is the persistence layer the regression gate
(``regress.compare``) and the trend reports read from.

Layout (default root ``results/registry/``, override with the
``REGRESS_REGISTRY`` env var or every CLI's ``--registry``):

    registry_meta.json            {"schema_version": N} — writer version
    index.jsonl                   one line per ingest, append-only:
                                  {seq, record_id, arm, status,
                                   metric_name, metric_value,
                                   ingested_at, source}
    records/<arm>/<record_id>.json   the full record

Properties the gate relies on:

- **Append-only.** Records are never rewritten; ``index.jsonl`` only
  grows. Ingest order (the ``seq`` counter) is the registry's clock —
  "last known good" means "highest seq with status ok", so wall-clock
  skew between hosts cannot reorder history.
- **Content-addressed.** ``record_id`` is a sha256 prefix over the
  canonical JSON of the *measurement* (arm, status, source, metric,
  result row, windows) — re-ingesting the same artifacts is a no-op, so
  the suite's finish path may blindly re-scan a results dir that was
  already ingested. The environment fingerprint is deliberately outside
  the hash: the same measurement ingested from two checkouts must not
  mint two records.
- **Partial runs are stored but never baselines.** A heartbeat-salvaged
  ``partial_<arm>.json`` (NaN scaling efficiency in metrics.csv) ingests
  with ``status: "partial"`` — visible in ``trend``, excluded from
  ``baseline()`` and from trend superlatives. A truncated run's
  last-window rate is not a run mean and must never anchor a verdict.
- **Resumed runs join partials in the never-baseline set.** A stitched
  run (``result.resumed`` true — chaos round, docs/FAULT_TOLERANCE.md)
  is an honest *record* but a dishonest *baseline*: its first timed
  window folds in the restore recompile and its step population spans
  two attempts, so ``baseline()``/``history_values()`` skip it the same
  way they skip partials. Rolled-back runs (``result.n_rollbacks`` > 0 —
  the numerics sentinel healed them in-process, self-healing round) are
  excluded for the same reason: their replayed region ran twice and the
  trip itself says the run hit a numerics incident.
- **Known-regressed records are banked, not adopted.** When the gate
  verdicts a regression, the candidate's record_id is appended to
  ``banked.jsonl`` (append-only, bank/unbank action lines): "last known
  good" then *skips* the banked record instead of adopting it as the
  next baseline — without this, one accepted regression silently
  ratchets the floor down for every later run. ``regress bank/unbank``
  manage the set by hand.
- **Schema drift refuses loudly.** Records and the registry meta carry
  ``schema_version``; a reader that encounters a NEWER version raises
  :class:`SchemaDrift` instead of guessing at fields it does not know —
  the same posture graftcheck takes for budgets frozen on a different
  jax version (exit 2, regenerate/upgrade, never silently compare).
"""

from __future__ import annotations

import glob
import hashlib
import json
import math
import os
import re
import subprocess
import time
from typing import Any, Dict, List, Optional, Tuple

#: Version of the record schema THIS reader/writer speaks. Bump on any
#: field change that an old reader would misinterpret; readers accept
#: <= their own version and refuse anything newer.
REGISTRY_SCHEMA_VERSION = 1

META_FILENAME = "registry_meta.json"
INDEX_FILENAME = "index.jsonl"
RECORDS_DIRNAME = "records"
BANKED_FILENAME = "banked.jsonl"

#: Statuses a record may carry. Only "ok" records are baseline-eligible.
STATUSES = ("ok", "partial")


class SchemaDrift(RuntimeError):
    """A record (or the registry meta) is newer than this reader."""


def default_registry_root() -> str:
    """``REGRESS_REGISTRY`` env var, else ``<repo>/results/registry``.

    The repo root is located relative to this file so bench.py, the
    scripts (which ``cd`` to the repo root) and out-of-tree callers all
    resolve the same default.
    """
    env = os.environ.get("REGRESS_REGISTRY")
    if env:
        return env
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(repo, "results", "registry")


def _sanitize(obj: Any) -> Any:
    """Non-finite floats -> None, recursively.

    Partial rows legitimately carry NaN (scaling efficiency); canonical
    JSON (allow_nan=False) would crash on them and non-strict NaN tokens
    would break strict consumers, so the registry stores null — the same
    convention the telemetry channel uses for non-finite losses.
    """
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    return obj


def _canonical(payload: Dict[str, Any]) -> str:
    return json.dumps(payload, sort_keys=True, separators=(",", ":"),
                      allow_nan=False)


def record_id_for(payload: Dict[str, Any]) -> str:
    """sha256 prefix over the measurement fields (see module docstring)."""
    # ``source`` stays OUT of the hash: the harness's result_<arm>.json and
    # the log-scraped result.json of the SAME run carry identical rows and
    # must dedupe to one record despite their different filenames.
    hashed = {
        k: payload.get(k)
        for k in ("arm", "status", "metric", "result", "windows",
                  "tokens_per_step")
    }
    return hashlib.sha256(_canonical(hashed).encode()).hexdigest()[:16]


def git_sha() -> Optional[str]:
    """Best-effort short sha of the repo this module lives in."""
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=repo,
            capture_output=True, text=True, timeout=10,
        )
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else None
    except Exception:
        return None


def env_fingerprint(result_row: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Run-environment identity stored beside (not hashed into) a record.

    Answers "is this delta a code change or an environment change" during
    triage: git sha, jax version, device kind/backend, and the mesh
    geometry + attention impl from the result row when one is given.
    """
    fp: Dict[str, Any] = {"git_sha": git_sha()}
    try:
        import jax

        fp["jax_version"] = jax.__version__
    except Exception:
        fp["jax_version"] = None
    r = result_row or {}
    fp["device_kind"] = r.get("device_kind") or None
    fp["backend"] = r.get("backend") or None
    fp["attention_impl"] = r.get("attention_impl") or None
    # Scheduling-relevant XLA flags (latency-hiding scheduler, async
    # collectives — utils.platform.scheduler_flags_fingerprint): an env
    # change that moves the collective schedule must be visible in triage.
    fp["xla_scheduler_flags"] = r.get("xla_scheduler_flags") or ""
    # Collective-matmul tp fusion (round 15): a structurally different
    # projection schedule — triage must see it beside the scheduler flags.
    fp["tp_collective_matmul"] = bool(r.get("tp_collective_matmul"))
    fp["mesh"] = {
        "world_size": r.get("world_size"),
        "tensor_parallel": r.get("tensor_parallel", 1),
        "sequence_parallel": r.get("sequence_parallel", 1),
        "pipeline_parallel": r.get("pipeline_parallel", 1),
        "expert_parallel": r.get("expert_parallel", 1),
    }
    return fp


def config_key(record: Dict[str, Any]) -> Tuple:
    """Geometry/config axes a baseline must share with its candidate.

    Comparing a b2xaccum2 run against a b1xaccum4 baseline would verdict
    a config change as a perf change — the same trap parse_metrics's
    scaling-efficiency grouping guards with its extended group columns.
    """
    r = record.get("result") or {}
    return tuple(
        r.get(k) for k in (
            "model_family", "strategy", "tier", "seq_len", "world_size",
            "per_device_batch", "grad_accum", "attention_impl", "sync_every",
            "tensor_parallel", "sequence_parallel", "pipeline_parallel",
            "pipeline_schedule", "expert_parallel", "n_experts",
            "param_dtype", "causal", "ring_zigzag",
            # Run length is methodology, not noise: a 12-step smoke value
            # must not enter a 100-step lineage's noise floor (short runs
            # over-weight the warm caches and the first windows).
            "steps", "warmup_steps",
        )
    ) + (
        record.get("metric", {}).get("name"),
        # Profiling is methodology too: the trace bracket around the timed
        # window adds collection overhead, so a PROFILE=1 run must not
        # gate against (or feed the noise floor of) an unprofiled lineage.
        # Anatomy fields are non-null exactly when the run profiled.
        r.get("comms_exposed_frac") is not None,
        # The latency-hiding scheduler changes the collective schedule —
        # a flagged run is a different measurement lineage than an
        # unflagged one (legacy records carry no field -> "" -> they stay
        # in the unflagged lineage, byte-compatible with their history).
        r.get("xla_scheduler_flags") or "",
        # Remat policy trades HBM for recompute: every --remat-sweep
        # point is its own lineage (absent on legacy rows -> None).
        r.get("remat_policy"),
        # The collective-matmul tp fusion replaces the projection
        # collectives with a ppermute ring — a different collective
        # schedule, so cmm and plain-tp runs are separate lineages
        # (legacy rows carry no field -> False -> the plain lineage).
        bool(r.get("tp_collective_matmul")),
        # Input path is methodology: a streaming (--data-path) run pays
        # host-read + device-put costs the synthetic table never does, so
        # it must not gate against (or feed the noise floor of) the
        # synthetic lineage. Legacy rows carry no field -> normalized to
        # "synthetic" so existing history stays one lineage.
        r.get("data_mode") or "synthetic",
    )


def make_record(
    *,
    arm: str,
    result_row: Dict[str, Any],
    windows: Optional[List[Dict[str, Any]]] = None,
    tokens_per_step: int = 0,
    status: str = "ok",
    source: str = "",
    metric: Optional[Dict[str, Any]] = None,
    masked_windows: int = 0,
) -> Dict[str, Any]:
    """Assemble a schema-versioned record payload (not yet ingested).

    ``metric`` defaults to the row's global ``tokens_per_sec``; legacy
    and bench.py callers override it (per-chip headline value).
    ``windows`` are the timed sync windows extracted from the run's
    telemetry JSONL (``stats.timed_windows``) — empty when the run had
    no telemetry file (bench.py in-process arms, legacy snapshots), in
    which case comparisons fall back to scalar-vs-history mode.
    ``masked_windows`` counts spike-flagged windows the extraction
    excluded (additive key, present only when nonzero — and outside the
    content hash, like ``source``, so masking accounting can never split
    one measurement into two records).
    """
    if status not in STATUSES:
        raise ValueError(f"unknown record status {status!r} "
                         f"(expected one of {STATUSES})")
    if metric is None:
        metric = {
            "name": "tokens_per_sec",
            "value": result_row.get("tokens_per_sec"),
            "higher_is_better": True,
        }
    payload = _sanitize({
        "schema_version": REGISTRY_SCHEMA_VERSION,
        "arm": arm,
        "status": status,
        "source": source,
        "metric": metric,
        "result": dict(result_row),
        "windows": list(windows or []),
        "tokens_per_step": int(tokens_per_step),
        "env": env_fingerprint(result_row),
    })
    if masked_windows:
        payload["masked_windows"] = int(masked_windows)
    payload["record_id"] = record_id_for(payload)
    return payload


def check_record_version(record: Dict[str, Any], origin: str = "") -> None:
    ver = record.get("schema_version")
    if not isinstance(ver, int) or ver > REGISTRY_SCHEMA_VERSION:
        raise SchemaDrift(
            f"record{' ' + origin if origin else ''} carries schema_version "
            f"{ver!r} but this reader speaks {REGISTRY_SCHEMA_VERSION} — "
            "refusing to interpret a newer schema; upgrade the tooling"
        )


class Registry:
    """Handle on one registry root. Opening never creates; ingest does."""

    def __init__(self, root: Optional[str] = None):
        self.root = root or default_registry_root()
        # Read caches, invalidated/extended by ingest: every gate/compare
        # path walks the index and re-loads records repeatedly, and one
        # Registry instance serves a whole CLI command — without these,
        # `gate --all` on an accumulating registry is O(arms x records^2)
        # file IO.
        self._index_cache: Optional[List[Dict[str, Any]]] = None
        self._record_cache: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._check_meta()

    # -- plumbing ----------------------------------------------------------

    @property
    def index_path(self) -> str:
        return os.path.join(self.root, INDEX_FILENAME)

    @property
    def meta_path(self) -> str:
        return os.path.join(self.root, META_FILENAME)

    def exists(self) -> bool:
        return os.path.exists(self.index_path)

    def _check_meta(self) -> None:
        if not os.path.exists(self.meta_path):
            return
        try:
            meta = json.load(open(self.meta_path))
        except (json.JSONDecodeError, OSError) as e:
            raise SchemaDrift(f"unreadable {self.meta_path}: {e}")
        ver = meta.get("schema_version")
        if not isinstance(ver, int) or ver > REGISTRY_SCHEMA_VERSION:
            raise SchemaDrift(
                f"registry at {self.root} was written with schema_version "
                f"{ver!r} but this reader speaks {REGISTRY_SCHEMA_VERSION} "
                "— refusing to ingest into (or read) a newer registry"
            )

    def _record_path(self, arm: str, record_id: str) -> str:
        return os.path.join(self.root, RECORDS_DIRNAME, arm,
                            f"{record_id}.json")

    # -- writes ------------------------------------------------------------

    def ingest(self, payload: Dict[str, Any]) -> Tuple[Dict[str, Any], bool]:
        """Write one record; returns (record, created).

        Idempotent on content: an already-present record_id is a no-op
        (the append-only index is not re-appended either).
        """
        check_record_version(payload, payload.get("record_id", ""))
        rid = payload.get("record_id") or record_id_for(payload)
        payload = dict(payload, record_id=rid)
        path = self._record_path(payload["arm"], rid)
        if os.path.exists(path):
            existing = json.load(open(path))
            # Self-heal a torn ingest: a crash between the record write
            # and the index append (the exact environment this registry
            # serves — preempted pods, killed suites) leaves the file on
            # disk but invisible to every index-driven read. The index is
            # the registry's clock, so repair = append now.
            if not any(l["record_id"] == rid for l in self.index_lines()):
                self._append_index(existing)
            return existing, False
        os.makedirs(os.path.dirname(path), exist_ok=True)
        if not os.path.exists(self.meta_path):
            os.makedirs(self.root, exist_ok=True)
            with open(self.meta_path, "w") as f:
                json.dump({"schema_version": REGISTRY_SCHEMA_VERSION,
                           "created_by": "regress.store"}, f, indent=2)
                f.write("\n")
        payload = dict(payload, ingested_at=round(time.time(), 3))
        with open(path, "w") as f:
            f.write(json.dumps(payload, indent=2, sort_keys=True))
            f.write("\n")
        self._record_cache[(payload["arm"], rid)] = payload
        self._append_index(payload)
        return payload, True

    def _append_index(self, payload: Dict[str, Any]) -> None:
        index_line = {
            "seq": len(self.index_lines()),
            "record_id": payload["record_id"],
            "arm": payload["arm"],
            "status": payload["status"],
            "metric_name": payload["metric"].get("name"),
            "metric_value": payload["metric"].get("value"),
            "source": payload.get("source", ""),
            "ingested_at": payload.get("ingested_at",
                                       round(time.time(), 3)),
        }
        with open(self.index_path, "a") as f:
            f.write(json.dumps(index_line, sort_keys=True) + "\n")
        if self._index_cache is not None:
            self._index_cache.append(index_line)

    # -- banked regressions ------------------------------------------------

    @property
    def banked_path(self) -> str:
        return os.path.join(self.root, BANKED_FILENAME)

    def banked_ids(self) -> set:
        """Record ids currently banked as known regressions.

        ``banked.jsonl`` is append-only action lines ({record_id, action
        bank|unbank, reason, at}); the effective set is the fold, so the
        registry's everything-is-append-only invariant holds here too.
        """
        if not os.path.exists(self.banked_path):
            return set()
        banked: set = set()
        with open(self.banked_path) as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    line = json.loads(raw)
                except json.JSONDecodeError:
                    # A torn append (process killed mid-write — the very
                    # preemption this ledger serves) must not wedge every
                    # gate/trend/baseline path; the lost action is at
                    # worst one bank the next gate run re-banks.
                    continue
                if line.get("action", "bank") == "bank":
                    banked.add(line["record_id"])
                else:
                    banked.discard(line["record_id"])
        return banked

    def bank(self, record_id: str, reason: str = "") -> bool:
        """Mark a record as a known regression; returns True when new.

        Banked records stay visible (trend, compare) but ``baseline()``
        and ``history_values()`` skip them — "last known good" must skip
        a banked regression instead of adopting it. Idempotent.
        """
        if record_id in self.banked_ids():
            return False
        with open(self.banked_path, "a") as f:
            f.write(json.dumps({
                "record_id": record_id, "action": "bank",
                "reason": reason, "at": round(time.time(), 3),
            }, sort_keys=True) + "\n")
        return True

    def unbank(self, record_id: str, reason: str = "") -> bool:
        """Lift a bank (e.g. the regression was accepted as the new
        normal and re-measured); returns True when it was banked."""
        if record_id not in self.banked_ids():
            return False
        with open(self.banked_path, "a") as f:
            f.write(json.dumps({
                "record_id": record_id, "action": "unbank",
                "reason": reason, "at": round(time.time(), 3),
            }, sort_keys=True) + "\n")
        return True

    # -- reads -------------------------------------------------------------

    def index_lines(self) -> List[Dict[str, Any]]:
        if self._index_cache is not None:
            return self._index_cache
        if not os.path.exists(self.index_path):
            return []
        lines: List[Dict[str, Any]] = []
        with open(self.index_path) as f:
            for raw in f:
                raw = raw.strip()
                if raw:
                    lines.append(json.loads(raw))
        self._index_cache = lines
        return lines

    def arms(self) -> List[str]:
        return sorted({l["arm"] for l in self.index_lines()})

    def load(self, arm: str, record_id: str) -> Dict[str, Any]:
        cached = self._record_cache.get((arm, record_id))
        if cached is not None:
            return cached
        path = self._record_path(arm, record_id)
        record = json.load(open(path))
        check_record_version(record, os.path.basename(path))
        self._record_cache[(arm, record_id)] = record
        return record

    def resolve(self, selector: str) -> Dict[str, Any]:
        """A record from an id prefix (unique across the registry)."""
        matches = [l for l in self.index_lines()
                   if l["record_id"].startswith(selector)]
        if not matches:
            raise KeyError(f"no record matching id prefix {selector!r}")
        ids = {m["record_id"] for m in matches}
        if len(ids) > 1:
            raise KeyError(
                f"id prefix {selector!r} is ambiguous ({sorted(ids)})"
            )
        m = matches[0]
        return self.load(m["arm"], m["record_id"])

    def records(self, arm: str) -> List[Dict[str, Any]]:
        """Full records for one arm, in ingest (seq) order."""
        return [
            self.load(l["arm"], l["record_id"])
            for l in self.index_lines() if l["arm"] == arm
        ]

    def latest(self, arm: str) -> Optional[Dict[str, Any]]:
        recs = self.records(arm)
        return recs[-1] if recs else None

    def baseline(
        self,
        arm: str,
        *,
        exclude_record_id: Optional[str] = None,
        match_config_of: Optional[Dict[str, Any]] = None,
    ) -> Optional[Dict[str, Any]]:
        """Last known good: newest ok record, never a partial one.

        ``exclude_record_id`` keeps a candidate from being its own
        baseline; ``match_config_of`` restricts to records sharing the
        candidate's :func:`config_key` so a geometry change can never
        masquerade as a perf delta. Banked regressions and resumed
        (stitched) rows are skipped too — neither is a clean measurement
        for anything to be judged against (module docstring).
        """
        for rec in self._eligible(arm, exclude_record_id, match_config_of):
            return rec
        return None

    def _eligible(
        self, arm: str,
        exclude_record_id: Optional[str],
        match_config_of: Optional[Dict[str, Any]],
    ):
        """Newest-first records eligible as baseline / noise-floor input.

        THE baseline-eligibility filter chain, shared by :meth:`baseline`,
        :meth:`history_values` and :meth:`result_history_values` so the
        primary and secondary noise floors can never disagree about which
        runs count: status ok, unbanked, not resumed, not rolled-back
        (sentinel-healed, ``n_rollbacks`` > 0), not supervisor-recovered
        (``supervision.n_attempts`` > 1) — the
        resume_geometry_changed check is defense in depth for a row whose
        accounting broke (flag without resumed; docs/FAULT_TOLERANCE.md)
        — not the candidate itself, and sharing the candidate's
        :func:`config_key`.
        """
        want = config_key(match_config_of) if match_config_of else None
        banked = self.banked_ids()
        for rec in reversed(self.records(arm)):
            if rec.get("status") != "ok":
                continue
            if rec.get("record_id") in banked:
                continue
            res = rec.get("result") or {}
            if res.get("resumed") or res.get("resume_geometry_changed"):
                continue
            if res.get("n_rollbacks"):
                continue
            # Supervisor-recovered rows (runtime/supervisor.py stamps the
            # recovery history only when recovery actually happened, i.e.
            # n_attempts > 1): the published measurement spans a restart —
            # recompile, possibly a geometry shrink leg — so like resumed
            # rows it is never a clean baseline/noise-floor sample.
            if (res.get("supervision") or {}).get("n_attempts", 1) > 1:
                continue
            if exclude_record_id and rec.get("record_id") == exclude_record_id:
                continue
            if want is not None and config_key(rec) != want:
                continue
            yield rec

    def history_values(
        self, arm: str, *, metric_name: str,
        exclude_record_id: Optional[str] = None,
        match_config_of: Optional[Dict[str, Any]] = None, limit: int = 8,
    ) -> List[float]:
        """Recent ok-record metric values — the noise-floor sample.

        ``match_config_of`` restricts to records sharing the candidate's
        :func:`config_key`: the noise floor must measure run-to-run
        jitter of ONE configuration, not the spread across historical
        config changes (a past legitimate improvement would otherwise
        inflate the floor until it masked real regressions). Banked
        regressions and resumed rows stay out for the same reason — a
        stitched run's recompile-polluted value is not run-to-run jitter.
        """
        vals: List[float] = []
        for rec in self._eligible(arm, exclude_record_id, match_config_of):
            m = rec.get("metric") or {}
            if m.get("name") != metric_name or m.get("value") is None:
                continue
            vals.append(float(m["value"]))
            if len(vals) >= limit:
                break
        return list(reversed(vals))

    def result_history_values(
        self, arm: str, *, result_key: str,
        exclude_record_id: Optional[str] = None,
        match_config_of: Optional[Dict[str, Any]] = None, limit: int = 8,
    ) -> List[float]:
        """Same-config history of a RESULT-ROW field (secondary metrics).

        The per-metric noise-floor sample behind the secondary-metric
        gate (``stats.SECONDARY_METRICS``): MFU, peak HBM and the
        step-anatomy fractions live in the result row rather than the
        headline ``metric`` slot, so their run-to-run spread is read from
        there — with exactly the baseline-eligibility filters
        :meth:`history_values` applies (the shared :meth:`_eligible`
        chain: ok-only, unbanked, non-resumed, matching config key).
        """
        vals: List[float] = []
        for rec in self._eligible(arm, exclude_record_id, match_config_of):
            v = (rec.get("result") or {}).get(result_key)
            if v is None or not isinstance(v, (int, float)):
                continue
            vals.append(float(v))
            if len(vals) >= limit:
                break
        return list(reversed(vals))


# ---------------------------------------------------------------------------
# Ingest paths: results dirs, and the legacy repo-root snapshots
# ---------------------------------------------------------------------------


def _windows_for_result(
    result_path: str, arm: str,
) -> Tuple[List[Dict[str, Any]], int, int]:
    """(timed windows, tokens_per_step, n spike-masked) from the sibling JSONL.

    Window-level anomaly masking (benchreg follow-up (c)): windows the
    recorder flagged inside an open step-time spike are excluded from the
    stored comparison sample — they measure the stall, not the code — and
    their count rides the record as ``masked_windows`` so the verdict
    line can say the masking happened.
    """
    tpath = os.path.join(os.path.dirname(result_path), f"telemetry_{arm}.jsonl")
    if not os.path.exists(tpath):
        return [], 0, 0
    from ..telemetry import read_events
    from . import stats

    try:
        events = read_events(tpath)
    except (OSError, ValueError):
        return [], 0, 0
    meta = next((e for e in events if e.get("event") == "run_meta"), {})
    kept, masked = stats.split_masked_windows(events)
    return kept, int(meta.get("tokens_per_step", 0) or 0), len(masked)


def ingest_results_dir(
    reg: Registry, results_dir: str,
) -> List[Tuple[Dict[str, Any], bool]]:
    """Scan a suite results tree: result_<arm>.json + partial_<arm>.json.

    Full rows ingest as ``ok`` with their telemetry windows when the
    sibling JSONL exists; heartbeat-salvaged partials ingest as
    ``partial`` (baseline-ineligible — the satellite contract pinned by
    tests/test_regress.py). Bare ``result.json`` scrapes (no arm in the
    filename) reconstruct the arm slug from the row itself.
    """
    from ..utils.metrics import arm_slug

    out: List[Tuple[Dict[str, Any], bool]] = []
    seen: set = set()
    for path in sorted(glob.glob(os.path.join(results_dir, "**",
                                              "result*.json"),
                                 recursive=True)):
        try:
            row = json.load(open(path))
        except (json.JSONDecodeError, OSError):
            continue
        if not isinstance(row, dict) or "tokens_per_sec" not in row:
            continue
        base = os.path.basename(path)
        if base.startswith("result_") and base.endswith(".json"):
            arm = base[len("result_"):-len(".json")]
        else:
            try:
                arm = arm_slug(
                    row["strategy"], row["world_size"], row["seq_len"],
                    row["tier"], row.get("model_family", "tinygpt"),
                )
            except KeyError:
                continue
        windows, tps, n_masked = _windows_for_result(path, arm)
        rec = make_record(
            arm=arm, result_row=row, windows=windows, tokens_per_step=tps,
            status="ok", source=os.path.relpath(path, results_dir),
            masked_windows=n_masked,
        )
        if rec["record_id"] in seen:
            continue  # result_<arm>.json + scraped result.json of one run
        seen.add(rec["record_id"])
        out.append(reg.ingest(rec))
    for path in sorted(glob.glob(os.path.join(results_dir, "**",
                                              "partial_*.json"),
                                 recursive=True)):
        try:
            row = json.load(open(path))
        except (json.JSONDecodeError, OSError):
            continue
        arm = row.get("arm") or os.path.basename(path)[
            len("partial_"):-len(".json")
        ]
        row = dict(row, partial=True)
        rec = make_record(
            arm=arm, result_row=row, status="partial",
            source=os.path.relpath(path, results_dir),
            metric={
                "name": "tokens_per_sec",
                "value": row.get("tokens_per_sec"),
                "higher_is_better": True,
            },
        )
        out.append(reg.ingest(rec))
    return out


def bench_arm_slug(metric_name: str) -> str:
    """`tinygpt_tierA_seq2048_tokens_per_sec_per_chip` -> bench lineage arm.

    bench.py rows and the legacy BENCH_r*.json snapshots share one arm
    name per headline metric, so today's bench run extends the trend the
    repo-root snapshots seeded.
    """
    stem = metric_name
    suffix = "_tokens_per_sec_per_chip"
    if stem.endswith(suffix):
        stem = stem[: -len(suffix)]
    return f"bench_{stem}"


def record_from_bench_row(
    row: Dict[str, Any], *, source: str, extra_result: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """A registry record from one bench.py contract row (or legacy parse).

    The headline metric is per-chip tokens/sec (the contract ``value``);
    there are no telemetry windows (bench arms run with results_dir=None)
    so comparisons use scalar-vs-history mode.
    """
    result = {k: v for k, v in row.items() if k != "flagship"}
    if extra_result:
        result.update(extra_result)
    return make_record(
        arm=bench_arm_slug(str(row.get("metric", "unknown"))),
        result_row=result,
        status="ok",
        source=source,
        metric={
            "name": "tokens_per_sec_per_chip",
            "value": row.get("value"),
            "higher_is_better": True,
        },
    )


def ingest_legacy(
    reg: Registry, root: Optional[str] = None,
) -> List[Tuple[Dict[str, Any], bool]]:
    """Seed the registry from the repo-root BENCH_r*/MULTICHIP_r* snapshots.

    The write-only driver trajectory becomes day-one trend history: each
    ``BENCH_rNN.json`` carries the headline contract row under
    ``parsed``; each ``MULTICHIP_rNN.json`` is a pass/fail dryrun record
    (metric ``multichip_ok`` 1/0). Snapshots ingest in round order.
    """
    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    out: List[Tuple[Dict[str, Any], bool]] = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        try:
            snap = json.load(open(path))
        except (json.JSONDecodeError, OSError):
            continue
        parsed = snap.get("parsed")
        if not isinstance(parsed, dict) or "metric" not in parsed:
            continue
        # The snapshots' own cmd field records a flagless `python
        # bench.py` — the CLI defaults. Backfilling every config_key axis
        # bench.py records today (run params + batch geometry) keys the
        # legacy rows into the same config lineage as a live default
        # invocation, so the committed seed serves as the live noise
        # floor instead of a disconnected history. Fields the snapshot
        # already carries (attention_impl from r02 on) are never
        # overridden — r01's pre-flash row stays its own lineage.
        # tests/test_regress.py pins the legacy<->live key match.
        defaults = {
            "strategy": "zero2", "tier": "A", "seq_len": 2048,
            "model_family": "tinygpt", "per_device_batch": 1,
            "grad_accum": 4, "sync_every": 10, "steps": 100,
            "warmup_steps": 5,
        }
        rec = record_from_bench_row(
            parsed, source=f"legacy:{os.path.basename(path)}",
            extra_result=dict(
                {k: v for k, v in defaults.items() if k not in parsed},
                legacy_round=snap.get("n"),
            ),
        )
        out.append(reg.ingest(rec))
    for path in sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json"))):
        try:
            snap = json.load(open(path))
        except (json.JSONDecodeError, OSError):
            continue
        if "ok" not in snap:
            continue
        n_dev = snap.get("n_devices", 0)
        # Round number from the filename (MULTICHIP_r03.json -> 3): the
        # snapshots carry no counter of their own, and without one five
        # identical all-green rounds would content-dedupe into a single
        # record and flatten the trend history.
        m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
        rec = make_record(
            arm=f"multichip_dryrun_{n_dev}dev",
            result_row={"n_devices": n_dev, "rc": snap.get("rc"),
                        "skipped": snap.get("skipped"),
                        "legacy_round": snap.get(
                            "n", int(m.group(1)) if m else None)},
            status="ok",
            source=f"legacy:{os.path.basename(path)}",
            metric={"name": "multichip_ok",
                    "value": 1.0 if snap.get("ok") else 0.0,
                    "higher_is_better": True},
        )
        out.append(reg.ingest(rec))
    return out
