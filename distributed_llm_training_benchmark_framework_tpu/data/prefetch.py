"""Bounded double-buffered host prefetch with device put.

The consumer half of the streaming data path (``data/stream.py`` is the
durable half): one background thread reads the next batches off the shard
stream, reshapes them to the step's ``(grad_accum, global_micro,
seq_len)`` layout and dispatches the host->device transfer, so the timed
loop overlaps input IO with device compute instead of serializing them.

Robustness-first design points:

- **Bounded queue (default depth 2 — a double buffer).** The producer can
  run at most ``depth`` batches ahead: memory stays bounded, and the
  exact-resume bookkeeping stays simple because every produced batch
  carries its own cursor snapshot (the loop persists the snapshot of the
  batch it actually *consumed*, never the read-ahead position).
- **Starvation is measured, then classified.** ``get()`` returns how long
  the timed loop waited; the loop folds those waits into
  ``data_stall_frac``. Past ``timeout`` it raises
  :class:`DataStallTimeout` and the loop aborts the run as
  ``reason=data_stall`` (exit ``EXIT_DATA_STALL``) — distinct from the
  watchdog's ``hang``: the device was fine, the input path starved it.
- **Producer errors surface in the consumer.** An exception on the
  prefetch thread (unreadable shard past retries, a chaos fault) is
  re-raised from ``get()`` — never a silently dead queue.
- **Per-host sharded device put.** Single-process runs ``jax.device_put``
  the whole batch with the strategy's batch sharding; multi-process runs
  assemble via ``jax.make_array_from_callback``, whose callback reads
  ONLY the record rows this host's addressable shards need — per-host
  shard ownership is implicit in the batch PartitionSpec, so a
  geometry-change resume recomputes it for free.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .stream import ShardedTokenStream

#: Producer-side poll cadence for the bounded queue put (keeps the thread
#: responsive to stop()).
_PUT_POLL_SEC = 0.2
#: Consumer-side poll cadence while waiting on a batch (accumulates the
#: measured wait between polls).
_GET_POLL_SEC = 0.05


class DataStallTimeout(RuntimeError):
    """``get()`` starved past the configured timeout."""

    def __init__(self, step: int, waited_sec: float):
        self.step = step
        self.waited_sec = waited_sec
        super().__init__(
            f"no batch for step {step} after {waited_sec:.1f}s"
        )


class HostPrefetcher:
    """Background producer of device-resident step batches.

    Produces batches for steps ``start_step .. stop_step-1`` in order;
    each queue item is ``(step, device_array, meta)`` where ``meta`` is
    the stream's exact-resume snapshot *after* that batch — the loop
    persists the consumed batch's snapshot into the checkpoint sidecar.
    """

    def __init__(
        self,
        stream: ShardedTokenStream,
        *,
        sharding: Any,
        grad_accum: int,
        global_micro: int,
        seq_len: int,
        start_step: int,
        stop_step: int,
        depth: int = 2,
        injector: Any = None,
        multi_process: bool = False,
    ):
        self.stream = stream
        self.sharding = sharding
        self.grad_accum = int(grad_accum)
        self.global_micro = int(global_micro)
        self.seq_len = int(seq_len)
        self.start_step = int(start_step)
        self.stop_step = int(stop_step)
        self.records_per_step = self.grad_accum * self.global_micro
        self.injector = injector
        self.multi_process = bool(multi_process)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(int(depth), 1))
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._produce, name="data-prefetch", daemon=True
        )

    def start(self) -> "HostPrefetcher":
        self._thread.start()
        return self

    # ------------------------------------------------------------------
    # Producer (prefetch thread)
    # ------------------------------------------------------------------

    def _device_put(self, step: int, cursor: int):
        shape = (self.grad_accum, self.global_micro, self.seq_len)
        if not self.multi_process:
            host = self.stream.read_records(
                cursor, cursor + self.records_per_step
            ).reshape(shape)
            import jax

            return jax.device_put(host, self.sharding)

        # Multi-host: assemble per shard so each host reads ONLY the
        # record rows its addressable devices own (the per-host sharded
        # input path; ownership is the batch PartitionSpec, recomputed
        # every run — a geometry change re-derives it for free).
        import jax

        # Per-batch dedup cache: make_array_from_callback invokes the
        # callback once per addressable DEVICE, with identical index
        # tuples for devices that replicate the batch across non-data
        # axes (tp/sp/pp members of one data group). Without the cache
        # each replica re-reads the same record span from disk — and a
        # genuinely corrupt record would be quarantined (and
        # records_skipped incremented) once PER REPLICA, breaking the
        # honest-ledger contract.
        cache: Dict[tuple, np.ndarray] = {}

        def cb(idx):
            accum_sl, batch_sl, seq_sl = idx
            a0, a1, _ = accum_sl.indices(self.grad_accum)
            b0, b1, _ = batch_sl.indices(self.global_micro)
            seq_key = seq_sl.indices(self.seq_len)
            key = (a0, a1, b0, b1, seq_key)
            hit = cache.get(key)
            if hit is not None:
                return hit
            rows = []
            for a in range(a0, a1):
                base = cursor + a * self.global_micro
                rows.append(self.stream.read_records(base + b0, base + b1))
            out = np.stack(rows, axis=0)[:, :, seq_sl]
            cache[key] = out
            return out

        return jax.make_array_from_callback(shape, self.sharding, cb)

    def _produce(self) -> None:
        try:
            cursor = self.stream.cursor
            # NOTE: this is the PRODUCER loop (prefetch thread), not the
            # timed step loop — its blocking IO is the whole point (the
            # loop variable is deliberately not named `step`, which is
            # the timed-loop shape graftcheck GC111 polices).
            for produced in range(self.start_step, self.stop_step):
                inj = self.injector
                if inj is not None and hasattr(inj, "data_stall_sec"):
                    stall = inj.data_stall_sec(produced)
                    if stall > 0:
                        # The injected input-source outage: the producer
                        # sleeps, the consumer starves, and the loop must
                        # classify reason=data_stall (never hang).
                        time.sleep(stall)
                if self._stop.is_set():
                    return
                arr = self._device_put(produced, cursor)
                cursor += self.records_per_step
                self.stream.cursor = cursor
                meta: Dict[str, Any] = {
                    "step": produced,
                    "cursor": cursor,
                    "records_skipped": self.stream.records_skipped,
                }
                while not self._stop.is_set():
                    try:
                        self._q.put((produced, arr, meta),
                                    timeout=_PUT_POLL_SEC)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surfaced by get(); never a dead queue
            self._error = e

    # ------------------------------------------------------------------
    # Consumer (main thread)
    # ------------------------------------------------------------------

    def get(
        self, step: int, timeout: float = 60.0
    ) -> Tuple[Any, Dict[str, Any], float]:
        """The batch for ``step`` -> (device_array, resume_meta, waited_sec).

        Raises :class:`DataStallTimeout` when no batch lands within
        ``timeout`` seconds, and re-raises any producer-thread error.
        Steps must be requested in production order (the loop's shape).
        """
        t0 = time.perf_counter()
        while True:
            try:
                got_step, arr, meta = self._q.get(timeout=_GET_POLL_SEC)
            except queue.Empty:
                # Drain-before-error: batches already produced are valid
                # progress — a read failure two steps AHEAD must surface
                # only after the consumer catches up to it, so the abort
                # step is deterministic relative to the failing record.
                if self._error is not None:
                    raise self._error
                waited = time.perf_counter() - t0
                if waited >= timeout:
                    raise DataStallTimeout(step, waited)
                continue
            if got_step != step:
                raise RuntimeError(
                    f"prefetch order broke: wanted step {step}, queue "
                    f"held step {got_step}"
                )
            return arr, meta, time.perf_counter() - t0

    def stop(self, join: bool = False, timeout: float = 10.0) -> None:
        """Signal the producer to exit; with ``join=True`` also wait.

        The join exists for the sentinel's stream rewind (train/loop.py
        ``_roll_back_if_tripped``): the producer thread advances
        ``stream.cursor`` as it reads ahead, so a ``stream.seek()``
        issued while the thread still runs could be silently overwritten
        by an in-flight batch. Joining — and draining the queue so a
        producer blocked on a full queue wakes up to see the stop event
        — guarantees the stream is quiescent before the rewind. The
        plain (no-join) form is the shutdown path's fire-and-forget.
        """
        self._stop.set()
        if not join:
            return
        deadline = time.perf_counter() + timeout
        while self._thread.is_alive() and time.perf_counter() < deadline:
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=_PUT_POLL_SEC)
