"""Seeded synthetic token dataset — zero I/O, fully reproducible.

Capability parity with the reference ``SyntheticDataset`` (reference
``benchmarking/train_harness.py:138-150``): a pre-materialized
``(size, seq_len)`` integer tensor drawn uniformly from the vocabulary with a
fixed seed (42), so every rank and every run sees identical data and the
benchmark measures compute/communication, never input pipeline.

Reference-parity semantics preserved:
- targets are the inputs themselves, NOT shifted (reference
  ``train_harness.py:359`` clones the batch as targets);
- default size=1000 samples, seed=42.

TPU-native differences:
- the table is a device-resident ``jnp`` array produced by
  ``jax.random.randint`` (threefry) — values differ from torch's generator,
  which is irrelevant for a synthetic benchmark; determinism is what matters;
- batching is a pure function of the step index (``batch_for_step``) instead
  of a stateful DataLoader + DistributedSampler: the *global* batch for step i
  is a deterministic slice, and sharding across devices/hosts is done by the
  strategy's batch PartitionSpec, not by a sampler object.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class SyntheticDataset:
    def __init__(
        self,
        vocab_size: int = 32000,
        seq_len: int = 2048,
        size: int = 1000,
        seed: int = 42,
    ):
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.size = size
        self.seed = seed
        # Materialize on host (numpy) so dataset construction never touches a
        # device; slices are shipped per-step (and sharded by the strategy).
        key = jax.random.key(seed)
        self.data = np.asarray(
            jax.random.randint(
                key, (size, seq_len), 0, vocab_size, dtype=jnp.int32
            )
        )

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, idx: int) -> np.ndarray:
        return self.data[idx]

    def batch_for_step(self, step: int, global_batch: int) -> np.ndarray:
        """Deterministic global batch for a step, wrapping around the table.

        Every process computes the same slice; device placement/sharding is the
        caller's job (jax.device_put with the strategy's batch sharding).
        """
        start = (step * global_batch) % self.size
        idx = (start + np.arange(global_batch)) % self.size
        return self.data[idx]
