"""Fault-tolerant sharded streaming input: checksummed token records on disk.

The synthetic table (``data/synthetic.py``) deliberately has zero I/O, so
the framework had never measured — let alone survived — an input-bound or
input-faulty run. This module is the durable half of the streaming data
path (ROADMAP direction 5): a deterministic reader over tokenized record
shards whose design axis is *robustness*:

- **Checksummed records.** Every record carries a CRC32 over its payload
  (``scripts/make_tokenized_shards.py`` writes the format). Disk bit-rot
  or a torn write is detected by *us* at read time, never surfaced as a
  garbage token id silently training the model sideways.
- **Skip-and-quarantine with an honest ledger.** A corrupt record is
  never trained on: its delivery slot is filled by the nearest valid
  record in the same shard (deterministic, so every host substitutes
  identically) and the quarantine ledger records (epoch, shard, offset,
  reason). ``records_skipped`` rides the result row and the telemetry
  stream — a healed input path is an honest record, not a silent one.
- **Bounded retry with exponential backoff.** Transient ``OSError``s
  (network filesystems, flaky mounts) are retried a bounded number of
  times with exponential backoff before failing loudly as
  :class:`DataReadError`.
- **Loud missing-shard refusal.** Discovery validates the
  ``shard_{i}-of-{n}`` set is complete; a missing shard refuses with the
  shard NAMED before any device work (the ``data-missing-shard@K`` chaos
  arm pins it) — training on a silently truncated corpus is the failure
  mode this refusal exists for.
- **Exact-resume cursor.** The stream's position is one geometry-
  independent number: ``cursor`` = global records delivered to training
  (epoch = cursor // total_records, disk index = cursor % total). The
  train loop persists it in a checkpoint sidecar
  (``runtime/checkpoint.py`` ``stream_<step>.json``) so a killed run
  resumes consuming precisely the un-consumed records — including across
  a geometry-change resume, where per-host shard ownership is recomputed
  from the new batch sharding while the cursor carries over unchanged.

Addressing is random-access by global record index (fixed-size records
per shard), which is what makes per-host sharded reads and exact resume
closed-form instead of stateful: host h never has to replay the stream
to find its rows, it just reads the indices its batch shards map to.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import re
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

#: On-disk shard format magic + version (bump on any layout change; readers
#: refuse a newer magic rather than guess).
SHARD_MAGIC = b"TOKREC01"
#: ``shard_{i:05d}-of-{n:05d}.tokrec`` — the ``-of-`` count is what lets
#: discovery prove completeness instead of trusting whatever files exist.
SHARD_FILENAME_RE = re.compile(r"^shard_(\d{5})-of-(\d{5})\.tokrec$")
STREAM_STATE_SCHEMA_VERSION = 1

#: Per-record CRC32 header size (4 bytes, little-endian).
_CRC_BYTES = 4

#: Transient-read-error policy: attempts and the base backoff (doubled per
#: retry). Small because the reader sits on the hot input path — a mount
#: that needs more than ~3 tries is an incident, not a transient.
DEFAULT_READ_RETRIES = 3
DEFAULT_RETRY_BACKOFF_SEC = 0.05

#: Exit code for a run aborted as input-starved (``reason=data_stall``):
#: distinct from preempted (75) and hung (76) — the device was healthy and
#: the process alive, the INPUT path starved the timed loop. Retryable
#: with --resume: the emergency checkpoint + stream sidecar make the
#: retry consume exactly the un-consumed records.
EXIT_DATA_STALL = 78


class MissingShardError(ValueError):
    """The shard set is incomplete; the message names the missing shard."""


class DataReadError(OSError):
    """A record read failed past the bounded retry budget (or a shard is
    corrupt beyond substitution)."""


class DataStalled(Exception):
    """The timed loop starved waiting on the input path past the
    configured timeout. Carries (stalled_step, waited_sec, saved_step);
    the harness maps it to :data:`EXIT_DATA_STALL`. The message only
    claims a checkpoint when one was actually committed — a stall before
    the first eligible boundary (or a failed emergency save) must not
    misdirect the operator toward a checkpoint that does not exist."""

    def __init__(self, step: int, waited_sec: float,
                 saved_step: Optional[int] = None):
        self.step = step
        self.waited_sec = waited_sec
        self.saved_step = saved_step
        tail = (
            f"emergency checkpoint at step {saved_step} + stream sidecar "
            "written — retry with --resume"
            if saved_step is not None else
            "no emergency checkpoint was committed (stalled before the "
            "first eligible boundary, or the save failed) — a retry "
            "resumes from the newest prior checkpoint, or cold-starts"
        )
        super().__init__(
            f"input path starved the timed loop at step {step} "
            f"({waited_sec:.1f}s past the data-stall timeout); {tail}"
        )


def shard_filename(index: int, num_shards: int) -> str:
    return f"shard_{index:05d}-of-{num_shards:05d}.tokrec"


def write_shard(
    path: str,
    tokens: np.ndarray,
    *,
    shard_index: int,
    num_shards: int,
    vocab_size: int,
    seed: int = 0,
) -> None:
    """Write one shard: magic + JSON header + CRC32-framed int32 records.

    ``tokens`` is ``(n_records, seq_len)`` integer data. Records are
    fixed-size (CRC + seq_len * 4 bytes), which is what makes the reader's
    random access closed-form. Written tmp+rename so a crashed generator
    never leaves a half-shard that discovery would accept.
    """
    tokens = np.ascontiguousarray(tokens, dtype=np.int32)
    n_records, seq_len = tokens.shape
    header = json.dumps({
        "schema_version": 1,
        "shard_index": shard_index,
        "num_shards": num_shards,
        "n_records": int(n_records),
        "seq_len": int(seq_len),
        "vocab_size": int(vocab_size),
        "dtype": "int32",
        "seed": int(seed),
    }, sort_keys=True).encode("ascii")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(SHARD_MAGIC)
        f.write(struct.pack("<I", len(header)))
        f.write(header)
        for row in tokens:
            payload = row.tobytes()
            f.write(struct.pack("<I", zlib.crc32(payload) & 0xFFFFFFFF))
            f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_shard_header(path: str) -> Tuple[Dict[str, Any], int]:
    """-> (header dict, payload byte offset of record 0). Refuses loudly
    on a wrong magic — a truncated/foreign file must not read as data."""
    with open(path, "rb") as f:
        magic = f.read(len(SHARD_MAGIC))
        if magic != SHARD_MAGIC:
            raise DataReadError(
                f"{path}: bad shard magic {magic!r} (expected "
                f"{SHARD_MAGIC!r}) — not a tokenized record shard, or torn"
            )
        (hlen,) = struct.unpack("<I", f.read(4))
        header = json.loads(f.read(hlen).decode("ascii"))
    return header, len(SHARD_MAGIC) + 4 + hlen


@dataclasses.dataclass(frozen=True)
class ShardInfo:
    index: int
    path: str
    n_records: int
    seq_len: int
    data_offset: int  # byte offset of record 0
    record_bytes: int  # CRC + payload


class ShardedTokenStream:
    """Deterministic random-access reader over a complete shard set.

    Thread model: all reads happen on the prefetch thread
    (``data/prefetch.py``); the quarantine ledger is the one shared piece
    of state and is drained by the main thread at sync-window boundaries
    (so its telemetry events respect the GC105 cadence) — hence the lock.

    ``injector`` is the chaos :class:`faults.FaultInjector` (or None): its
    ``data_missing_shard`` / ``data_corrupt_payload`` /
    ``data_read_delay_sec`` hooks make the data-fault matrix
    deterministic without ever mutating the shard files themselves.
    """

    def __init__(
        self,
        data_dir: str,
        *,
        seq_len: Optional[int] = None,
        injector: Any = None,
        read_retries: int = DEFAULT_READ_RETRIES,
        retry_backoff_sec: float = DEFAULT_RETRY_BACKOFF_SEC,
    ):
        self.data_dir = data_dir
        self.injector = injector
        self.read_retries = int(read_retries)
        self.retry_backoff_sec = float(retry_backoff_sec)
        self.cursor = 0  # records DELIVERED to training (global, monotonic)
        self.records_skipped = 0
        self._ledger: List[Dict[str, Any]] = []
        self._ledger_drained = 0
        self._lock = threading.Lock()
        self._files: Dict[int, Any] = {}  # shard index -> open file handle
        self.shards = self._discover()
        self.seq_len = self.shards[0].seq_len
        if seq_len is not None and seq_len != self.seq_len:
            raise ValueError(
                f"--data-path shards carry seq_len={self.seq_len} but the "
                f"run requested seq_len={seq_len}; regenerate the shards "
                "(scripts/make_tokenized_shards.py) or match --seq-len"
            )
        #: Cumulative record-count boundaries for global-index -> shard
        #: mapping (supports unequal shard sizes via searchsorted).
        self._bounds = np.cumsum([s.n_records for s in self.shards])
        self.total_records = int(self._bounds[-1])

    # ------------------------------------------------------------------
    # Discovery
    # ------------------------------------------------------------------

    def _discover(self) -> List[ShardInfo]:
        if not os.path.isdir(self.data_dir):
            raise MissingShardError(
                f"--data-path {self.data_dir} is not a directory"
            )
        found: Dict[int, str] = {}
        declared_n: Optional[int] = None
        for path in sorted(glob.glob(os.path.join(self.data_dir, "shard_*.tokrec"))):
            m = SHARD_FILENAME_RE.match(os.path.basename(path))
            if not m:
                continue
            idx, n = int(m.group(1)), int(m.group(2))
            if declared_n is None:
                declared_n = n
            elif n != declared_n:
                raise MissingShardError(
                    f"{self.data_dir}: mixed shard sets ({n} vs "
                    f"{declared_n} in the -of- counts) — one directory, "
                    "one generation"
                )
            found[idx] = path
        if not found or declared_n is None:
            raise MissingShardError(
                f"no shard_*-of-*.tokrec files under {self.data_dir} "
                "(generate dev shards with scripts/make_tokenized_shards.py)"
            )
        # Chaos hook: the data-missing-shard@K arm withholds shard K from
        # discovery, so the refusal below fires exactly as it would for a
        # real hole — loud, named, pre-dispatch.
        withheld = (
            self.injector.data_missing_shard()
            if self.injector is not None
            and hasattr(self.injector, "data_missing_shard") else None
        )
        if withheld is not None:
            found.pop(withheld, None)
        missing = [i for i in range(declared_n) if i not in found]
        if missing:
            raise MissingShardError(
                f"incomplete shard set under {self.data_dir}: missing "
                f"shard {missing[0]} of {declared_n} (expected "
                f"{shard_filename(missing[0], declared_n)}); refusing to "
                "train on a silently truncated corpus"
            )
        shards: List[ShardInfo] = []
        for idx in range(declared_n):
            header, data_offset = read_shard_header(found[idx])
            if int(header.get("shard_index", idx)) != idx:
                raise DataReadError(
                    f"{found[idx]}: header shard_index="
                    f"{header.get('shard_index')} does not match its "
                    f"filename index {idx}"
                )
            seq = int(header["seq_len"])
            shards.append(ShardInfo(
                index=idx, path=found[idx],
                n_records=int(header["n_records"]), seq_len=seq,
                data_offset=data_offset,
                record_bytes=_CRC_BYTES + seq * 4,
            ))
        if len({s.seq_len for s in shards}) != 1:
            raise DataReadError(
                f"{self.data_dir}: shards disagree on seq_len "
                f"({sorted({s.seq_len for s in shards})}) — one directory, "
                "one generation"
            )
        return shards

    def describe(self) -> str:
        return (
            f"{len(self.shards)} shards, {self.total_records} records x "
            f"seq_len {self.seq_len} under {self.data_dir}"
        )

    # ------------------------------------------------------------------
    # Exact-resume state
    # ------------------------------------------------------------------

    def seek(self, cursor: int) -> None:
        """Position the stream at a delivered-records cursor (>= 0)."""
        if cursor < 0:
            raise ValueError(f"stream cursor must be >= 0, got {cursor}")
        self.cursor = int(cursor)

    def state_dict(self) -> Dict[str, Any]:
        """The exact-resume iterator state (checkpoint-sidecar payload)."""
        return {
            "schema_version": STREAM_STATE_SCHEMA_VERSION,
            "cursor": int(self.cursor),
            "records_skipped": int(self.records_skipped),
            "total_records": int(self.total_records),
        }

    # ------------------------------------------------------------------
    # Reads
    # ------------------------------------------------------------------

    def _file(self, shard: ShardInfo):
        f = self._files.get(shard.index)
        if f is None:
            f = open(shard.path, "rb")
            self._files[shard.index] = f
        return f

    def _locate(self, disk_index: int) -> Tuple[ShardInfo, int]:
        s = int(np.searchsorted(self._bounds, disk_index, side="right"))
        shard = self.shards[s]
        prev = int(self._bounds[s - 1]) if s > 0 else 0
        return shard, disk_index - prev

    def _read_span(self, shard: ShardInfo, offset: int, n: int) -> bytes:
        """``n`` contiguous framed records' bytes in ONE seek+read, with
        bounded retry/backoff on transient OSErrors (and a handle re-open
        per retry — a gone-stale NFS handle is the classic transient).
        Records are fixed-size frames, so batch reads are one contiguous
        span per shard — per-record round trips on a network filesystem
        would land directly in the measured data_stall_frac."""
        pos = shard.data_offset + offset * shard.record_bytes
        want = n * shard.record_bytes
        last_err: Optional[OSError] = None
        for attempt in range(self.read_retries + 1):
            try:
                f = self._file(shard)
                f.seek(pos)
                buf = f.read(want)
                if len(buf) != want:
                    raise OSError(
                        f"short read ({len(buf)} of {want} bytes) at "
                        f"record {offset}"
                    )
                return buf
            except OSError as e:
                last_err = e
                self._files.pop(shard.index, None)
                if attempt < self.read_retries:
                    time.sleep(self.retry_backoff_sec * (2 ** attempt))
        raise DataReadError(
            f"{shard.path}: record(s) {offset}..{offset + n - 1} "
            f"unreadable after {self.read_retries + 1} attempts "
            f"({last_err})"
        )

    def _read_raw(self, shard: ShardInfo, offset: int) -> bytes:
        """One framed record's bytes (the substitution path's unit read)."""
        return self._read_span(shard, offset, 1)

    def _decode(self, shard: ShardInfo, offset: int,
                raw: bytes) -> Optional[np.ndarray]:
        """CRC-verify + decode one framed record; None on checksum fail."""
        (crc,) = struct.unpack("<I", raw[:_CRC_BYTES])
        payload = raw[_CRC_BYTES:]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            return None
        return np.frombuffer(payload, dtype=np.int32).copy()

    def _substitute(self, shard: ShardInfo, bad_offset: int) -> Tuple[int, np.ndarray]:
        """The nearest VALID record in the same shard (previous first,
        then forward) — deterministic, so every host fills the slot with
        identical content. Raises DataReadError when the whole shard is
        corrupt (substitution must not loop forever on dead data)."""
        candidates = list(range(bad_offset - 1, -1, -1)) + list(
            range(bad_offset + 1, shard.n_records)
        )
        for off in candidates:
            row = self._decode(shard, off, self._read_raw(shard, off))
            if row is not None:
                return off, row
        raise DataReadError(
            f"{shard.path}: every record failed its checksum — the shard "
            "is corrupt beyond substitution; regenerate it"
        )

    def _deliver(self, shard: ShardInfo, offset: int, raw: bytes,
                 global_index: int) -> np.ndarray:
        """Decode-or-heal one framed record: injector hooks, CRC verify,
        and the substitution + ledger path on a mismatch."""
        inj = self.injector
        if inj is not None and hasattr(inj, "data_read_delay_sec"):
            delay = inj.data_read_delay_sec(global_index)
            if delay > 0:
                time.sleep(delay)
        if inj is not None and hasattr(inj, "data_corrupt_payload"):
            raw = raw[:_CRC_BYTES] + inj.data_corrupt_payload(
                global_index, raw[_CRC_BYTES:]
            )
        row = self._decode(shard, offset, raw)
        if row is None:
            sub_off, row = self._substitute(shard, offset)
            with self._lock:
                self.records_skipped += 1
                self._ledger.append({
                    "epoch": int(global_index // self.total_records),
                    "shard": shard.index,
                    "record": int(offset),
                    "global_index": int(global_index),
                    "reason": "crc_mismatch",
                    "substitute_record": int(sub_off),
                })
        return row

    def _read_one(self, global_index: int) -> np.ndarray:
        """One delivered record by global index, healing corruption."""
        shard, offset = self._locate(global_index % self.total_records)
        return self._deliver(shard, offset, self._read_raw(shard, offset),
                             global_index)

    def read_records(self, start: int, stop: int) -> np.ndarray:
        """Records ``[start, stop)`` in the global delivered-index space
        (epoch wrap handled) as an ``(stop-start, seq_len)`` int32 array.

        Reads are batched: each contiguous run of records inside one
        shard is ONE seek+read (fixed-size frames make the span closed
        form), then CRC-verified per frame — on a network filesystem the
        per-record round trips this avoids would otherwise inflate the
        very data_stall_frac the gate polices.
        """
        if stop < start:
            raise ValueError(f"bad record range [{start}, {stop})")
        out = np.empty((stop - start, self.seq_len), dtype=np.int32)
        i = 0
        g = start
        while g < stop:
            disk_index = g % self.total_records
            shard, offset = self._locate(disk_index)
            run = min(
                stop - g,                      # what the caller wants
                shard.n_records - offset,      # what this shard holds
                self.total_records - disk_index,  # this epoch's remainder
            )
            span = self._read_span(shard, offset, run)
            rb = shard.record_bytes
            for k in range(run):
                out[i] = self._deliver(
                    shard, offset + k, span[k * rb:(k + 1) * rb], g + k
                )
                i += 1
            g += run
        return out

    def next_batch(self, n: int) -> np.ndarray:
        """The next ``n`` records at the cursor; advances it."""
        batch = self.read_records(self.cursor, self.cursor + n)
        self.cursor += n
        return batch

    # ------------------------------------------------------------------
    # Quarantine ledger
    # ------------------------------------------------------------------

    def drain_quarantine(self) -> List[Dict[str, Any]]:
        """Ledger entries added since the last drain (main-thread side:
        the train loop emits one ``data_corrupt_record`` telemetry event
        per entry at its next sync-window boundary)."""
        with self._lock:
            new = self._ledger[self._ledger_drained:]
            self._ledger_drained = len(self._ledger)
            return list(new)

    @property
    def quarantine_ledger(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ledger)

    def close(self) -> None:
        for f in self._files.values():
            try:
                f.close()
            except OSError:
                pass
        self._files.clear()
