from .synthetic import SyntheticDataset

__all__ = ["SyntheticDataset"]
