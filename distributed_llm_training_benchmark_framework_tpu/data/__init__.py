"""Input pipelines: the zero-IO synthetic table and the streaming path.

- :mod:`.synthetic` — the reference-parity seeded token table (default:
  every arm's byte-identical, input-never-bound baseline).
- :mod:`.stream` — the fault-tolerant sharded record reader (checksummed
  records, skip-and-quarantine, bounded retry, exact-resume cursor).
- :mod:`.prefetch` — the bounded double-buffered host prefetcher with
  per-host sharded device put and measured-starvation accounting
  (``data_stall_frac``).
"""

from .prefetch import DataStallTimeout, HostPrefetcher  # noqa: F401
from .stream import (  # noqa: F401
    EXIT_DATA_STALL,
    DataReadError,
    DataStalled,
    MissingShardError,
    ShardedTokenStream,
)
from .synthetic import SyntheticDataset

__all__ = [
    "DataReadError",
    "DataStallTimeout",
    "DataStalled",
    "EXIT_DATA_STALL",
    "HostPrefetcher",
    "MissingShardError",
    "ShardedTokenStream",
    "SyntheticDataset",
]
