"""TPU-native distributed LLM training benchmark framework.

A brand-new, TPU-first re-design of the capabilities of
``deepaksatna/Distributed-LLM-Training-Benchmark-Framework`` (the reference):
four distributed-training strategy arms (ddp / fsdp / zero2 / zero3) expressed
as *sharding specifications* over a ``jax.sharding.Mesh`` applied to a single
shared, jitted train step — instead of the reference's four divergent
wrapper-object code paths (reference ``benchmarking/train_harness.py:207-275``).

Subpackages
-----------
- ``models``    TinyGPT decoder-only transformer (pure functional JAX)
- ``ops``       attention kernels (jnp reference + Pallas flash / ring attention)
- ``parallel``  mesh construction, strategy sharding specs, collectives
- ``train``     unified train step, timed benchmark loop, CLI harness
- ``data``      synthetic dataset (seeded, zero-I/O)
- ``utils``     metrics/result schema, HBM probes, config files
- ``analysis``  parse -> metrics.csv -> plots -> Markdown report pipeline
- ``runtime``   multi-host init (jax.distributed), profiling, checkpointing
"""

__version__ = "0.1.0"

# Older jax runtimes (0.4.x) lack a few new public API names the codebase
# targets (set_mesh, shard_map, typeof, get_abstract_mesh); install the
# equivalence shims before any subpackage import can touch them. No-op on
# current jax — and skipped entirely when jax is not installed at all, so
# the pure-stdlib analysis CLIs (validate_results, parse_metrics) keep
# working on scrape-and-validate machines without a jax install.
try:
    from .utils import jax_compat as _jax_compat

    _jax_compat.install()
    del _jax_compat
except ModuleNotFoundError as _e:
    # Swallow ONLY "jax is not installed"; a partially-installed jax whose
    # submodules fail mid-install must fail loudly here, not as an
    # unexplained AttributeError at first use.
    if _e.name != "jax":
        raise
    del _e
