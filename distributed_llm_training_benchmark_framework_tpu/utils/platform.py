"""Platform selection guard + per-device-kind hardware peaks.

Some TPU environments register their platform plugin from ``sitecustomize`` at
interpreter startup and force ``jax_platforms`` via ``jax.config.update``,
which silently overrides a user's ``JAX_PLATFORMS`` environment variable. The
CPU-smoke and virtual-mesh test paths (SURVEY §4) depend on that variable
working, so every CLI entry point calls :func:`honor_jax_platforms_env` first.

This module is also the one place the roofline peaks live:
:func:`device_peak_flops` (bf16 FLOP/s, delegating to the spec table in
``utils.flops``) and :func:`device_peak_hbm_gbps` (HBM bandwidth). The
step-anatomy engine (``analysis/step_anatomy.py``) positions every traced
arm against both axes; keeping the tables here means a new device kind is
added exactly once.
"""

from __future__ import annotations

import os
from typing import Optional


def honor_jax_platforms_env() -> None:
    """Make JAX_PLATFORMS from the environment win over config forced earlier."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        cur = jax.config.jax_platforms or ""
    except AttributeError:
        cur = ""
    if cur.split(",")[0] == want.split(",")[0]:
        return
    jax.config.update("jax_platforms", want)
    try:
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            from jax.extend.backend import clear_backends

            clear_backends()
    except Exception:
        pass


# HBM bandwidth per chip, GB/s (decimal), public spec-sheet numbers — the
# roofline's memory axis. Same substring-match convention as the peak-TFLOPs
# table in utils/flops.py; order matters (more specific names first).
_PEAK_HBM_GBPS = (
    ("TPU v6 lite", 1640.0),  # Trillium / v6e
    ("TPU v6", 1640.0),
    ("TPU v5 lite", 819.0),  # v5e
    ("TPU v5e", 819.0),
    ("TPU v5p", 2765.0),
    ("TPU v5", 2765.0),
    ("TPU v4 lite", 614.0),  # v4i
    ("TPU v4", 1228.0),
    ("TPU v3", 900.0),
    ("TPU v2", 700.0),
)


def device_peak_hbm_gbps(device_kind: str) -> Optional[float]:
    """HBM GB/s peak for a device kind, or None if unknown (e.g. CPU)."""
    for name, peak in _PEAK_HBM_GBPS:
        if name.lower() in device_kind.lower():
            return peak
    return None


def device_peak_flops(device_kind: str) -> Optional[float]:
    """bf16 peak FLOP/s per chip (the roofline's compute axis), or None.

    Thin unit-converting wrapper over ``utils.flops.device_peak_tflops`` so
    the spec numbers exist in exactly one table while roofline consumers
    pull both axes from this module.
    """
    from . import flops as flops_mod

    peak_t = flops_mod.device_peak_tflops(device_kind)
    return peak_t * 1e12 if peak_t is not None else None


def allreduce_promotion_disabled(flags: str) -> bool:
    """True iff an ``--xla_disable_hlo_passes`` list in ``flags`` names the
    all-reduce-promotion pass.

    A plain substring test would be satisfied by the string appearing inside
    any unrelated flag value; this parses the actual pass list (last
    occurrence wins, matching XLA's flag parsing).
    """
    disabled = False
    for tok in flags.split():
        if tok.startswith("--xla_disable_hlo_passes="):
            passes = tok.split("=", 1)[1].split(",")
            disabled = "all-reduce-promotion" in (p.strip() for p in passes)
    return disabled
