"""Platform selection guard.

Some TPU environments register their platform plugin from ``sitecustomize`` at
interpreter startup and force ``jax_platforms`` via ``jax.config.update``,
which silently overrides a user's ``JAX_PLATFORMS`` environment variable. The
CPU-smoke and virtual-mesh test paths (SURVEY §4) depend on that variable
working, so every CLI entry point calls :func:`honor_jax_platforms_env` first.
"""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    """Make JAX_PLATFORMS from the environment win over config forced earlier."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        cur = jax.config.jax_platforms or ""
    except AttributeError:
        cur = ""
    if cur.split(",")[0] == want.split(",")[0]:
        return
    jax.config.update("jax_platforms", want)
    try:
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            from jax.extend.backend import clear_backends

            clear_backends()
    except Exception:
        pass


def allreduce_promotion_disabled(flags: str) -> bool:
    """True iff an ``--xla_disable_hlo_passes`` list in ``flags`` names the
    all-reduce-promotion pass.

    A plain substring test would be satisfied by the string appearing inside
    any unrelated flag value; this parses the actual pass list (last
    occurrence wins, matching XLA's flag parsing).
    """
    disabled = False
    for tok in flags.split():
        if tok.startswith("--xla_disable_hlo_passes="):
            passes = tok.split("=", 1)[1].split(",")
            disabled = "all-reduce-promotion" in (p.strip() for p in passes)
    return disabled
