"""Platform selection guard + per-device-kind hardware peaks.

Some TPU environments register their platform plugin from ``sitecustomize`` at
interpreter startup and force ``jax_platforms`` via ``jax.config.update``,
which silently overrides a user's ``JAX_PLATFORMS`` environment variable. The
CPU-smoke and virtual-mesh test paths (SURVEY §4) depend on that variable
working, so every CLI entry point calls :func:`honor_jax_platforms_env` first.

This module is also the one place the roofline peaks live:
:func:`device_peak_flops` (bf16 FLOP/s, delegating to the spec table in
``utils.flops``) and :func:`device_peak_hbm_gbps` (HBM bandwidth). The
step-anatomy engine (``analysis/step_anatomy.py``) positions every traced
arm against both axes; keeping the tables here means a new device kind is
added exactly once.
"""

from __future__ import annotations

import os
import re
from typing import Optional


def honor_jax_platforms_env() -> None:
    """Make JAX_PLATFORMS from the environment win over config forced earlier."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        cur = jax.config.jax_platforms or ""
    except AttributeError:
        cur = ""
    if cur.split(",")[0] == want.split(",")[0]:
        return
    jax.config.update("jax_platforms", want)
    try:
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            from jax.extend.backend import clear_backends

            clear_backends()
    except Exception:
        pass


# HBM bandwidth per chip, GB/s (decimal), public spec-sheet numbers — the
# roofline's memory axis. Same substring-match convention as the peak-TFLOPs
# table in utils/flops.py; order matters (more specific names first).
_PEAK_HBM_GBPS = (
    ("TPU v6 lite", 1640.0),  # Trillium / v6e
    ("TPU v6", 1640.0),
    ("TPU v5 lite", 819.0),  # v5e
    ("TPU v5e", 819.0),
    ("TPU v5p", 2765.0),
    ("TPU v5", 2765.0),
    ("TPU v4 lite", 614.0),  # v4i
    ("TPU v4", 1228.0),
    ("TPU v3", 900.0),
    ("TPU v2", 700.0),
)


def device_peak_hbm_gbps(device_kind: str) -> Optional[float]:
    """HBM GB/s peak for a device kind, or None if unknown (e.g. CPU)."""
    for name, peak in _PEAK_HBM_GBPS:
        if name.lower() in device_kind.lower():
            return peak
    return None


def device_peak_flops(device_kind: str) -> Optional[float]:
    """bf16 peak FLOP/s per chip (the roofline's compute axis), or None.

    Thin unit-converting wrapper over ``utils.flops.device_peak_tflops`` so
    the spec numbers exist in exactly one table while roofline consumers
    pull both axes from this module.
    """
    from . import flops as flops_mod

    peak_t = flops_mod.device_peak_tflops(device_kind)
    return peak_t * 1e12 if peak_t is not None else None


# ---------------------------------------------------------------------------
# Latency-hiding scheduler flags (round 8, overlap work)
# ---------------------------------------------------------------------------

#: XLA flags that turn on the latency-hiding scheduler + async collectives
#: on TPU — the compiler half of the zero2 per-block reduce-scatter overlap
#: (the model half is tinygpt.block_grad_spec). One canonical tuple so the
#: harness (--xla-latency-hiding), the entrypoint (XLA_LATENCY_HIDING=1)
#: and the docs all name the same set. TPU-only: XLA aborts on unknown
#: flags, so :func:`apply_latency_hiding_flags` gates the append on
#: :func:`tpu_xla_plausible` (a CPU dryrun warns and no-ops).
LATENCY_HIDING_XLA_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
)

#: XLA_FLAGS tokens that change the collective schedule: these join the
#: run's env fingerprint AND the registry config key (a flagged run is a
#: different measurement lineage than an unflagged one — regress.store).
_SCHEDULER_FLAG_RE = re.compile(
    r"--xla\S*(?:latency_hiding|async_collective|overlap_compute"
    r"|collective_scheduler|scheduling)\S*"
)


def tpu_xla_plausible() -> bool:
    """True when the process can plausibly parse TPU-targeting XLA flags.

    XLA ABORTS the process on unknown flags in ``XLA_FLAGS`` (a fatal
    check in parse_flags_from_env.cc, not a warning), and the
    latency-hiding set is ``--xla_tpu_*`` — unknown to a CPU/GPU-only
    jaxlib. So: apply only when ``JAX_PLATFORMS``/``JAX_PLATFORM_NAME``
    names a tpu-like platform, or (platform unforced) a TPU plugin is
    importable. A forced-CPU env (the dryrun/test path) always skips.
    """
    env = (os.environ.get("JAX_PLATFORMS")
           or os.environ.get("JAX_PLATFORM_NAME") or "").lower()
    if "tpu" in env:
        return True
    if env:  # explicitly forced to cpu/gpu/axon/... — not our flag set
        return False
    import importlib.util

    try:
        return (importlib.util.find_spec("libtpu") is not None
                or importlib.util.find_spec("jax_plugins.libtpu")
                is not None)
    except (ImportError, ValueError):
        return False


def apply_latency_hiding_flags() -> str:
    """Append :data:`LATENCY_HIDING_XLA_FLAGS` to ``XLA_FLAGS`` (idempotent).

    Must run BEFORE jax initializes its backend — callers are the harness
    and bench.py flag handlers, which run it next to
    :func:`honor_jax_platforms_env`. Returns the resulting ``XLA_FLAGS``.

    On a host whose XLA cannot know the TPU flag set
    (:func:`tpu_xla_plausible` False) this warns and no-ops instead of
    letting XLA's unknown-flag check abort the process — the run then
    records an empty ``xla_scheduler_flags`` fingerprint and stays in
    the unflagged regress lineage, so the degrade is never silent in
    the registry.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if not tpu_xla_plausible():
        import sys

        print(
            "WARNING: --xla-latency-hiding skipped: no TPU platform/plugin "
            "visible, and XLA aborts on unknown --xla_tpu_* flags "
            "(xla_scheduler_flags stays empty for this run)",
            file=sys.stderr,
        )
        return flags
    present = set(flags.split())
    missing = [f for f in LATENCY_HIDING_XLA_FLAGS if f not in present]
    if missing:
        flags = (flags + " " + " ".join(missing)).strip()
        os.environ["XLA_FLAGS"] = flags
    return flags


def scheduler_flags_fingerprint(flags: Optional[str] = None) -> str:
    """The scheduling-relevant subset of ``XLA_FLAGS``, sorted and joined.

    Empty string when none are set — the default lineage. Recorded into
    every result row (``xla_scheduler_flags``) so the regress registry can
    keep flagged and unflagged lineages apart (store.config_key).
    """
    if flags is None:
        flags = os.environ.get("XLA_FLAGS", "")
    return " ".join(sorted(set(_SCHEDULER_FLAG_RE.findall(flags))))


def allreduce_promotion_disabled(flags: str) -> bool:
    """True iff an ``--xla_disable_hlo_passes`` list in ``flags`` names the
    all-reduce-promotion pass.

    A plain substring test would be satisfied by the string appearing inside
    any unrelated flag value; this parses the actual pass list (last
    occurrence wins, matching XLA's flag parsing).
    """
    disabled = False
    for tok in flags.split():
        if tok.startswith("--xla_disable_hlo_passes="):
            passes = tok.split("=", 1)[1].split(",")
            disabled = "all-reduce-promotion" in (p.strip() for p in passes)
    return disabled
