"""Platform selection guard.

Some TPU environments register their platform plugin from ``sitecustomize`` at
interpreter startup and force ``jax_platforms`` via ``jax.config.update``,
which silently overrides a user's ``JAX_PLATFORMS`` environment variable. The
CPU-smoke and virtual-mesh test paths (SURVEY §4) depend on that variable
working, so every CLI entry point calls :func:`honor_jax_platforms_env` first.
"""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    """Make JAX_PLATFORMS from the environment win over config forced earlier."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        cur = jax.config.jax_platforms or ""
    except AttributeError:
        cur = ""
    if cur.split(",")[0] == want.split(",")[0]:
        return
    jax.config.update("jax_platforms", want)
    try:
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            from jax.extend.backend import clear_backends

            clear_backends()
    except Exception:
        pass
