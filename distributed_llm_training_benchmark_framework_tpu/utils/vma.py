"""Varying-manual-axes (vma) helpers.

Inside a partially-manual ``jax.shard_map`` every value's type tracks which
manual mesh axes it varies over; zeros initializers, scan carries and Pallas
out_shapes must declare vma that matches what the computation produces or the
checker rejects the program. These helpers centralize the introspection so a
JAX rename of the ``vma`` aval attribute or the ``pcast`` signature is a
one-file fix.
"""

from __future__ import annotations

import functools
from typing import FrozenSet, Iterable, Tuple

import jax
from jax import lax


def vma_of(*arrays) -> FrozenSet[str]:
    """Union of the manual mesh axes the given values vary over."""
    axes = set()
    for a in arrays:
        axes |= set(getattr(jax.typeof(a), "vma", ()) or ())
    return frozenset(axes)


def pcast_missing(x, axes: Iterable[str]):
    """pcast ``x`` to vary over ``axes``, skipping axes it already varies
    over (pcast rejects varying->varying).

    On jax runtimes without ``lax.pcast`` (pre-vma shard_map, where the
    compat layer runs shard_map with replication checking off) there is no
    varying-axes type system to satisfy, so this is the identity.
    """
    if not hasattr(lax, "pcast"):
        return x
    have = vma_of(x)
    need = tuple(a for a in axes if a not in have)
    return lax.pcast(x, need, to="varying") if need else x


def pcast_like(x, *like):
    """pcast ``x`` to vary over every axis any of ``like`` varies over."""
    return pcast_missing(x, sorted(vma_of(*like)))


@functools.lru_cache(maxsize=None)
def _legacy_pcast_varying(axes: Tuple[str, ...]):
    """Identity whose cotangent psums over ``axes`` — pcast's transpose.

    Pre-vma runtimes have no ``lax.pcast``, but some call sites depend on
    more than the type cast: the transpose of invariant->varying is a psum,
    and pipeline backward passes lean on exactly that reduction (e.g. the
    1F1B embed vjp, where the cotangent is nonzero on stage 0 only and the
    parameter gradient must come back already summed across stages). A
    plain-identity degrade (``pcast_missing``'s contract) would silently
    drop that psum, so this reconstructs it with a custom_vjp.
    """

    @jax.custom_vjp
    def cast(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (lax.psum(g, axes),)

    cast.defvjp(fwd, bwd)
    return cast


def pcast_varying(x, axes: Iterable[str]):
    """``lax.pcast(x, axes, to='varying')`` with a legacy-jax fallback
    whose TRANSPOSE is preserved.

    Unlike :func:`pcast_missing` (identity on pre-vma runtimes — right for
    pure type plumbing, wrong wherever the pcast transpose psum carries
    real gradient flow), this keeps the backward psum alive on both
    runtimes. Use it when the call site differentiates through the cast.
    """
    axes = tuple(axes)
    if not axes:
        return x
    if hasattr(lax, "pcast"):
        return pcast_missing(x, axes)
    return _legacy_pcast_varying(axes)(x)
