"""Varying-manual-axes (vma) helpers.

Inside a partially-manual ``jax.shard_map`` every value's type tracks which
manual mesh axes it varies over; zeros initializers, scan carries and Pallas
out_shapes must declare vma that matches what the computation produces or the
checker rejects the program. These helpers centralize the introspection so a
JAX rename of the ``vma`` aval attribute or the ``pcast`` signature is a
one-file fix.
"""

from __future__ import annotations

from typing import FrozenSet, Iterable

import jax
from jax import lax


def vma_of(*arrays) -> FrozenSet[str]:
    """Union of the manual mesh axes the given values vary over."""
    axes = set()
    for a in arrays:
        axes |= set(getattr(jax.typeof(a), "vma", ()) or ())
    return frozenset(axes)


def pcast_missing(x, axes: Iterable[str]):
    """pcast ``x`` to vary over ``axes``, skipping axes it already varies
    over (pcast rejects varying->varying).

    On jax runtimes without ``lax.pcast`` (pre-vma shard_map, where the
    compat layer runs shard_map with replication checking off) there is no
    varying-axes type system to satisfy, so this is the identity.
    """
    if not hasattr(lax, "pcast"):
        return x
    have = vma_of(x)
    need = tuple(a for a in axes if a not in have)
    return lax.pcast(x, need, to="varying") if need else x


def pcast_like(x, *like):
    """pcast ``x`` to vary over every axis any of ``like`` varies over."""
    return pcast_missing(x, sorted(vma_of(*like)))
