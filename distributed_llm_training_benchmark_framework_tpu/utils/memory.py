"""Per-chip HBM footprint estimation — fail fast instead of OOM-ing.

The reference framework has no memory model at all: requesting its 1.68B
"stress tier" on hardware that cannot hold it dies in the allocator mid-run
(its own suite never ran tier B — reference ``scripts/run_all_benchmarks.sh``
keeps those lines commented out). Here the harness estimates the per-chip
footprint *before* initializing anything, prints the breakdown, and refuses
with an explanation when the estimate exceeds device capacity.

Method:

- **Parameter-shaped state is exact**: ``jax.eval_shape`` over ``init_params``
  and ``optimizer.init`` gives the true byte counts; each leaf is divided by
  the product of mesh-axis sizes its PartitionSpec shards over (the same
  specs the train step jits with), so DDP/FSDP/ZeRO/TP/PP layouts all read
  their real per-chip share. Gradients mirror params (fp32 accumulators),
  sharded when the strategy reduce-scatters them (ZeRO-2/3, FSDP).
- **Activations are analytic** (intentionally a model, not a measurement —
  the point is to predict before allocating): per-layer live tensors for the
  fwd+bwd of one microbatch, ``~14 * B * S * D`` compute-dtype bytes dense,
  plus the O(S^2) score/prob tensors ONLY for the materialized 'reference'
  attention (flash/ring never materialize them — their activation term is
  what makes long-context tier-A runs fit), plus the fp32 logits + cotangent
  at the head. Remat collapses the per-layer term to the boundary residual
  plus one layer's recompute peak.

Scope: single-host estimates for the dp/tp/pp axes the benchmark arms use.
Numbers are estimates (XLA fusion, padding and collective buffers move the
real peak ±20%); the capacity check applies a safety margin accordingly.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import numpy as np

# Per-chip HBM capacity in GiB, matched by substring against
# Device.device_kind (same convention as flops._PEAK_TFLOPS_BF16).
_HBM_GIB = (
    ("TPU v6 lite", 32.0),
    ("TPU v6", 32.0),
    ("TPU v5 lite", 16.0),
    ("TPU v5e", 16.0),
    ("TPU v5p", 95.0),
    ("TPU v5", 95.0),
    ("TPU v4 lite", 8.0),
    ("TPU v4", 32.0),
    ("TPU v3", 16.0),
    ("TPU v2", 8.0),
)


def device_hbm_bytes(device_kind: str) -> Optional[int]:
    """Per-chip HBM capacity for a device kind, or None if unknown (CPU)."""
    for name, gib in _HBM_GIB:
        if name.lower() in device_kind.lower():
            return int(gib * 1024**3)
    return None


def _sharded_bytes(shapes, specs, mesh) -> int:
    """Total bytes of a shape-tree, each leaf divided by its shard factor."""
    total = 0
    shape_leaves = jax.tree_util.tree_leaves(shapes)
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)
    )
    if len(shape_leaves) != len(spec_leaves):
        # A silent zip-truncation here would under-estimate HBM and defeat
        # the fail-fast pre-flight check — structure drift must fail loudly.
        raise ValueError(
            f"shape tree has {len(shape_leaves)} leaves but spec tree has "
            f"{len(spec_leaves)}; the trees must mirror each other"
        )
    for shape_leaf, spec_leaf in zip(shape_leaves, spec_leaves):
        nbytes = int(np.prod(shape_leaf.shape) or 1) * shape_leaf.dtype.itemsize
        factor = 1
        if isinstance(spec_leaf, jax.sharding.PartitionSpec):
            for entry in spec_leaf:
                for ax in (entry,) if isinstance(entry, str) else (entry or ()):
                    factor *= mesh.shape.get(ax, 1)
        total += nbytes // max(factor, 1)
    return total


@dataclasses.dataclass
class HBMEstimate:
    params: int
    grads: int
    opt_state: int
    activations: int
    logits: int
    dataset: int

    @property
    def total(self) -> int:
        return (
            self.params + self.grads + self.opt_state
            + self.activations + self.logits + self.dataset
        )

    def breakdown(self) -> Dict[str, float]:
        gib = 1024**3
        return {
            "params_gib": self.params / gib,
            "grads_gib": self.grads / gib,
            "opt_state_gib": self.opt_state / gib,
            "activations_gib": self.activations / gib,
            "logits_gib": self.logits / gib,
            "dataset_gib": self.dataset / gib,
            "total_gib": self.total / gib,
        }


def estimate_hbm(
    model_config: Any,
    strategy: Any,
    mesh: Any,
    per_device_batch: int,
    seq_len: int,
    dataset_size: int = 0,
) -> HBMEstimate:
    """Estimate the per-chip HBM footprint of one training arm."""
    from ..models import tinygpt
    from ..parallel import strategies as strat

    cfg = model_config
    params_shape = jax.eval_shape(
        functools.partial(tinygpt.init_params, cfg), jax.random.key(0)
    )
    scan_stacked = bool(getattr(cfg, "scan_layers", True))
    param_specs = strat.param_partition_specs(
        params_shape, mesh, shard=strategy.shard_params, kv_heads=cfg.kv_heads,
        scan_stacked=scan_stacked,
    )
    grad_specs = strat.param_partition_specs(
        params_shape, mesh,
        shard=strategy.shard_params or strategy.shard_grads,
        kv_heads=cfg.kv_heads,
        scan_stacked=scan_stacked,
    )
    optimizer = strat.make_optimizer(strategy)
    opt_shape = jax.eval_shape(optimizer.init, params_shape)
    opt_specs = strat.opt_state_partition_specs(
        optimizer, params_shape, param_specs, mesh,
        shard=strategy.shard_opt_state, kv_heads=cfg.kv_heads,
        scan_stacked=scan_stacked,
    )

    params_b = _sharded_bytes(params_shape, param_specs, mesh)
    grads_b = _sharded_bytes(params_shape, grad_specs, mesh)
    if getattr(strategy, "offload_opt_state", False):
        # The WHOLE optimizer state lives in pinned HOST memory and the
        # update + apply run on the host (strategies.offload_update_and_
        # apply) — nothing of it occupies HBM.
        opt_b = 0
    else:
        opt_b = _sharded_bytes(opt_shape, opt_specs, mesh)

    # --- analytic activations for one microbatch's fwd+bwd on this chip ---
    B = per_device_batch  # per-data-parallel-shard batch
    S, D, L, H, V = seq_len, cfg.n_embd, cfg.n_layer, cfg.n_head, cfg.vocab_size
    tp = mesh.shape.get("model", 1)
    pp = mesh.shape.get("pipe", 1)
    cbytes = jnp_itemsize(cfg.compute_dtype)
    # ln/qkv/attn-out residuals (~10·BSD) + the MLP hidden tensors: F/D
    # widths of it for GELU, 2F/D (gate+up) for SwiGLU. Default geometry
    # (F=4D, gelu) reproduces the original 14·BSD coefficient. GQA's k/v
    # are repeated to full H before attention (models.tinygpt), so no
    # activation credit is taken for kv_heads < n_head.
    F = getattr(cfg, "mlp_dim", 4 * D) or 4 * D
    mlp_widths = (2 if getattr(cfg, "mlp_act", "gelu") == "swiglu" else 1) * F / D
    dense_per_layer = int((10 + mlp_widths) * B * S * D) * cbytes
    # Megatron TP shards the head and MLP activations.
    dense_per_layer = dense_per_layer // max(tp, 1)
    if cfg.attention_impl == "reference":
        # scores + probs materialize per head, fp32 softmax: the O(S^2) term.
        dense_per_layer += 2 * B * (H // max(tp, 1)) * S * S * 4
    layers_here = L // max(pp, 1)
    from ..models.tinygpt import normalize_remat

    pol = normalize_remat("full" if cfg.remat == "auto" else cfg.remat)
    if pol == "full":
        # Only the layer-boundary residual (+grad) survives; one layer's
        # working set is live during its backward recompute.
        act_b = layers_here * 2 * B * S * D * cbytes + dense_per_layer
    elif pol == "dots":
        # Matmul outputs are saved (~qkv 3BSD + attn-out BSD + mlp 5BSD +
        # boundary 2BSD ≈ 11·BSD per layer); elementwise intermediates are
        # recomputed within one layer's working set.
        act_b = layers_here * 11 * B * S * D * cbytes + dense_per_layer
    else:
        act_b = layers_here * dense_per_layer
    # fp32 logits + cotangent at the LM head.
    logits_b = 2 * B * S * V * 4

    dataset_b = dataset_size * seq_len * 4  # device-resident int32 table

    return HBMEstimate(
        params=params_b, grads=grads_b, opt_state=opt_b,
        activations=act_b, logits=logits_b, dataset=dataset_b,
    )


def jnp_itemsize(dtype: Any) -> int:
    return int(np.dtype(jax.numpy.dtype(dtype)).itemsize)


def format_breakdown(est: HBMEstimate, device_kind: str) -> str:
    b = est.breakdown()
    cap = device_hbm_bytes(device_kind)
    lines = [
        "Estimated per-chip HBM footprint:",
        f"  params:      {b['params_gib']:7.2f} GiB",
        f"  grads:       {b['grads_gib']:7.2f} GiB",
        f"  opt state:   {b['opt_state_gib']:7.2f} GiB",
        f"  activations: {b['activations_gib']:7.2f} GiB (analytic)",
        f"  logits:      {b['logits_gib']:7.2f} GiB",
        f"  dataset:     {b['dataset_gib']:7.2f} GiB",
        f"  total:       {b['total_gib']:7.2f} GiB"
        + (f" / {cap / 1024**3:.0f} GiB {device_kind}" if cap else ""),
    ]
    return "\n".join(lines)


# Headroom for remat-policy selection (resolve_auto_remat): the analytic
# estimate must stay below this fraction of HBM before a cheaper policy is
# chosen. Derived from the measured est->actual bias (docs/PERFORMANCE.md).
AUTO_REMAT_MARGIN = 0.70
# When the analytic margin rejects a policy but the estimate still fits
# nominal capacity, the resolver can ask XLA directly (an abstract AOT
# compile of the real step — train.step.abstract_step_peak_bytes) and
# accept on the MEASURED buffer-assignment peak. 0.96 of nominal keeps
# ~4% runtime headroom below XLA's own usable limit (~98.4% of nominal on
# v5e: "15.75G of 16G" in compiler OOM reports).
AOT_PROBE_ACCEPT_MARGIN = 0.96


def check_fits(
    est: HBMEstimate, device_kind: str, margin: float = 0.95
) -> Optional[str]:
    """Return a refusal message if the estimate exceeds usable capacity.

    ``margin`` reserves headroom for XLA scratch/fragmentation. Unknown
    device kinds (CPU hosts) are never refused.
    """
    cap = device_hbm_bytes(device_kind)
    if cap is None or est.total <= cap * margin:
        return None
    b = est.breakdown()
    hints = []
    if b["opt_state_gib"] + b["grads_gib"] > 0.4 * b["total_gib"]:
        hints.append("a sharded arm (fsdp/zero3) or more chips")
    if b["activations_gib"] > 0.3 * b["total_gib"]:
        hints.append("--remat, a smaller --per-device-batch, or --attention flash")
    hint = f" Try {' and '.join(hints)}." if hints else ""
    return (
        f"Estimated footprint {b['total_gib']:.1f} GiB exceeds "
        f"{cap / 1024**3:.0f} GiB on {device_kind} "
        f"(margin {margin:.0%}).{hint}\n{format_breakdown(est, device_kind)}"
    )


def resolve_auto_remat(
    model_config: Any,
    strategy: Any,
    mesh: Any,
    per_device_batch: int,
    seq_len: int,
    dataset_size: int = 0,
    device_kind: str = "",
    aot_probe: Optional[Any] = None,
) -> Any:
    """Resolve a strategy's remat="auto" to the cheapest policy that fits.

    Tries "none" -> "dots" -> "full" against :func:`estimate_hbm` +
    :func:`check_fits` for this arm's actual (batch, seq, mesh) geometry.
    Remat trades recompute for memory; paying the tax when the arm already
    fits measured ~20% of zero3's single-chip throughput (docs/PERFORMANCE
    .md), so the tax is only paid under actual memory pressure. Returns the
    strategy unchanged unless remat == "auto". Unknown device kinds (CPU)
    are never refused by check_fits, so they resolve to "none".

    The analytic policy choice uses a STRICTER margin than the go/no-go
    pre-flight (AUTO_REMAT_MARGIN vs check_fits' 0.95): measured peaks run
    up to ~13% above the analytic estimate (XLA temp buffers the model
    ignores — see the est-vs-measured table in docs/PERFORMANCE.md), so a
    nominal analytic fit near capacity cannot be trusted. But an analytic
    REJECTION near capacity cannot be trusted either: at 16K the cheapest
    policy that actually fits ("none", measured buffer-assignment peak
    15.53e9 of 17.18e9 bytes) is 26% faster than "full", and the analytic
    margin alone would forfeit that. So when ``aot_probe`` is provided
    (a callable (remat_policy) -> Optional[peak_bytes] — the harness wires
    train.step.abstract_step_peak_bytes), policies in the ambiguous band
    (analytic margin rejects, estimate still <= nominal capacity) are
    decided by an abstract AOT compile of the real step: accept iff XLA's
    measured buffer-assignment peak fits AOT_PROBE_ACCEPT_MARGIN. Costs one
    extra XLA compile per probed policy, only ever near capacity.
    """
    import dataclasses as _dc

    if getattr(strategy, "remat", None) != "auto":
        return strategy
    cap = device_hbm_bytes(device_kind)
    for pol in ("none", "dots", "full"):
        cand = _dc.replace(strategy, remat=pol)
        cfg = _dc.replace(model_config, remat=pol)
        est = estimate_hbm(
            cfg, cand, mesh, per_device_batch, seq_len, dataset_size=dataset_size
        )
        if check_fits(est, device_kind, margin=AUTO_REMAT_MARGIN) is None:
            return cand
        # Probe band capped at the downstream pre-flight's own margin
        # (0.95): a probe-accepted policy must also pass check_fits in the
        # benchmark loop, or the resolver would hand back an arm the
        # pre-flight immediately refuses (where escalating would have run).
        if (
            aot_probe is not None and cap is not None
            and check_fits(est, device_kind) is None
        ):
            peak = aot_probe(pol)
            if peak is not None and peak <= cap * AOT_PROBE_ACCEPT_MARGIN:
                return cand
    # Nothing fits; return the most memory-frugal policy and let the
    # pre-flight check downstream produce the refusal message.
    return _dc.replace(strategy, remat="full")
