"""Benchmark metrics + the result.json / stdout-marker export protocol.

This is the reference's core subsystem (SURVEY §5.5), reproduced
contract-for-contract so downstream tooling (collect scripts, parsers,
plotters) works unchanged against TPU pod logs:

- result schema: identical keys to reference ``train_harness.py:415-429`` /
  ``results/example_output/README.md:26-41`` (``peak_vram_gb`` keeps its name
  for schema compatibility — on TPU it reports peak HBM bytes in use), plus
  additive TPU fields (``peak_hbm_gb``, ``device_kind``, ``backend``,
  ``n_params``) that no reference consumer needs to read;
- file name: ``result_{strategy}_ws{N}_seq{L}_tier{T}.json``
  (reference ``train_harness.py:443-446``);
- stdout markers: ``BENCHMARK_RESULT_JSON_START`` / ``_END`` delimit the JSON
  on stdout (reference ``train_harness.py:452-456``) — the load-bearing export
  channel, because pod filesystems are ephemeral and results get scraped from
  ``kubectl logs`` (reference ``scripts/collect_results.sh:50-52``).

Metric formulas (parity, reference ``train_harness.py:399-413``):
- ``tokens_per_sec = tokens_per_step / mean_step_time`` — with the one honest
  correction that ``tokens_per_step`` includes ``grad_accum``, because our
  accumulation is real (the reference's is inert for DDP/FSDP yet it still
  reports per-microbatch tokens);
- ``h2d_gbps_per_gpu = batch*seq*4 bytes / step_time / 1e9`` — the reference's
  admitted FP32-equivalent transfer proxy, kept for comparability;
- warmup steps are excluded from timing (reference ``train_harness.py:388-390``).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional

MARKER_START = "BENCHMARK_RESULT_JSON_START"
MARKER_END = "BENCHMARK_RESULT_JSON_END"


def arm_slug(
    strategy: str, world_size: int, seq_len: int, tier: str,
    model_family: str = "tinygpt",
) -> str:
    """The run's artifact stem: ``result_<slug>.json`` pairs with
    ``telemetry_<slug>.jsonl`` (the flight recorder's file), and
    validate_results cross-checks them purely by this slug — so there is
    exactly one place that builds it. Non-default families suffix the
    name; the tinygpt form stays bit-compatible with the reference scheme
    (train_harness.py:443-446)."""
    fam = "" if model_family == "tinygpt" else f"_{model_family}"
    return f"{strategy}_ws{world_size}_seq{seq_len}_tier{tier}{fam}"


def tokens_per_step(
    per_device_batch: int, grad_accum: int, seq_len: int, dp: int,
    expert_parallel: int = 1,
) -> int:
    """Global tokens one optimizer step consumes (see compute_result's
    honest-accounting note) — shared with the telemetry recorder so
    heartbeat tokens/sec can never drift from the published formula."""
    return per_device_batch * grad_accum * seq_len * dp * expert_parallel


def peak_hbm_bytes() -> Optional[int]:
    """Peak device-memory bytes in use, or None when the backend can't say.

    TPU runtimes expose ``memory_stats()['peak_bytes_in_use']`` per device
    (the HBM analogue of ``torch.cuda.max_memory_allocated``, reference
    ``train_harness.py:406-408``); CPU backends typically return None.
    """
    import jax

    peaks = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and "peak_bytes_in_use" in stats:
            peaks.append(int(stats["peak_bytes_in_use"]))
    return max(peaks) if peaks else None


def hbm_bytes_in_use() -> Optional[int]:
    """Current device-memory bytes in use, or None when the backend
    can't say — the live sibling of :func:`peak_hbm_bytes`, sampled per
    sync window by the flight recorder so the HBM high-water timeline is
    reconstructible from telemetry alone (docs/OBSERVABILITY.md memory
    anatomy)."""
    import jax

    vals = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_in_use" in stats:
            vals.append(int(stats["bytes_in_use"]))
    return max(vals) if vals else None


def buffer_assignment_peak_bytes(ma) -> int:
    """XLA's buffer-assignment peak from a ``memory_analysis()`` result.

    Current jaxlib exposes ``peak_memory_in_bytes`` directly; older
    ``CompiledMemoryStats`` (pre-0.4.38) only carries the component sizes,
    whose sum (arguments + outputs + temporaries, donation-aliased bytes
    counted once) is the same buffer-assignment quantity. Returns 0 when
    neither form is available.
    """
    peak = int(getattr(ma, "peak_memory_in_bytes", 0) or 0)
    if peak > 0:
        return peak
    try:
        parts = (
            int(getattr(ma, "argument_size_in_bytes", 0) or 0)
            + int(getattr(ma, "output_size_in_bytes", 0) or 0)
            + int(getattr(ma, "temp_size_in_bytes", 0) or 0)
            - int(getattr(ma, "alias_size_in_bytes", 0) or 0)
        )
        return max(parts, 0)
    except Exception:
        return 0


def measure_peak_hbm(
    compiled_step=None, host_offload: bool = False,
    prior_peak_bytes: Optional[int] = None,
) -> tuple[float, str]:
    """Measured per-device peak memory in GB, with provenance.

    Fallback chain (first rung that yields a number wins):

    1. ``allocator`` — per-device ``memory_stats()['peak_bytes_in_use']``,
       the runtime allocator's true high-water mark (reference parity:
       ``torch.cuda.max_memory_allocated``, ``train_harness.py:406-408``).
       Works on standard Cloud TPU runtimes; returns None on some PJRT
       plugins (and on CPU). The high-water mark is PROCESS-lifetime and
       has no reset API, so when several arms run in one process (bench.py
       measures parity then flagship) a later arm would silently inherit
       an earlier, larger arm's peak: callers pass ``prior_peak_bytes``
       (the mark observed before their run) and this rung only claims the
       number when the run actually raised it; otherwise the chain falls
       through to the per-executable rung 2.
    2. ``xla_buffer_assignment`` — ``compiled_step.memory_analysis()``
       ``.peak_memory_in_bytes``: the XLA compiler's buffer-assignment peak
       for the train-step executable (arguments + outputs + temporaries,
       donation-aliased). This is what the device allocator actually
       reserves to run the step, i.e. a *measured* property of the compiled
       program, not an analytic estimate. ``jax.profiler
       .device_memory_profile()`` would be the natural rung here, but on
       PJRT C-API runtimes that don't implement
       ``PJRT_Executable_SizeOfGeneratedCodeInBytes`` it aborts the whole
       process with an uncatchable CHECK failure (see
       docs/TROUBLESHOOTING.md), so it is deliberately excluded.
    3. ``live_arrays`` — sum of bytes of all live ``jax.Array``s on the
       largest-resident device: a floor (params + opt state + dataset, no
       in-step temporaries). Reported so the column is never silently zero.
    4. ``unavailable`` — 0.0.

    Returns (peak_gb, method).
    """
    peak = peak_hbm_bytes()
    if peak and (prior_peak_bytes is None or peak > prior_peak_bytes):
        return peak / 1e9, "allocator"
    if compiled_step is not None:
        try:
            ma = compiled_step.memory_analysis()
            peak_bytes = buffer_assignment_peak_bytes(ma)
            # Host-offload arms only (``host_offload``): the
            # buffer-assignment peak sums ALL memory spaces, so pinned-host
            # buffers (fp32 masters + Adam moments) would masquerade as
            # HBM. Report the device space only — and only when the
            # subtraction leaves a device-plausible remainder, so an XLA
            # version whose peak already excludes host space can't be
            # clamped to a bogus ~0 under an authoritative-sounding tag.
            # (Host outputs alias the donated host arguments, so only
            # arguments + temps are subtracted — outputs would
            # double-count.)
            host_bytes = sum(
                int(getattr(ma, f, 0) or 0)
                for f in (
                    "host_argument_size_in_bytes",
                    "host_temp_size_in_bytes",
                )
            )
            # The remainder must still be device-plausible: at minimum the
            # device-resident arguments (compute params, dataset, grads)
            # live in HBM at peak. If an XLA version's peak already
            # excludes host space, peak - host falls BELOW that floor and
            # we fall through to the raw value instead of underreporting.
            dev_arg_floor = max(
                0,
                int(getattr(ma, "argument_size_in_bytes", 0) or 0)
                - int(getattr(ma, "host_argument_size_in_bytes", 0) or 0),
            )
            if (
                host_offload
                and peak_bytes > 0
                and 0 < host_bytes < peak_bytes
                and peak_bytes - host_bytes >= dev_arg_floor
            ):
                return (
                    (peak_bytes - host_bytes) / 1e9,
                    "xla_buffer_assignment_minus_host",
                )
            if peak_bytes > 0:
                return peak_bytes / 1e9, "xla_buffer_assignment"
        except Exception:
            pass
    try:
        import jax

        per_device: Dict[Any, int] = {}
        for a in jax.live_arrays():
            for shard in a.addressable_shards:
                per_device[shard.device] = per_device.get(shard.device, 0) + int(
                    shard.data.nbytes
                )
        if per_device:
            return max(per_device.values()) / 1e9, "live_arrays"
    except Exception:
        pass
    return 0.0, "unavailable"


@dataclasses.dataclass
class BenchmarkResult:
    strategy: str
    world_size: int
    rank: int
    seq_len: int
    tier: str
    steps: int
    per_device_batch: int
    grad_accum: int
    tokens_per_sec: float
    mean_step_time_sec: float
    mean_loss: float
    peak_vram_gb: float  # schema-compat name; peak HBM GB on TPU
    h2d_gbps_per_gpu: float
    # --- additive TPU-native fields (ignored by reference-era consumers) ---
    peak_hbm_gb: float = 0.0
    # Provenance of peak_hbm_gb — see measure_peak_hbm():
    # allocator | xla_buffer_assignment | live_arrays | unavailable
    peak_hbm_method: str = "unavailable"
    # Pre-flight analytic estimate (utils.memory), published alongside the
    # measurement so the model's accuracy is auditable (docs/PERFORMANCE.md).
    est_hbm_gb: float = 0.0
    device_kind: str = ""
    backend: str = ""
    n_params: int = 0
    attention_impl: str = "reference"
    dropout: float = 0.0
    # Analytic model-FLOPs accounting (utils.flops); the reference has no
    # FLOPs metric at all (train_harness.py:399-413 is its whole surface).
    flops_per_token: float = 0.0
    model_tflops_per_sec_per_chip: float = 0.0
    mfu_pct: float = 0.0  # 0.0 when the device kind's peak is unknown (CPU)
    # Cost efficiency at public on-demand $/chip-hr (utils.flops price table);
    # 0.0 for unknown device kinds. Reference parity: README.md:270-276.
    usd_per_chip_hour: float = 0.0
    tokens_per_dollar: float = 0.0
    # Per-step wall-time distribution over the timed (post-warmup) steps.
    # Individually meaningful when sync_every == 1 (each step fenced, the
    # reference's per-step loss.item() discipline); with sync_every > 1 each
    # step carries its window's mean, so the spread understates true variance
    # — consumers must check sync_every before using these.
    sync_every: int = 1
    step_time_p50_sec: float = 0.0
    step_time_p95_sec: float = 0.0
    step_time_max_sec: float = 0.0
    step_time_cv_pct: float = 0.0  # stddev / mean * 100
    tensor_parallel: int = 1
    sequence_parallel: int = 1
    pipeline_parallel: int = 1
    pipeline_schedule: str = "gpipe"  # meaningful when pipeline_parallel > 1
    virtual_stages: int = 1  # interleaved schedule: layer chunks per stage
    expert_parallel: int = 1
    n_experts: int = 0
    # The remat policy the run actually executed with ("none"/"dots"/"full")
    # — provenance for strategies whose "auto" resolves per-geometry.
    remat_policy: str = "none"
    # Parameter storage dtype ('f32'/'bf16') and host optimizer offload —
    # run identity for arms sharing (strategy, tier, seq) geometry.
    param_dtype: str = "f32"
    offload_opt_state: bool = False
    # Delayed (one-step-stale) host update — changes training semantics,
    # so it is run identity (an overlapped arm is not the serial arm).
    offload_delayed_update: bool = False
    # First delayed step when the serial->delayed transition knob is used
    # (0 = delayed from the start); also run identity.
    offload_dpu_start_step: int = 0
    # Causal (autoregressive) masking — False is reference parity
    # (train_harness.py:127 applies no mask); True halves attention FLOPs
    # and, on causal rings, turns on the zigzag load-balanced layout.
    causal: bool = False
    # Ring-attention zigzag layout mode ('auto'/'on'/'off') — run identity
    # for the scaling-day zigzag A/B arms, which differ in nothing else.
    ring_zigzag: str = "auto"
    # Collective-matmul tp fusion (round 15, ops/collective_matmul.py) —
    # run identity: the ppermute-ring projection schedule is a different
    # measurement than the plain tp lowering, so cmm and non-cmm runs
    # must never cross-gate (store.config_key includes this field,
    # mirroring xla_scheduler_flags).
    tp_collective_matmul: bool = False
    # MoE runs: measured fraction (%) of (token, choice) expert assignments
    # dropped by the capacity limit on the trained params (models.tinygpt
    # .moe_overflow_fraction diagnostic); None for dense runs or when the
    # diagnostic could not run under the run's sharding.
    expert_overflow_pct: Optional[float] = None
    # Model family ('tinygpt' = reference parity architecture; 'llama' =
    # the RMSNorm/RoPE/SwiGLU/GQA family, models.llama) — run identity: a
    # llama tier-A row is a different model than a tinygpt tier-A row.
    model_family: str = "tinygpt"
    # Loss-descent endpoints: means of the first/last ``loss_window_steps``
    # timed (post-warmup) per-step losses. mean_loss alone cannot distinguish
    # a training run from a frozen one (a flat line and a descent can share a
    # mean); the validator's descent envelope
    # (analysis.validate_results) compares these. 0.0 when no losses.
    loss_first_window: float = 0.0
    loss_last_window: float = 0.0
    loss_window_steps: int = 0
    # True when the run restored a checkpoint and continued (--resume): its
    # loss starts wherever the checkpoint left off, so the from-scratch
    # descent envelope does not apply.
    resumed: bool = False
    # Honest stitched-run accounting (chaos round, docs/FAULT_TOLERANCE.md):
    # how many times this arm resumed (the checkpoint dir's restart
    # ledger), which step it restored, and the loss recorded at that
    # checkpoint's save boundary. validate_results checks pre/post loss
    # continuity across the stitch, and the regress registry refuses
    # resumed rows as baselines — a stitched run must never pollute the
    # noise floor or pose as a clean measurement. All defaults for
    # non-resumed runs and pre-chaos artifacts.
    n_restarts: int = 0
    resume_step: int = -1
    resume_baseline_loss: float = 0.0
    # Numerics-sentinel accounting (self-healing round, docs/
    # FAULT_TOLERANCE.md): how many times the run rolled back in-process
    # to its last validated checkpoint after a sentinel trip (NaN/loss
    # envelope/grad explosion/parameter-checksum SDC), and how many steps
    # those rollbacks replayed. Replayed steps are EXCLUDED from the
    # timed step-time distribution (their windows fold the restore);
    # validate_results checks the two fields cohere, and the regress
    # registry keeps rolled-back rows out of the baseline set exactly
    # like resumed/partial ones — a healed run is an honest record but
    # not a clean measurement.
    n_rollbacks: int = 0
    rollback_steps_replayed: int = 0
    # True when the resume crossed a mesh-geometry change (elastic resume:
    # the checkpoint was saved under a different dp/tp/sp/pp/ep mesh and
    # was reshard-restored against this run's PartitionSpecs). Implies
    # resumed=true (validate_results enforces the coherence); such rows
    # join plain resumed rows in the regress never-baseline set.
    resume_geometry_changed: bool = False
    # --- flight-recorder phase attribution (telemetry.TelemetryRecorder,
    # round 8) — where the run's wall time actually went. Measured from
    # recorder start to result computation; the run's telemetry JSONL
    # (telemetry_<arm>.jsonl, run_end event) carries the final total
    # including emission itself. The phase fields are disjoint by
    # construction, so their sum never exceeds wall_time_total_sec
    # (validate_results enforces it). All 0.0 for pre-round-8 artifacts.
    wall_time_total_sec: float = 0.0
    time_in_init_sec: float = 0.0
    time_in_compile_sec: float = 0.0
    time_in_warmup_sec: float = 0.0
    time_in_timed_sec: float = 0.0
    time_in_checkpoint_sec: float = 0.0
    time_in_trace_sec: float = 0.0
    # Count of anomaly events (NaN loss, step-time spikes) the recorder
    # screened over the run's sync windows; validate_results rejects rows
    # whose telemetry shows them unresolved.
    n_anomalies: int = 0
    # --- step-anatomy attribution (analysis/step_anatomy.py) — the
    # trace-derived decomposition of the timed device steps, published
    # only when the run captured a --profile-dir trace (None otherwise /
    # for pre-anatomy artifacts). The three step components are additive:
    # anatomy_compute_frac + comms_exposed_frac + anatomy_idle_frac == 1
    # (overlapped collective time is accounted inside compute;
    # comms_overlap_frac reports it as a fraction OF collective time).
    # comms_exposed_frac is a first-class secondary metric in the regress
    # gate (stats.SECONDARY_METRICS); validate_results envelopes all of
    # them (fractions in [0,1], components summing to <= 1).
    anatomy_compute_frac: Optional[float] = None
    comms_exposed_frac: Optional[float] = None
    comms_overlap_frac: Optional[float] = None
    anatomy_idle_frac: Optional[float] = None
    # Pipeline arms only: the device-idle fraction inside the step IS the
    # schedule's bubble (ROADMAP direction 3's per-schedule metric).
    bubble_frac: Optional[float] = None
    # Roofline position: achieved vs peak FLOP/s and HBM GB/s (peaks from
    # utils/platform.py; achieved from the jitted step's cost_analysis()
    # over the traced median step). None on unknown device kinds (CPU).
    roofline_flops_pct_of_peak: Optional[float] = None
    roofline_hbm_pct_of_peak: Optional[float] = None
    # Across rank-sibling traces / device lanes: how far the slowest
    # lane's median step sits above the fastest's (percent).
    straggler_skew_pct: Optional[float] = None
    # Scheduling-relevant XLA_FLAGS subset (utils.platform
    # .scheduler_flags_fingerprint) — "" for the default lineage. Run
    # identity: the latency-hiding scheduler changes the collective
    # schedule, so flagged and unflagged runs must never cross-gate in the
    # regress registry (store.config_key includes this field).
    xla_scheduler_flags: str = ""
    # --- memory-anatomy reconciliation (analysis/memory_anatomy.py) —
    # the per-chip HBM peak, attributed. ``hbm_estimate`` persists the
    # pre-flight analytic breakdown (utils.memory.HBMEstimate.breakdown,
    # GiB keys — previously print-only); ``hbm_measured`` is the
    # allocator's peak in GiB or None-with-reason when the backend lacks
    # memory_stats(); ``hbm_attribution`` splits the reference peak
    # (source in ``hbm_attribution_source``, total in
    # ``hbm_reference_gib``) across params/grads/opt_state/activations/
    # dataset/xla_temp plus a SIGNED unattributed residual that closes
    # the books exactly. ``hbm_model_drift_frac`` — |reference −
    # analytic| / analytic — is a gated secondary metric
    # (regress.stats.SECONDARY_METRICS): the estimator's ±20% disclaimer
    # as a tested invariant. All None for pre-memory-anatomy artifacts.
    hbm_estimate: Optional[Dict[str, float]] = None
    hbm_measured: Optional[float] = None
    hbm_measured_reason: str = ""
    hbm_attribution: Optional[Dict[str, float]] = None
    hbm_attribution_source: str = ""
    hbm_reference_gib: Optional[float] = None
    hbm_model_drift_frac: Optional[float] = None
    # --- streaming-data-path accounting (data/stream.py +
    # data/prefetch.py, docs/FAULT_TOLERANCE.md) — run identity plus the
    # input-path honesty ledger. ``data_mode`` is 'synthetic' (the
    # default zero-IO table; all fields below stay at their inert
    # defaults) or 'stream' (--data-path). ``data_stall_frac`` — fraction
    # of timed step wall spent starved for input — is a gated secondary
    # metric (regress.stats.SECONDARY_METRICS, abs-pp, lower-better) so
    # an input-bound regression fails `regress gate --all` by name.
    # ``records_skipped`` counts corrupt records healed by substitution
    # (one quarantine-ledger entry + data_corrupt_record telemetry event
    # each; validate_results cross-checks the counts). The cursor pair
    # makes resume stream-position continuity closed-form: cursor_end -
    # cursor_start == records_consumed == steps_run x records/step, and a
    # same-geometry resume must start exactly where the checkpoint's
    # sidecar left off (no replayed or skipped records across a stitch).
    data_mode: str = "synthetic"
    data_stall_frac: Optional[float] = None
    data_stall_sec: float = 0.0
    records_consumed: int = 0
    records_skipped: int = 0
    stream_cursor_start: int = -1
    stream_cursor_end: int = -1

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def result_filename(self) -> str:
        return "result_" + arm_slug(
            self.strategy, self.world_size, self.seq_len, self.tier,
            self.model_family,
        ) + ".json"


def compute_result(
    *,
    strategy: str,
    world_size: int,
    rank: int,
    seq_len: int,
    tier: str,
    steps: int,
    per_device_batch: int,
    grad_accum: int,
    step_times: List[float],
    losses: List[float],
    device_kind: str = "",
    backend: str = "",
    n_params: int = 0,
    attention_impl: str = "reference",
    dropout: float = 0.0,
    flops_per_token: float = 0.0,
    est_hbm_gb: float = 0.0,
    compiled_step=None,
    sync_every: int = 1,
    tensor_parallel: int = 1,
    sequence_parallel: int = 1,
    pipeline_parallel: int = 1,
    pipeline_schedule: str = "gpipe",
    virtual_stages: int = 1,
    expert_parallel: int = 1,
    n_experts: int = 0,
    remat_policy: str = "none",
    param_dtype: str = "f32",
    offload_opt_state: bool = False,
    offload_delayed_update: bool = False,
    offload_dpu_start_step: int = 0,
    causal: bool = False,
    ring_zigzag: str = "auto",
    tp_collective_matmul: bool = False,
    expert_overflow_pct: Optional[float] = None,
    model_family: str = "tinygpt",
    resumed: bool = False,
    n_restarts: int = 0,
    resume_step: int = -1,
    resume_baseline_loss: float = 0.0,
    resume_geometry_changed: bool = False,
    n_rollbacks: int = 0,
    rollback_steps_replayed: int = 0,
    prior_peak_bytes: Optional[int] = None,
    wall_time_total_sec: float = 0.0,
    phase_times: Optional[Dict[str, float]] = None,
    n_anomalies: int = 0,
    step_anatomy: Optional[Dict[str, Any]] = None,
    memory_anatomy: Optional[Dict[str, Any]] = None,
    data_mode: str = "synthetic",
    data_stall_frac: Optional[float] = None,
    data_stall_sec: float = 0.0,
    records_consumed: int = 0,
    records_skipped: int = 0,
    stream_cursor_start: int = -1,
    stream_cursor_end: int = -1,
) -> BenchmarkResult:
    def _scheduler_flags() -> str:
        from . import platform as platform_mod

        return platform_mod.scheduler_flags_fingerprint()

    mean_step = sum(step_times) / len(step_times) if step_times else 0.0
    mean_loss = sum(losses) / len(losses) if losses else 0.0
    # Descent endpoints: window of up to 10 steps, at most a fifth of the
    # timed run each so the two windows never overlap at benchmark lengths.
    if losses:
        lw = max(1, min(10, len(losses) // 5))
        loss_first = sum(losses[:lw]) / lw
        loss_last = sum(losses[-lw:]) / lw
    else:
        lw, loss_first, loss_last = 0, 0.0, 0.0
    # Honest accounting: a step consumes per_device_batch * grad_accum
    # sequences per *data-parallel replica* (our accumulation is real, and
    # tensor/sequence-parallel groups jointly compute one example rather than
    # multiplying throughput; see module docstring). With tp=sp=1 this is the
    # reference's formula (train_harness.py:403). Expert-parallel groups DO
    # multiply throughput: the batch is sharded over ('data', 'expert')
    # (strategies.batch_partition_spec), so each expert-axis member consumes
    # its own per_device_batch sequences.
    dp = world_size // (
        tensor_parallel * sequence_parallel * pipeline_parallel * expert_parallel
    )
    step_tokens = tokens_per_step(
        per_device_batch, grad_accum, seq_len, dp, expert_parallel
    )
    tps = step_tokens / mean_step if mean_step > 0 else 0.0
    bytes_per_step = per_device_batch * grad_accum * seq_len * 4
    h2d = (bytes_per_step / mean_step) / 1e9 if mean_step > 0 else 0.0
    peak_gb, peak_method = measure_peak_hbm(
        compiled_step, host_offload=offload_opt_state,
        prior_peak_bytes=prior_peak_bytes,
    )
    from . import flops as flops_mod

    tps_per_chip = tps / world_size if world_size else 0.0
    tflops_per_chip = flops_mod.achieved_tflops_per_sec(tps_per_chip, flops_per_token)
    mfu = flops_mod.mfu_pct(tps_per_chip, flops_per_token, device_kind)
    price = flops_mod.device_usd_per_chip_hour(device_kind)
    tok_per_usd = flops_mod.tokens_per_dollar(tps_per_chip, device_kind)
    if step_times:
        ts = sorted(step_times)
        n = len(ts)
        p50 = ts[n // 2]
        p95 = ts[min(n - 1, int(0.95 * (n - 1) + 0.5))]
        t_max = ts[-1]
        var = sum((t - mean_step) ** 2 for t in step_times) / n
        cv = 100.0 * var**0.5 / mean_step if mean_step > 0 else 0.0
    else:
        p50 = p95 = t_max = cv = 0.0
    pt = phase_times or {}
    # Step-anatomy fields (analysis.step_anatomy.result_fields keys):
    # unknown keys are refused rather than silently dropped — the engine
    # and the result schema must not drift apart.
    anatomy = dict(step_anatomy or {})
    anatomy_fields = {
        k: anatomy.pop(k, None) for k in (
            "anatomy_compute_frac", "comms_exposed_frac",
            "comms_overlap_frac", "anatomy_idle_frac", "bubble_frac",
            "roofline_flops_pct_of_peak", "roofline_hbm_pct_of_peak",
            "straggler_skew_pct",
        )
    }
    if anatomy:
        raise ValueError(
            f"unknown step_anatomy keys {sorted(anatomy)} (the engine's "
            "result_fields and BenchmarkResult must agree)"
        )
    # Memory-anatomy fields (analysis.memory_anatomy.result_fields keys):
    # same refusal contract as step_anatomy — the engine and the result
    # schema must not drift apart.
    mem = dict(memory_anatomy or {})
    mem_fields = {
        k: mem.pop(k, None if k not in (
            "hbm_measured_reason", "hbm_attribution_source",
        ) else "") for k in (
            "hbm_estimate", "hbm_measured", "hbm_measured_reason",
            "hbm_attribution", "hbm_attribution_source",
            "hbm_reference_gib", "hbm_model_drift_frac",
        )
    }
    if mem_fields["hbm_measured_reason"] is None:
        mem_fields["hbm_measured_reason"] = ""
    if mem_fields["hbm_attribution_source"] is None:
        mem_fields["hbm_attribution_source"] = ""
    if mem:
        raise ValueError(
            f"unknown memory_anatomy keys {sorted(mem)} (the engine's "
            "result_fields and BenchmarkResult must agree)"
        )
    return BenchmarkResult(
        strategy=strategy,
        world_size=world_size,
        rank=rank,
        seq_len=seq_len,
        tier=tier,
        steps=steps,
        per_device_batch=per_device_batch,
        grad_accum=grad_accum,
        tokens_per_sec=tps,
        mean_step_time_sec=mean_step,
        mean_loss=mean_loss,
        peak_vram_gb=peak_gb,
        h2d_gbps_per_gpu=h2d,
        peak_hbm_gb=peak_gb,
        peak_hbm_method=peak_method,
        est_hbm_gb=est_hbm_gb,
        device_kind=device_kind,
        backend=backend,
        n_params=n_params,
        attention_impl=attention_impl,
        dropout=dropout,
        flops_per_token=flops_per_token,
        model_tflops_per_sec_per_chip=tflops_per_chip,
        mfu_pct=mfu if mfu is not None else 0.0,
        usd_per_chip_hour=price if price is not None else 0.0,
        tokens_per_dollar=tok_per_usd if tok_per_usd is not None else 0.0,
        sync_every=sync_every,
        step_time_p50_sec=p50,
        step_time_p95_sec=p95,
        step_time_max_sec=t_max,
        step_time_cv_pct=cv,
        tensor_parallel=tensor_parallel,
        sequence_parallel=sequence_parallel,
        pipeline_parallel=pipeline_parallel,
        pipeline_schedule=pipeline_schedule,
        virtual_stages=virtual_stages,
        expert_parallel=expert_parallel,
        n_experts=n_experts,
        remat_policy=remat_policy,
        param_dtype=param_dtype,
        offload_opt_state=offload_opt_state,
        offload_delayed_update=offload_delayed_update,
        offload_dpu_start_step=offload_dpu_start_step,
        causal=causal,
        ring_zigzag=ring_zigzag,
        tp_collective_matmul=tp_collective_matmul,
        expert_overflow_pct=expert_overflow_pct,
        model_family=model_family,
        loss_first_window=loss_first,
        loss_last_window=loss_last,
        loss_window_steps=lw,
        resumed=resumed,
        n_restarts=n_restarts,
        resume_step=resume_step,
        resume_baseline_loss=round(resume_baseline_loss, 6),
        resume_geometry_changed=resume_geometry_changed,
        n_rollbacks=n_rollbacks,
        rollback_steps_replayed=rollback_steps_replayed,
        wall_time_total_sec=round(wall_time_total_sec, 4),
        time_in_init_sec=round(pt.get("init", 0.0), 4),
        time_in_compile_sec=round(pt.get("compile", 0.0), 4),
        time_in_warmup_sec=round(pt.get("warmup", 0.0), 4),
        time_in_timed_sec=round(pt.get("timed", 0.0), 4),
        time_in_checkpoint_sec=round(pt.get("checkpoint", 0.0), 4),
        time_in_trace_sec=round(pt.get("trace", 0.0), 4),
        n_anomalies=n_anomalies,
        xla_scheduler_flags=_scheduler_flags(),
        data_mode=data_mode,
        data_stall_frac=data_stall_frac,
        data_stall_sec=data_stall_sec,
        records_consumed=records_consumed,
        records_skipped=records_skipped,
        stream_cursor_start=stream_cursor_start,
        stream_cursor_end=stream_cursor_end,
        **anatomy_fields,
        **mem_fields,
    )


def emit_result(result: BenchmarkResult, results_dir: str, is_main: bool = True) -> Optional[str]:
    """Write result.json + print the marker-delimited JSON block (rank 0 only).

    Console format parity: reference ``train_harness.py:431-456``.
    """
    if not is_main:
        return None
    payload = json.dumps(result.to_dict(), indent=2)

    print("\n" + "=" * 80)
    print("Benchmark Results:")
    print(f"  Tokens/sec:       {result.tokens_per_sec:,.0f}")
    if result.mfu_pct > 0:
        print(
            f"  Model TFLOP/s/chip: {result.model_tflops_per_sec_per_chip:.1f}"
            f"  (MFU {result.mfu_pct:.1f}%)"
        )
    print(f"  Mean step time:   {result.mean_step_time_sec:.4f}s")
    if result.sync_every == 1 and result.step_time_p95_sec > 0:
        print(
            f"  Step time p50/p95/max: {result.step_time_p50_sec:.4f}s /"
            f" {result.step_time_p95_sec:.4f}s / {result.step_time_max_sec:.4f}s"
            f"  (cv {result.step_time_cv_pct:.1f}%)"
        )
    if result.tokens_per_dollar > 0:
        print(
            f"  Tokens/$:         {result.tokens_per_dollar:,.0f}"
            f"  (at ${result.usd_per_chip_hour:.2f}/chip-hr on-demand)"
        )
    print(
        f"  Peak HBM/chip:    {result.peak_hbm_gb:.2f} GB"
        f" ({result.peak_hbm_method})"
    )
    if result.hbm_attribution is not None:
        attr = result.hbm_attribution
        measured = (
            f"{result.hbm_measured:.2f} GiB measured"
            if result.hbm_measured is not None
            else f"measured n/a ({result.hbm_measured_reason})"
        )
        drift = (
            f", model drift {100.0 * result.hbm_model_drift_frac:.1f}%"
            if result.hbm_model_drift_frac is not None else ""
        )
        print(
            f"  HBM anatomy:      {measured}; "
            f"{result.hbm_attribution_source} peak "
            f"{result.hbm_reference_gib or 0:.2f} GiB = params "
            f"{attr.get('params', 0):.2f} + grads {attr.get('grads', 0):.2f}"
            f" + opt {attr.get('opt_state', 0):.2f} + act "
            f"{attr.get('activations', 0):.2f} + data "
            f"{attr.get('dataset', 0):.2f} + xla-temp "
            f"{attr.get('xla_temp', 0):.2f} "
            f"{attr.get('unattributed', 0):+.2f} residual{drift}"
        )
    print(f"  H2D GB/s/chip:    {result.h2d_gbps_per_gpu:.3f}")
    print(f"  Mean loss:        {result.mean_loss:.4f}")
    if result.data_mode == "stream":
        print(
            f"  Data path:        stream — stall "
            f"{100.0 * (result.data_stall_frac or 0.0):.1f}% of timed wall "
            f"({result.data_stall_sec:.2f}s), {result.records_consumed} "
            f"records consumed (cursor {result.stream_cursor_start} -> "
            f"{result.stream_cursor_end}), {result.records_skipped} "
            "skipped/quarantined"
        )
    if result.wall_time_total_sec > 0:
        print(
            f"  Wall time:        {result.wall_time_total_sec:.1f}s"
            f"  (compile {result.time_in_compile_sec:.1f}s,"
            f" warmup {result.time_in_warmup_sec:.1f}s,"
            f" timed {result.time_in_timed_sec:.1f}s,"
            f" checkpoint {result.time_in_checkpoint_sec:.1f}s)"
        )
    if result.comms_exposed_frac is not None:
        anatomy = (
            f"  Step anatomy:     compute "
            f"{100.0 * (result.anatomy_compute_frac or 0):.1f}% / exposed "
            f"comms {100.0 * result.comms_exposed_frac:.1f}% / idle "
            f"{100.0 * (result.anatomy_idle_frac or 0):.1f}%"
        )
        if result.comms_overlap_frac is not None:
            anatomy += (f"  (overlap {100.0 * result.comms_overlap_frac:.1f}%"
                        " of collective time)")
        if result.bubble_frac is not None:
            anatomy += f"  bubble {100.0 * result.bubble_frac:.1f}%"
        print(anatomy)
    if result.n_anomalies > 0:
        print(f"  ANOMALIES:        {result.n_anomalies} (see telemetry JSONL)")
    if result.resumed:
        stitch = (
            ", geometry changed" if result.resume_geometry_changed else ""
        )
        print(
            f"  RESUMED:          from step {result.resume_step} "
            f"(restart #{result.n_restarts}{stitch}) — stitched run, "
            "never a regression baseline"
        )
    if result.n_rollbacks > 0:
        print(
            f"  ROLLBACKS:        {result.n_rollbacks} sentinel "
            f"rollback(s), {result.rollback_steps_replayed} step(s) "
            "replayed — healed run, never a regression baseline"
        )
    print("=" * 80 + "\n")

    os.makedirs(results_dir, exist_ok=True)
    path = os.path.join(results_dir, result.result_filename())
    with open(path, "w") as f:
        f.write(payload)
    print(f"Results saved to: {path}")

    print("\n" + "=" * 80)
    print(MARKER_START)
    print(payload)
    print(MARKER_END)
    print("=" * 80 + "\n")
    return path
