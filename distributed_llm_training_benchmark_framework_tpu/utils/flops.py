"""Analytic model-FLOPs accounting and MFU (model FLOPs utilization).

The reference's metric surface stops at tokens/sec and a transfer proxy
(reference ``benchmarking/train_harness.py:399-413``) — it never relates
throughput to what the silicon could do. We add the standard accounting:

- ``train_flops_per_token(config)``: analytic fwd+bwd FLOPs per token for the
  TinyGPT architecture (matmul-dominated terms only, the PaLM/Chinchilla
  convention). Backward is counted as 2x forward; rematerialized recompute is
  deliberately NOT counted — MFU measures useful model FLOPs, so remat shows
  up as lower MFU, not higher FLOPs.
- ``device_peak_tflops(device_kind)``: bf16 peak per chip for known TPU
  generations (public spec-sheet numbers).
- MFU = achieved model TFLOP/s/chip ÷ peak TFLOP/s/chip.

Counting detail (per token, forward):
- per layer: QKV projection ``2*D*3D``, attention output projection ``2*D*D``,
  MLP ``2*(D*4D + 4D*D)`` → ``24*D^2`` total matmul FLOPs;
- attention itself: ``QK^T`` is S MACs per head-dim per key → ``2*S*D``, and
  ``probs @ V`` another ``2*S*D`` → ``4*S*D`` per layer;
- LM head (weight-tied, counted once): ``2*D*V``;
- MoE variant: the MLP term runs ``top_k`` experts per token plus a
  ``2*D*E`` router.

Training multiplies forward by 3 (bwd ≈ 2x fwd for matmuls).
"""

from __future__ import annotations

from typing import Optional

# bf16 peak TFLOP/s per chip, public spec numbers. Matched by substring
# against jax's Device.device_kind (e.g. "TPU v5 lite", "TPU v4").
# Order matters: more specific names first ("v5 lite" before "v5").
_PEAK_TFLOPS_BF16 = (
    ("TPU v6 lite", 918.0),  # Trillium / v6e
    ("TPU v6", 918.0),
    ("TPU v5 lite", 197.0),  # v5e
    ("TPU v5e", 197.0),
    ("TPU v5p", 459.0),
    ("TPU v5", 459.0),
    ("TPU v4 lite", 138.0),  # v4i
    ("TPU v4", 275.0),
    ("TPU v3", 123.0),
    ("TPU v2", 45.0),
)


def device_peak_tflops(device_kind: str) -> Optional[float]:
    """bf16 peak TFLOP/s for a device kind, or None if unknown (e.g. CPU)."""
    for name, peak in _PEAK_TFLOPS_BF16:
        if name.lower() in device_kind.lower():
            return peak
    return None


# Public on-demand US-region list prices, USD per chip-hour (Cloud TPU pricing
# page, mid-2025; multi-chip pod types priced per chip). The reference's
# cost-efficiency metric (reference README.md:270-276) uses its cloud's A10
# on-demand rate the same way. Same substring-match convention as the peak
# table; order matters.
_ONDEMAND_USD_PER_CHIP_HR = (
    ("TPU v6 lite", 2.70),  # Trillium / v6e
    ("TPU v6", 2.70),
    ("TPU v5 lite", 1.20),  # v5e
    ("TPU v5e", 1.20),
    ("TPU v5p", 4.20),
    ("TPU v5", 4.20),
    ("TPU v4", 3.22),
    ("TPU v3", 2.00),
    ("TPU v2", 1.125),
)


def device_usd_per_chip_hour(device_kind: str) -> Optional[float]:
    """On-demand $/chip-hour for a device kind, or None if unknown (CPU)."""
    for name, price in _ONDEMAND_USD_PER_CHIP_HR:
        if name.lower() in device_kind.lower():
            return price
    return None


def tokens_per_dollar(
    tokens_per_sec_per_chip: float, device_kind: str
) -> Optional[float]:
    """Training cost efficiency: tokens processed per on-demand dollar.

    The reference publishes this per arm (reference README.md:270-276,
    tokens/$ at the A10's hourly rate); computed here from the same
    per-chip throughput the rest of the metric surface uses.
    """
    price = device_usd_per_chip_hour(device_kind)
    if price is None or tokens_per_sec_per_chip <= 0:
        return None
    return tokens_per_sec_per_chip * 3600.0 / price


def forward_flops_per_token(config) -> float:
    """Analytic forward-pass FLOPs per token.

    Generalized over the architecture-family knobs (models.tinygpt): GQA
    shrinks the K/V projection to ``2*kv_heads*head_dim`` columns, SwiGLU's
    MLP runs three matrices (``6*D*F`` vs GELU's ``4*D*F``), and RoPE adds
    no matmul FLOPs (elementwise rotation — not counted, per the
    PaLM/Chinchilla convention). The LM head term is ``2*D*V`` tied or
    untied alike. Defaults reproduce the original TinyGPT accounting
    exactly (kv=H, F=4D, gelu -> 8*D^2 attention projections + 16*D^2 MLP).
    """
    D, L, V, S = config.n_embd, config.n_layer, config.vocab_size, config.block_size
    H = config.n_head
    Hkv = getattr(config, "kv_heads", H) or H
    F = getattr(config, "mlp_dim", 4 * D) or 4 * D
    Dh = D // H
    if getattr(config, "n_experts", 0) > 0:
        mlp = 2 * config.expert_top_k * (2 * D * F) + 2 * D * config.n_experts
    elif getattr(config, "mlp_act", "gelu") == "swiglu":
        mlp = 2 * (2 * D * F + F * D)  # gate + up + down
    else:
        mlp = 2 * (D * F + F * D)
    # Causal masking halves the score-matrix work: the flash/ring kernels
    # skip fully-masked tiles (ops/flash_attention.py `live`), so charging
    # full S would overstate MFU on --causal runs by up to ~1.5x at 16K.
    # The exact executed fraction is (S + block)/2S; the standard 1/2
    # accounting (PaLM-style MFU) is used so causal and non-causal rows
    # stay comparable across block sizes.
    attn_tokens = S / 2 if getattr(config, "causal", False) else S
    per_layer = (
        2 * D * (H * Dh)  # Q projection
        + 2 * D * (2 * Hkv * Dh)  # K/V projections
        + 2 * (H * Dh) * D  # attention output projection
        + mlp
        + 4 * attn_tokens * (H * Dh)  # QK^T and probs@V
    )
    return float(L * per_layer + 2 * D * V)


def train_flops_per_token(config) -> float:
    """fwd+bwd FLOPs per token (bwd = 2x fwd; remat recompute not counted)."""
    return 3.0 * forward_flops_per_token(config)


def achieved_tflops_per_sec(
    tokens_per_sec_per_chip: float, flops_per_token: float
) -> float:
    """Model TFLOP/s per chip actually delivered at a given throughput."""
    return tokens_per_sec_per_chip * flops_per_token / 1e12


def mfu_pct(
    tokens_per_sec_per_chip: float,
    flops_per_token: float,
    device_kind: str,
) -> Optional[float]:
    """Model-FLOPs utilization in percent, or None for unknown device kinds."""
    peak = device_peak_tflops(device_kind)
    if peak is None or flops_per_token <= 0 or tokens_per_sec_per_chip <= 0:
        return None
    return 100.0 * achieved_tflops_per_sec(tokens_per_sec_per_chip, flops_per_token) / peak
