"""Polyfills for newer-JAX public APIs on older jax runtimes.

The codebase targets the current JAX API surface (``jax.set_mesh``,
``jax.shard_map``, ``jax.typeof``, ``jax.sharding.get_abstract_mesh``).
Some deployment images pin an older jax (observed: 0.4.37) where those
names do not exist yet but the underlying machinery does:

- ``jax.set_mesh(mesh)``              -> entering the ``Mesh`` context
  manager sets the thread-local resource env, which is what the fallback
  ``get_abstract_mesh`` below reads back.
- ``jax.shard_map(..., axis_names=)`` -> ``jax.experimental.shard_map
  .shard_map(..., auto=mesh.axis_names - axis_names)``. The old API tracks
  replication via ``check_rep`` instead of the vma type system; the
  wrapper passes ``check_rep=False`` because programs written for the vma
  world carry no replication annotations the old checker could verify
  (``utils.vma`` degrades to no-ops on the same condition).
- ``jax.typeof(x)``                   -> ``jax.core.get_aval(x)`` (the old
  avals simply lack the ``vma`` attribute, which ``utils.vma`` treats as
  "varies over nothing").
- ``jax.sharding.get_abstract_mesh()``-> the resource env's physical mesh
  (``None``-like empty mesh when no ``set_mesh`` context is active; all
  callers only probe ``.axis_names`` / ``.shape``, which a concrete
  ``Mesh`` satisfies).

``install()`` is idempotent and a strict no-op on jax versions that
already export the real APIs — the polyfill never shadows an upstream
implementation.

Legacy partial-auto caveats (all worked around in ``parallel/`` as of
the schedule-auditor round — see ``pipeline._legacy_partial_auto``):
typed PRNG keys crossing the boundary get a rank-0 sharding validated
against their rank-1 u32 physical shape (keys now cross as raw key
data); ``lax.axis_index`` lowers to a bare partition-id the SPMD
partitioner refuses beside a real auto axis (a P('pipe')-sharded iota
derives the stage id from data); and a ppermute beside a >1 auto axis
dies in the partitioner outright (the pipeline region goes manual over
'data' too on this runtime, with explicit reductions). One REMAINING
limitation: pipeline x tensor-parallel needs a >1 auto 'model' axis
around the ring — structurally impossible here, refused/skipped with
the reason. Everything else (all strategy arms, tp, sp rings/Ulysses,
MoE ep, the llama family, all three pipeline schedules incl. e2e CLI
runs, bench.py both arms) runs fully under the polyfill.
"""

from __future__ import annotations

import contextlib
import functools


def install() -> None:
    """Install missing new-API names onto ``jax``. Safe to call repeatedly."""
    import jax

    if not hasattr(jax, "typeof"):
        import jax.core

        jax.typeof = jax.core.get_aval

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _legacy_shard_map

        @functools.wraps(_legacy_shard_map)
        def _shard_map(f, mesh=None, in_specs=None, out_specs=None,
                       axis_names=None, check_vma=None, **kwargs):
            if mesh is None:
                mesh = _current_mesh()
                if mesh is None:
                    raise ValueError(
                        "jax.shard_map polyfill: no mesh argument and no "
                        "surrounding set_mesh context"
                    )
            if axis_names is not None:
                auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            else:
                auto = frozenset()
            mapped = _legacy_shard_map(
                f, mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=False, auto=auto, **kwargs,
            )
            # The legacy partially-auto path exists only under jit
            # (_shard_map_impl raises NotImplementedError eagerly); under an
            # outer jit trace the inner jit is inlined, so this wrap is
            # semantics-free.
            return jax.jit(mapped) if auto else mapped

        jax.shard_map = _shard_map

    if not hasattr(jax.lax, "axis_size"):
        from jax import lax as _lax

        def _axis_size(axis_name):
            # psum of a Python literal constant-folds to the axis size
            # (no runtime collective) on every jax version.
            return _lax.psum(1, axis_name)

        jax.lax.axis_size = _axis_size

    if not hasattr(jax, "set_mesh"):

        @contextlib.contextmanager
        def _set_mesh(mesh):
            # Entering the Mesh context sets the thread-local resource env
            # that the get_abstract_mesh fallback reads back.
            with mesh:
                yield mesh

        jax.set_mesh = _set_mesh

    if not hasattr(jax.sharding, "get_abstract_mesh"):
        jax.sharding.get_abstract_mesh = _current_mesh

    try:
        from jax.experimental.pallas import tpu as pltpu

        if not hasattr(pltpu, "CompilerParams") and hasattr(
            pltpu, "TPUCompilerParams"
        ):
            # Renamed upstream (TPUCompilerParams -> CompilerParams); same
            # dataclass either way.
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except Exception:
        pass


def _current_mesh():
    """The mesh of the innermost active ``set_mesh`` context, or None."""
    from jax._src.mesh import thread_resources

    mesh = thread_resources.env.physical_mesh
    return mesh if mesh.axis_names else None
