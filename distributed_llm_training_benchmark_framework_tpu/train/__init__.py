from .step import TrainState, create_train_state, make_train_step

__all__ = ["TrainState", "create_train_state", "make_train_step"]
